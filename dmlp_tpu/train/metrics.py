"""Throughput and MFU accounting (BASELINE.json metric: samples/sec/chip).

The reference's only metric is wall-clock ms (common.cpp:130); the training
extension reports the driver-requested rates on top: samples/sec/chip and
model FLOPs utilization, using the standard 6 * batch * matmul-params
estimate for fwd+bwd FLOPs (2 fwd + 4 bwd per weight element per example).
"""

from __future__ import annotations

from typing import Optional

import jax

from dmlp_tpu.train.model import num_matmul_params

# Peak dense (bf16) FLOP/s per chip by PJRT device kind prefix; fallback is
# deliberately conservative so MFU is never overstated on unknown hardware.
PEAK_FLOPS_BY_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6": 918e12,
}
FALLBACK_PEAK_FLOPS = 100e12


def peak_flops_per_chip(device: Optional[jax.Device] = None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for prefix, peak in PEAK_FLOPS_BY_KIND.items():
        if kind.startswith(prefix):
            return peak
    return FALLBACK_PEAK_FLOPS


def train_step_flops(params, batch_size: int) -> float:
    """~FLOPs of one fwd+bwd step (6 per weight element per example)."""
    return 6.0 * batch_size * num_matmul_params(params)


def throughput_metrics(params, batch_size: int, step_time_s: float,
                       n_chips: int,
                       peak_per_chip: Optional[float] = None) -> dict:
    samples_per_sec = batch_size / step_time_s
    flops = train_step_flops(params, batch_size)
    peak = peak_per_chip if peak_per_chip is not None else peak_flops_per_chip()
    return {
        "samples_per_sec": samples_per_sec,
        "samples_per_sec_per_chip": samples_per_sec / n_chips,
        "step_time_ms": step_time_s * 1e3,
        "model_flops_per_step": flops,
        "mfu": flops / (step_time_s * n_chips * peak),
    }
