"""Synthetic labeled data for the training extension.

Two sources, both seeded/deterministic like the reference generator
(generate_input.py:37-50):

- :func:`teacher_batches` — a learnable task: labels are the argmax of a
  fixed random linear teacher over uniform attribute vectors (so loss
  actually falls and tests can assert learning).
- :func:`knn_input_batches` — batches drawn from a parsed KNN problem
  instance (io.grammar), training a classifier on the same records the
  parity engine consumes.
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np


def teacher_batches(num_attrs: int, num_classes: int, batch_size: int,
                    seed: int = 42) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Infinite stream of (x (B, A) f32, y (B,) i32) from a linear teacher."""
    rng = np.random.default_rng(seed)
    teacher = rng.normal(size=(num_attrs, num_classes)).astype(np.float32)
    while True:
        x = rng.uniform(-1.0, 1.0, (batch_size, num_attrs)).astype(np.float32)
        y = np.argmax(x @ teacher, axis=1).astype(np.int32)
        yield x, y


def prefetch_to_device(it: Iterator[Tuple[np.ndarray, np.ndarray]],
                       shardings: Tuple, depth: int = 2,
                       ) -> Iterator[Tuple]:
    """Double-buffered device feed: keep ``depth`` batches in flight.

    ``jax.device_put`` is async, so enqueueing the next batch's transfer
    before the current step's results are consumed overlaps host->device
    DMA with device compute — without this the train loop eats a full
    transfer latency per step (the round-1 loop's synchronous per-step
    device_put, flagged in VERDICT.md "What's weak" #3).
    """
    import collections

    import jax

    xsh, ysh = shardings
    buf: collections.deque = collections.deque()
    for xy in it:
        buf.append((jax.device_put(xy[0], xsh), jax.device_put(xy[1], ysh)))
        if len(buf) >= depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


def knn_input_batches(inp, batch_size: int, seed: int = 42,
                      ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Infinite shuffled epochs over a KNNInput's labeled data points."""
    rng = np.random.default_rng(seed)
    x_all = np.asarray(inp.data_attrs, np.float32)
    y_all = np.asarray(inp.labels, np.int32)
    n = x_all.shape[0]
    if batch_size > n:
        raise ValueError(f"batch_size {batch_size} > {n} data points")
    while True:
        perm = rng.permutation(n)
        for i0 in range(0, n - batch_size + 1, batch_size):
            sel = perm[i0:i0 + batch_size]
            yield x_all[sel], y_all[sel]
