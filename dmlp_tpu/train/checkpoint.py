"""Checkpoint / resume via orbax (survey §5.4 — absent in the reference).

The reference has no checkpointing at all; its only "resume" is benchmark
output caching (run_bench.sh:79-84). The training extension gets real
save/restore: the TrainState pytree (params, optimizer moments, step
counter) round-trips through orbax, preserving shardings on restore when a
target template is supplied.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_checkpoint(directory: str, state: Any, step: Optional[int] = None,
                    ) -> str:
    """Write ``state`` under directory/step_<n>; returns the path."""
    if step is None:
        step = int(jax.device_get(state["step"]))
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    ckpt = _checkpointer()
    ckpt.save(path, state, force=True)
    ckpt.wait_until_finished()
    return path


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = [int(name[5:]) for name in os.listdir(directory)
             if name.startswith("step_") and name[5:].isdigit()]
    return max(steps) if steps else None


def restore_checkpoint(directory: str, target: Any,
                       step: Optional[int] = None) -> Any:
    """Restore the given (or latest) step. ``target`` is a state template
    with the desired shapes/dtypes/shardings (e.g. a freshly built
    TrainState); restored arrays adopt its placement."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    return _checkpointer().restore(path, target=target)
