"""Weak-scaling sweep: per-chip training throughput vs mesh size.

The BASELINE ladder's top rung (BASELINE.md "v5p-128 weak-scaling sweep on
generate_input.py synthetic data"): run the same per-chip workload on
growing dp meshes and watch samples/sec/chip — flat = perfect weak scaling,
droop = collective overhead. Global batch scales with the dp degree
(batch_per_chip stays fixed), the tp degree is constant, so the dp gradient
all-reduce is the only added cost per rung.

On a single-chip or CPU host the sweep runs on virtual devices
(XLA_FLAGS=--xla_force_host_platform_device_count=N) for correctness and
trend shape; absolute numbers come from real multi-chip meshes, where the
same code runs unchanged (the mesh is the only variable).

Usage::

    python -m dmlp_tpu.train.sweep --mesh-sizes 1,2,4,8 --steps 20 \
        --batch-per-chip 256 --dims 64,256,256,10 [--out sweep.jsonl]
        [--offload]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional, Sequence

import jax


def sweep_point(n_chips: int, dims: Sequence[int], batch_per_chip: int,
                steps: int, dtype: Optional[str] = "bfloat16",
                offload: bool = False, pool: int = 2) -> dict:
    """One rung: dp=n_chips mesh, global batch = batch_per_chip * n_chips."""
    import jax.numpy as jnp

    from dmlp_tpu.train.data import teacher_batches
    from dmlp_tpu.train.loop import build_sharded_state
    from dmlp_tpu.train.metrics import throughput_metrics
    from dmlp_tpu.train.sharding import batch_shardings, make_train_mesh
    from dmlp_tpu.train.step import make_optimizer, make_train_step

    devices = jax.devices()[:n_chips]
    if len(devices) < n_chips:
        raise ValueError(f"need {n_chips} devices, have {len(devices)}")
    mesh = make_train_mesh((n_chips, 1), devices)
    batch = batch_per_chip * n_chips
    optimizer = make_optimizer("sgd", 1e-2)
    state = build_sharded_state(mesh, dims, optimizer, offload=offload)
    cdtype = jnp.bfloat16 if dtype == "bfloat16" else None
    if offload:
        from dmlp_tpu.train.step import make_offload_train_step
        step_fn = make_offload_train_step(optimizer, cdtype, state)
    else:
        step_fn = make_train_step(optimizer, cdtype)
    xsh, ysh = batch_shardings(mesh)

    data = teacher_batches(dims[0], dims[-1], batch, seed=1)
    batches = [tuple(jax.device_put(a, s) for a, s in
                     zip(next(data), (xsh, ysh))) for _ in range(pool)]

    for i in range(2):  # compile + settle
        state, m = step_fn(state, *batches[i % pool])
    jax.device_get(m["loss"])
    t0 = time.perf_counter()
    for i in range(steps):
        state, m = step_fn(state, *batches[i % pool])
    jax.device_get(m["loss"])  # fence
    dt = (time.perf_counter() - t0) / steps

    tm = throughput_metrics(state["params"], batch, dt, n_chips)
    return {
        "n_chips": n_chips,
        "global_batch": batch,
        "samples_per_sec_per_chip": round(tm["samples_per_sec_per_chip"], 1),
        "step_time_ms": round(tm["step_time_ms"], 2),
        "mfu": round(tm["mfu"], 4),
        "dims": list(dims),
        "offload": offload,
        "dtype": dtype or "float32",
    }


def decompose(points: list) -> Optional[dict]:
    """Fitted communication-overhead decomposition across the rungs.

    Weak scaling keeps the per-chip workload constant, so the 1-chip rung
    is the compute-only floor and any step-time growth is collective
    overhead. The dp gradient all-reduce's ring cost scales as
    2(n-1)/n * bytes / bw, so the model is

        step_ms(n) = t_compute + t_allreduce_full * (n - 1) / n

    fitted by least squares over the rungs; per-point fields report the
    raw overhead vs rung 1. On virtual CPU devices the collectives are
    shared-memory copies, not ICI — the decomposition then characterizes
    the sweep PLUMBING (trend shape, overhead accounting), not hardware
    scaling, and is labeled as such.
    """
    if len(points) < 2:
        return None
    import numpy as np

    n = np.array([p["n_chips"] for p in points], float)
    t = np.array([p["step_time_ms"] for p in points], float)
    x = (n - 1.0) / n
    a = np.vstack([np.ones_like(x), x]).T
    (t_compute, t_ar), *_ = np.linalg.lstsq(a, t, rcond=None)
    resid = t - a @ np.array([t_compute, t_ar])
    # Compute-only floor: the measured 1-chip rung when present (its comm
    # term is exactly zero), else the fitted intercept as an extrapolated
    # fallback — the intercept alone misreports fit residual as per-rung
    # communication when the model fits poorly (virtual-device contention).
    ones = [p["step_time_ms"] for p in points if p["n_chips"] == 1]
    base = float(ones[0]) if ones else float(t_compute)
    for p in points:
        p["comm_overhead_ms"] = round(p["step_time_ms"] - base, 2)
        p["comm_fraction"] = round(
            max(p["step_time_ms"] - base, 0.0) / p["step_time_ms"], 4)
    return {"model": "step_ms = t_compute + t_allreduce_full * (n-1)/n",
            "t_compute_ms": round(float(t_compute), 2),
            "t_allreduce_full_ms": round(float(t_ar), 2),
            "max_abs_resid_ms": round(float(np.abs(resid).max()), 2)}


def run_sweep(mesh_sizes: Sequence[int], dims: Sequence[int],
              batch_per_chip: int, steps: int,
              dtype: Optional[str] = "bfloat16", offload: bool = False,
              out=None) -> list:
    results = []
    for n in mesh_sizes:
        point = sweep_point(n, dims, batch_per_chip, steps, dtype, offload)
        results.append(point)
        if out is not None:
            # Stream each rung as it lands — the largest mesh is exactly
            # where a crash/preemption happens, and earlier rungs must
            # survive it. The decomposition fields are appended to the
            # summary line instead of mutating already-written points.
            out.write(json.dumps(point) + "\n")
            out.flush()
    fit = decompose(results)
    if out is not None:
        if fit is not None:
            virtual = jax.devices()[0].platform == "cpu"
            out.write(json.dumps({
                "summary": fit,
                "per_rung_comm": [
                    {"n_chips": p["n_chips"],
                     "comm_overhead_ms": p["comm_overhead_ms"],
                     "comm_fraction": p["comm_fraction"]}
                    for p in results],
                "scope": ("plumbing-only: virtual CPU devices share the "
                          "same physical cores, so the overhead term "
                          "absorbs compute contention as well as the "
                          "shared-memory collectives (a large "
                          "max_abs_resid_ms flags exactly this); hardware "
                          "scaling needs a real multi-chip mesh"
                          if virtual else "hardware"),
            }) + "\n")
        out.flush()
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dmlp_tpu.train.sweep",
                                description=__doc__)
    p.add_argument("--mesh-sizes", default="1,2,4,8")
    p.add_argument("--dims", default="64,256,256,10")
    p.add_argument("--batch-per-chip", type=int, default=256)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--offload", action="store_true")
    p.add_argument("--out", default=None, help="JSONL output path "
                   "(default: stdout)")
    args = p.parse_args(argv)

    sizes = [int(s) for s in args.mesh_sizes.split(",")]
    dims = tuple(int(d) for d in args.dims.split(","))
    out = open(args.out, "w") if args.out else sys.stdout
    try:
        run_sweep(sizes, dims, args.batch_per_chip, args.steps,
                  None if args.dtype == "float32" else args.dtype,
                  args.offload, out)
    finally:
        if args.out:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
