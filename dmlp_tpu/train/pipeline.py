"""Pipeline-parallel training (GPipe-style microbatching) over a
("dp", "pp") mesh — the pp rung of the mesh-parallelism ladder next to
the dp x tp step (train.step / train.sharding).

The reference has no training at all (survey §2: TP/PP absent); this is
part of the north-star extension, built the TPU way rather than as a
port of MPMD pipeline frameworks: ONE jitted SPMD program in which

- the layer stack is split into S contiguous stages, stacked into
  uniform (S, P, H, H) arrays and sharded over the mesh's "pp" axis
  (each pp cell holds only its stage's weights);
- a ``lax.scan`` over M + S - 1 ticks runs the pipeline schedule: at
  tick t, stage s computes microbatch m = t - s and hands its
  activation to stage s+1 via ``lax.ppermute`` over ICI — the bubble
  (ticks where m is out of range) is masked, not branched, because XLA
  wants static control flow;
- the loss leaves the shard_map as per-cell PARTIALS (nonzero only on
  each dp row's last stage) summed outside in plain math — no
  collective touches the loss path, so the grad transpose is exact by
  construction — and plain ``jax.grad`` differentiates through the
  scan + ppermute (XLA emits the reverse-schedule permutes): no
  hand-written backward pass.

Input projection and readout are computed per pp cell (they are O(H)
of the O(P * H^2) stage work; only the cells whose values reach the
loss contribute gradients); batches shard over "dp", so data
parallelism composes with the pipeline in the same program.

Microbatch semantics: the loss is the mean over the full (per-dp-cell)
batch, so gradients equal the unpipelined model's — proven by the
equivalence test against a flat single-device stack
(tests/test_train_pipeline.py).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from dmlp_tpu.utils.compat import shard_map

DP_AXIS = "dp"
PP_AXIS = "pp"

PipeParams = Dict[str, jax.Array]


def make_axes_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Mesh over the leading len(axes) devices — the one mesh builder the
    pp/pp3/ep entry points share (axis names and sizes as an ordered
    dict)."""
    devices = list(devices if devices is not None else jax.devices())
    if any(v < 1 for v in axes.values()):
        raise ValueError(f"mesh axes must be >= 1, got {axes}")
    total = int(np.prod(list(axes.values())))
    if total > len(devices):
        raise ValueError(f"need {total} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:total]).reshape(*axes.values()),
                tuple(axes))


def make_pp_mesh(dp: int, pp: int, devices=None) -> Mesh:
    return make_axes_mesh({DP_AXIS: dp, PP_AXIS: pp}, devices)


def init_pipeline(key, d_in: int, hidden: int, n_classes: int,
                  stages: int, layers_per_stage: int,
                  dtype=jnp.float32) -> PipeParams:
    """Uniform pipeline body: stages x layers_per_stage (H, H) layers,
    plus replicated input projection and readout. Stacked so the stage
    axis shards with P("pp", ...)."""
    ks = jax.random.split(key, 4)
    s, p, h = stages, layers_per_stage, hidden
    scale = jnp.sqrt(2.0 / h).astype(dtype)
    return {
        "in_w": jax.random.normal(ks[0], (d_in, h), dtype)
        * jnp.sqrt(2.0 / d_in).astype(dtype),
        "in_b": jnp.zeros((h,), dtype),
        "pp_w": jax.random.normal(ks[1], (s, p, h, h), dtype) * scale,
        "pp_b": jnp.zeros((s, p, h), dtype),
        "out_w": jax.random.normal(ks[2], (h, n_classes), dtype)
        * jnp.sqrt(2.0 / h).astype(dtype),
        "out_b": jnp.zeros((n_classes,), dtype),
    }


# Single source of truth for per-param partition specs (placement and
# shard_map in_specs both derive from it).
PP_PSPECS = {
    "in_w": P(None, None), "in_b": P(None),
    "pp_w": P(PP_AXIS, None, None, None),
    "pp_b": P(PP_AXIS, None, None),
    "out_w": P(None, None), "out_b": P(None),
}


def pipeline_param_shardings(mesh: Mesh):
    return {k: NamedSharding(mesh, spec) for k, spec in PP_PSPECS.items()}


def _partials_train_step(sharded_loss, optimizer, n_dp: int):
    """Jitted donated train step over a partial-loss shard_map program:
    the per-cell partials (one nonzero cell per dp row) sum to the batch
    loss in plain math here. Shared by the 2D and 3D pipeline steps."""
    def loss_fn(params, x, y):
        loss_p, acc_p = sharded_loss(params, x, y)
        return loss_p.sum() / n_dp, acc_p.sum() / n_dp

    def step(state, x, y):
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], x, y)
        updates, opt = optimizer.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt": opt, "step": state["step"] + 1},
                {"loss": loss, "accuracy": acc})

    return jax.jit(step, donate_argnums=(0,))


def place_state(params, shardings, optimizer):
    """device_put params per sharding table; moments inherit placement.
    Shared by the pipeline and MoE state builders. The step counter is
    placed replicated on the same mesh — a default-device scalar would
    make the jit reject mesh-committed batch arguments as an
    incompatible device set."""
    placed = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    mesh = next(iter(shardings.values())).mesh
    step0 = jax.device_put(jnp.zeros((), jnp.int32),
                           NamedSharding(mesh, P()))
    return {"params": placed, "opt": optimizer.init(placed), "step": step0}


def _stage_block(w, b, h):
    """One stage's layers_per_stage dense+relu layers. w: (P, H, H)."""
    def layer(h, wb):
        wi, bi = wb
        return jax.nn.relu(
            jax.lax.dot_general(h, wi, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            + bi).astype(h.dtype), None
    h, _ = jax.lax.scan(layer, h, (w, b))
    return h


def _pp_body(params, x, y, *, n_stages: int, n_micro: int, n_classes: int):
    """Per-(dp, pp)-cell pipelined loss (runs inside shard_map).

    ``params["pp_w"]`` arrives as this cell's (1, P, H, H) stage slice;
    x/y are this dp cell's local batch, replicated over pp.
    """
    assert params["out_w"].shape[1] == n_classes, \
        (params["out_w"].shape, n_classes)
    s_idx = jax.lax.axis_index(PP_AXIS)
    w_s = params["pp_w"][0]
    b_s = params["pp_b"][0]

    h0 = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    mb = h0.shape[0] // n_micro
    h_mb = h0.reshape(n_micro, mb, -1)

    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        act, ys = carry
        m = t - s_idx  # this stage's microbatch index at this tick
        # Stage 0 pulls fresh microbatches; later stages consume the
        # activation handed over at the previous tick. Bubbles (m out of
        # range) compute on zeros and are masked at collection.
        fresh = h_mb[jnp.clip(t, 0, n_micro - 1)]
        inp = jnp.where(s_idx == 0, fresh, act)
        out = _stage_block(w_s, b_s, inp)
        # Last stage collects its finished microbatch.
        take = (s_idx == n_stages - 1) & (m >= 0) & (m < n_micro)
        ys = jnp.where(
            take,
            jax.lax.dynamic_update_index_in_dim(
                ys, out, jnp.clip(m, 0, n_micro - 1), 0),
            ys)
        # Hand the activation to the next stage (stage 0 receives zeros;
        # the last stage's output is not forwarded).
        # check: comms-model=pipeline_ppermute_traffic
        act = jax.lax.ppermute(out, PP_AXIS, perm) if n_stages > 1 else out
        return (act, ys), None

    ys0 = jnp.zeros_like(h_mb)
    act0 = jnp.zeros_like(h_mb[0])
    (_, ys), _ = jax.lax.scan(tick, (act0, ys0),
                              jnp.arange(n_micro + n_stages - 1))

    # Loss as a PER-CELL PARTIAL (nonzero only on the last stage), summed
    # OUTSIDE the shard_map: no collective touches the loss path, so the
    # grad transpose is exact by construction — replicated-output specs
    # under check_vma=False are a known axis-size-overcount sharp edge,
    # and in-body psums on the loss would reintroduce it.
    h_out = ys.reshape(h0.shape)
    logits = h_out @ params["out_w"] + params["out_b"]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    last = (s_idx == n_stages - 1).astype(loss.dtype)
    return (loss * last)[None], (acc * last)[None]


def make_pp_train_step(mesh: Mesh, optimizer: optax.GradientTransformation,
                       *, n_micro: int, n_classes: int):
    """Jitted (state, x, y) -> (state', {loss, accuracy}) over the
    ("dp", "pp") mesh. ``state`` = {"params", "opt", "step"} with params
    placed by pipeline_param_shardings."""
    n_stages = mesh.devices.shape[1]

    n_dp = mesh.devices.shape[0]
    body = functools.partial(_pp_body, n_stages=n_stages, n_micro=n_micro,
                             n_classes=n_classes)
    sharded_loss = shard_map(
        body, mesh=mesh,
        in_specs=(PP_PSPECS, P(DP_AXIS, None), P(DP_AXIS)),
        out_specs=(P((DP_AXIS, PP_AXIS)), P((DP_AXIS, PP_AXIS))),
        check_vma=False)

    return _partials_train_step(sharded_loss, optimizer, n_dp)


def build_pp_state(mesh: Mesh, optimizer, d_in: int, hidden: int,
                   n_classes: int, layers_per_stage: int, seed: int = 0):
    """Init + place pipeline params; optimizer moments inherit placement."""
    stages = mesh.devices.shape[1]
    params = init_pipeline(jax.random.PRNGKey(seed), d_in, hidden,
                           n_classes, stages, layers_per_stage)
    return place_state(params, pipeline_param_shardings(mesh), optimizer)


def flatten_pipeline(params: PipeParams) -> Tuple:
    """The mathematically equivalent single-device stack:
    in -> S*P dense+relu (H, H) layers -> readout. For the equivalence
    test and for flat-reference inference."""
    s, p, h, _ = params["pp_w"].shape
    ws = np.asarray(params["pp_w"]).reshape(s * p, h, h)
    bs = np.asarray(params["pp_b"]).reshape(s * p, h)
    return (np.asarray(params["in_w"]), np.asarray(params["in_b"]),
            ws, bs, np.asarray(params["out_w"]), np.asarray(params["out_b"]))


def flat_forward(flat, x):
    """NumPy/JAX reference forward for flatten_pipeline output."""
    in_w, in_b, ws, bs, out_w, out_b = flat
    h = x.astype(jnp.float32) @ in_w + in_b
    for wi, bi in zip(ws, bs):
        h = jax.nn.relu(h @ wi + bi)
    return h @ out_w + out_b


# ---------------------------------------------------------------------------
# Interleaved schedule (1F1B-interleaved / Megatron virtual stages): each pp
# cell holds V non-contiguous stage CHUNKS (cell s owns chunks s, s+S, ...,
# s+(V-1)S of the V*S-chunk layer sequence); the scan runs chunk c of
# microbatch m at tick m + c, activations ride a uniform +1 ring ppermute
# (the S-1 -> 0 wraparound carries the level-up hop). Each tick costs a
# 1/V stage slice, so the pipeline fill/drain shrinks: forward span
# (M - 1 + V*S) * F/V = ((M-1)/V + S) * F vs GPipe's (M - 1 + S) * F —
# the bubble term drops by V, which is the whole point at small M
# (VERDICT r4 item 6). Backward is still jax.grad through the scan (the
# reverse schedule inherits the same 1/V tick cost).
#
# Why not plain (non-interleaved) 1F1B: in a single-jit SPMD program the
# backward schedule is XLA's reverse of the forward scan, and
# non-interleaved 1F1B has exactly GPipe's bubble ((S-1)/(M+S-1)) — its
# advantage is peak activation memory (O(S) in-flight microbatches instead
# of O(M)), which in this design is the remat lever (jax.checkpoint on the
# tick body), not a schedule change. Interleaving is the schedule lever
# that actually moves the bubble, so that is what ships.
#
# The masked schedule needs at most one active chunk per cell per tick,
# which holds when n_micro <= n_stages — exactly the small-M regime where
# GPipe's bubble hurts; larger M should use GPipe (its bubble term is
# already amortized there).
# ---------------------------------------------------------------------------


def init_pipeline_interleaved(key, d_in: int, hidden: int, n_classes: int,
                              stages: int, n_virtual: int,
                              layers_per_chunk: int,
                              dtype=jnp.float32) -> PipeParams:
    """V*S chunk layer stack: pp_w (V, S, P, H, H); chunk (l, s) holds
    layers [(l*S + s) * P, ...) of the flat sequence, so axis order
    (level, stage) IS the model's layer order under reshape."""
    ks = jax.random.split(key, 4)
    v, s, p, h = n_virtual, stages, layers_per_chunk, hidden
    scale = jnp.sqrt(2.0 / h).astype(dtype)
    return {
        "in_w": jax.random.normal(ks[0], (d_in, h), dtype)
        * jnp.sqrt(2.0 / d_in).astype(dtype),
        "in_b": jnp.zeros((h,), dtype),
        "pp_w": jax.random.normal(ks[1], (v, s, p, h, h), dtype) * scale,
        "pp_b": jnp.zeros((v, s, p, h), dtype),
        "out_w": jax.random.normal(ks[2], (h, n_classes), dtype)
        * jnp.sqrt(2.0 / h).astype(dtype),
        "out_b": jnp.zeros((n_classes,), dtype),
    }


PPI_PSPECS = {
    "in_w": P(None, None), "in_b": P(None),
    "pp_w": P(None, PP_AXIS, None, None, None),
    "pp_b": P(None, PP_AXIS, None, None),
    "out_w": P(None, None), "out_b": P(None),
}


def pipeline_interleaved_param_shardings(mesh: Mesh):
    return {k: NamedSharding(mesh, spec) for k, spec in PPI_PSPECS.items()}


def _ppi_body(params, x, y, *, n_stages: int, n_micro: int, n_virtual: int,
              n_classes: int):
    """Per-(dp, pp)-cell interleaved pipelined loss partial.

    At tick t, this cell's active chunk is the (l, s_idx) with
    r = t - s_idx, l = r // S, m = r % S (unique because M <= S); chunk
    level is a traced dynamic index into the cell's (V, P, H, H) slice.
    Bubbles compute on zeros and are masked at collection, like _pp_body.
    """
    assert params["out_w"].shape[1] == n_classes
    s_idx = jax.lax.axis_index(PP_AXIS)
    w_v = params["pp_w"][:, 0]          # (V, P, H, H) — this cell's chunks
    b_v = params["pp_b"][:, 0]

    h0 = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    mb = h0.shape[0] // n_micro
    h_mb = h0.reshape(n_micro, mb, -1)
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        act, ys = carry
        r = t - s_idx
        lvl = jnp.where(r >= 0, r // n_stages, 0)
        m = jnp.where(r >= 0, r % n_stages, 0)
        active = (r >= 0) & (lvl < n_virtual) & (m < n_micro)
        w_l = jax.lax.dynamic_index_in_dim(
            w_v, jnp.clip(lvl, 0, n_virtual - 1), 0, keepdims=False)
        b_l = jax.lax.dynamic_index_in_dim(
            b_v, jnp.clip(lvl, 0, n_virtual - 1), 0, keepdims=False)
        fresh = h_mb[jnp.clip(m, 0, n_micro - 1)]
        inp = jnp.where((s_idx == 0) & (lvl == 0), fresh, act)
        out = _stage_block(w_l, b_l, inp)
        take = active & (s_idx == n_stages - 1) & (lvl == n_virtual - 1)
        ys = jnp.where(
            take,
            jax.lax.dynamic_update_index_in_dim(
                ys, out, jnp.clip(m, 0, n_micro - 1), 0),
            ys)
        # check: comms-model=pipeline_ppermute_traffic
        act = jax.lax.ppermute(out, PP_AXIS, ring) if n_stages > 1 else out
        return (act, ys), None

    n_ticks = n_micro - 1 + n_virtual * n_stages
    (_, ys), _ = jax.lax.scan(
        tick, (jnp.zeros_like(h_mb[0]), jnp.zeros_like(h_mb)),
        jnp.arange(n_ticks))

    logits = ys.reshape(h0.shape) @ params["out_w"] + params["out_b"]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    last = (s_idx == n_stages - 1).astype(loss.dtype)
    return (loss * last)[None], (acc * last)[None]


def make_ppi_train_step(mesh: Mesh, optimizer: optax.GradientTransformation,
                        *, n_micro: int, n_virtual: int, n_classes: int):
    """Jitted interleaved-schedule train step over ("dp", "pp"). Requires
    n_micro <= n_stages (one active chunk per cell per tick)."""
    n_dp, n_stages = mesh.devices.shape
    if n_micro > n_stages:
        raise ValueError(
            f"interleaved schedule needs n_micro <= n_stages "
            f"({n_micro} > {n_stages}); use the gpipe schedule there")
    body = functools.partial(_ppi_body, n_stages=n_stages, n_micro=n_micro,
                             n_virtual=n_virtual, n_classes=n_classes)
    sharded_loss = shard_map(
        body, mesh=mesh,
        in_specs=(PPI_PSPECS, P(DP_AXIS, None), P(DP_AXIS)),
        out_specs=(P((DP_AXIS, PP_AXIS)), P((DP_AXIS, PP_AXIS))),
        check_vma=False)
    return _partials_train_step(sharded_loss, optimizer, n_dp)


def build_ppi_state(mesh: Mesh, optimizer, d_in: int, hidden: int,
                    n_classes: int, n_virtual: int, layers_per_chunk: int,
                    seed: int = 0):
    stages = mesh.devices.shape[1]
    params = init_pipeline_interleaved(
        jax.random.PRNGKey(seed), d_in, hidden, n_classes, stages,
        n_virtual, layers_per_chunk)
    return place_state(params, pipeline_interleaved_param_shardings(mesh),
                       optimizer)


def flatten_interleaved(params: PipeParams) -> Tuple:
    """Flat single-device stack for the interleaved layout (chunk order
    (level, stage) = the model's layer order)."""
    v, s, p, h, _ = params["pp_w"].shape
    ws = np.asarray(params["pp_w"]).reshape(v * s * p, h, h)
    bs = np.asarray(params["pp_b"]).reshape(v * s * p, h)
    return (np.asarray(params["in_w"]), np.asarray(params["in_b"]),
            ws, bs, np.asarray(params["out_w"]), np.asarray(params["out_b"]))


def schedule_ticks(schedule: str, n_micro: int, n_stages: int,
                   n_virtual: int = 1) -> int:
    """Scan tick count of each schedule — the bubble arithmetic for the
    PIPEBENCH record: each tick costs ~one stage-chunk of compute (a full
    stage for gpipe, a 1/V slice for interleaved)."""
    if schedule == "gpipe":
        return n_micro + n_stages - 1
    if schedule == "interleaved":
        return n_micro - 1 + n_virtual * n_stages
    raise ValueError(f"unknown schedule {schedule!r}")


def bubble_fraction(schedule: str, n_micro: int, n_stages: int,
                    n_virtual: int = 1) -> float:
    """Idle fraction of one device's pipeline span, in stage-work units
    (a unit = one full stage pass over one microbatch; fwd and bwd scale
    identically). Per device the useful work is always M units (its V
    chunks sum to one stage's layers); the span is the tick count times
    the per-tick cost:

    - gpipe:       (M + S - 1) ticks x 1 unit      -> span M + S - 1
    - interleaved: (M - 1 + V*S) ticks x 1/V unit  -> span (M-1)/V + S

    so interleaving divides the (S - 1)-shaped fill/drain term by V."""
    span = (schedule_ticks(schedule, n_micro, n_stages, n_virtual)
            / (n_virtual if schedule == "interleaved" else 1))
    return 1.0 - n_micro / span


# ---------------------------------------------------------------------------
# 3D composition: dp x tp x pp in one jit. Stage layers come in Megatron
# col/row pairs — the column-split matmul shards its OUTPUT dim over "tp",
# the row-split one its INPUT dim, so each pair needs exactly one tp psum —
# while the pp schedule (scan + ppermute) and the dp batch split are
# unchanged from the 2D form above. Grad-exact vs the flat stack
# (tests/test_train_pipeline.py::test_pp3_step_matches_flat_reference).
# ---------------------------------------------------------------------------

TP_AXIS = "tp"

PP3_PSPECS = {
    "in_w": P(None, None), "in_b": P(None),
    # column-parallel: output dim tp-sharded (bias follows its output)
    "wc": P(PP_AXIS, None, None, TP_AXIS),
    "bc": P(PP_AXIS, None, TP_AXIS),
    # row-parallel: input dim tp-sharded; bias replicated (added after psum)
    "wr": P(PP_AXIS, None, TP_AXIS, None),
    "br": P(PP_AXIS, None, None),
    "out_w": P(None, None), "out_b": P(None),
}


def make_pp3_mesh(dp: int, tp: int, pp: int, devices=None) -> Mesh:
    return make_axes_mesh({DP_AXIS: dp, TP_AXIS: tp, PP_AXIS: pp}, devices)


def init_pipeline3(key, d_in: int, hidden: int, n_classes: int,
                   stages: int, pairs_per_stage: int,
                   dtype=jnp.float32) -> PipeParams:
    """Col/row layer pairs per stage: h -> relu(h@Wc + bc) -> @Wr (+psum)
    -> relu(+br)."""
    ks = jax.random.split(key, 4)
    s, p2, h = stages, pairs_per_stage, hidden
    scale = jnp.sqrt(2.0 / h).astype(dtype)
    return {
        "in_w": jax.random.normal(ks[0], (d_in, h), dtype)
        * jnp.sqrt(2.0 / d_in).astype(dtype),
        "in_b": jnp.zeros((h,), dtype),
        "wc": jax.random.normal(ks[1], (s, p2, h, h), dtype) * scale,
        "bc": jnp.zeros((s, p2, h), dtype),
        "wr": jax.random.normal(ks[2], (s, p2, h, h), dtype) * scale,
        "br": jnp.zeros((s, p2, h), dtype),
        "out_w": jax.random.normal(ks[3], (h, n_classes), dtype)
        * jnp.sqrt(2.0 / h).astype(dtype),
        "out_b": jnp.zeros((n_classes,), dtype),
    }


def pipeline3_param_shardings(mesh: Mesh):
    return {k: NamedSharding(mesh, spec) for k, spec in PP3_PSPECS.items()}


def _stage_block3(wc, bc, wr, br, h):
    """One stage's col/row pairs on this tp cell's shard: wc (P2, H, Hl),
    wr (P2, Hl, H); one tp psum per pair."""
    def pair(h, wb):
        wci, bci, wri, bri = wb
        u = jax.nn.relu(
            jax.lax.dot_general(h, wci, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32) + bci)
        v = jax.lax.dot_general(u, wri, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        v = jax.lax.psum(v, TP_AXIS)  # check: comms-model=tp_psum_activation_traffic
        return jax.nn.relu(v + bri).astype(h.dtype), None
    h, _ = jax.lax.scan(pair, h, (wc, bc, wr, br))
    return h


def _pp3_body(params, x, y, *, n_stages: int, n_micro: int, n_classes: int):
    """Per-(dp, tp, pp)-cell pipelined loss partial."""
    assert params["out_w"].shape[1] == n_classes
    s_idx = jax.lax.axis_index(PP_AXIS)
    t_idx = jax.lax.axis_index(TP_AXIS)
    wc, bc = params["wc"][0], params["bc"][0]
    wr, br = params["wr"][0], params["br"][0]

    h0 = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    mb = h0.shape[0] // n_micro
    h_mb = h0.reshape(n_micro, mb, -1)
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        act, ys = carry
        m = t - s_idx
        fresh = h_mb[jnp.clip(t, 0, n_micro - 1)]
        inp = jnp.where(s_idx == 0, fresh, act)
        out = _stage_block3(wc, bc, wr, br, inp)
        take = (s_idx == n_stages - 1) & (m >= 0) & (m < n_micro)
        ys = jnp.where(
            take,
            jax.lax.dynamic_update_index_in_dim(
                ys, out, jnp.clip(m, 0, n_micro - 1), 0),
            ys)
        # check: comms-model=pipeline_ppermute_traffic
        act = jax.lax.ppermute(out, PP_AXIS, perm) if n_stages > 1 else out
        return (act, ys), None

    (_, ys), _ = jax.lax.scan(
        tick, (jnp.zeros_like(h_mb[0]), jnp.zeros_like(h_mb)),
        jnp.arange(n_micro + n_stages - 1))

    logits = ys.reshape(h0.shape) @ params["out_w"] + params["out_b"]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    # Partial nonzero on exactly one (tp, pp) cell per dp row: the same
    # no-collective-on-the-loss-path rule as the 2D form. (Every tp cell
    # of the last stage holds identical post-psum activations; only tp 0
    # reports.)
    mine = ((s_idx == n_stages - 1) & (t_idx == 0)).astype(loss.dtype)
    return (loss * mine)[None], (acc * mine)[None]


def make_pp3_train_step(mesh: Mesh, optimizer: optax.GradientTransformation,
                        *, n_micro: int, n_classes: int):
    """Jitted (state, x, y) -> (state', metrics) over ("dp", "tp", "pp")."""
    n_dp, _n_tp, n_stages = mesh.devices.shape
    body = functools.partial(_pp3_body, n_stages=n_stages, n_micro=n_micro,
                             n_classes=n_classes)
    sharded_loss = shard_map(
        body, mesh=mesh,
        in_specs=(PP3_PSPECS, P(DP_AXIS, None), P(DP_AXIS)),
        out_specs=(P((DP_AXIS, TP_AXIS, PP_AXIS)),
                   P((DP_AXIS, TP_AXIS, PP_AXIS))),
        check_vma=False)

    return _partials_train_step(sharded_loss, optimizer, n_dp)


def build_pp3_state(mesh: Mesh, optimizer, d_in: int, hidden: int,
                    n_classes: int, pairs_per_stage: int, seed: int = 0):
    stages = mesh.devices.shape[2]
    params = init_pipeline3(jax.random.PRNGKey(seed), d_in, hidden,
                            n_classes, stages, pairs_per_stage)
    return place_state(params, pipeline3_param_shardings(mesh), optimizer)


def pp3_reference_forward(params: PipeParams, x) -> jax.Array:
    """Unsharded reference for the 3D step (equivalence oracle)."""
    h = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    s, p2 = params["wc"].shape[:2]
    wc = params["wc"].reshape(s * p2, *params["wc"].shape[2:])
    bc = params["bc"].reshape(s * p2, -1)
    wr = params["wr"].reshape(s * p2, *params["wr"].shape[2:])
    br = params["br"].reshape(s * p2, -1)
    for i in range(s * p2):
        u = jax.nn.relu(h @ wc[i] + bc[i])
        h = jax.nn.relu(u @ wr[i] + br[i])
    return h @ params["out_w"] + params["out_b"]
