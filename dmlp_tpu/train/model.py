"""Pure-JAX MLP classifier over the framework's attribute-vector data model.

The model consumes the same record shape the KNN engine does — a float
attribute vector per example, an integer label — so the training extension
and the parity engine share one data pipeline (io.grammar / io.datagen).
Params are a plain pytree (dict of layers), which keeps sharding annotations
(train.sharding) and orbax checkpointing trivially composable.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

Params = Dict[str, Dict[str, jax.Array]]


def init_mlp(key: jax.Array, layer_dims: Sequence[int],
             dtype=jnp.float32) -> Params:
    """He-initialized MLP params for dims [in, h1, ..., num_classes]."""
    params: Params = {}
    keys = jax.random.split(key, len(layer_dims) - 1)
    for i, (din, dout) in enumerate(zip(layer_dims[:-1], layer_dims[1:])):
        params[f"layer{i}"] = {
            "w": (jax.random.normal(keys[i], (din, dout), dtype)
                  * jnp.sqrt(2.0 / din).astype(dtype)),
            "b": jnp.zeros((dout,), dtype),
        }
    return params


def mlp_apply(params: Params, x: jax.Array,
              compute_dtype=None) -> jax.Array:
    """Forward pass -> logits (..., num_classes).

    ``compute_dtype=bfloat16`` runs the matmuls on the MXU in bf16 with f32
    accumulation (preferred_element_type); params stay in their storage
    dtype, logits are returned in f32 for a stable softmax.
    """
    n = len(params)
    h = x if compute_dtype is None else x.astype(compute_dtype)
    for i in range(n):
        layer = params[f"layer{i}"]
        w, b = layer["w"], layer["b"]
        if compute_dtype is not None:
            w = w.astype(compute_dtype)
        h = jax.lax.dot_general(h, w, (((h.ndim - 1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        h = h + b.astype(h.dtype)
        if i < n - 1:
            h = jax.nn.relu(h)
            if compute_dtype is not None:
                h = h.astype(compute_dtype)
    return h


def num_matmul_params(params: Any) -> int:
    """Total weight-matrix elements (for the 6*N*B FLOP estimate).

    Dispatches on the param-family layout: the dp x tp MLP
    (dict-of-layers with "w"), the pipeline families (stacked "pp_w" or
    "wc"/"wr"), and the MoE family ("up"/"down" expert stacks + router).
    MoE counts ALL expert elements — the dense-dispatch step really
    multiplies by every expert — so its MFU stays honest for the
    implementation as built.
    """
    if "pp_w" in params:
        return int(params["in_w"].size + params["pp_w"].size
                   + params["out_w"].size)
    if "wc" in params:
        return int(params["in_w"].size + params["wc"].size
                   + params["wr"].size + params["out_w"].size)
    if "up" in params:
        return int(params["in_w"].size + params["router"].size
                   + params["up"].size + params["down"].size
                   + params["out_w"].size)
    return sum(int(v["w"].size) for v in params.values())
