"""Mesh + sharding rules for the training extension.

A 2D ``Mesh(("dp", "tp"))``: batches shard over "dp" (data parallelism —
the analog of the reference's dataset axis), weight matrices shard over
"tp" (tensor parallelism, Megatron-style alternating column/row splits so
consecutive layers need only one collective pair per block).

Everything is declarative: the train step is jitted with these
``NamedSharding``s and XLA inserts the collectives — the dp gradient
all-reduce (the MPI_Allreduce of the north star) and the tp activation
psum — over ICI. No hand-written communication.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
TP_AXIS = "tp"


def make_train_mesh(shape: Optional[Tuple[int, int]] = None,
                    devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """(dp, tp) mesh; shape=None uses all devices as dp (tp=1)."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices), 1)
    dp, tp = shape
    if dp * tp > len(devices):
        raise ValueError(f"mesh shape {shape} needs {dp * tp} devices, "
                         f"have {len(devices)}")
    return Mesh(np.asarray(devices[:dp * tp]).reshape(dp, tp),
                (DP_AXIS, TP_AXIS))


def param_shardings(params, mesh: Mesh, memory_kind: Optional[str] = None):
    """Megatron-style alternating tp shard: even layers split the output
    dim (column parallel), odd layers the input dim (row parallel); biases
    follow their layer's output split. Replicated over dp, so jitted grads
    inherit a dp all-reduce.

    ``memory_kind="pinned_host"`` places params in host DRAM (the bench_4
    host-offload analog, BASELINE.md "host-DRAM param offload"): the train
    step then streams each layer to device memory right before its matmul
    (step.make_train_step(offload=True)) and writes the update back, so HBM
    never holds the full parameter set.
    """
    n = len(params)

    def sh(spec):
        if memory_kind is None:
            return NamedSharding(mesh, spec)
        return NamedSharding(mesh, spec, memory_kind=memory_kind)

    def spec(i: int):
        col = (i % 2 == 0)
        wspec = P(None, TP_AXIS) if col else P(TP_AXIS, None)
        bspec = P(TP_AXIS) if col else P(None)
        return {"w": sh(wspec), "b": sh(bspec)}

    return {f"layer{i}": spec(i) for i in range(n)}


def batch_shardings(mesh: Mesh):
    """(x, y) sharded over dp on the batch axis, replicated over tp."""
    return (NamedSharding(mesh, P(DP_AXIS, None)),
            NamedSharding(mesh, P(DP_AXIS)))
