"""Training-loop CLI: the north-star benchmark entry point.

Runs a sharded train step over a device mesh with JSON metrics
(samples/sec/chip, MFU — BASELINE.json's metric set) and orbax
checkpoint/resume. ``--parallelism`` picks the mesh family: the dp x tp
MLP (default; offload ladder + compute dtype), the dp x pp /
dp x tp x pp pipelined stack, or the dp x ep MoE. Usage::

    python -m dmlp_tpu.train.loop --steps 200 --batch 4096 \
        --dims 64,512,512,10 [--mesh DP,TP] [--optimizer sgd|adam]
        [--compute-dtype bfloat16] [--offload [none|params|all]]
        [--checkpoint-dir ckpt --ckpt-every 100] [--resume]
        [--metrics-file metrics.jsonl] [--compile-cache DIR]
    python -m dmlp_tpu.train.loop --parallelism dp_pp  --mesh 2,4 \
        --dims 64,256,10 --microbatches 8
    python -m dmlp_tpu.train.loop --parallelism dp_pp3 --mesh 1,2,4 \
        --dims 64,256,10
    python -m dmlp_tpu.train.loop --parallelism dp_ep  --mesh 2,4 \
        --dims 64,256,512,10 --experts 8 \
        [--moe-dispatch dense|a2a] [--capacity-factor 1.0]
"""

from __future__ import annotations

import argparse
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.resilience import inject as rs_inject
from dmlp_tpu.resilience import retry as rs_retry
from dmlp_tpu.resilience import stats as rs_stats
from dmlp_tpu.train import checkpoint as ckpt_lib
from dmlp_tpu.train.data import teacher_batches
from dmlp_tpu.train.metrics import throughput_metrics
from dmlp_tpu.train.model import init_mlp
from dmlp_tpu.train.sharding import batch_shardings, make_train_mesh, param_shardings
from dmlp_tpu.train.step import init_state, make_optimizer, make_train_step
from dmlp_tpu.utils.metrics_log import MetricsLogger


def resolve_offload_level(offload) -> str:
    """Normalize the offload policy: "none" | "params" | "all".

    Bools stay accepted ("all"/"none") for the original binary API. The
    ladder trades HBM capacity against stream traffic (the step streams
    exactly the host-resident leaves, step.make_train_step):

    - "none":   everything HBM-resident — fastest, most HBM.
    - "params": params in host DRAM, optimizer moments HBM-resident —
      halves the per-step stream bytes vs "all" (params down + updated
      params up; moments never cross), so the latency-hiding scheduler
      hides the streams under the matmuls at batch sizes where "all"
      still exposes transfer (TRAINBENCH_r04 ladder).
    - "all":    params + moments in host DRAM — maximum HBM savings, the
      bench_4 "host-DRAM param offload" analog, stream-bound at ~5 GB/s.
    """
    if isinstance(offload, bool) or offload is None:
        return "all" if offload else "none"
    if offload in ("0", "1"):  # env-var style (TRAIN_OFFLOAD=1)
        return "all" if offload == "1" else "none"
    if offload not in ("none", "params", "all"):
        raise ValueError(f"unknown offload level {offload!r}")
    return offload


def build_sharded_state(mesh, dims, optimizer, seed: int = 0,
                        offload=False):
    """Init params on host, place them with the tp/dp shardings, then build
    the optimizer state on the placed params so moments inherit placement.
    ``offload`` (resolve_offload_level) picks which leaves live in host
    DRAM."""
    level = resolve_offload_level(offload)
    params = init_mlp(jax.random.PRNGKey(seed), dims)
    placed = jax.tree.map(
        lambda p, s: jax.device_put(p, s), params,
        param_shardings(params, mesh))
    state = init_state(placed, optimizer)
    if level != "none":
        # Init in HBM first, then evict: eager zeros_like on a host-memory
        # array trips a make_array_from_callback memory-kind mismatch in
        # this JAX, so optimizer moments can't be *created* there directly.
        from dmlp_tpu.utils.compat import host_memory_kind
        hk = host_memory_kind()
        to_host = lambda a: jax.device_put(  # noqa: E731
            a, a.sharding.with_memory_kind(hk))
        state["params"] = jax.tree.map(to_host, state["params"])
        if level == "all":
            state["opt"] = jax.tree.map(to_host, state["opt"])
    return state


def _build_parallel(parallelism: str, mesh_shape, dims, optimizer,
                    compute_dtype, offload, seed: int, n_micro: int,
                    n_experts: int, batch: int = 0,
                    moe_dispatch: str = "dense",
                    capacity_factor: float = 1.0,
                    pp_schedule: str = "gpipe", n_virtual: int = 2):
    """(mesh, state, step_fn, data_dims, batch_shardings) for the chosen
    parallelism family. "dp_tp" is the full-featured default (offload
    levels, compute dtype); "dp_pp"/"dp_pp3"/"dp_ep" run the pipeline/MoE
    steps — their mesh comes from --mesh (DP,PP / DP,TP,PP / DP,EP), dims
    are (in, hidden, classes) for the pipelines (layers spread uniformly
    over stages, 2 per stage) and (in, hidden, ffn, classes) for the MoE.
    ``moe_dispatch`` picks the MoE form (dp_ep only): "dense" one-hot
    (capacity-free, masked compute) or "a2a" (capacity + all-to-all
    production dispatch; ``capacity_factor`` scales the per-(source,
    destination) slot count around the uniform-routing expectation,
    train.experts.a2a_capacity). ``pp_schedule`` picks the dp_pp
    schedule: "gpipe" or "interleaved" (V = ``n_virtual`` chunks per
    stage; bubble / V at n_micro <= stages, pipeline.bubble_fraction)."""
    # MoE-dispatch flags raise when inapplicable (same no-silent-ignore
    # rule as --compute-dtype/--offload below): a benchmark invoked with
    # --moe-dispatch a2a that silently trained the dp_tp MLP would
    # misattribute its numbers.
    if moe_dispatch != "dense" and parallelism != "dp_ep":
        raise ValueError(f"--moe-dispatch applies to dp_ep only, "
                         f"not {parallelism}")
    if capacity_factor != 1.0 and not (parallelism == "dp_ep"
                                       and moe_dispatch == "a2a"):
        raise ValueError("--capacity-factor applies to the dp_ep a2a "
                         "dispatch only (dense is capacity-free)")
    if pp_schedule != "gpipe" and parallelism != "dp_pp":
        raise ValueError(f"--pp-schedule applies to dp_pp only, "
                         f"not {parallelism}")
    if n_virtual != 2 and pp_schedule != "interleaved":
        raise ValueError("--virtual-stages applies to the interleaved "
                         "dp_pp schedule only")
    if parallelism == "dp_tp":
        mesh = make_train_mesh(mesh_shape)
        offload = resolve_offload_level(offload)
        state = build_sharded_state(mesh, dims, optimizer, seed,
                                    offload=offload)
        cdtype = jnp.bfloat16 if compute_dtype == "bfloat16" else None
        if offload != "none":
            from dmlp_tpu.train.step import make_offload_train_step
            step_fn = make_offload_train_step(optimizer, cdtype, state)
        else:
            step_fn = make_train_step(optimizer, cdtype)
        return (mesh, state, step_fn, (dims[0], dims[-1]),
                batch_shardings(mesh))

    # The pipeline/MoE families run f32 without host offload; silently
    # ignoring these flags would misattribute benchmark numbers.
    if compute_dtype is not None:
        raise ValueError(f"--compute-dtype applies to dp_tp only, "
                         f"not {parallelism}")
    if resolve_offload_level(offload) != "none":
        raise ValueError(f"--offload applies to dp_tp only, "
                         f"not {parallelism}")

    if parallelism in ("dp_pp", "dp_pp3"):
        from dmlp_tpu.train import pipeline as pl
        if len(dims) != 3:
            raise ValueError(f"{parallelism} wants --dims in,hidden,classes")
        d_in, hidden, n_classes = dims
        if parallelism == "dp_pp":
            dp, pp = mesh_shape or (1, len(jax.devices()))
            mesh = pl.make_pp_mesh(dp, pp)
            if pp_schedule == "interleaved":
                # Same model as the gpipe branch (2 layers per stage, the
                # documented dp_pp architecture): V chunks of 2/V layers.
                # A V that doesn't divide it would silently change the
                # depth and make schedule A/Bs compare different models.
                lps = 2
                if lps % n_virtual:
                    raise ValueError(
                        f"--virtual-stages must divide the dp_pp model's "
                        f"{lps} layers per stage (got {n_virtual}); deeper "
                        "chunking is a library-API choice "
                        "(pipeline.build_ppi_state)")
                state = pl.build_ppi_state(mesh, optimizer, d_in, hidden,
                                           n_classes, n_virtual=n_virtual,
                                           layers_per_chunk=lps // n_virtual,
                                           seed=seed)
                step_fn = pl.make_ppi_train_step(mesh, optimizer,
                                                 n_micro=n_micro,
                                                 n_virtual=n_virtual,
                                                 n_classes=n_classes)
                return mesh, state, step_fn, (d_in, n_classes), \
                    batch_shardings(mesh)
            state = pl.build_pp_state(mesh, optimizer, d_in, hidden,
                                      n_classes, 2, seed=seed)
            step_fn = pl.make_pp_train_step(mesh, optimizer, n_micro=n_micro,
                                            n_classes=n_classes)
        else:
            dp, tp, pp = mesh_shape or (1, 2, len(jax.devices()) // 2)
            mesh = pl.make_pp3_mesh(dp, tp, pp)
            state = pl.build_pp3_state(mesh, optimizer, d_in, hidden,
                                       n_classes, 2, seed=seed)
            step_fn = pl.make_pp3_train_step(mesh, optimizer,
                                             n_micro=n_micro,
                                             n_classes=n_classes)
        return mesh, state, step_fn, (d_in, n_classes), \
            batch_shardings(mesh)

    if parallelism == "dp_ep":
        from dmlp_tpu.train import experts as ex
        if len(dims) != 4:
            raise ValueError("dp_ep wants --dims in,hidden,ffn,classes")
        d_in, hidden, ffn, n_classes = dims
        dp, ep = mesh_shape or (1, len(jax.devices()))
        mesh = ex.make_ep_mesh(dp, ep)
        state = ex.build_moe_state(mesh, optimizer, d_in, hidden, ffn,
                                   n_classes, n_experts, seed=seed)
        if moe_dispatch == "a2a":
            capacity = ex.a2a_capacity(batch, dp, ep, capacity_factor)
            step_fn = ex.make_moe_a2a_train_step(mesh, optimizer,
                                                 n_experts=n_experts,
                                                 n_classes=n_classes,
                                                 capacity=capacity)
            return mesh, state, step_fn, (d_in, n_classes), \
                ex.a2a_batch_shardings(mesh)
        step_fn = ex.make_moe_train_step(mesh, optimizer,
                                         n_experts=n_experts,
                                         n_classes=n_classes)
        return mesh, state, step_fn, (d_in, n_classes), \
            batch_shardings(mesh)

    raise ValueError(f"unknown parallelism {parallelism!r}")


def train(steps: int = 100, batch: int = 1024,
          dims: Sequence[int] = (64, 256, 256, 10),
          mesh_shape=None, optimizer_name: str = "sgd", lr: float = 1e-2,
          compute_dtype: Optional[str] = None, seed: int = 0,
          checkpoint_dir: Optional[str] = None, ckpt_every: int = 100,
          resume: bool = False, metrics: Optional[MetricsLogger] = None,
          log_every: int = 10, offload=False, parallelism: str = "dp_tp",
          n_micro: int = 4, n_experts: int = 8,
          moe_dispatch: str = "dense", capacity_factor: float = 1.0,
          pp_schedule: str = "gpipe", n_virtual: int = 2,
          sanitize: bool = False, nan_guard: bool = False,
          lr_backoff: float = 0.5, max_rollbacks: int = 3):
    optimizer = make_optimizer(optimizer_name, lr)
    mesh, state, step_fn, (d_in, n_classes), shardings = _build_parallel(
        parallelism, mesh_shape, tuple(dims), optimizer, compute_dtype,
        offload, seed, n_micro, n_experts, batch=batch,
        moe_dispatch=moe_dispatch, capacity_factor=capacity_factor,
        pp_schedule=pp_schedule, n_virtual=n_virtual)
    n_chips = mesh.devices.size
    start_step = 0
    if resume and checkpoint_dir and ckpt_lib.latest_step(checkpoint_dir) is not None:
        state = ckpt_lib.restore_checkpoint(checkpoint_dir, state)
        start_step = int(jax.device_get(state["step"]))

    from dmlp_tpu.train.data import prefetch_to_device

    def make_data(skip: int):
        """The seed-keyed batch stream positioned ``skip`` batches past
        this run's start — a NaN-guard rollback re-creates it so the
        replayed steps consume EXACTLY the batches the first pass did
        (step-identical recovery; proven in tests/test_train.py)."""
        it = teacher_batches(d_in, n_classes, batch, seed=seed + 1)
        for _ in range(skip):
            next(it)
        return prefetch_to_device(it, shardings)

    data = make_data(0)

    # LR-backoff escalation rebuilds the step with a decayed LR when the
    # SAME step produces a non-finite loss twice (deterministic replay
    # would otherwise diverge identically forever). Optimizer-state
    # structure is LR-independent (optax), so the live moments carry
    # over. dp_tp only — the pipeline/MoE step factories don't take a
    # bare optimizer swap; rollback still works there, escalation raises.
    def _rebuild_step_dp_tp(new_lr: float):
        opt2 = make_optimizer(optimizer_name, new_lr)
        cdtype = jnp.bfloat16 if compute_dtype == "bfloat16" else None
        if resolve_offload_level(offload) != "none":
            from dmlp_tpu.train.step import make_offload_train_step
            return make_offload_train_step(opt2, cdtype, state)
        return make_train_step(opt2, cdtype)

    rebuild_step = _rebuild_step_dp_tp if parallelism == "dp_tp" else None

    # Analytic collective-traffic accounting for this run's mesh
    # (obs.comms): the grad psum over dp, plus the MoE all-to-all when
    # the a2a dispatch runs — logged once so per-step records stay small.
    if metrics is not None:
        comms = _train_comms(state, mesh, parallelism, dims, batch,
                             moe_dispatch, capacity_factor, steps,
                             n_micro=n_micro, pp_schedule=pp_schedule,
                             n_virtual=n_virtual)
        if comms is not None:
            metrics.log(event="comms", **comms)

    # Sanitized training: transfer guard + leak check + debug_nans around
    # the step loop (dmlp_tpu.check.sanitize). The readbacks below are
    # explicit device_get / post-device_get floats, so a clean loop is
    # byte-identical; a NaN-producing step raises AT the op.
    from dmlp_tpu.check.sanitize import maybe_sanitized

    def san():  # fresh context per step: @contextmanager cms are one-shot
        return maybe_sanitized(train=True, force=sanitize)

    # Every step must be recoverable: a non-finite loss BEFORE the
    # first periodic checkpoint would otherwise have nothing to roll
    # back to (ckpt_every can exceed the divergence step) — seed the
    # dir with the start state, which save-at-end would overwrite only
    # at the same-or-later step anyway.
    if nan_guard and checkpoint_dir \
            and ckpt_lib.latest_step(checkpoint_dir) is None:
        ckpt_lib.save_checkpoint(checkpoint_dir, state, step=start_step)

    last = {}
    hlo_sig = None   # (step_fn, abstract arg specs) for the one-shot
    # compiled-program record logged after the loop (obs.hlo)
    t_window = time.perf_counter()
    window_steps = 0
    cur_lr = lr
    total_rollbacks = 0
    rollbacks_at: dict = {}   # step index -> rollback count at that step
    end = start_step + steps
    i = start_step
    while i < end:
        xd, yd = next(data)
        if hlo_sig is None and metrics is not None:
            # Shape specs only — no buffers kept alive across the loop.
            try:
                hlo_sig = (step_fn, jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    (state, xd, yd)))
            except Exception:  # check: no-retry
                # Introspection is best-effort: a spec-capture failure
                # must never take down a training step.
                hlo_sig = None

        def _step_op():
            # The injection fire rides INSIDE the retried op: a
            # transient fault at this site is consumed on attempt 1 and
            # the retry's re-dispatch (same state/batch — pure) lands.
            acts = rs_inject.fire("train.step", step=i) or ()
            with obs_span("train.step"), san():
                s2, m2 = step_fn(state, xd, yd)
            return acts, s2, m2

        actions, new_state, m = rs_retry.call_with_retry(
            _step_op, "train.step")

        if nan_guard:
            # Per-step loss readback (opt-in: --nan-guard; the default
            # loop keeps its async log_every cadence). An injected
            # "nan" action poisons the detector input — the rollback
            # machinery is driven without corrupting any real state.
            import math
            loss_val = (float("nan") if "nan" in actions
                        else float(jax.device_get(m["loss"])))
            if not math.isfinite(loss_val):
                if not checkpoint_dir:
                    raise RuntimeError(
                        f"non-finite loss at step {i + 1} and nowhere "
                        "to roll back to — the NaN guard needs "
                        "checkpoint_dir/--checkpoint-dir")
                total_rollbacks += 1
                rs_stats.record_rollback()
                if total_rollbacks > max_rollbacks:
                    raise RuntimeError(
                        f"non-finite loss persisted through "
                        f"{max_rollbacks} rollback(s) — giving up at "
                        f"step {i + 1}")
                seen = rollbacks_at.get(i, 0)
                rollbacks_at[i] = seen + 1
                if seen >= 1:
                    # Same step diverged twice: replay alone cannot fix
                    # a deterministic divergence — decay the LR.
                    if rebuild_step is None:
                        raise RuntimeError(
                            f"step {i + 1} diverged twice and LR "
                            f"backoff is unsupported for parallelism="
                            f"{parallelism} (dp_tp only)")
                    cur_lr *= lr_backoff
                    step_fn = rebuild_step(cur_lr)
                faulted_at = i
                state = ckpt_lib.restore_checkpoint(checkpoint_dir, state)
                i = int(jax.device_get(state["step"]))
                if i > faulted_at:
                    raise RuntimeError(
                        f"latest checkpoint is step {i}, AHEAD of the "
                        f"faulted step {faulted_at} — rolling back would "
                        f"jump forward (stale checkpoint_dir "
                        f"{checkpoint_dir!r} from an earlier run?)")
                if i < start_step:
                    raise RuntimeError(
                        f"checkpoint step {i} precedes this run's data "
                        f"stream start {start_step} — cannot replay")
                from dmlp_tpu.obs import trace as obs_trace
                obs_trace.instant("resilience.rollback", to_step=i,
                                  lr=cur_lr)
                data = make_data(i - start_step)
                t_window = time.perf_counter()
                window_steps = 0
                continue

        state = new_state
        window_steps += 1
        if (i + 1) % log_every == 0 or i + 1 == end:
            with obs_span("train.log_window", step=i + 1) as sp:
                m = jax.device_get(m)
                sp.fence(state["params"])
            dt = (time.perf_counter() - t_window) / window_steps
            t_window = time.perf_counter()
            window_steps = 0
            last = {"step": i + 1, "loss": float(m["loss"]),
                    "accuracy": float(m["accuracy"]),
                    **throughput_metrics(state["params"], batch, dt, n_chips)}
            if metrics is not None:
                metrics.log(**last)
        if checkpoint_dir and (i + 1) % ckpt_every == 0:
            with obs_span("train.checkpoint", step=i + 1):
                ckpt_lib.save_checkpoint(checkpoint_dir, state, step=i + 1)
        i += 1
    if checkpoint_dir:
        ckpt_lib.save_checkpoint(checkpoint_dir, state, step=end)
    if metrics is not None and hlo_sig is not None:
        # One-shot compiled-program record (obs.hlo): which collectives
        # the compiled step ACTUALLY dispatches, alongside the analytic
        # event="comms" summary logged before the loop. AOT lower runs
        # after the step loop (untimed) and never raises into training.
        try:
            from dmlp_tpu.obs import hlo as obs_hlo
            fn, specs = hlo_sig
            rep = obs_hlo.report_for_fn(fn, specs, label="train.step")
            if rep is None:
                metrics.log(event="hlo", hlo_unavailable=
                            "step program could not be lowered for "
                            "introspection")
            else:
                ev = {"event": "hlo", "fingerprint": rep.fingerprint}
                for kind, agg in sorted(rep.totals.items()):
                    key = kind.replace("-", "_")
                    ev[f"{key}_bytes"] = agg["bytes_moved"]
                    ev[f"{key}_count"] = agg["count"]
                if "hlo_memory_unavailable" not in rep.memory:
                    ev["hlo_temp_bytes"] = rep.memory.get("temp_bytes", 0)
                metrics.log(**ev)
        except Exception:
            pass  # check: no-retry — observability must not fail a run
    return state, last


def _train_comms(state, mesh, parallelism: str, dims, batch: int,
                 moe_dispatch: str, capacity_factor: float,
                 steps: int, n_micro: int = 4, pp_schedule: str = "gpipe",
                 n_virtual: int = 1) -> Optional[dict]:
    """obs.comms summary for this run's collective paths, from the real
    mesh/param shapes; None when the run dispatches no collectives."""
    import numpy as _np

    from dmlp_tpu.obs import comms as obs_comms

    param_bytes = int(sum(
        _np.prod(x.shape) * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(state["params"])))
    moe = None
    moe_dense = None
    if parallelism == "dp_ep" and moe_dispatch == "a2a":
        from dmlp_tpu.train.experts import a2a_capacity
        dp, ep = mesh.devices.shape
        moe = {"ep": ep, "hidden": dims[1],
               "capacity": a2a_capacity(batch, dp, ep, capacity_factor)}
    elif parallelism == "dp_ep":
        # Dense one-hot dispatch: the combine is ONE ep psum of the
        # (dp-local tokens, hidden) partials per step
        # (experts._moe_body; obs.comms.ep_psum_combine_traffic).
        dp, ep = mesh.devices.shape
        moe_dense = {"ep": ep, "hidden": dims[1],
                     "tokens": max(batch // dp, 1)}
    pipeline = None
    if parallelism in ("dp_pp", "dp_pp3"):
        # Activation hand-off shapes exactly as the step dispatches them:
        # each dp cell's local batch splits into n_micro microbatches of
        # (micro_rows, hidden) f32 activations; the ppermute runs
        # independently per (dp[, tp]) cell group.
        dp, pp = mesh.devices.shape[0], mesh.devices.shape[-1]
        groups = int(_np.prod(mesh.devices.shape[:-1]))
        sched = pp_schedule if parallelism == "dp_pp" else "gpipe"
        pipeline = {"pp": pp, "n_micro": n_micro,
                    "micro_rows": max(batch // dp // max(n_micro, 1), 1),
                    "hidden": dims[1], "schedule": sched,
                    "n_virtual": n_virtual if sched == "interleaved" else 1,
                    "n_groups": groups}
        if parallelism == "dp_pp3":
            # dp_pp3 stage blocks psum each col/row pair's activation
            # over tp (pipeline._stage_block3; 2 pairs per stage).
            pipeline["tp"] = mesh.devices.shape[1]
            pipeline["n_pairs"] = 2
    traffic = obs_comms.train_step_comms(param_bytes, mesh.devices.shape,
                                         steps=steps, moe=moe,
                                         pipeline=pipeline,
                                         moe_dense=moe_dense)
    return obs_comms.summarize(traffic) if traffic else None


def _params_checksum(state) -> str:
    """sha256 over the (deterministically ordered) param leaves' bytes —
    the step-identical-recovery fingerprint in train RunRecords."""
    import hashlib

    import numpy as _np

    h = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves(state["params"])
    for leaf in jax.device_get(leaves):
        a = _np.asarray(leaf)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dmlp_tpu.train", description=__doc__)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--dims", type=str, default="64,256,256,10",
                   help="comma-separated layer dims: in,hidden...,classes "
                        "(dp_pp/dp_pp3: in,hidden,classes; dp_ep: "
                        "in,hidden,ffn,classes)")
    p.add_argument("--mesh", type=str, default=None,
                   help="DP,TP (dp_tp) / DP,PP (dp_pp) / DP,TP,PP "
                        "(dp_pp3) / DP,EP (dp_ep)")
    p.add_argument("--parallelism", default="dp_tp",
                   choices=["dp_tp", "dp_pp", "dp_pp3", "dp_ep"],
                   help="mesh-parallelism family: dp x tp MLP (default; "
                        "full feature set), dp x pp / dp x tp x pp "
                        "pipelined stack, dp x ep MoE")
    p.add_argument("--microbatches", type=int, default=4,
                   help="pipeline microbatches per step (dp_pp/dp_pp3)")
    p.add_argument("--pp-schedule", default="gpipe",
                   choices=["gpipe", "interleaved"],
                   help="dp_pp schedule: gpipe, or interleaved virtual "
                        "stages (1F1B-interleaved; bubble / V, needs "
                        "microbatches <= PP)")
    p.add_argument("--virtual-stages", type=int, default=2,
                   help="interleaved schedule: stage chunks per pp cell")
    p.add_argument("--experts", type=int, default=8,
                   help="MoE expert count (dp_ep; divisible by EP)")
    p.add_argument("--moe-dispatch", default="dense",
                   choices=["dense", "a2a"],
                   help="dp_ep dispatch: dense one-hot (capacity-free, "
                        "masked compute) or capacity + all-to-all (the "
                        "production EP form; tokens route to the "
                        "expert-owning cells over ICI, overflow drops to "
                        "the residual path)")
    p.add_argument("--capacity-factor", type=float, default=1.0,
                   help="a2a capacity factor: per-(source, destination) "
                        "slots = ceil(cf * local_tokens / EP); cf >= EP "
                        "guarantees zero drops")
    p.add_argument("--optimizer", default="sgd", choices=["sgd", "adam"])
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--compute-dtype", default=None,
                   choices=[None, "bfloat16"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=100)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--metrics-file", default=None)
    p.add_argument("--record", metavar="FILE", default=None,
                   help="write one versioned RunRecord (obs.run) "
                        "summarizing the run to FILE — the "
                        "ledger-ingestible train artifact "
                        "(python -m dmlp_tpu.report)")
    p.add_argument("--trace", metavar="FILE", default=None,
                   help="write a Perfetto/Chrome-trace JSON of the run's "
                        "step/checkpoint spans to FILE (obs.trace)")
    p.add_argument("--telemetry", metavar="FILE", default=None,
                   help="live telemetry (obs.telemetry): periodic "
                        "OpenMetrics snapshot rewrite of FILE (step "
                        "latency histograms, device-memory watermarks, "
                        "resilience counters) + crash flight recorder "
                        "(FLIGHT_*.json next to FILE)")
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--sanitize", action="store_true",
                   help="wrap every train step in jax.transfer_guard("
                        "'disallow') + jax.checking_leaks + "
                        "jax.debug_nans (dmlp_tpu.check.sanitize); "
                        "$DMLP_TPU_SANITIZE=1 enables it too")
    p.add_argument("--nan-guard", action="store_true",
                   help="per-step non-finite-loss guard: on NaN/inf "
                        "loss, restore the latest checkpoint, replay "
                        "the stream step-identically, and decay the LR "
                        "(x0.5) if the same step diverges twice "
                        "(needs --checkpoint-dir)")
    p.add_argument("--faults", metavar="FILE", default=None,
                   help="deterministic fault-injection schedule (JSON; "
                        "dmlp_tpu.resilience.inject); $DMLP_TPU_FAULTS "
                        "sets it too")
    p.add_argument("--offload", nargs="?", const="all", default="none",
                   choices=["none", "params", "all"],
                   help="host-DRAM offload level: 'params' keeps moments "
                        "in HBM (half the stream bytes of 'all'); bare "
                        "--offload means 'all' (the bench_4 host-offload "
                        "analog)")
    p.add_argument("--compile-cache", metavar="DIR", default=None,
                   help="persistent XLA compilation cache dir (best "
                        "effort; re-runs at the same shapes skip the "
                        "step-function compiles); "
                        "$DMLP_TPU_COMPILE_CACHE is the ambient form "
                        "(flag wins)")
    args = p.parse_args(argv)

    from dmlp_tpu.utils.compile_cache import enable_from_flag
    enable_from_flag(args.compile_cache)
    mesh_shape = None
    if args.mesh:
        mesh_shape = tuple(int(d) for d in args.mesh.split(","))
    tracer = None
    rs_stats.reset()   # resets the registry's resilience.* counters too
    telemetry_session = None
    if args.telemetry:
        from dmlp_tpu.obs import telemetry
        telemetry_session = telemetry.start(path=args.telemetry)
    if args.trace:
        from dmlp_tpu.obs import trace as obs_trace
        tracer = obs_trace.install(obs_trace.Tracer())
    schedule = rs_inject.install_from_env(args.faults)
    final_state = None
    try:
        mlog = (MetricsLogger(path=args.metrics_file)
                if args.metrics_file else MetricsLogger())
        with mlog as metrics:
            final_state, last = train(
                steps=args.steps, batch=args.batch,
                dims=tuple(int(d) for d in args.dims.split(",")),
                mesh_shape=mesh_shape, optimizer_name=args.optimizer,
                lr=args.lr, compute_dtype=args.compute_dtype,
                seed=args.seed, checkpoint_dir=args.checkpoint_dir,
                ckpt_every=args.ckpt_every, resume=args.resume,
                metrics=metrics, log_every=args.log_every,
                offload=args.offload, parallelism=args.parallelism,
                n_micro=args.microbatches, n_experts=args.experts,
                moe_dispatch=args.moe_dispatch,
                capacity_factor=args.capacity_factor,
                pp_schedule=args.pp_schedule,
                n_virtual=args.virtual_stages,
                sanitize=args.sanitize, nan_guard=args.nan_guard)
    except Exception:
        # Exception, not BaseException: a SystemExit/KeyboardInterrupt
        # is not a crash (cli.py has the same rule).
        if telemetry_session is not None:
            from dmlp_tpu.obs import telemetry
            telemetry.dump_on_crash("crash")
        raise
    finally:
        if schedule is not None:
            rs_inject.write_log_if_requested()
            rs_inject.uninstall()
        if tracer is not None:
            from dmlp_tpu.obs import trace as obs_trace
            tracer.write(args.trace)
            obs_trace.uninstall()
        if telemetry_session is not None:
            telemetry_session.close()
    if args.record:
        from dmlp_tpu.obs.run import (RunRecord, current_device,
                                      round_from_name)
        artifacts = {}
        if args.trace:
            artifacts["trace"] = args.trace
        if args.metrics_file:
            artifacts["metrics"] = args.metrics_file
        rec_metrics = dict(last)
        # Analytic per-device peak-HBM model for this run's step
        # (obs.memwatch train term set) + watermark reconcile — the mem
        # block carries the explicit marker where the backend reports
        # no memory.
        try:
            from dmlp_tpu.obs import memwatch
            model = memwatch.train_step_model(
                [int(d) for d in args.dims.split(",")], args.batch,
                optimizer=args.optimizer, mesh_shape=mesh_shape,
                compute_dtype=args.compute_dtype)
            # The (closed) session's sampler keeps its tracked peaks;
            # without a session, fall back to a one-shot basis.
            measured = (telemetry_session.sampler.measured_peak()
                        if telemetry_session is not None
                        else memwatch.measured_watermark())
            rec_metrics["mem"] = memwatch.reconcile(model, measured)
        except Exception:  # check: no-retry — obs never fails the run
            pass
        if final_state is not None:
            # Bitwise state fingerprint: the chaos harness proves a
            # NaN-faulted run resumed step-identically by comparing
            # this against the fault-free run's checksum.
            rec_metrics["params_checksum"] = _params_checksum(final_state)
        if rs_stats.any_activity() or schedule is not None:
            rec_metrics["resilience"] = rs_stats.snapshot()
        RunRecord(
            kind="train", tool="dmlp_tpu.train",
            config={"parallelism": args.parallelism,
                    "dims": [int(d) for d in args.dims.split(",")],
                    "batch": args.batch, "steps": args.steps,
                    "mesh": mesh_shape and list(mesh_shape),
                    "optimizer": args.optimizer,
                    "compute_dtype": args.compute_dtype,
                    "offload": args.offload,
                    "moe_dispatch": args.moe_dispatch,
                    "pp_schedule": args.pp_schedule,
                    "nan_guard": args.nan_guard},
            metrics=rec_metrics, artifacts=artifacts,
            device=current_device(),
            round=round_from_name(args.record)).write(args.record)
    print(f"final: {last}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
