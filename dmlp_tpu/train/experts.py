"""Expert parallelism: a top-1-routed MoE FFN with experts sharded over
an ("dp", "ep") mesh — the ep rung of the mesh-parallelism ladder next
to dp x tp (train.step/sharding) and dp x pp (train.pipeline).

The reference has no MoE (survey §2: EP n/a); this is north-star
extension surface, built SPMD: expert weights are stacked (E, H, F) /
(E, F, H) and sharded over "ep" so each cell holds E/ep experts; inside
``shard_map`` every cell computes its LOCAL experts over all (per-dp)
tokens under the routing mask and the contributions ``psum`` over "ep".
This is the dense one-hot dispatch: exact and capacity-free (no dropped
tokens, no load-balancing loss required for correctness), at the cost
of masked compute proportional to local experts — the classic
capacity + all-to-all dispatch is the production scaling path and is
deliberately out of scope here; what this module pins down is the
sharded-expert placement, the routing math, and gradients through the
psum combine (equivalence-tested against the unsharded reference in
tests/test_train_experts.py).

Gradient hygiene: the loss leaves the shard_map as per-cell partials
(nonzero on ep cell 0 only) summed outside — the same
no-replicated-outputs rule as train.pipeline, so the transpose is exact
under check_vma=False.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
EP_AXIS = "ep"

MoeParams = Dict[str, jax.Array]


def make_ep_mesh(dp: int, ep: int, devices=None) -> Mesh:
    from dmlp_tpu.train.pipeline import make_axes_mesh
    return make_axes_mesh({DP_AXIS: dp, EP_AXIS: ep}, devices)


def init_moe(key, d_in: int, hidden: int, ffn: int, n_classes: int,
             n_experts: int, dtype=jnp.float32) -> MoeParams:
    ks = jax.random.split(key, 5)
    h, f, e = hidden, ffn, n_experts
    return {
        "in_w": jax.random.normal(ks[0], (d_in, h), dtype)
        * jnp.sqrt(2.0 / d_in).astype(dtype),
        "in_b": jnp.zeros((h,), dtype),
        "router": jax.random.normal(ks[1], (h, e), dtype)
        * jnp.sqrt(1.0 / h).astype(dtype),
        "up": jax.random.normal(ks[2], (e, h, f), dtype)
        * jnp.sqrt(2.0 / h).astype(dtype),
        "down": jax.random.normal(ks[3], (e, f, h), dtype)
        * jnp.sqrt(2.0 / f).astype(dtype),
        "out_w": jax.random.normal(ks[4], (h, n_classes), dtype)
        * jnp.sqrt(2.0 / h).astype(dtype),
        "out_b": jnp.zeros((n_classes,), dtype),
    }


# Single source of truth for per-param partition specs: device placement
# (moe_param_shardings) and the shard_map in_specs both derive from it,
# so they can never disagree.
MOE_PSPECS = {
    "in_w": P(None, None), "in_b": P(None),
    "router": P(None, None),
    "up": P(EP_AXIS, None, None),
    "down": P(EP_AXIS, None, None),
    "out_w": P(None, None), "out_b": P(None),
}


def moe_param_shardings(mesh: Mesh):
    return {k: NamedSharding(mesh, spec) for k, spec in MOE_PSPECS.items()}


def moe_reference_forward(params: MoeParams, x) -> jax.Array:
    """Unsharded reference: identical math on one device (the
    equivalence oracle). Top-1 routing, router-prob scaling, residual."""
    h = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    logits = h @ params["router"]
    probs = jax.nn.softmax(logits, -1)
    sel = jnp.argmax(logits, -1)                        # (B,)
    onehot = jax.nn.one_hot(sel, params["router"].shape[1],
                            dtype=h.dtype)              # (B, E)
    gate = jnp.sum(probs * onehot, -1, keepdims=True)   # (B, 1)
    # Dense dispatch: every expert over every token, masked + combined.
    up = jnp.einsum("bh,ehf->ebf", h, params["up"])
    act = jax.nn.relu(up)
    down = jnp.einsum("ebf,efh->ebh", act, params["down"])
    expert_out = jnp.einsum("ebh,be->bh", down, onehot)
    h = h + gate * expert_out                           # residual
    return h @ params["out_w"] + params["out_b"]


def _moe_body(params, x, y, *, n_experts: int, n_classes: int):
    """Per-(dp, ep)-cell loss partial (inside shard_map): this cell's
    expert slice over the dp-local tokens, psum-combined over ep."""
    assert params["out_w"].shape[1] == n_classes, \
        (params["out_w"].shape, n_classes)
    ep_idx = jax.lax.axis_index(EP_AXIS)
    e_local = params["up"].shape[0]
    e_base = ep_idx * e_local

    h = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    logits = h @ params["router"]
    probs = jax.nn.softmax(logits, -1)
    sel = jnp.argmax(logits, -1)
    onehot = jax.nn.one_hot(sel, n_experts, dtype=h.dtype)
    gate = jnp.sum(probs * onehot, -1, keepdims=True)

    # This cell's experts only; mask selects tokens routed to them.
    local_hot = jax.lax.dynamic_slice_in_dim(onehot, e_base, e_local, 1)
    up = jnp.einsum("bh,ehf->ebf", h, params["up"])
    act = jax.nn.relu(up)
    down = jnp.einsum("ebf,efh->ebh", act, params["down"])
    local_out = jnp.einsum("ebh,be->bh", down, local_hot)
    expert_out = jax.lax.psum(local_out, EP_AXIS)       # combine over ep
    h = h + gate * expert_out

    out = h @ params["out_w"] + params["out_b"]
    loss = optax.softmax_cross_entropy_with_integer_labels(out, y).mean()
    acc = jnp.mean((jnp.argmax(out, -1) == y).astype(jnp.float32))
    first = (ep_idx == 0).astype(loss.dtype)
    return (loss * first)[None], (acc * first)[None]


def make_moe_train_step(mesh: Mesh, optimizer: optax.GradientTransformation,
                        *, n_experts: int, n_classes: int):
    """Jitted (state, x, y) -> (state', {loss, accuracy}) over ("dp", "ep");
    state params placed by moe_param_shardings."""
    n_dp = mesh.devices.shape[0]
    body = functools.partial(_moe_body, n_experts=n_experts,
                             n_classes=n_classes)
    sharded_loss = jax.shard_map(
        body, mesh=mesh,
        in_specs=(MOE_PSPECS, P(DP_AXIS, None), P(DP_AXIS)),
        out_specs=(P((DP_AXIS, EP_AXIS)), P((DP_AXIS, EP_AXIS))),
        check_vma=False)

    from dmlp_tpu.train.pipeline import _partials_train_step
    return _partials_train_step(sharded_loss, optimizer, n_dp)


def build_moe_state(mesh: Mesh, optimizer, d_in: int, hidden: int, ffn: int,
                    n_classes: int, n_experts: int, seed: int = 0):
    from dmlp_tpu.train.pipeline import place_state
    params = init_moe(jax.random.PRNGKey(seed), d_in, hidden, ffn,
                      n_classes, n_experts)
    return place_state(params, moe_param_shardings(mesh), optimizer)
