"""Expert parallelism: a top-1-routed MoE FFN with experts sharded over
an ("dp", "ep") mesh — the ep rung of the mesh-parallelism ladder next
to dp x tp (train.step/sharding) and dp x pp (train.pipeline).

The reference has no MoE (survey §2: EP n/a); this is north-star
extension surface, built SPMD: expert weights are stacked (E, H, F) /
(E, F, H) and sharded over "ep" so each cell holds E/ep experts; inside
``shard_map`` every cell computes its LOCAL experts over all (per-dp)
tokens under the routing mask and the contributions ``psum`` over "ep".
Two dispatches are provided:

- DENSE one-hot (make_moe_train_step): exact and capacity-free (no
  dropped tokens), at the cost of masked compute proportional to local
  experts — the semantics-pinning form.
- CAPACITY + ALL-TO-ALL (make_moe_a2a_train_step): the production
  scaling form — tokens shard over BOTH mesh axes, route to the
  expert-owning cells via ``lax.all_to_all`` over ICI, and tokens
  beyond ``capacity`` per (source, destination) pair drop to the
  residual path. With capacity >= local tokens it is grad-exact vs the
  unsharded reference; drop semantics are pinned by a drop-aware test.

Both are equivalence-tested in tests/test_train_experts.py.

Gradient hygiene: the loss leaves the shard_map as per-cell partials
(nonzero on ep cell 0 only) summed outside — the same
no-replicated-outputs rule as train.pipeline, so the transpose is exact
under check_vma=False.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from dmlp_tpu.utils.compat import axis_size, shard_map

DP_AXIS = "dp"
EP_AXIS = "ep"

MoeParams = Dict[str, jax.Array]


def make_ep_mesh(dp: int, ep: int, devices=None) -> Mesh:
    from dmlp_tpu.train.pipeline import make_axes_mesh
    return make_axes_mesh({DP_AXIS: dp, EP_AXIS: ep}, devices)


def init_moe(key, d_in: int, hidden: int, ffn: int, n_classes: int,
             n_experts: int, dtype=jnp.float32) -> MoeParams:
    ks = jax.random.split(key, 5)
    h, f, e = hidden, ffn, n_experts
    return {
        "in_w": jax.random.normal(ks[0], (d_in, h), dtype)
        * jnp.sqrt(2.0 / d_in).astype(dtype),
        "in_b": jnp.zeros((h,), dtype),
        "router": jax.random.normal(ks[1], (h, e), dtype)
        * jnp.sqrt(1.0 / h).astype(dtype),
        "up": jax.random.normal(ks[2], (e, h, f), dtype)
        * jnp.sqrt(2.0 / h).astype(dtype),
        "down": jax.random.normal(ks[3], (e, f, h), dtype)
        * jnp.sqrt(2.0 / f).astype(dtype),
        "out_w": jax.random.normal(ks[4], (h, n_classes), dtype)
        * jnp.sqrt(2.0 / h).astype(dtype),
        "out_b": jnp.zeros((n_classes,), dtype),
    }


# Single source of truth for per-param partition specs: device placement
# (moe_param_shardings) and the shard_map in_specs both derive from it,
# so they can never disagree.
MOE_PSPECS = {
    "in_w": P(None, None), "in_b": P(None),
    "router": P(None, None),
    "up": P(EP_AXIS, None, None),
    "down": P(EP_AXIS, None, None),
    "out_w": P(None, None), "out_b": P(None),
}


def moe_param_shardings(mesh: Mesh):
    return {k: NamedSharding(mesh, spec) for k, spec in MOE_PSPECS.items()}


def moe_reference_forward(params: MoeParams, x) -> jax.Array:
    """Unsharded reference: identical math on one device (the
    equivalence oracle). Top-1 routing, router-prob scaling, residual."""
    h = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    logits = h @ params["router"]
    probs = jax.nn.softmax(logits, -1)
    sel = jnp.argmax(logits, -1)                        # (B,)
    onehot = jax.nn.one_hot(sel, params["router"].shape[1],
                            dtype=h.dtype)              # (B, E)
    gate = jnp.sum(probs * onehot, -1, keepdims=True)   # (B, 1)
    # Dense dispatch: every expert over every token, masked + combined.
    up = jnp.einsum("bh,ehf->ebf", h, params["up"])
    act = jax.nn.relu(up)
    down = jnp.einsum("ebf,efh->ebh", act, params["down"])
    expert_out = jnp.einsum("ebh,be->bh", down, onehot)
    h = h + gate * expert_out                           # residual
    return h @ params["out_w"] + params["out_b"]


def _moe_body(params, x, y, *, n_experts: int, n_classes: int):
    """Per-(dp, ep)-cell loss partial (inside shard_map): this cell's
    expert slice over the dp-local tokens, psum-combined over ep."""
    assert params["out_w"].shape[1] == n_classes, \
        (params["out_w"].shape, n_classes)
    ep_idx = jax.lax.axis_index(EP_AXIS)
    e_local = params["up"].shape[0]
    e_base = ep_idx * e_local

    h = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    logits = h @ params["router"]
    probs = jax.nn.softmax(logits, -1)
    sel = jnp.argmax(logits, -1)
    onehot = jax.nn.one_hot(sel, n_experts, dtype=h.dtype)
    gate = jnp.sum(probs * onehot, -1, keepdims=True)

    # This cell's experts only; mask selects tokens routed to them.
    local_hot = jax.lax.dynamic_slice_in_dim(onehot, e_base, e_local, 1)
    up = jnp.einsum("bh,ehf->ebf", h, params["up"])
    act = jax.nn.relu(up)
    down = jnp.einsum("ebf,efh->ebh", act, params["down"])
    local_out = jnp.einsum("ebh,be->bh", down, local_hot)
    # check: comms-model=ep_psum_combine_traffic
    expert_out = jax.lax.psum(local_out, EP_AXIS)       # combine over ep
    h = h + gate * expert_out

    out = h @ params["out_w"] + params["out_b"]
    loss = optax.softmax_cross_entropy_with_integer_labels(out, y).mean()
    acc = jnp.mean((jnp.argmax(out, -1) == y).astype(jnp.float32))
    first = (ep_idx == 0).astype(loss.dtype)
    return (loss * first)[None], (acc * first)[None]


def make_moe_train_step(mesh: Mesh, optimizer: optax.GradientTransformation,
                        *, n_experts: int, n_classes: int):
    """Jitted (state, x, y) -> (state', {loss, accuracy}) over ("dp", "ep");
    state params placed by moe_param_shardings."""
    n_dp = mesh.devices.shape[0]
    body = functools.partial(_moe_body, n_experts=n_experts,
                             n_classes=n_classes)
    sharded_loss = shard_map(
        body, mesh=mesh,
        in_specs=(MOE_PSPECS, P(DP_AXIS, None), P(DP_AXIS)),
        out_specs=(P((DP_AXIS, EP_AXIS)), P((DP_AXIS, EP_AXIS))),
        check_vma=False)

    from dmlp_tpu.train.pipeline import _partials_train_step
    return _partials_train_step(sharded_loss, optimizer, n_dp)


def build_moe_state(mesh: Mesh, optimizer, d_in: int, hidden: int, ffn: int,
                    n_classes: int, n_experts: int, seed: int = 0):
    from dmlp_tpu.train.pipeline import place_state
    params = init_moe(jax.random.PRNGKey(seed), d_in, hidden, ffn,
                      n_classes, n_experts)
    return place_state(params, moe_param_shardings(mesh), optimizer)


# ---------------------------------------------------------------------------
# Production-style dispatch: capacity + all-to-all. Tokens shard over BOTH
# mesh axes (batch split dp x ep); each cell routes its local tokens to the
# expert-owning ep cells through lax.all_to_all over ICI, computes its own
# experts on what arrives, and returns results through the reverse
# all_to_all. Tokens beyond `capacity` per (source cell, destination cell)
# are dropped to the residual path — the standard MoE capacity semantics.
# With capacity >= local tokens nothing drops and the step is grad-exact vs
# moe_reference_forward (tests/test_train_experts.py).
# ---------------------------------------------------------------------------


def _moe_a2a_body(params, x, y, *, n_experts: int, n_classes: int,
                  capacity: int):
    ep_idx = jax.lax.axis_index(EP_AXIS)
    n_ep = axis_size(EP_AXIS)
    e_local = params["up"].shape[0]
    bl, hdim = x.shape[0], params["in_w"].shape[1]

    h = x.astype(jnp.float32) @ params["in_w"] + params["in_b"]
    logits = h @ params["router"]
    probs = jax.nn.softmax(logits, -1)
    sel = jnp.argmax(logits, -1)                         # (Bl,) global id
    gate = jnp.take_along_axis(probs, sel[:, None], 1)   # (Bl, 1)

    dest = sel // e_local                                # owning ep cell
    e_loc = sel % e_local
    # Rank of each token within its destination group (position order).
    hot = (dest[:, None] == jnp.arange(n_ep)[None, :]).astype(jnp.int32)
    rank = jnp.take_along_axis(jnp.cumsum(hot, 0) - 1, dest[:, None],
                               1)[:, 0]                  # (Bl,)
    kept = rank < capacity

    # Scatter local tokens into the (n_ep, C, H) send buffer; slot payload
    # carries the expert-local id (+1; 0 = empty slot) alongside.
    send = jnp.zeros((n_ep, capacity, hdim), h.dtype)
    meta = jnp.zeros((n_ep, capacity), jnp.int32)
    # Dropped tokens scatter OUT OF RANGE so mode="drop" discards them —
    # aiming them at slot (0, 0) would clobber the real rank-0 token of
    # destination 0 with zeros.
    di = jnp.where(kept, dest, n_ep)
    ri = jnp.where(kept, rank, capacity)
    send = send.at[di, ri].set(h, mode="drop")
    meta = meta.at[di, ri].set(e_loc + 1, mode="drop")

    # Dispatch over ICI: slot [s, c] on this cell is now source cell s's
    # c-th token destined to OUR experts.
    recv = jax.lax.all_to_all(send, EP_AXIS, 0, 0)   # check: comms-model=moe_a2a_traffic
    rmeta = jax.lax.all_to_all(meta, EP_AXIS, 0, 0)  # check: comms-model=moe_a2a_traffic

    toks = recv.reshape(n_ep * capacity, hdim)
    tmeta = rmeta.reshape(n_ep * capacity)
    ehot = jax.nn.one_hot(tmeta - 1, e_local, dtype=toks.dtype)
    ehot = ehot * (tmeta > 0)[:, None]                   # empty slots -> 0
    up = jnp.einsum("th,ehf->tef", toks, params["up"])
    act = jax.nn.relu(up)
    down = jnp.einsum("tef,efh->teh", act, params["down"])
    out_toks = jnp.einsum("teh,te->th", down, ehot)

    # Return through the reverse all_to_all (same slot layout back).
    # check: comms-model=moe_a2a_traffic
    ret = jax.lax.all_to_all(
        out_toks.reshape(n_ep, capacity, hdim), EP_AXIS, 0, 0)
    # Gather back with in-range indices (dropped tokens read slot (0, 0)
    # and are masked to the residual-only path).
    expert_out = jnp.where(kept[:, None],
                           ret[jnp.where(kept, dest, 0),
                               jnp.where(kept, rank, 0)], 0.0)

    h = h + gate * expert_out
    out = h @ params["out_w"] + params["out_b"]
    ce = optax.softmax_cross_entropy_with_integer_labels(out, y)
    acc = (jnp.argmax(out, -1) == y).astype(jnp.float32)
    # Per-cell SUM partials; the caller divides by the global batch — the
    # same no-collective-on-the-loss-path rule as the dense dispatch.
    return ce.sum()[None], acc.sum()[None]


def a2a_capacity(batch: int, dp: int, ep: int,
                 capacity_factor: float = 1.0) -> int:
    """Per-(source cell, destination cell) dispatch slots for the a2a step.

    Local tokens per cell = batch / (dp * ep); uniform routing sends
    local/ep of them to each destination, so capacity =
    ceil(cf * local / ep). cf >= ep makes capacity >= local tokens —
    zero drops regardless of routing skew (the grad-exact regime the
    equivalence tests pin)."""
    if batch % (dp * ep):
        raise ValueError(f"a2a dispatch needs batch divisible by dp*ep "
                         f"({batch} % {dp * ep})")
    local = batch // (dp * ep)
    return max(1, int(np.ceil(capacity_factor * local / ep)))


def a2a_batch_shardings(mesh: Mesh):
    """(x, y) sharded over BOTH mesh axes on the batch dim — the a2a
    step's input layout (dense keeps train.sharding.batch_shardings)."""
    return (NamedSharding(mesh, P((DP_AXIS, EP_AXIS), None)),
            NamedSharding(mesh, P((DP_AXIS, EP_AXIS))))


def make_moe_a2a_train_step(mesh: Mesh,
                            optimizer: optax.GradientTransformation, *,
                            n_experts: int, n_classes: int, capacity: int):
    """Jitted capacity + all-to-all MoE step over ("dp", "ep"): the batch
    splits over BOTH axes (x arrives P(("dp","ep"), None)); per-cell CE
    sums are divided by the global batch size outside the shard_map."""
    if capacity < 1:
        raise ValueError("capacity must be >= 1 (0 would make zero-width "
                         "dispatch buffers; to drop everything, don't run "
                         "the experts)")
    body = functools.partial(_moe_a2a_body, n_experts=n_experts,
                             n_classes=n_classes, capacity=capacity)
    sharded_loss = shard_map(
        body, mesh=mesh,
        in_specs=(MOE_PSPECS, P((DP_AXIS, EP_AXIS), None),
                  P((DP_AXIS, EP_AXIS))),
        out_specs=(P((DP_AXIS, EP_AXIS)), P((DP_AXIS, EP_AXIS))),
        check_vma=False)

    def loss_fn(params, x, y):
        loss_p, acc_p = sharded_loss(params, x, y)
        b = x.shape[0]
        return loss_p.sum() / b, acc_p.sum() / b

    def step(state, x, y):
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], x, y)
        updates, opt = optimizer.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt": opt, "step": state["step"] + 1},
                {"loss": loss, "accuracy": acc})

    return jax.jit(step, donate_argnums=(0,))
