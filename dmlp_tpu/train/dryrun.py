"""Multi-chip training dry run — called by __graft_entry__.dryrun_multichip.

Builds a (dp, tp) mesh over the given devices, jits the FULL train step
(fwd/bwd + optimizer + declarative dp gradient all-reduce + tp-sharded
weights) and runs a few steps on tiny shapes, asserting losses are finite
and the dp/tp result matches a single-device run of the same step.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from dmlp_tpu.train.loop import build_sharded_state
from dmlp_tpu.train.sharding import batch_shardings, make_train_mesh
from dmlp_tpu.train.step import init_state, make_optimizer, make_train_step
from dmlp_tpu.train.model import init_mlp


def dryrun_train(devices: Sequence[jax.Device]) -> None:
    n = len(devices)
    tp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = n // tp
    dims = (16, 32, 32, 8)
    batch = 8 * dp
    optimizer = make_optimizer("sgd", 0.05)

    mesh = make_train_mesh((dp, tp), devices=devices)
    state = build_sharded_state(mesh, dims, optimizer, seed=3)
    step_fn = make_train_step(optimizer)
    xsh, ysh = batch_shardings(mesh)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, dims[0])).astype(np.float32)
    y = rng.integers(0, dims[-1], batch).astype(np.int32)

    state, m = step_fn(state, jax.device_put(x, xsh), jax.device_put(y, ysh))
    state, m2 = step_fn(state, jax.device_put(x, xsh), jax.device_put(y, ysh))
    loss0, loss1 = float(m["loss"]), float(m2["loss"])
    assert np.isfinite(loss0) and np.isfinite(loss1), (loss0, loss1)
    assert loss1 < loss0, "second step on same batch must reduce loss"

    # Cross-check the sharded step against a single-device run.
    sstate = init_state(init_mlp(jax.random.PRNGKey(3), dims), optimizer)
    sstep = make_train_step(optimizer)
    sstate, sm = sstep(sstate, x, y)
    np.testing.assert_allclose(float(sm["loss"]), loss0, rtol=2e-5)

    # Pipeline parallelism: one (dp, pp) microbatched step, checked
    # against the mathematically equivalent flat stack.
    if n >= 4:
        import jax.numpy as jnp
        import optax

        from dmlp_tpu.train.pipeline import (build_pp_state, flat_forward,
                                             flatten_pipeline, make_pp_mesh,
                                             make_pp_train_step)
        pp = 4
        dp_pp = n // pp
        pmesh = make_pp_mesh(dp_pp, pp, devices=devices)
        pstate = build_pp_state(pmesh, optimizer, 6, 16, 4, 2, seed=5)
        flat = flatten_pipeline(pstate["params"])
        pstep = make_pp_train_step(pmesh, optimizer, n_micro=2, n_classes=4)
        xb = rng.normal(size=(8 * dp_pp, 6)).astype(np.float32)
        yb = rng.integers(0, 4, 8 * dp_pp).astype(np.int32)
        pstate, pm = pstep(pstate, jnp.asarray(xb), jnp.asarray(yb))
        want = float(optax.softmax_cross_entropy_with_integer_labels(
            flat_forward(flat, jnp.asarray(xb)), jnp.asarray(yb)).mean())
        np.testing.assert_allclose(float(pm["loss"]), want, rtol=2e-5)

        # Expert parallelism: one (dp, ep) MoE step, checked against the
        # unsharded reference forward.
        from dmlp_tpu.train.experts import (build_moe_state, make_ep_mesh,
                                            make_moe_train_step,
                                            moe_reference_forward)
        emesh = make_ep_mesh(dp_pp, 4, devices=devices)
        estate = build_moe_state(emesh, optimizer, 6, 16, 24, 4, 8, seed=9)
        ref = {k: jnp.asarray(np.asarray(v))
               for k, v in estate["params"].items()}
        estep = make_moe_train_step(emesh, optimizer, n_experts=8,
                                    n_classes=4)
        estate, em = estep(estate, jnp.asarray(xb), jnp.asarray(yb))
        ew = float(optax.softmax_cross_entropy_with_integer_labels(
            moe_reference_forward(ref, jnp.asarray(xb)),
            jnp.asarray(yb)).mean())
        np.testing.assert_allclose(float(em["loss"]), ew, rtol=2e-5)

        # Production capacity + all-to-all MoE dispatch (VERDICT r4 item
        # 1/4): capacity = local tokens (a2a_capacity with cf = ep) means
        # zero drops, so the loss must match the SAME unsharded reference
        # the dense dispatch was checked against.
        from dmlp_tpu.train.experts import (a2a_batch_shardings,
                                            a2a_capacity,
                                            make_moe_a2a_train_step)
        bt = xb.shape[0]
        cap = a2a_capacity(bt, dp_pp, 4, capacity_factor=4.0)
        assert cap >= bt // (dp_pp * 4), (cap, bt)  # zero-drop regime
        astate = build_moe_state(emesh, optimizer, 6, 16, 24, 4, 8, seed=9)
        astep = make_moe_a2a_train_step(emesh, optimizer, n_experts=8,
                                        n_classes=4, capacity=cap)
        xsh_a, ysh_a = a2a_batch_shardings(emesh)
        astate, am = astep(astate, jax.device_put(jnp.asarray(xb), xsh_a),
                           jax.device_put(jnp.asarray(yb), ysh_a))
        np.testing.assert_allclose(float(am["loss"]), ew, rtol=2e-5)

        # 3D dp x tp x pp composition (VERDICT r4 item 4): one microbatched
        # step over the (dp, 2, 2) mesh vs the unpipelined, unsharded
        # reference forward.
        from dmlp_tpu.train.pipeline import (build_pp3_state, make_pp3_mesh,
                                             make_pp3_train_step,
                                             pp3_reference_forward)
        p3mesh = make_pp3_mesh(dp_pp, 2, 2, devices=devices)
        p3state = build_pp3_state(p3mesh, optimizer, 6, 16, 4, 2, seed=13)
        p3ref = {k: jnp.asarray(np.asarray(v))
                 for k, v in p3state["params"].items()}
        p3step = make_pp3_train_step(p3mesh, optimizer, n_micro=2,
                                     n_classes=4)
        p3state, p3m = p3step(p3state, jnp.asarray(xb), jnp.asarray(yb))
        p3want = float(optax.softmax_cross_entropy_with_integer_labels(
            pp3_reference_forward(p3ref, jnp.asarray(xb)),
            jnp.asarray(yb)).mean())
        np.testing.assert_allclose(float(p3m["loss"]), p3want, rtol=2e-5)
