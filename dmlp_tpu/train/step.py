"""The jitted train step: fwd/bwd on the MXU, declarative gradient sync.

The north-star analog of the reference's collective layer: where MPI code
would call MPI_Allreduce on gradients, here the dp-replicated param
placement makes XLA emit the all-reduce itself when the jitted step runs
over the mesh (sharding.py). The step is a pure function over a TrainState
pytree, so it composes with orbax checkpointing (train.checkpoint) and
donation (the state buffer is reused in place).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from dmlp_tpu.train.model import mlp_apply

TrainState = Dict[str, Any]  # {"params": pytree, "opt": optax state, "step": i32}


def make_optimizer(name: str = "sgd", lr: float = 1e-2,
                   momentum: float = 0.9) -> optax.GradientTransformation:
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum)
    if name == "adam":
        return optax.adam(lr)
    raise ValueError(f"unknown optimizer {name!r}")


def init_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    """Build the train state; called on already-placed (sharded) params so
    the optimizer moments inherit the param shardings."""
    return {"params": params, "opt": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32)}


def make_train_step(optimizer: optax.GradientTransformation,
                    compute_dtype=None,
                    ) -> Callable[[TrainState, jax.Array, jax.Array],
                                  Tuple[TrainState, Dict[str, jax.Array]]]:
    """Jitted (state, x, y) -> (state', {loss, accuracy}).

    Donates the state: params/opt buffers are updated in place on device.
    Sharding is carried by the operands (place params with
    sharding.param_shardings and batches with batch_shardings); XLA
    propagates it through grads and inserts the dp all-reduce.
    """

    def step(state: TrainState, x: jax.Array, y: jax.Array):
        def loss_fn(params):
            logits = mlp_apply(params, x, compute_dtype)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"])
        updates, opt = optimizer.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt": opt, "step": state["step"] + 1}
        return new_state, {"loss": loss, "accuracy": acc}

    return jax.jit(step, donate_argnums=(0,))
