"""The jitted train step: fwd/bwd on the MXU, declarative gradient sync.

The north-star analog of the reference's collective layer: where MPI code
would call MPI_Allreduce on gradients, here the dp-replicated param
placement makes XLA emit the all-reduce itself when the jitted step runs
over the mesh (sharding.py). The step is a pure function over a TrainState
pytree, so it composes with orbax checkpointing (train.checkpoint) and
donation (the state buffer is reused in place).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from dmlp_tpu.train.model import mlp_apply

TrainState = Dict[str, Any]  # {"params": pytree, "opt": optax state, "step": i32}


def make_optimizer(name: str = "sgd", lr: float = 1e-2,
                   momentum: float = 0.9) -> optax.GradientTransformation:
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum)
    if name == "adam":
        return optax.adam(lr)
    raise ValueError(f"unknown optimizer {name!r}")


def init_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    """Build the train state; called on already-placed (sharded) params so
    the optimizer moments inherit the param shardings."""
    return {"params": params, "opt": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32)}


def make_train_step(optimizer: optax.GradientTransformation,
                    compute_dtype=None, offload_state: TrainState = None,
                    ) -> Callable[[TrainState, jax.Array, jax.Array],
                                  Tuple[TrainState, Dict[str, jax.Array]]]:
    """Jitted (state, x, y) -> (state', {loss, accuracy}).

    Donates the state: params/opt buffers are updated in place on device.
    Sharding is carried by the operands (place params with
    sharding.param_shardings and batches with batch_shardings); XLA
    propagates it through grads and inserts the dp all-reduce.

    Host offload (the bench_4 analog): pass the placed state (host-DRAM
    leaves per build_sharded_state's offload level) as ``offload_state``.
    The step streams host-resident leaves to HBM (in-jit ``device_put``
    to the ``with_memory_kind("device")`` shardings) right before use,
    and their updated values are written back to host DRAM via the jit's
    ``out_shardings``; XLA's latency-hiding scheduler overlaps the
    per-layer transfers with the matmuls, so HBM holds working copies
    only for the step's duration. Mixed states work by construction:
    an already-HBM leaf's "device" sharding equals its own, so its
    device_put and out_sharding are no-ops — the "params" offload level
    (moments HBM-resident, half the stream bytes) needs no special case
    here.

    Runtime note: XLA:CPU's SPMD partitioner rejects host-memory stores on
    multi-device shardings ("Side-effect ops cannot be replicated"), so on
    the CPU test platform offload works on (1, 1) meshes only; TPU
    runtimes own the host-offload feature.
    """
    offload = offload_state is not None
    out_shardings = None
    if offload:
        work = {"params": offload_state["params"],
                "opt": offload_state["opt"]}
        host_sh = jax.tree.map(lambda a: a.sharding, work)
        dev_sh = jax.tree.map(
            lambda a: a.sharding.with_memory_kind("device"), work)
        out_shardings = ({"params": host_sh["params"], "opt": host_sh["opt"],
                          "step": None}, None)

    def step(state: TrainState, x: jax.Array, y: jax.Array):
        params_w, opt_w = state["params"], state["opt"]
        if offload:
            params_w = jax.tree.map(jax.device_put, params_w,
                                    dev_sh["params"])
            opt_w = jax.tree.map(jax.device_put, opt_w, dev_sh["opt"])

        def loss_fn(params):
            logits = mlp_apply(params, x, compute_dtype)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params_w)
        updates, opt = optimizer.update(grads, opt_w, params_w)
        params = optax.apply_updates(params_w, updates)
        new_state = {"params": params, "opt": opt, "step": state["step"] + 1}
        return new_state, {"loss": loss, "accuracy": acc}

    return jax.jit(step, donate_argnums=(0,), out_shardings=out_shardings)


@functools.lru_cache(maxsize=1)
def supports_injit_offload() -> bool:
    """Whether this runtime compiles host-memory placements inside jit.

    TPU runtimes do; XLA:CPU lacks the annotate_device_placement custom
    call ("No registered implementation ... for Host"), so the eager
    fallback (make_eager_offload_step) is used there. Probe-compiled once,
    like ops.pallas_distance.native_pallas_backend.
    """
    try:
        dev = jax.devices()[0]
        from dmlp_tpu.utils.compat import host_memory_kind
        hsh = jax.sharding.SingleDeviceSharding(
            dev, memory_kind=host_memory_kind())
        dsh = jax.sharding.SingleDeviceSharding(dev, memory_kind="device")
        w = jax.device_put(jnp.ones((8,)), hsh)
        f = jax.jit(lambda a: jax.device_put(a, dsh) * 2.0,
                    out_shardings=hsh)
        return bool(jax.device_get(f(w))[0] == 2.0)
    except Exception:
        return False


def make_eager_offload_step(optimizer: optax.GradientTransformation,
                            compute_dtype=None, host_state: TrainState = None,
                            ) -> Callable:
    """Offload fallback for runtimes without in-jit host-memory support.

    State lives in host DRAM between steps; each call eagerly streams
    params/moments to HBM, runs the regular jitted step (donated, so HBM
    copies die with the step), and evicts the updated values back. Slower
    than the in-jit form (no transfer/compute overlap) but runs everywhere,
    so CPU CI can exercise the offload semantics end-to-end.
    """
    inner = make_train_step(optimizer, compute_dtype)
    work = {"params": host_state["params"], "opt": host_state["opt"]}
    host_sh = jax.tree.map(lambda a: a.sharding, work)
    dev_sh = jax.tree.map(
        lambda a: a.sharding.with_memory_kind("device"), work)

    def step(state: TrainState, x, y):
        ws = {"params": jax.tree.map(jax.device_put, state["params"],
                                     dev_sh["params"]),
              "opt": jax.tree.map(jax.device_put, state["opt"],
                                  dev_sh["opt"]),
              "step": state["step"]}
        new, m = inner(ws, x, y)
        out = {"params": jax.tree.map(jax.device_put, new["params"],
                                      host_sh["params"]),
               "opt": jax.tree.map(jax.device_put, new["opt"],
                                   host_sh["opt"]),
               "step": new["step"]}
        return out, m

    return step


def make_offload_train_step(optimizer: optax.GradientTransformation,
                            compute_dtype=None, state: TrainState = None,
                            ) -> Callable:
    """The host-offload step for this runtime: in-jit streaming where the
    compiler supports it, the eager round-trip elsewhere."""
    if supports_injit_offload():
        return make_train_step(optimizer, compute_dtype, offload_state=state)
    return make_eager_offload_step(optimizer, compute_dtype, host_state=state)
