"""North-star training benchmark: measured samples/sec/chip + MFU.

BASELINE.json's metric set ("samples/sec/chip", north star >= 40% MFU) needs
a number measured on the real chip, not just the accounting in
train.metrics. ``train_bench()`` runs the dp x tp sharded train step
(train.step) at a matmul-heavy shape and reports measured throughput as one
JSON-able dict (bench.py prints it when BENCH_MODE=train).

Batches come from a small device-resident pool, cycled across steps: the
benchmark measures the training step (fwd/bwd/update on the MXU + XLA
gradient sync), not the host link. The host input path with prefetch is
train.loop / train.data.prefetch_to_device; the reference's timed region
similarly excludes ingest (common.cpp:122-131 starts after stdin parsing).

Env knobs: TRAIN_DIMS ("1024,8192,8192,1024"), TRAIN_BATCH (8192),
TRAIN_STEPS (30), TRAIN_DTYPE ("bfloat16"|"float32"), TRAIN_MESH ("DP,TP").
"""

from __future__ import annotations

import os
import time

import jax


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def train_bench() -> dict:
    import jax.numpy as jnp

    from dmlp_tpu.train.data import teacher_batches
    from dmlp_tpu.train.loop import build_sharded_state
    from dmlp_tpu.train.metrics import peak_flops_per_chip, throughput_metrics
    from dmlp_tpu.train.sharding import batch_shardings, make_train_mesh
    from dmlp_tpu.train.step import make_optimizer, make_train_step

    from dmlp_tpu.train.loop import resolve_offload_level
    offload = resolve_offload_level(os.environ.get("TRAIN_OFFLOAD", "0"))
    dims = tuple(int(d) for d in
                 os.environ.get("TRAIN_DIMS", "1024,8192,8192,1024").split(","))
    # Offload streams the full f32 params+moments (1.34 GB/step at the
    # default dims) between host DRAM and HBM every step; at batch 8192
    # the step's 4.1 TFLOP can't cover that even with perfect overlap
    # (~27% MFU ceiling on this host link, 18.7% measured). 4x the batch
    # gives the latency-hiding scheduler enough matmul to hide the
    # streams: 53.5% MFU measured on v5e — past the >= 40% north star.
    batch = _env_int("TRAIN_BATCH", 32768 if offload != "none" else 8192)
    steps = _env_int("TRAIN_STEPS", 30)
    pool = _env_int("TRAIN_POOL", 4)
    dtype = os.environ.get("TRAIN_DTYPE", "bfloat16")
    mesh_shape = None
    if os.environ.get("TRAIN_MESH"):
        dp, tp = os.environ["TRAIN_MESH"].split(",")
        mesh_shape = (int(dp), int(tp))

    mesh = make_train_mesh(mesh_shape)
    n_chips = mesh.devices.size
    optimizer = make_optimizer("sgd", 1e-2)
    state = build_sharded_state(mesh, dims, optimizer, offload=offload)
    cdtype = jnp.bfloat16 if dtype == "bfloat16" else None
    if offload != "none":
        from dmlp_tpu.train.step import make_offload_train_step
        step_fn = make_offload_train_step(optimizer, cdtype, state)
    else:
        step_fn = make_train_step(optimizer, cdtype)
    xsh, ysh = batch_shardings(mesh)

    data = teacher_batches(dims[0], dims[-1], batch, seed=1)
    batches = []
    for _ in range(pool):
        x, y = next(data)
        batches.append((jax.device_put(x, xsh), jax.device_put(y, ysh)))

    # Warmup: compile + settle (donation means state flows through).
    for i in range(3):
        state, m = step_fn(state, *batches[i % pool])
    jax.device_get(m["loss"])  # fence — compile and warmup fully done

    t0 = time.perf_counter()
    for i in range(steps):
        state, m = step_fn(state, *batches[i % pool])
    loss = float(jax.device_get(m["loss"]))  # fence
    dt = (time.perf_counter() - t0) / steps

    tm = throughput_metrics(state["params"], batch, dt, n_chips)
    return {
        "metric": "train_samples_per_sec_per_chip",
        "value": round(tm["samples_per_sec_per_chip"], 1),
        "unit": "samples/s/chip",
        # No measured reference baseline exists for training (BASELINE.md:
        # "published: {}"); report progress against the driver's north-star
        # >= 40% MFU target instead.
        "vs_baseline": round(tm["mfu"] / 0.40, 3),
        "mfu": round(tm["mfu"], 4),
        "step_time_ms": round(tm["step_time_ms"], 2),
        "model_tflops_per_step": round(tm["model_flops_per_step"] / 1e12, 3),
        "peak_tflops_per_chip": round(peak_flops_per_chip() / 1e12, 1),
        "final_loss": round(loss, 4),
        "shape": {"dims": list(dims), "batch": batch, "steps": steps,
                  "dtype": dtype, "n_chips": int(n_chips),
                  "offload": offload,
                  "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
                  "mode": "train"},
    }


if __name__ == "__main__":
    import json
    print(json.dumps(train_bench()))
