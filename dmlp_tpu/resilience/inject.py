"""Deterministic, seedable fault injection at named hazard points.

The paper's correctness story is exact differential verification; the
resilience story extends it: *recovery must preserve the contract
checksums*, and the only way to prove that in CI is to make faults
reproducible. This module is the reproducibility half — a fault
*schedule* (JSON, loaded from ``--faults FILE`` or ``$DMLP_TPU_FAULTS``)
names injection sites registered at the tree's real hazard points and
fires deterministic faults there; the same schedule + seed yields the
same injection log, run after run (the chaos harness replays this
twice and diffs the logs).

Schedule schema (``schema: 1``)::

    {"schema": 1, "seed": 7, "faults": [
        {"site": "single.stage_put", "kind": "delay", "ms": 40,
         "times": 2, "prob": 0.5},
        {"site": "single.fetch", "kind": "transient"},
        {"site": "single.extract_solve", "kind": "oom", "times": 2},
        {"site": "train.step", "kind": "nan", "when": {"step": 5}},
        {"site": "io.parse", "kind": "corrupt"}
    ]}

Per entry: ``site`` is an exact name or an ``fnmatch`` glob over the
registered catalog (:data:`SITES`; an entry matching no registered site
is a load-time error — typos must fail loudly); ``kind`` is one of
``delay`` (sleep ``ms`` — the straggler), ``transient`` (raise
:class:`InjectedTransientError` — the retry layer's food), ``oom``
(raise :class:`SimulatedResourceExhausted` — the degradation ladder's
food), ``corrupt`` / ``nan`` (passive actions the site applies itself:
deterministic byte corruption of the parse payload, a poisoned train
loss); ``times`` bounds total fires (default 1), ``after`` skips the
first N eligible hits, ``prob`` fires probabilistically — drawn from the
schedule's own seeded PRNG in hit order, so runs are bit-reproducible —
and ``when`` restricts to hits whose context matches (e.g.
``{"step": 5}`` or ``{"rung": "tuned"}``).

Hooks are near-free when no schedule is installed: :func:`fire` is a
module-global None check, exactly the obs.trace pattern.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import time
from typing import Any, Dict, List, Optional, Sequence

from dmlp_tpu.resilience import stats

#: Injection-site catalog — the registered hazard points. ``fire()``
#: calls with a name outside this table raise at schedule *load* time
#: (unknown sites in a schedule are typos, not latent coverage).
SITES: Dict[str, str] = {
    "io.parse": "input-grammar parse of the full problem payload "
                "(io.grammar.parse_input; corrupt faults truncate the "
                "bytes, the parser raises ParseError, the pristine "
                "payload is re-parsed)",
    "single.stage_put": "host->device staging of one data/query block "
                        "(engine.single.stage_put — every chunked driver "
                        "stages through it)",
    "single.fetch": "fenced device_get readback of candidate lists "
                    "(engine.single.resilient_get)",
    "single.extract_solve": "fused extract-kernel solve dispatch "
                            "(engine.single._solve_extract*; oom faults "
                            "here drive the degradation ladder)",
    "sharded.solve": "mesh shard-solve dispatch (engine.sharded "
                     "solve_merged / solve_local_shards / solve_global)",
    "sharded.fetch": "fenced device_get readback in the mesh engines",
    "dist.rank_solve": "per-rank shard solve inside the distributed "
                       "contract (parallel.distributed.solve_segment)",
    "dist.allgather": "host all-gather of the candidate tensors "
                      "(parallel.distributed)",
    "train.step": "one optimizer step (train.loop; nan faults poison "
                  "the step's loss so the NaN guard's rollback path "
                  "can be driven deterministically)",
    "serve.admit": "serving-daemon admission decision "
                   "(serve.admission.AdmissionController.decide; an oom "
                   "fault here is the injected memory squeeze — the "
                   "controller must SHED the request before any "
                   "allocation, visibly, with no ladder degradation)",
    "serve.solve": "serving-daemon micro-batch solve execution "
                   "(serve.batching.MicroBatcher._execute_batch, on "
                   "the single consumer thread; a delay fault is the "
                   "injected straggler solve — per-replica service "
                   "time inflates while the CPU idles, the lever "
                   "tools/slo_smoke.py uses to make replica capacity "
                   "sleep-bound on a CPU-only container; a transient "
                   "fault fails the whole batch visibly)",
    "serve.ingest": "serving-daemon ingest execution "
                    "(serve.batching.MicroBatcher._execute_ingest; a "
                    "transient fault here is the injected DROPPED "
                    "ingest — this replica's corpus silently lags the "
                    "fleet until the router's checksum-driven "
                    "consistency repair re-delivers the rows)",
}

KINDS = ("delay", "transient", "oom", "corrupt", "nan")

#: passive kinds are ACTIONS the site itself must apply (fire() returns
#: them); sites whose hooks discard the return value would log such a
#: fault as fired while doing nothing — so a schedule placing a passive
#: kind anywhere but its consuming site(s) is rejected at load time.
PASSIVE_CONSUMERS = {"corrupt": ("io.parse",), "nan": ("train.step",)}

#: injectable sleep for tests (delay faults must not slow the suite)
_sleep = time.sleep


class InjectedFault(RuntimeError):
    """Base class for all injected failures."""


class InjectedTransientError(InjectedFault):
    """A transient failure (classified retryable by resilience.retry)."""


class SimulatedResourceExhausted(InjectedFault):
    """A simulated device OOM; message carries the RESOURCE_EXHAUSTED
    marker so the ladder's classifier treats real XLA OOMs the same."""


class FaultEntry:
    """One schedule line plus its runtime fire-count state."""

    __slots__ = ("site", "kind", "times", "prob", "after", "ms", "when",
                 "message", "hits", "fired")

    def __init__(self, site: str, kind: str, times: int = 1,
                 prob: float = 1.0, after: int = 0, ms: float = 0.0,
                 when: Optional[Dict[str, Any]] = None, message: str = ""):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(valid: {', '.join(KINDS)})")
        if not any(fnmatch.fnmatchcase(name, site) for name in SITES):
            raise ValueError(
                f"fault site {site!r} matches no registered injection "
                f"site (catalog: {', '.join(sorted(SITES))})")
        consumers = PASSIVE_CONSUMERS.get(kind)
        if consumers is not None:
            stray = [n for n in SITES
                     if fnmatch.fnmatchcase(n, site) and n not in consumers]
            if stray:
                raise ValueError(
                    f"passive fault kind {kind!r} is only consumed at "
                    f"{', '.join(consumers)}; site {site!r} also matches "
                    f"{', '.join(stray)}, where it would count as fired "
                    "while doing nothing")
        if not (0.0 <= prob <= 1.0):
            raise ValueError(f"prob must be in [0, 1], got {prob}")
        if times < 1 or after < 0 or ms < 0:
            raise ValueError("times >= 1, after >= 0, ms >= 0 required")
        self.site, self.kind = site, kind
        self.times, self.prob, self.after = int(times), float(prob), int(after)
        self.ms = float(ms)
        self.when = dict(when or {})
        self.message = message
        self.hits = 0
        self.fired = 0

    def matches(self, site: str, ctx: Dict[str, Any]) -> bool:
        if not fnmatch.fnmatchcase(site, self.site):
            return False
        return all(ctx.get(k) == v for k, v in self.when.items())


class FaultSchedule:
    """A loaded, validated schedule with its seeded PRNG + fire log."""

    def __init__(self, entries: Sequence[FaultEntry], seed: int = 0,
                 source: Optional[str] = None):
        self.entries = list(entries)
        self.seed = int(seed)
        self.source = source
        self._rng = random.Random(self.seed)
        self.log: List[dict] = []

    @classmethod
    def from_dict(cls, doc: Dict[str, Any],
                  source: Optional[str] = None) -> "FaultSchedule":
        if doc.get("schema") != 1:
            raise ValueError(f"fault schedule schema must be 1, got "
                             f"{doc.get('schema')!r}")
        faults = doc.get("faults")
        if not isinstance(faults, list) or not faults:
            raise ValueError("fault schedule needs a non-empty 'faults' "
                             "list")
        entries = []
        for i, f in enumerate(faults):
            if not isinstance(f, dict) or "site" not in f or "kind" not in f:
                raise ValueError(f"faults[{i}] must be an object with "
                                 "'site' and 'kind'")
            known = {"site", "kind", "times", "prob", "after", "ms",
                     "when", "message"}
            extra = set(f) - known
            if extra:
                raise ValueError(f"faults[{i}] has unknown field(s) "
                                 f"{sorted(extra)}")
            entries.append(FaultEntry(**f))
        return cls(entries, seed=int(doc.get("seed", 0)), source=source)

    @classmethod
    def from_file(cls, path: str) -> "FaultSchedule":
        with open(path) as f:
            try:
                doc = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"fault schedule {path} is not JSON: "
                                 f"{e}") from None
        return cls.from_dict(doc, source=path)

    def fire(self, site: str, ctx: Dict[str, Any]) -> List[str]:
        """Evaluate every matching entry at this hit; raise for active
        faults, sleep for delays, return passive actions ("corrupt" /
        "nan") for the site to apply. Every decision is logged.

        A passive action is only *consumed* when it is actually
        delivered: if a later raising fault fires in the same call, the
        caller never sees the actions list, so any passive entry this
        call tentatively fired is rolled back (budget and log) and
        fires again on the retry's re-invocation — the injection log
        never claims a fault that had no effect."""
        actions: List[str] = []
        # passive entries tentatively consumed this call, with the index
        # of their log record (for exact rollback if a raiser fires)
        pending: List[tuple] = []
        for e in self.entries:
            if not e.matches(site, ctx):
                continue
            e.hits += 1
            if e.hits <= e.after or e.fired >= e.times:
                continue
            fired = True if e.prob >= 1.0 else self._rng.random() < e.prob
            self.log.append({"site": site, "kind": e.kind, "hit": e.hits,
                             "fired": fired,
                             **({"ctx": _json_ctx(ctx)} if ctx else {})})
            if not fired:
                continue
            if e.kind in ("transient", "oom"):
                for p, idx in reversed(pending):
                    p.fired -= 1
                    del self.log[idx]
            e.fired += 1
            stats.record_fault(site, e.kind)
            from dmlp_tpu.obs import trace as obs_trace
            obs_trace.instant("resilience.fault", site=site, kind=e.kind)
            detail = f" ({e.message})" if e.message else ""
            if e.kind == "delay":
                _sleep(e.ms / 1e3)
            elif e.kind == "transient":
                raise InjectedTransientError(
                    f"injected transient fault at {site}{detail}")
            elif e.kind == "oom":
                raise SimulatedResourceExhausted(
                    f"RESOURCE_EXHAUSTED (injected) at {site}{detail}")
            else:
                actions.append(e.kind)
                pending.append((e, len(self.log) - 1))
        return actions

    def log_json(self) -> str:
        return json.dumps({"schema": 1, "seed": self.seed,
                           "source": self.source, "log": self.log},
                          sort_keys=True, indent=1)

    def write_log(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.log_json() + "\n")
        os.replace(tmp, path)


def _json_ctx(ctx: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in ctx.items()
            if isinstance(v, (str, int, float, bool, type(None)))}


# -- process-wide hook (the obs.trace install pattern) -----------------------
_active: Optional[FaultSchedule] = None


def install(schedule: FaultSchedule) -> FaultSchedule:
    global _active
    _active = schedule
    return schedule


def uninstall() -> None:
    global _active
    _active = None


def active() -> Optional[FaultSchedule]:
    return _active


def fire(site: str, **ctx) -> Optional[List[str]]:
    """The injection hook every registered hazard point calls. Returns
    the passive actions to apply (or None — the common fast path), and
    raises for transient/oom faults. A no-op unless a schedule is
    installed AND resilience is enabled."""
    sched = _active
    if sched is None:
        return None
    if os.environ.get("DMLP_TPU_RESILIENCE", "1") == "0":
        return None
    return sched.fire(site, ctx)


def install_from_env(flag_path: Optional[str] = None
                     ) -> Optional[FaultSchedule]:
    """Install a schedule from ``flag_path`` (a CLI ``--faults`` value)
    or ``$DMLP_TPU_FAULTS``; returns it, or None when neither is set."""
    path = flag_path or os.environ.get("DMLP_TPU_FAULTS")
    if not path:
        return None
    return install(FaultSchedule.from_file(path))


def write_log_if_requested() -> None:
    """Persist the active schedule's injection log to
    ``$DMLP_TPU_FAULT_LOG`` (the chaos harness's determinism probe)."""
    sched = _active
    path = os.environ.get("DMLP_TPU_FAULT_LOG")
    if sched is not None and path:
        sched.write_log(path)


def corrupt_bytes(data):
    """Deterministic payload corruption for ``corrupt`` actions:
    truncate to <= 3/4 length AT A LINE BOUNDARY, so at least one whole
    record line disappears and the grammar's record-count check is
    *guaranteed* to raise ParseError. A mid-token cut or a bit flip
    could by luck still parse — silently wrong answers are the one
    failure mode a byte-identity chaos harness must never inject.
    Accepts bytes or str (the io layer reads either)."""
    nl = b"\n" if isinstance(data, bytes) else "\n"
    empty = b"" if isinstance(data, bytes) else ""
    if not data:
        return empty
    # Exclude a trailing newline so rfind below can only pick a
    # newline strictly BEFORE the last line — cutting there always
    # removes >= 1 line, never just the final terminator.
    body = data[:-1] if data.endswith(nl) else data
    cut = body.rfind(nl, 0, min((len(data) * 3) // 4, len(body)))
    if cut <= 0:
        return empty
    return data[: cut + 1]
