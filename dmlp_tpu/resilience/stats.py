"""Process-wide resilience accounting — the RunRecord/metrics feed.

As of the telemetry round these counters live in the ONE process-wide
metrics registry (:data:`dmlp_tpu.obs.telemetry.REGISTRY`) instead of a
private dict: the ``resilience.*`` counters are the same objects a live
scrape (``--telemetry``), the flight recorder, and the end-of-run
``resilience`` block in metrics summaries all read — one source of
truth, no end-of-run copy that can drift from what a mid-run observer
saw. The record hooks stay cheap unconditional integer bumps (the
registry is stdlib-only and always present; *export* is what
``--telemetry`` opts into), and :func:`snapshot` keeps its exact
historical shape so RunRecords and the chaos harness are unchanged.

Only the ordered degradation *transition list* stays module-local: the
chaos harness asserts the ladder's step sequence, and a labeled counter
keeps counts, not order (the registry carries those counts too, under
``resilience.degradations``).
"""

from __future__ import annotations

import threading
from typing import List

from dmlp_tpu.obs.telemetry import REGISTRY

_lock = threading.Lock()
_degradations: List[str] = []   # ordered transitions (counts mirror the
#                                 resilience.degradations counter labels)


def _counters() -> dict:
    """The resilience counter set, registered once per name (literal
    snake_case dotted names — check rule R6)."""
    return {
        "retries": REGISTRY.counter("resilience.retries"),
        "rollbacks": REGISTRY.counter("resilience.rollbacks"),
        "restarts": REGISTRY.counter("resilience.restarts"),
        "timeouts": REGISTRY.counter("resilience.timeouts"),
        "faults_injected": REGISTRY.counter("resilience.faults_injected"),
        "degradations": REGISTRY.counter("resilience.degradations"),
    }


def reset() -> None:
    with _lock:
        _degradations.clear()
    REGISTRY.reset(prefix="resilience")


def record_retry(site: str) -> None:
    REGISTRY.counter("resilience.retries").inc(label=site)


def record_degradation(frm: str, to: str) -> None:
    with _lock:
        _degradations.append(f"{frm}->{to}")
    REGISTRY.counter("resilience.degradations").inc(label=f"{frm}->{to}")


def record_fault(site: str, kind: str) -> None:
    REGISTRY.counter("resilience.faults_injected").inc(label=kind)


def record_rollback() -> None:
    REGISTRY.counter("resilience.rollbacks").inc()


def record_restart() -> None:
    REGISTRY.counter("resilience.restarts").inc()


def record_timeout(site: str) -> None:
    REGISTRY.counter("resilience.timeouts").inc(label=site)


def any_activity() -> bool:
    c = _counters()
    return any(c[name].total() for name in
               ("retries", "rollbacks", "restarts", "timeouts",
                "faults_injected", "degradations"))


def snapshot() -> dict:
    """A JSON-ready copy of the counters — the ``resilience`` block the
    metrics summary and RunRecords carry. Always includes every field
    so consumers (the chaos harness) can assert zeros explicitly. Reads
    the REGISTRY (the telemetry scrape's source), not a private dict."""
    c = _counters()
    with _lock:
        degr = list(_degradations)
    return {
        "retries": int(c["retries"].total()),
        "rollbacks": int(c["rollbacks"].total()),
        "restarts": int(c["restarts"].total()),
        "timeouts": int(c["timeouts"].total()),
        "faults_injected": int(c["faults_injected"].total()),
        "degradations": degr,
        "retry_sites": {k: int(v)
                        for k, v in c["retries"].by_label().items()},
    }
