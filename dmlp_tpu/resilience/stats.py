"""Process-wide resilience accounting — the RunRecord/metrics feed.

One module-level :class:`ResilienceStats` collects what the resilience
layer actually did during a run (retries taken, degradation-ladder
steps, faults the injection framework fired, train rollbacks,
supervision restarts), mirroring the obs counters' install/collect
shape: the engines and wrappers record unconditionally (cheap integer
bumps), emitters snapshot once per run into the metrics summary /
RunRecord ``resilience`` block, and the chaos harness asserts recovery
was *visible*, not silent.

Import-light by design (stdlib only): every resilience hook sits on a
hot path that must cost nothing when nothing goes wrong.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List


@dataclasses.dataclass
class ResilienceStats:
    """Counters for one process's resilience activity."""

    retries: int = 0
    rollbacks: int = 0
    restarts: int = 0
    timeouts: int = 0
    faults_injected: int = 0
    degradations: List[str] = dataclasses.field(default_factory=list)
    retry_sites: Dict[str, int] = dataclasses.field(default_factory=dict)

    def any_activity(self) -> bool:
        return bool(self.retries or self.rollbacks or self.restarts
                    or self.timeouts or self.faults_injected
                    or self.degradations)


_lock = threading.Lock()
_stats = ResilienceStats()


def reset() -> None:
    global _stats
    with _lock:
        _stats = ResilienceStats()


def record_retry(site: str) -> None:
    with _lock:
        _stats.retries += 1
        _stats.retry_sites[site] = _stats.retry_sites.get(site, 0) + 1


def record_degradation(frm: str, to: str) -> None:
    with _lock:
        _stats.degradations.append(f"{frm}->{to}")


def record_fault(site: str, kind: str) -> None:
    with _lock:
        _stats.faults_injected += 1


def record_rollback() -> None:
    with _lock:
        _stats.rollbacks += 1


def record_restart() -> None:
    with _lock:
        _stats.restarts += 1


def record_timeout(site: str) -> None:
    with _lock:
        _stats.timeouts += 1


def any_activity() -> bool:
    with _lock:
        return _stats.any_activity()


def snapshot() -> dict:
    """A JSON-ready copy of the counters — the ``resilience`` block the
    metrics summary and RunRecords carry. Always includes every field
    so consumers (the chaos harness) can assert zeros explicitly."""
    with _lock:
        return {
            "retries": _stats.retries,
            "rollbacks": _stats.rollbacks,
            "restarts": _stats.restarts,
            "timeouts": _stats.timeouts,
            "faults_injected": _stats.faults_injected,
            "degradations": list(_stats.degradations),
            "retry_sites": dict(_stats.retry_sites),
        }
