"""Resilience subsystem: deterministic fault injection, retry/backoff,
graceful degradation, and cluster supervision.

The contract is *byte-identical recovery*: every mechanism here either
re-runs a pure re-runnable operation (retry), steps to an
exactness-preserving alternative (the degradation ladder, whose last
rung is the float64 oracle itself), or rolls state back to a checkpoint
and replays (the train NaN guard) — so a faulted run's output checksums
equal the fault-free run's, which ``tools/chaos_run.py`` /
``make chaos-smoke`` proves under seeded fault schedules.

Layout: :mod:`.inject` (seeded fault schedules + the named injection
sites), :mod:`.retry` (bounded backoff + error classification),
:mod:`.degrade` (the OOM ladder), :mod:`.supervise` (heartbeat/timeout
rank supervision + degraded fallback), :mod:`.stats` (the counters the
metrics summaries and RunRecords surface as their ``resilience``
block).
"""

from dmlp_tpu.resilience.inject import (FaultSchedule, InjectedFault,
                                        InjectedTransientError,
                                        SimulatedResourceExhausted)
from dmlp_tpu.resilience.retry import (DEFAULT_POLICY, OperationTimeout,
                                       RetryPolicy, call_with_retry,
                                       call_with_timeout, classify,
                                       resilience_enabled)

__all__ = [
    "FaultSchedule", "InjectedFault", "InjectedTransientError",
    "SimulatedResourceExhausted", "RetryPolicy", "DEFAULT_POLICY",
    "OperationTimeout", "call_with_retry", "call_with_timeout",
    "classify", "resilience_enabled",
]
