"""Bounded retry with exponential backoff, deterministic jitter, and
transient-vs-fatal error classification.

The repo's operations are unusually retry-friendly: staging, solve
dispatch, and readback are all pure functions of host arrays already in
memory, so re-running them cannot change answers — the chaos harness
proves that end to end. This module supplies the one retry loop every
wrapped site shares:

- **classification** (:func:`classify`): three-way. ``transient``
  (injected transients, connection/timeout errors, jax runtime errors
  carrying the UNAVAILABLE / DEADLINE_EXCEEDED / ABORTED markers) is
  retried here; ``oom`` (simulated or real RESOURCE_EXHAUSTED) is NOT —
  retrying the same allocation is futile, the degradation ladder
  (resilience.degrade) owns that recovery; everything else is ``fatal``
  and propagates immediately.
- **deterministic jitter**: the backoff delay's jitter fraction is a
  hash of (policy seed, site, attempt) — full de-thundering across
  sites, bit-reproducible across runs (a chaos run's timing profile is
  part of its replayability).
- **injectable clock/sleep**: tests pass ``sleep=`` and never wait.

``$DMLP_TPU_RESILIENCE=0`` disables the layer wholesale (wrappers become
direct calls) — the off arm of the chaos harness's zero-fault overhead
A/B.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Callable, Optional

from dmlp_tpu.resilience import stats
from dmlp_tpu.resilience.inject import (InjectedTransientError,
                                        SimulatedResourceExhausted)

#: substrings of runtime-error text classified transient (the PJRT /
#: gRPC status names a flaky dispatch or readback surfaces as)
TRANSIENT_MARKERS = ("DEADLINE_EXCEEDED", "UNAVAILABLE", "ABORTED",
                     "injected transient")

#: substrings classified as out-of-memory (ladder recovery, not retry)
OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def resilience_enabled() -> bool:
    """The layer-wide kill switch ($DMLP_TPU_RESILIENCE=0 disables) —
    checked per call so the chaos overhead A/B can flip it per run."""
    return os.environ.get("DMLP_TPU_RESILIENCE", "1") != "0"


def classify(exc: BaseException) -> str:
    """"transient" | "oom" | "fatal" for an exception."""
    if isinstance(exc, SimulatedResourceExhausted):
        return "oom"
    if isinstance(exc, (InjectedTransientError, ConnectionError,
                        TimeoutError, InterruptedError, OperationTimeout)):
        return "transient"
    msg = str(exc)
    if any(m in msg for m in OOM_MARKERS):
        return "oom"
    if any(m in msg for m in TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff: attempt n (0-based) sleeps
    ``min(base_ms * multiplier**n, cap_ms) * (1 + jitter * h)`` where
    ``h`` is the deterministic per-(seed, site, attempt) hash fraction."""

    attempts: int = 3
    base_ms: float = 25.0
    cap_ms: float = 2000.0
    multiplier: float = 2.0
    jitter: float = 0.25
    seed: int = 0


DEFAULT_POLICY = RetryPolicy()


def backoff_ms(policy: RetryPolicy, site: str, attempt: int) -> float:
    raw = min(policy.base_ms * policy.multiplier ** attempt, policy.cap_ms)
    digest = hashlib.sha256(
        f"{policy.seed}:{site}:{attempt}".encode()).digest()
    frac = int.from_bytes(digest[:8], "big") / 2 ** 64
    return raw * (1.0 + policy.jitter * frac)


def call_with_retry(op: Callable, site: str,
                    policy: Optional[RetryPolicy] = None,
                    classify_fn: Callable = classify,
                    sleep: Callable = time.sleep):
    """Run ``op()`` with bounded transient retries; fatal and oom
    errors propagate immediately (oom belongs to the degradation
    ladder). Every retry records a ``resilience.retry`` span and bumps
    the stats counters — recovery is never silent."""
    if not resilience_enabled():
        return op()
    policy = policy or DEFAULT_POLICY
    attempt = 0
    while True:
        try:
            return op()
        except Exception as e:
            clc = classify_fn(e)
            if clc != "transient" or attempt + 1 >= policy.attempts:
                # Post-mortem evidence BEFORE the raise unwinds: a
                # fatal-classified (or retries-exhausted) fault dumps
                # the flight recorder while the last spans/events are
                # still in the ring (no-op without a telemetry session;
                # oom propagates to the ladder, which is recovery, not
                # death — event only, no dump).
                from dmlp_tpu.obs import telemetry
                telemetry.flight_fault(
                    site=site, classification=clc,
                    error=type(e).__name__,
                    dump=clc == "fatal" or (clc == "transient"
                                            and attempt + 1
                                            >= policy.attempts))
                raise
            delay = backoff_ms(policy, site, attempt)
            stats.record_retry(site)
            from dmlp_tpu.obs.trace import span as obs_span
            with obs_span("resilience.retry", site=site,
                          attempt=attempt + 1,
                          backoff_ms=round(delay, 2),
                          error=type(e).__name__):
                sleep(delay / 1e3)
            attempt += 1


class OperationTimeout(RuntimeError):
    """An operation exceeded its deadline (see call_with_timeout)."""


def call_with_timeout(op: Callable, timeout_s: float, site: str = "",
                      clock: Callable = time.monotonic):
    """Run ``op`` on a worker thread and join with a deadline; raises
    :class:`OperationTimeout` (classified transient) when the deadline
    passes. NOTE: Python cannot kill the worker — a genuinely hung
    ``op`` leaks its (daemon) thread, so this guards *operations whose
    hang modes eventually resolve* (slow readbacks, stalled I/O); hung
    *processes* are the supervision loop's job (resilience.supervise),
    which can actually kill them."""
    result: list = []
    error: list = []

    def _worker():
        try:
            result.append(op())
        except BaseException as e:  # check: no-retry — relayed to caller
            error.append(e)

    t = threading.Thread(target=_worker, daemon=True,
                         name=f"resilience-timeout:{site}")
    t0 = clock()
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        stats.record_timeout(site)
        raise OperationTimeout(
            f"operation at {site or '<unnamed>'} exceeded "
            f"{timeout_s:.3g}s (waited {clock() - t0:.3g}s; worker "
            "thread abandoned)")
    if error:
        raise error[0]
    return result[0]
