"""Cluster supervision: heartbeat + timeout detection of dead or hung
ranks, bounded relaunch, and degraded single-process fallback.

The reference's only hang protection is ``mpirun --timeout`` — kill
everything and report nothing. The supervisor here is the launcher-side
half of a real failure-handling story (PAPERS.md's large-cluster
training systems treat this as a first-class subsystem):

- every rank process writes a **heartbeat file** (``hb-rank<NN>``,
  mtime refreshed by a daemon thread started when
  ``$DMLP_TPU_HEARTBEAT`` names the file — dmlp_tpu.distributed does
  this automatically);
- the supervisor polls child liveness + heartbeat freshness under one
  **cluster deadline**: a rank that exits nonzero, a heartbeat that
  goes stale (crashed/frozen interpreter), or a blown deadline
  (livelocked collective — heartbeat threads keep beating through
  those, which is exactly why the deadline exists too) fails the
  launch;
- a failed launch kills the whole cluster and **relaunches** (bounded;
  each restart is recorded);
- exhausted restarts fall back to the caller's **degraded
  single-process solve** — same contract checksums, no mesh. The
  degradation is recorded, never silent.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from typing import Callable, List, Optional, Tuple

from dmlp_tpu.resilience import stats

#: env var naming the heartbeat file a rank process must keep fresh
HEARTBEAT_ENV = "DMLP_TPU_HEARTBEAT"


def heartbeat_file(directory: str, rank: int) -> str:
    return os.path.join(directory, f"hb-rank{rank:02d}")


def start_heartbeat(path: str, interval_s: float = 0.5) -> threading.Event:
    """Start the daemon heartbeat thread; returns its stop event.
    Detects crashed or frozen interpreters — a livelocked C++
    collective releases the GIL and beats on, which the supervisor's
    cluster deadline covers instead."""
    stop = threading.Event()

    def _beat():
        while not stop.is_set():
            try:
                with open(path, "a"):
                    os.utime(path, None)
            except OSError:
                pass  # a beat miss only ages the file
            stop.wait(interval_s)

    threading.Thread(target=_beat, daemon=True,
                     name="resilience-heartbeat").start()
    return stop


def maybe_start_heartbeat_from_env() -> Optional[threading.Event]:
    """Start the heartbeat when the supervisor asked for one
    ($DMLP_TPU_HEARTBEAT) — called by rank entry points."""
    path = os.environ.get(HEARTBEAT_ENV)
    return start_heartbeat(path) if path else None


class ClusterFailure(RuntimeError):
    """Every supervised launch failed and no fallback was provided."""

    def __init__(self, report: dict):
        super().__init__(f"supervised cluster failed: {report}")
        self.report = report


def _kill_all(procs: List[subprocess.Popen]) -> None:
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass  # already killed; nothing left to do


def run_supervised(make_cluster: Callable[[int], List[List[str]]],
                   workdir: str, *, env: Optional[dict] = None,
                   cluster_timeout_s: float = 300.0,
                   hb_stale_s: float = 15.0, poll_s: float = 0.1,
                   max_launches: int = 2,
                   fallback: Optional[Callable[[], Tuple[bytes, bytes]]]
                   = None,
                   clock: Callable = time.monotonic,
                   ) -> Tuple[bytes, bytes, dict]:
    """Launch-and-watch loop. ``make_cluster(attempt)`` returns one argv
    per rank (fresh coordinator port per attempt); rank files land under
    ``workdir``. Returns (rank-0 stdout bytes, rank-0 stderr bytes,
    report). On total failure, runs ``fallback()`` — the degraded
    single-process solve — or raises :class:`ClusterFailure`."""
    os.makedirs(workdir, exist_ok=True)
    report: dict = {"launches": [], "fallback": False}
    base_env = dict(env if env is not None else os.environ)

    for attempt in range(max(max_launches, 1)):
        argvs = make_cluster(attempt)
        hb_dir = os.path.join(workdir, f"hb-attempt{attempt}")
        os.makedirs(hb_dir, exist_ok=True)
        outs, errs, procs = [], [], []
        for rank, argv in enumerate(argvs):
            e = dict(base_env)
            e[HEARTBEAT_ENV] = heartbeat_file(hb_dir, rank)
            out_f = open(os.path.join(
                workdir, f"rank{rank}.a{attempt}.out"), "wb")
            err_f = open(os.path.join(
                workdir, f"rank{rank}.a{attempt}.err"), "wb")
            outs.append(out_f)
            errs.append(err_f)
            procs.append(subprocess.Popen(argv, stdout=out_f, stderr=err_f,
                                          env=e))
        deadline = clock() + cluster_timeout_s
        failure = None
        while failure is None:
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                bad = [i for i, rc in enumerate(rcs) if rc != 0]
                failure = (f"rank(s) {bad} exited nonzero {rcs}"
                           if bad else "")
                break
            dead = [i for i, rc in enumerate(rcs)
                    if rc is not None and rc != 0]
            if dead:
                failure = f"rank(s) {dead} died mid-run (rc {rcs})"
                break
            if clock() > deadline:
                failure = (f"cluster deadline {cluster_timeout_s:.3g}s "
                           "exceeded (hung rank or livelocked "
                           "collective)")
                break
            now = time.time()
            stale = []
            for i in range(len(procs)):
                if rcs[i] is not None:
                    continue
                # Single stat, no exists()+getmtime() TOCTOU: the rank
                # process owns the file and a relaunch sweeps the
                # attempt dir, so it can vanish between the two calls —
                # the old two-step read crashed the supervisor exactly
                # when a rank died mid-poll (R7 audit).
                try:
                    mtime = os.path.getmtime(heartbeat_file(hb_dir, i))
                except OSError:
                    continue        # no beat yet (or swept): the
                    #                 cluster deadline covers it
                if now - mtime > hb_stale_s:
                    stale.append(i)
            if stale:
                failure = (f"heartbeat stale (> {hb_stale_s:.3g}s) for "
                           f"rank(s) {stale}")
                break
            time.sleep(poll_s)
        _kill_all(procs)
        for f in outs + errs:
            f.close()
        report["launches"].append({"attempt": attempt,
                                   "ok": failure == "",
                                   **({"failure": failure} if failure
                                      else {})})
        if failure:
            # Flight-recorder evidence for the post-mortem (no-op
            # without a telemetry session): which launch died and why.
            from dmlp_tpu.obs import telemetry
            telemetry.flight_event("supervise.launch_failed",
                                   attempt=attempt, reason=failure)
        if failure == "":
            with open(os.path.join(workdir, f"rank0.a{attempt}.out"),
                      "rb") as f:
                out_b = f.read()
            with open(os.path.join(workdir, f"rank0.a{attempt}.err"),
                      "rb") as f:
                err_b = f.read()
            return out_b, err_b, report
        if attempt + 1 < max_launches:
            stats.record_restart()
            from dmlp_tpu.obs import trace as obs_trace
            obs_trace.instant("resilience.restart", attempt=attempt,
                              reason=failure)

    if fallback is None:
        raise ClusterFailure(report)
    stats.record_degradation("cluster", "single-process")
    from dmlp_tpu.obs import trace as obs_trace
    obs_trace.instant("resilience.fallback", to="single-process")
    report["fallback"] = True
    out_b, err_b = fallback()
    return out_b, err_b, report
