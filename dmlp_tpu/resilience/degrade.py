"""Graceful-degradation ladder for the extract/solve path.

On device memory exhaustion (simulated RESOURCE_EXHAUSTED from the
fault injector, or a real XLA OOM — resilience.retry.classify treats
them identically) the single-chip solve steps DOWN a ladder instead of
crashing, and every rung preserves the contract checksums exactly:

1. ``lowp``       — the normal path: the bound-based pruned two-stage
                    solve COMPOSED with the low-precision first pass
                    (``config.precision``/``$DMLP_TPU_PRECISION``
                    resolving to "bf16"): one MXU pass per tile
                    instead of HIGHEST-precision f32's ~3, candidate
                    windows and every prune/gate threshold widened by
                    the analytic ``engine.finalize.lowp_eps`` bound.
                    With precision resolving to "f32" (the default and
                    the ``DMLP_TPU_PRECISION=f32`` kill switch) this
                    rung is exactly the pruned solve — the kill switch
                    pins the precision without consuming a ladder
                    step. An OOM steps down to the f32 first pass (a
                    bf16-inflated candidate window is the first
                    allocation to give back).
2. ``prune``      — the bound-based pruned two-stage solve
                    (ops.summaries) over the fused megakernel at f32 —
                    only survivor blocks are staged/folded. The
                    ``DMLP_TPU_PRUNE=0`` kill switch pins this rung to
                    the dense fused solve without consuming a ladder
                    step.
3. ``fused``      — the dense scan on the fused distance→top-k
                    streaming megakernel (ops.pallas_fused) where its
                    supports() holds, two-pass extraction otherwise.
                    The ``DMLP_TPU_FUSED=0`` kill switch (mirroring
                    ``DMLP_TPU_RESILIENCE``) pins this rung to the
                    two-pass kernel without consuming a ladder step.
4. ``tuned``      — the two-pass extraction kernel with the autotuner's
                    cached variant (dmlp_tpu.tune): the fused kernel's
                    (identical-size, but separately-tuned) tiles are
                    the first thing to give back on a fused-path OOM.
5. ``heuristic``  — the extraction kernel with the heuristic variant
                    (tune-cache lookups suppressed): a swept variant's
                    larger tiles are the next allocation to give back;
                    results are bit-identical by the PR 3 contract.
6. ``streaming``  — the chunked multipass streaming fold
                    (engine.single._solve_pipelined): no running-list
                    kernel state, the live tile shrinks to one
                    (query_block x chunk) slab.
7. ``host``       — the float64 golden solve on the host
                    (golden.fast.knn_golden_fast): zero device memory;
                    it IS the oracle the contract diffs against, so
                    byte-identity is by construction.

Each step records a ``resilience.degrade`` trace event and a stats
degradation entry, so the ledger and chaos harness can see recovery
happen (and measure what it cost).
"""

from __future__ import annotations

import contextlib
from typing import Callable, List

from dmlp_tpu.resilience import stats
from dmlp_tpu.resilience.retry import classify, resilience_enabled

RUNGS = ("lowp", "prune", "fused", "tuned", "heuristic", "streaming",
         "host")


@contextlib.contextmanager
def _rung_context(engine, rung: str):
    """Configure the engine for one rung. ``_degrade_rung`` is consulted
    by engine.single._solve/_solve_segments (``streaming`` skips every
    extract-kernel path; the top ``lowp``/``prune`` rungs may run the
    bound-based scan pruning, and only ``lowp`` may run the bf16 first
    pass) and by ops.pallas_fused.resolve_topk_kernel (the ``lowp``/
    ``prune``/``fused`` rungs may dispatch the fused megakernel);
    ``heuristic`` suppresses autotuner cache lookups for the
    duration."""
    prev = getattr(engine, "_degrade_rung", "fused")
    engine._degrade_rung = rung
    # Live rung gauge: numeric ladder position (0 = lowp ... 6 = host)
    # so a scrape mid-incident sees WHERE the solve currently sits.
    from dmlp_tpu.obs import telemetry
    telemetry.registry().gauge("resilience.degrade_rung").set(
        RUNGS.index(rung))
    try:
        if rung == "heuristic":
            from dmlp_tpu.tune import cache as tune_cache
            with tune_cache.suppressed():
                yield
        else:
            yield
    finally:
        engine._degrade_rung = prev


def _host_fallback(inp) -> List:
    """Rung 4: the float64 host oracle (exact by construction)."""
    from dmlp_tpu.golden.fast import knn_golden_fast
    from dmlp_tpu.obs.trace import span as obs_span
    with obs_span("resilience.host_fallback",
                  nq=inp.params.num_queries, n=inp.params.num_data):
        return knn_golden_fast(inp)


def run_ladder(engine, inp, solve: Callable):
    """Run ``solve(inp)`` (normally ``engine._run``), stepping down the
    ladder on each OOM-class failure; the last rung needs no device
    memory at all. Non-OOM errors propagate unchanged — the ladder
    trades capacity, it does not paper over bugs.

    ``DMLP_TPU_RESILIENCE=0`` disables the LADDER (no step-downs), not
    the top rung's feature set: the solve still runs at RUNGS[0], so
    the low-precision first pass and the pruned two-stage solve keep
    their own kill switches (``DMLP_TPU_PRECISION``/``DMLP_TPU_PRUNE``)
    instead of silently riding the resilience one — the chaos overhead
    A/B's resilience-off arm must differ from the on arm by the
    wrappers only."""
    if not resilience_enabled():
        engine.last_degrade_rung = RUNGS[0]
        with _rung_context(engine, RUNGS[0]):
            return solve(inp)
    engine.last_degrade_rung = RUNGS[0]
    for i, rung in enumerate(RUNGS):
        try:
            engine.last_degrade_rung = rung
            if rung == "host":
                return _host_fallback(inp)
            with _rung_context(engine, rung):
                return solve(inp)
        except Exception as e:
            if classify(e) != "oom" or i + 1 >= len(RUNGS):
                raise
            nxt = RUNGS[i + 1]
            stats.record_degradation(rung, nxt)
            from dmlp_tpu.obs import trace as obs_trace
            # The instant also lands in the flight recorder when a
            # telemetry session is active (obs.trace instant observer).
            obs_trace.instant("resilience.degrade", frm=rung, to=nxt,
                              error=str(e)[:200])
    raise AssertionError("unreachable: the host rung returns or raises")
