"""Multi-host launcher: the TPU-native ``mpirun ./engine < input``.

One process per host (run_bench.sh:82-84's mpirun analog), each executing::

    python -m dmlp_tpu.distributed --input FILE \
        [--coordinator HOST:PORT --processes N --process-id I | --auto]
        [--mode sharded|ring] [--mesh R,C] [--select ...] [--warmup]

Flow per process (parallel.distributed.distributed_contract_run):
``initialize()`` (the MPI_Init analog) -> sharded file read (each process
parses only the rows its mesh devices own — no rank-0 ingest,
cf. common.cpp:93-117) -> per-shard device top-k -> distributed float64
rescore on the shard-owning process -> host all-gather of the small
candidate tensors -> merge/vote/report; process 0 prints the canonical
``Query i checksum: c`` stdout in query order and the ``Time taken: <ms>
ms`` stderr contract line (common.cpp:70,130).

Managed environments (Cloud TPU pods, SLURM) use ``--auto`` and JAX
self-detects topology; explicit coordinator flags mirror mpirun's
rank/size for manual or test launches.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from dmlp_tpu.config import EngineConfig


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="dmlp_tpu.distributed",
                                description=__doc__)
    p.add_argument("--input", required=True,
                   help="input file (every process reads its own slice — "
                        "stdin cannot be sharded)")
    p.add_argument("--mode", default="sharded", choices=["sharded", "ring"])
    p.add_argument("--mesh", default=None, help="R,C (data x query axes); "
                   "default auto-factorizes all devices")
    p.add_argument("--select", default="auto",
                   choices=["auto", "sort", "topk", "seg", "extract"])
    p.add_argument("--data-block", type=int, default=None)
    p.add_argument("--pallas", action="store_true")
    p.add_argument("--debug", action="store_true")
    p.add_argument("--warmup", action="store_true",
                   help="run the solve once untimed first (exclude XLA "
                        "compile from the contract timing)")
    p.add_argument("--coordinator", default=None, help="HOST:PORT of "
                   "process 0 (jax.distributed coordinator)")
    p.add_argument("--processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--auto", action="store_true",
                   help="let jax.distributed self-detect topology")
    p.add_argument("--trace", metavar="DIR", default=None,
                   help="per-rank cluster tracing: every rank writes "
                        "DIR/trace-rank<NN>.json (distinct Perfetto pid "
                        "per rank); merge with tools/merge_traces.py")
    args = p.parse_args(argv)

    from dmlp_tpu.parallel.distributed import (distributed_contract_run,
                                               initialize)
    initialize(coordinator_address=args.coordinator,
               num_processes=args.processes, process_id=args.process_id,
               auto=args.auto)

    tracer = None
    if args.trace:
        import os

        import jax

        from dmlp_tpu.obs import dist_trace
        # Rank identity comes from the cluster runtime; the env override
        # lets single-process runs emulate a rank of a larger trace set
        # (used by tools/obs_dist_smoke.py on jax builds whose CPU
        # backend cannot run multi-process computations at all).
        rank = int(os.environ.get("DMLP_TPU_TRACE_RANK",
                                  jax.process_index()))
        nranks = int(os.environ.get("DMLP_TPU_TRACE_RANKS",
                                    jax.process_count()))
        tracer = dist_trace.install(args.trace, rank, nranks)

    from dmlp_tpu.cli import make_engine, parse_mesh_arg
    mesh_shape = parse_mesh_arg(p, args.mesh)
    config = EngineConfig(mode=args.mode, mesh_shape=mesh_shape,
                          select=args.select, data_block=args.data_block,
                          use_pallas=args.pallas, debug=args.debug)
    engine = make_engine(config)
    if tracer is not None:
        tracer.record_mesh(engine.mesh)

    # stdout is the results channel (checksums only — the grader diffs it,
    # survey §4); Gloo's C++ collectives print connection banners straight
    # to fd 1, so fd 1 points at stderr for the whole solve and the real
    # stdout is restored only for the final canonical report.
    import io
    import os
    real_stdout = os.dup(1)
    os.dup2(2, 1)
    buf = io.StringIO()
    try:
        distributed_contract_run(args.input, engine, out=buf,
                                 warmup=args.warmup)
    finally:
        sys.stdout.flush()
        os.dup2(real_stdout, 1)
        os.close(real_stdout)
        if tracer is not None:
            # Per-rank file + uninstall AFTER the contract run: the trace
            # write is filesystem-only, so the stdout/stderr contract
            # channels stay byte-identical with tracing enabled.
            from dmlp_tpu.obs import trace as obs_trace
            try:
                tracer.write_rank_file(args.trace)
            finally:
                obs_trace.uninstall()
    sys.stdout.write(buf.getvalue())
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
