"""Multi-host launcher: the TPU-native ``mpirun ./engine < input``.

One process per host (run_bench.sh:82-84's mpirun analog), each executing::

    python -m dmlp_tpu.distributed --input FILE \
        [--coordinator HOST:PORT --processes N --process-id I | --auto]
        [--mode sharded|ring] [--mesh R,C] [--select ...] [--warmup]

Flow per process (parallel.distributed.distributed_contract_run):
``initialize()`` (the MPI_Init analog) -> sharded file read (each process
parses only the rows its mesh devices own — no rank-0 ingest,
cf. common.cpp:93-117) -> per-shard device top-k -> distributed float64
rescore on the shard-owning process -> host all-gather of the small
candidate tensors -> merge/vote/report; process 0 prints the canonical
``Query i checksum: c`` stdout in query order and the ``Time taken: <ms>
ms`` stderr contract line (common.cpp:70,130).

Managed environments (Cloud TPU pods, SLURM) use ``--auto`` and JAX
self-detects topology; explicit coordinator flags mirror mpirun's
rank/size for manual or test launches.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from dmlp_tpu.config import EngineConfig


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="dmlp_tpu.distributed",
                                description=__doc__)
    p.add_argument("--input", required=True,
                   help="input file (every process reads its own slice — "
                        "stdin cannot be sharded)")
    p.add_argument("--mode", default="sharded", choices=["sharded", "ring"])
    p.add_argument("--mesh", default=None, help="R,C (data x query axes); "
                   "default auto-factorizes all devices")
    p.add_argument("--select", default="auto",
                   choices=["auto", "sort", "topk", "seg", "extract"])
    p.add_argument("--data-block", type=int, default=None)
    p.add_argument("--pallas", action="store_true")
    p.add_argument("--debug", action="store_true")
    p.add_argument("--warmup", action="store_true",
                   help="run the solve once untimed first (exclude XLA "
                        "compile from the contract timing)")
    p.add_argument("--coordinator", default=None, help="HOST:PORT of "
                   "process 0 (jax.distributed coordinator)")
    p.add_argument("--processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--auto", action="store_true",
                   help="let jax.distributed self-detect topology")
    p.add_argument("--trace", metavar="DIR", default=None,
                   help="per-rank cluster tracing: every rank writes "
                        "DIR/trace-rank<NN>.json (distinct Perfetto pid "
                        "per rank); merge with tools/merge_traces.py")
    p.add_argument("--faults", metavar="FILE", default=None,
                   help="deterministic fault-injection schedule (JSON; "
                        "dmlp_tpu.resilience.inject); $DMLP_TPU_FAULTS "
                        "sets it too")
    p.add_argument("--telemetry", metavar="FILE", default=None,
                   help="per-rank live telemetry (obs.telemetry): "
                        "OpenMetrics snapshot rewrite of FILE "
                        "(.rankNN-suffixed when processes > 1, like "
                        "$DMLP_TPU_FAULT_LOG) + crash flight recorder")
    p.add_argument("--supervise", type=int, default=None, metavar="N",
                   help="launcher mode: spawn N rank processes of this "
                        "entry under heartbeat + timeout supervision "
                        "(resilience.supervise) — a dead or hung rank "
                        "kills and relaunches the cluster (bounded), "
                        "then falls back to a degraded single-process "
                        "solve with identical contract checksums")
    p.add_argument("--supervise-timeout", type=float, default=300.0,
                   help="cluster deadline per supervised launch (s)")
    p.add_argument("--supervise-dir", default=None,
                   help="supervisor workdir for rank logs + heartbeat "
                        "files (default: a temp dir)")
    p.add_argument("--max-launches", type=int, default=2,
                   help="supervised cluster launches before degrading "
                        "to the single-process fallback")
    args = p.parse_args(argv)

    if args.supervise is not None:
        return _run_supervisor(args)

    # Supervised ranks carry $DMLP_TPU_HEARTBEAT; beat so the
    # supervisor can tell crashed/frozen from merely slow.
    from dmlp_tpu.resilience.supervise import maybe_start_heartbeat_from_env
    maybe_start_heartbeat_from_env()
    from dmlp_tpu.resilience import inject as rs_inject
    schedule = rs_inject.install_from_env(args.faults)

    from dmlp_tpu.parallel.distributed import (distributed_contract_run,
                                               initialize)
    initialize(coordinator_address=args.coordinator,
               num_processes=args.processes, process_id=args.process_id,
               auto=args.auto)

    telemetry_session = None
    if args.telemetry:
        # One telemetry file per process (ranks share the argv), same
        # suffix convention as the fault log below. The sampler's
        # heartbeat.age_s gauge reads the supervisor's
        # $DMLP_TPU_HEARTBEAT file when one is set. Started strictly
        # AFTER initialize(): the sampler polls jax.devices() once jax
        # is imported, and a tick landing before distributed init
        # would initialize the local single-process backend first.
        tpath = args.telemetry
        if (args.processes or 1) > 1:
            tpath += f".rank{args.process_id or 0:02d}"
        from dmlp_tpu.obs import telemetry
        telemetry_session = telemetry.start(path=tpath)

    tracer = None
    if args.trace:
        import os

        import jax

        from dmlp_tpu.obs import dist_trace
        # Rank identity comes from the cluster runtime; the env override
        # lets single-process runs emulate a rank of a larger trace set
        # (used by tools/obs_dist_smoke.py on jax builds whose CPU
        # backend cannot run multi-process computations at all).
        rank = int(os.environ.get("DMLP_TPU_TRACE_RANK",
                                  jax.process_index()))
        nranks = int(os.environ.get("DMLP_TPU_TRACE_RANKS",
                                    jax.process_count()))
        tracer = dist_trace.install(args.trace, rank, nranks)

    from dmlp_tpu.cli import make_engine, parse_mesh_arg
    mesh_shape = parse_mesh_arg(p, args.mesh)
    config = EngineConfig(mode=args.mode, mesh_shape=mesh_shape,
                          select=args.select, data_block=args.data_block,
                          use_pallas=args.pallas, debug=args.debug)
    engine = make_engine(config)
    if tracer is not None:
        tracer.record_mesh(engine.mesh)

    # stdout is the results channel (checksums only — the grader diffs it,
    # survey §4); Gloo's C++ collectives print connection banners straight
    # to fd 1, so fd 1 points at stderr for the whole solve and the real
    # stdout is restored only for the final canonical report.
    import io
    import os
    real_stdout = os.dup(1)
    os.dup2(2, 1)
    buf = io.StringIO()
    try:
        distributed_contract_run(args.input, engine, out=buf,
                                 warmup=args.warmup)
    except Exception:
        if telemetry_session is not None:
            # The dying rank's own post-mortem: the parent supervisor
            # only sees launch_failed; the ring buffer lives here.
            from dmlp_tpu.obs import telemetry
            telemetry.dump_on_crash("crash")
        raise
    finally:
        sys.stdout.flush()
        os.dup2(real_stdout, 1)
        os.close(real_stdout)
        if tracer is not None:
            # Per-rank file + uninstall AFTER the contract run: the trace
            # write is filesystem-only, so the stdout/stderr contract
            # channels stay byte-identical with tracing enabled.
            from dmlp_tpu.obs import trace as obs_trace
            try:
                tracer.write_rank_file(args.trace)
            finally:
                obs_trace.uninstall()
        if schedule is not None:
            # $DMLP_TPU_FAULT_LOG determinism probe, per rank (ranks
            # share the env, so multi-process runs suffix the path —
            # one injection log per process, no last-writer-wins).
            log_path = os.environ.get("DMLP_TPU_FAULT_LOG")
            if log_path and (args.processes or 1) > 1:
                log_path += f".rank{args.process_id or 0:02d}"
            if log_path:
                schedule.write_log(log_path)
            rs_inject.uninstall()
        if telemetry_session is not None:
            telemetry_session.close()
    sys.stdout.write(buf.getvalue())
    sys.stdout.flush()
    return 0


def _run_supervisor(args) -> int:
    """Launcher mode (``--supervise N``): build per-rank argvs of this
    same entry (fresh coordinator port per attempt), run them under the
    heartbeat/timeout supervision loop, and degrade to an in-process
    single-process contract solve when every launch fails — the output
    checksums are identical either way (that is the whole engine
    contract), so a supervised run survives a broken cluster runtime
    visibly but correctly."""
    import io
    import socket
    import tempfile

    workdir = args.supervise_dir or tempfile.mkdtemp(prefix="dmlp-sup-")
    base = [sys.executable, "-m", "dmlp_tpu.distributed",
            "--input", args.input, "--mode", args.mode,
            "--select", args.select]
    if args.mesh:
        base += ["--mesh", args.mesh]
    if args.data_block is not None:
        base += ["--data-block", str(args.data_block)]
    for flag, on in (("--pallas", args.pallas), ("--debug", args.debug),
                     ("--warmup", args.warmup)):
        if on:
            base.append(flag)
    if args.trace:
        base += ["--trace", args.trace]
    if args.faults:
        base += ["--faults", args.faults]
    if args.telemetry:
        base += ["--telemetry", args.telemetry]

    def make_cluster(attempt: int):
        # NOTE: same probe-then-rebind TOCTOU window as the bench
        # harness's multiproc launcher; a lost port surfaces as a failed
        # launch and the supervisor's relaunch is the retry.
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        return [base + ["--coordinator", f"localhost:{port}",
                        "--processes", str(args.supervise),
                        "--process-id", str(rank)]
                for rank in range(args.supervise)]

    def fallback():
        from dmlp_tpu.cli import make_engine, parse_mesh_arg
        from dmlp_tpu.parallel.distributed import distributed_contract_run
        config = EngineConfig(mode=args.mode,
                              mesh_shape=parse_mesh_arg(
                                  argparse.ArgumentParser(), args.mesh),
                              select=args.select,
                              data_block=args.data_block,
                              use_pallas=args.pallas, debug=args.debug)
        engine = make_engine(config)
        out, err = io.StringIO(), io.StringIO()
        distributed_contract_run(args.input, engine, out=out, err=err,
                                 warmup=args.warmup)
        return out.getvalue().encode(), err.getvalue().encode()

    from dmlp_tpu.resilience.supervise import run_supervised
    out_b, err_b, report = run_supervised(
        make_cluster, workdir,
        cluster_timeout_s=args.supervise_timeout,
        max_launches=args.max_launches, fallback=fallback)
    for launch in report["launches"]:
        if launch.get("failure"):
            sys.stderr.write(f"supervise: launch {launch['attempt']} "
                             f"failed: {launch['failure']}\n")
    if report["fallback"]:
        sys.stderr.write("supervise: degraded to single-process "
                         "fallback (checksums unchanged)\n")
    sys.stdout.buffer.write(out_b)
    sys.stdout.flush()
    sys.stderr.write(err_b.decode())
    return 0


if __name__ == "__main__":
    sys.exit(main())
