"""Ring-streaming engine (CLI registry home; implementation in sharded.py,
which both mesh engines share — they differ only in the cross-shard merge:
all-gather vs merge-top-k ring all-reduce)."""

from dmlp_tpu.engine.sharded import RingEngine  # noqa: F401
