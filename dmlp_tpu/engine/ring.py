"""Ring-streaming engine (CLI registry home; implementation in sharded.py,
which both mesh engines share — they differ only in the cross-shard merge:
all-gather vs merge-top-k ring all-reduce).

Observability rides the shared implementation too: the ring merge's
``ppermute`` traffic is accounted per solve in ``engine.last_comms``
(obs.comms.ring_topk_traffic — R-1 hops of the O(k) accumulator; same
per-device wire bytes as the all-gather, O(k) instead of O(R*k) peak
memory), and the phase spans / cost-counter hooks land under the same
``sharded.*`` trace names.
"""

from dmlp_tpu.engine.sharded import RingEngine  # noqa: F401
