"""2D-mesh sharded KNN engine (survey §7 L2) — the reference grid, declarative.

The reference's distribution phases P1-P3 (grid build + rank-0 Scatterv +
axis Bcasts, engine.cpp:40-209) collapse into sharding annotations: the
dataset is placed with ``P("data", None)`` (sharded over mesh rows,
replicated over columns) and the queries with ``P("query", None)`` — XLA
materializes the movement, and there is no rank-0 ingest bottleneck (each
process would feed its own shard in multi-host, see
dmlp_tpu.parallel.distributed).

Per-(row, col) cell, ``shard_map`` runs the same streaming distance+top-k
the single-chip engine uses on its (data-shard x query-shard) tile — the
analog of the reference's local hot loop (engine.cpp:233-257) — then merges
across the ``"data"`` axis either by all-gather (engine.cpp:282-308 analog)
or by a ring all-reduce with merge-top-k as combiner (O(k) memory, the
long-context pattern; dmlp_tpu.parallel.collectives).

Uneven shards are pad-to-multiple + sentinel masking (replacing the
remainder arithmetic at engine.cpp:62-63,136-137).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.finalize import (boundary_overflow, finalize_host,
                                      lowp_eps, repair_boundary_overflow,
                                      staging_eps)
from dmlp_tpu.engine.single import (ChunkThrottle, MeasuredIters,
                                    fit_blocks, flush_measured_iters,
                                    pad_dataset, resilient_get,
                                    resolve_kcap, round_up)
from dmlp_tpu.io.grammar import KNNInput
from dmlp_tpu.io.report import QueryResult
from dmlp_tpu.obs import counters as obs_counters
from dmlp_tpu.obs import memwatch, telemetry
from dmlp_tpu.obs.comms import engine_comms
from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.ops.topk import TopK, select_topk, streaming_topk
from dmlp_tpu.parallel.collectives import allgather_merge_topk, ring_allreduce_topk
from dmlp_tpu.parallel.mesh import DATA_AXIS, QUERY_AXIS, make_mesh
from dmlp_tpu.resilience import inject as rs_inject
from dmlp_tpu.resilience import retry as rs_retry
from dmlp_tpu.utils.compat import shard_map


def _chunk_span(sc, ck: int):
    """This shard's (id_base, n_real) for one staged chunk, inside a
    shard_map cell. ``sc = [n, toff, shard_rows]``. Caps real rows at BOTH
    the dataset end and this shard's boundary: plan_chunks may overshoot
    (nchunks * chunk_rows > shard_rows), and an uncapped tail would
    re-fold the next shard's first rows — duplicate candidates after the
    merge. Shared by the extract and outlier chunk folds so the cap can
    never desynchronize between them."""
    rr = jax.lax.axis_index(DATA_AXIS)
    id_base = rr * sc[2] + sc[1]
    n_real = jnp.clip(jnp.minimum(sc[0] - id_base, sc[2] - sc[1]), 0, ck)
    return id_base, n_real


def _np_staging_dtype(staging: str):
    """Host wire dtype for the engine's CURRENT staging state. Staging
    sites must read this (via ShardedEngine._np_dtype), never re-resolve
    the config (config.resolve_dtype): that maps dtype="auto" back to
    bfloat16 on TPU even while no_auto_coarsen has swapped the engine to
    float32 for a device-full run, which would silently stage bf16 under
    a float32 ordering contract."""
    if staging == "bfloat16":
        import ml_dtypes
        return ml_dtypes.bfloat16
    return np.float32


def _labels_for_ids(ids, lab_g):
    """Gather labels for global ids (-1 stays -1) from the replicated
    label vector — shared by the chunk merge and the outlier fold."""
    nl = lab_g.shape[0]
    return jnp.where(ids >= 0, lab_g[jnp.clip(ids, 0, max(nl - 1, 0))], -1)


class ShardedEngine:
    """All-gather-merge engine over a 2D ("data", "query") mesh."""

    _merge_strategy = "allgather"

    def __init__(self, config: EngineConfig = EngineConfig(mode="sharded"),
                 mesh: Optional[Mesh] = None):
        self.config = config
        self.mesh = mesh if mesh is not None else make_mesh(config.mesh_shape)
        self._staging = config.resolve_dtype()
        self._dtype = (jnp.bfloat16 if self._staging == "bfloat16"
                       else jnp.float32)
        self._fns: Dict[Tuple, object] = {}  # compiled-program cache
        self.last_phase_ms: Dict[str, float] = {}
        self.last_hetk = None  # (bulk, outlier) counts when routing split
        self.last_comms: list = []  # obs.comms traffic of the last solve
        # Which kernel the last extract-select solve baked into its mesh
        # programs ("fused" | "extract" | None) — artifacts report it.
        self.last_extract_impl = None
        # (site, device iters-sum scalar, shape) queue for the measured
        # extraction term — same protocol as engine.single (the mesh
        # programs return per-shard kernel iters through their fold
        # outputs; engine.single.flush_measured_iters drains post-fence)
        self._pending_iters: list = []
        # Analytic per-device peak-HBM model of the last solve
        # (obs.memwatch); populated only under a telemetry session.
        self.last_mem_model = None
        # Pruned two-stage solve accounting (ops.summaries.note_scan);
        # None until a staging path runs.
        self.last_prune = None
        # First-pass precision record of the last solve ({"active",
        # "configured"}); None until _solve_segments runs. The mesh
        # engines have no resilience ladder, so active == configured.
        self.last_precision = None

    def _np_dtype(self):
        """Wire dtype from the engine's (possibly no_auto_coarsen-swapped)
        staging state — see _np_staging_dtype."""
        return _np_staging_dtype(self._staging)

    # -- sharded placement ---------------------------------------------------
    def _shard_inputs(self, inp: KNNInput, data_block: int, qgran: int = 8):
        import time as _time
        t0 = _time.perf_counter()
        with obs_span("sharded.stage_enqueue",
                      mesh=list(self.mesh.devices.shape)):
            out = self._shard_inputs_inner(inp, data_block, qgran)
        # Host-side staging enqueue (pad + convert + async device_put) —
        # transfer wait lands in "fetch" like the other enqueue phases.
        self.last_phase_ms["stage_enqueue"] = \
            (_time.perf_counter() - t0) * 1e3
        # Monolithic staging is by definition a dense scan; record it so
        # the scanned-bytes series covers every path (ops.summaries).
        from dmlp_tpu.ops.summaries import note_scan
        dense = inp.params.num_data * inp.params.num_attrs \
            * np.dtype(self._np_dtype()).itemsize
        note_scan(self, scanned_bytes=dense, dense_bytes=dense,
                  blocks_total=self.mesh.devices.shape[0],
                  blocks_pruned=0)
        return out

    def _shard_inputs_inner(self, inp: KNNInput, data_block: int,
                            qgran: int = 8):
        r, c = self.mesh.devices.shape
        q = inp.params.num_queries
        na = inp.params.num_attrs
        # r * round_up(ceil(n/r), b) == round_up(n, r*b), so the per-shard
        # row count divides data_block as streaming_topk requires.
        attrs, labels, ids = pad_dataset(inp, r * data_block, np.float32)
        qpad = c * round_up(max(-(-q // c), 1), qgran)
        q_attrs = np.zeros((qpad, na), np.float32); q_attrs[:q] = inp.query_attrs

        dsh = NamedSharding(self.mesh, P(DATA_AXIS, None))
        dsh1 = NamedSharding(self.mesh, P(DATA_AXIS))
        qsh = NamedSharding(self.mesh, P(QUERY_AXIS, None))
        # One-hop staging: device_put with the target sharding directly.
        # jnp.asarray first would land the full array on the default device
        # and reshard from there — a second full copy, and on a tunneled
        # host link a second full transfer.
        np_dtype = self._np_dtype()
        return (jax.device_put(attrs.astype(np_dtype, copy=False), dsh),
                jax.device_put(labels, dsh1),
                jax.device_put(ids, dsh1),
                jax.device_put(q_attrs.astype(np_dtype, copy=False), qsh))

    def _extract_impl(self, select: str, qb: int, b: int, a: int,
                      k: int) -> str:
        """Which top-k kernel ("fused" | "extract") the mesh programs
        bake in for this per-cell dispatch shape — resolved HERE, on the
        host, OUTSIDE every jitted program (lint R203), and threaded by
        the callers into the ``_fns`` cache key of any compiled program
        that bakes the choice in: the fused/two-pass selection is part
        of the compiled-program cache key by construction (flipping
        $DMLP_TPU_FUSED mid-process compiles the other program instead
        of silently replaying the stale one). Non-extract selects pin
        the default label without consulting the resolver (one guard
        here instead of one per call site)."""
        if select != "extract":
            return "extract"
        from dmlp_tpu.ops.pallas_fused import resolve_topk_kernel
        _, impl = resolve_topk_kernel(
            qb, b, a, k, rung=getattr(self, "_degrade_rung", "fused"))
        impl = impl or "extract"  # plan already validated ex_supports
        self.last_extract_impl = impl
        return impl

    # -- the compiled sharded program ---------------------------------------
    def _solve_shard_fn(self, k: int, data_block: int, select: str,
                        impl: str = "extract", precision: str = "f32"):
        """Per-cell solver closure: the flagship fused/extraction kernel
        when the plan selected it (its SMEM runtime scalars make the
        per-shard id_base/n_real traced values, so one compiled kernel
        serves every shard), the streaming fold otherwise. ``impl``
        ("fused" | "extract", from _extract_impl) picks which kernel an
        extract-select program dispatches — the caller must key its
        compiled-program cache on it. Returns (TopK, iters)
        where ``iters`` is this cell's summed kernel loop-iteration
        count as a (1, 1) i32 — the per-shard extract iters previously
        trapped inside the shard_map program, now threaded through the
        fold outputs so the mesh engines can report the MEASURED
        extraction term (the streaming selects have no such loop and
        return 0). Lists are possibly UNSORTED — both merges re-select
        with the composite sort."""
        if select == "extract":
            from dmlp_tpu.ops.pallas_distance import native_pallas_backend
            from dmlp_tpu.ops.pallas_extract import extract_topk
            from dmlp_tpu.ops.pallas_fused import fused_topk
            kern = fused_topk if impl == "fused" else extract_topk
            interpret = not native_pallas_backend()

            def solve_shard(data_a, data_l, data_i, q_attrs):
                sr = data_a.shape[0]
                # Shards hold contiguous global rows with sentinel tails
                # (pad_dataset / padded_shard), so ids are affine per
                # shard: base from the first id, count from the mask.
                nreal = jnp.sum((data_i >= 0).astype(jnp.int32))
                base = jnp.maximum(data_i[0], 0)
                od, oi, its = kern(q_attrs, data_a, n_real=nreal,
                                   id_base=base, kc=k,
                                   interpret=interpret,
                                   precision=precision)
                lab = jnp.where(
                    oi >= 0, data_l[jnp.clip(oi - base, 0, sr - 1)], -1)
                return TopK(od, lab, oi), \
                    jnp.sum(its, dtype=jnp.int32)[None, None]
            return solve_shard

        use_pallas = self.config.use_pallas

        def solve_shard(data_a, data_l, data_i, q_attrs):
            top = streaming_topk(q_attrs, data_a, data_l, data_i,
                                 k=k, data_block=data_block,
                                 select=select, use_pallas=use_pallas)
            return top, jnp.zeros((1, 1), jnp.int32)
        return solve_shard

    def _fn(self, k: int, data_block: int, select: str,
            impl: str = "extract", precision: str = "f32"):
        # ``precision`` (the first-pass dot dtype, resolved OUTSIDE the
        # jit like impl) keys every compiled program that bakes a
        # kernel dispatch in — R2 discipline, same contract as impl.
        key = (k, data_block, select, impl, precision)
        if key not in self._fns:
            merge = self._merge_strategy
            solve_shard = self._solve_shard_fn(k, data_block, select, impl,
                                               precision)
            if merge == "gspmd":
                # Compiler-scheduled merged program (merge="auto"): the
                # same per-shard fold vmapped over a data-sharded 3D
                # view, merge point spelled as a data->query reshard
                # constraint instead of an explicit collective (mirrors
                # engine.auto._fn_auto; _plan_shard never plans
                # "extract" here, so solve_shard is a streaming fold).
                mesh = self.mesh
                r, c = mesh.devices.shape
                d3 = NamedSharding(mesh, P(DATA_AXIS, None, None))
                d2 = NamedSharding(mesh, P(DATA_AXIS, None))
                d1 = NamedSharding(mesh, P(DATA_AXIS))
                qsh = NamedSharding(mesh, P(QUERY_AXIS, None))
                ish = NamedSharding(mesh, P(DATA_AXIS, QUERY_AXIS))

                def merged(data_a, data_l, data_i, q_attrs):
                    sr = data_a.shape[0] // r
                    a3 = jax.lax.with_sharding_constraint(
                        data_a.reshape(r, sr, data_a.shape[1]), d3)
                    l2 = jax.lax.with_sharding_constraint(
                        data_l.reshape(r, sr), d2)
                    i2 = jax.lax.with_sharding_constraint(
                        data_i.reshape(r, sr), d2)
                    tops, its = jax.vmap(
                        lambda a, lab, ids: solve_shard(
                            a, lab, ids, q_attrs))(a3, l2, i2)
                    qpad = q_attrs.shape[0]
                    md = jnp.moveaxis(tops.dists, 0, 1).reshape(qpad, -1)
                    ml = jnp.moveaxis(tops.labels, 0, 1).reshape(qpad, -1)
                    mi = jnp.moveaxis(tops.ids, 0, 1).reshape(qpad, -1)
                    md = jax.lax.with_sharding_constraint(md, qsh)
                    ml = jax.lax.with_sharding_constraint(ml, qsh)
                    mi = jax.lax.with_sharding_constraint(mi, qsh)
                    top = select_topk(md, ml, mi, k)
                    # (R, C) iters matching the shard_map out_spec shape;
                    # streaming folds report zero, so the column
                    # replication cannot overcount a measured term.
                    its_rc = jnp.broadcast_to(its.reshape(r, 1), (r, c))
                    return top, jax.lax.with_sharding_constraint(
                        its_rc, ish)

                self._fns[key] = jax.jit(
                    merged, in_shardings=(d2, d1, d1, qsh),
                    out_shardings=(TopK(qsh, qsh, qsh), ish))
                return self._fns[key]

            def local(data_a, data_l, data_i, q_attrs):
                top, its = solve_shard(data_a, data_l, data_i, q_attrs)
                if merge == "allgather":
                    return allgather_merge_topk(top, k, DATA_AXIS), its
                return ring_allreduce_topk(top, k, DATA_AXIS), its

            sharded = shard_map(
                local, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                          P(QUERY_AXIS, None)),
                out_specs=(P(QUERY_AXIS, None),
                           P(DATA_AXIS, QUERY_AXIS)),
                check_vma=False)
            self._fns[key] = jax.jit(sharded)
        return self._fns[key]

    # -- public API ----------------------------------------------------------
    def _plan_local(self, inp: KNNInput):
        """(select, data_block, qgran, k) for the single-host merged path.
        Prefers the extraction kernel when the per-shard tiling supports
        it (per-cell queries then pad to whole QUERY_TILE tiles, like
        engine.single — an 8*prime count would degenerate to an 8-row
        tile), else the streaming select; explicit data_block pins
        streaming (the kernel chooses its own block sizes). The returned
        ``k`` is exactly the value the supports() gate validated."""
        cfg = self.config
        n = inp.params.num_data
        r, c = self.mesh.devices.shape
        kmax = int(inp.ks.max()) if inp.params.num_queries else 1
        shard_rows_est = round_up(max(-(-n // r), 1), 8)
        if cfg.data_block is None \
                and cfg.resolve_select(shard_rows_est) == "extract":
            from dmlp_tpu.ops.pallas_extract import QUERY_TILE
            from dmlp_tpu.ops.pallas_extract import supports as ex_supports
            sr = round_up(max(-(-n // r), 1),
                          cfg.resolve_granule("extract"))
            qb_local = round_up(max(-(-inp.params.num_queries // c), 1),
                                QUERY_TILE)
            k = resolve_kcap(cfg, kmax, "extract", sr * r,
                             staging=self._staging)
            if ex_supports(qb_local, sr, inp.params.num_attrs, k):
                return "extract", sr, QUERY_TILE, k
        select = cfg.resolve_streaming_select(shard_rows_est)
        if cfg.data_block is not None:
            data_block = min(cfg.data_block, shard_rows_est)
        else:
            data_block = fit_blocks(max(-(-n // r), 1),
                                    cfg.resolve_data_block(select),
                                    granule=cfg.resolve_granule(select))
        shard_rows = round_up(max(-(-n // r), 1), data_block)
        return select, data_block, 8, resolve_kcap(
            cfg, kmax, select, shard_rows * r, staging=self._staging)

    # -- pipelined chunked staging (VERDICT r3 item 1) -----------------------
    def _chunk_fold_fn(self, k: int, interpret: bool,
                       impl: str = "extract", precision: str = "f32"):
        """Per-chunk fold program: every (row, col) cell folds its slice of
        the staged chunk into its running (qloc, K) lists with the
        fused/extraction kernel (``impl``, resolved by _extract_impl
        OUTSIDE this jit and part of this cache key). ``sc = [n, toff,
        shard_rows]`` rides as traced
        scalars (the kernel takes them in SMEM), so ONE compiled program
        serves every chunk of every input at the same shapes."""
        key = ("chunkfold", k, interpret, impl, precision)
        if key not in self._fns:
            from dmlp_tpu.ops.pallas_extract import extract_topk
            from dmlp_tpu.ops.pallas_fused import fused_topk
            kern = fused_topk if impl == "fused" else extract_topk

            def local(cd, ci, chunk_a, q_attrs, sc, live):
                # ``live`` is the per-shard prune mask of this chunk
                # (P("data")-sharded, (1,) per cell): a pruned shard's
                # piece arrives zero-filled and folds with n_real = 0 —
                # every id masks to the sentinel, so the fold is a
                # provable no-op (each shard prunes locally before its
                # fold; the cross-shard merge is unchanged). Dense
                # solves pass all-ones.
                id_base, n_real = _chunk_span(sc, chunk_a.shape[0])
                n_real = jnp.where(live[0] > 0, n_real, 0)
                od, oi, its = kern(q_attrs, chunk_a, cd[0], ci[0],
                                   n_real=n_real, id_base=id_base,
                                   kc=k, interpret=interpret,
                                   precision=precision)
                # Per-cell summed kernel loop iterations ride out as a
                # third fold output ((R, C) after shard_map) so the
                # measured extraction term covers the mesh path too.
                return od[None], oi[None], \
                    jnp.sum(its, dtype=jnp.int32)[None, None]

            self._fns[key] = jax.jit(shard_map(
                local, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, QUERY_AXIS, None),
                          P(DATA_AXIS, QUERY_AXIS, None),
                          P(DATA_AXIS, None), P(QUERY_AXIS, None), P(),
                          P(DATA_AXIS)),
                out_specs=(P(DATA_AXIS, QUERY_AXIS, None),
                           P(DATA_AXIS, QUERY_AXIS, None),
                           P(DATA_AXIS, QUERY_AXIS)),
                check_vma=False))
        return self._fns[key]

    def _chunk_init_fn(self, r: int, qpad: int, k: int):
        key = ("chunkinit", r, qpad, k)
        if key not in self._fns:
            csh3 = NamedSharding(self.mesh, P(DATA_AXIS, QUERY_AXIS, None))
            self._fns[key] = jax.jit(
                lambda: (jnp.full((r, qpad, k), jnp.inf, jnp.float32),
                         jnp.full((r, qpad, k), -1, jnp.int32)),
                out_shardings=(csh3, csh3))
        return self._fns[key]

    def _chunk_merge_fn(self, k: int):
        """Cross-shard merge epilogue for the chunked driver: resolve
        labels from the replicated (tiny) labels array, then the engine's
        merge collective — which re-selects with the composite sort, so
        the kernel's unsorted lists come out selection-ordered."""
        key = ("chunkmerge", k, self._merge_strategy)
        if key not in self._fns:
            merge = self._merge_strategy
            if merge == "gspmd":
                # Compiler-scheduled variant (the auto engine's merge
                # point, reachable here through MeshResidentEngine
                # merge="auto"): collapse the shard axis into the
                # candidate axis and constrain the result onto the query
                # axis — GSPMD schedules the data->query reshard the
                # shard_map branch below spells out by hand. Same
                # composite re-select, so the selection order matches.
                csh3 = NamedSharding(self.mesh,
                                     P(DATA_AXIS, QUERY_AXIS, None))
                rsh = NamedSharding(self.mesh, P())
                qsh = NamedSharding(self.mesh, P(QUERY_AXIS, None))

                def merged(cd, ci, lab_g):
                    qpad = cd.shape[1]
                    md = jnp.moveaxis(cd, 0, 1).reshape(qpad, -1)
                    mi = jnp.moveaxis(ci, 0, 1).reshape(qpad, -1)
                    ml = _labels_for_ids(mi, lab_g)
                    md = jax.lax.with_sharding_constraint(md, qsh)
                    ml = jax.lax.with_sharding_constraint(ml, qsh)
                    mi = jax.lax.with_sharding_constraint(mi, qsh)
                    return select_topk(md, ml, mi, k)

                self._fns[key] = jax.jit(
                    merged, in_shardings=(csh3, csh3, rsh),
                    out_shardings=TopK(qsh, qsh, qsh))
                return self._fns[key]

            def local(cd, ci, lab_g):
                ids = ci[0]
                top = TopK(cd[0], _labels_for_ids(ids, lab_g), ids)
                if merge == "allgather":
                    return allgather_merge_topk(top, k, DATA_AXIS)
                return ring_allreduce_topk(top, k, DATA_AXIS)

            self._fns[key] = jax.jit(shard_map(
                local, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, QUERY_AXIS, None),
                          P(DATA_AXIS, QUERY_AXIS, None), P()),
                out_specs=P(QUERY_AXIS, None),
                check_vma=False))
        return self._fns[key]

    # -- heterogeneous-k outlier programs (mesh form of single's router) ----
    def _outlier_init_fn(self, r: int, qo_pad: int, ko: int):
        key = ("outinit", r, qo_pad, ko)
        if key not in self._fns:
            csh3 = NamedSharding(self.mesh, P(DATA_AXIS, QUERY_AXIS, None))
            self._fns[key] = jax.jit(
                lambda: (jnp.full((r, qo_pad, ko), jnp.inf, jnp.float32),
                         jnp.full((r, qo_pad, ko), -1, jnp.int32),
                         jnp.full((r, qo_pad, ko), -1, jnp.int32)),
                out_shardings=(csh3, csh3, csh3))
        return self._fns[key]

    def _outlier_fold_fn(self, ko: int, select_out: str):
        """Per-chunk streaming fold for the wide-k outlier queries, on the
        SAME staged chunk arrays the extraction kernel consumes: each
        (row, col) cell derives its chunk's labels/ids on device (labels
        gathered from the replicated label vector, ids from the shard's
        affine row range) — the outlier path adds zero host->device attr
        traffic, exactly like engine.single._outlier_fold."""
        key = ("outfold", ko, select_out)
        if key not in self._fns:
            from dmlp_tpu.ops.topk import make_block_step
            use_pallas = self.config.use_pallas

            def local(cd, cl, ci, chunk_a, qo, lab_g, sc, live):
                ck = chunk_a.shape[0]
                id_base, n_real = _chunk_span(sc, ck)
                n_real = jnp.where(live[0] > 0, n_real, 0)
                iota = jnp.arange(ck, dtype=jnp.int32)
                bids = jnp.where(iota < n_real, id_base + iota, -1)
                blabels = _labels_for_ids(bids, lab_g)
                step = make_block_step(select_out, ko, use_pallas,
                                       jnp.float32)
                top = step(TopK(cd[0], cl[0], ci[0]), qo, chunk_a,
                           blabels, bids)
                return top.dists[None], top.labels[None], top.ids[None]

            self._fns[key] = jax.jit(shard_map(
                local, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, QUERY_AXIS, None),
                          P(DATA_AXIS, QUERY_AXIS, None),
                          P(DATA_AXIS, QUERY_AXIS, None),
                          P(DATA_AXIS, None), P(QUERY_AXIS, None),
                          P(), P(), P(DATA_AXIS)),
                out_specs=(P(DATA_AXIS, QUERY_AXIS, None),
                           P(DATA_AXIS, QUERY_AXIS, None),
                           P(DATA_AXIS, QUERY_AXIS, None)),
                check_vma=False))
        return self._fns[key]

    def _outlier_merge_fn(self, ko: int):
        key = ("outmerge", ko, self._merge_strategy)
        if key not in self._fns:
            merge = self._merge_strategy

            def local(cd, cl, ci):
                top = TopK(cd[0], cl[0], ci[0])
                if merge == "allgather":
                    return allgather_merge_topk(top, ko, DATA_AXIS)
                return ring_allreduce_topk(top, ko, DATA_AXIS)

            self._fns[key] = jax.jit(shard_map(
                local, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, QUERY_AXIS, None),
                          P(DATA_AXIS, QUERY_AXIS, None),
                          P(DATA_AXIS, QUERY_AXIS, None)),
                out_specs=P(QUERY_AXIS, None),
                check_vma=False))
        return self._fns[key]

    def _plan_prune_mesh(self, inp: KNNInput, r: int, shard_rows: int,
                         nchunks: int, chunk_rows: int,
                         allow_prune: bool, precision: str = "f32"):
        """Stage 0+1 for the mesh chunk driver: per-(shard, chunk)
        survivor mask ((R, T) bool) + stats, or (None, None) when
        pruning is inactive. Blocks are each shard's chunk-aligned
        contiguous global row ranges — exactly what _chunk_span folds —
        scored against ALL queries (every data shard meets every query
        shard across the mesh columns)."""
        n = inp.params.num_data
        if (not allow_prune or not self.config.exact or n == 0
                or inp.params.num_queries == 0 or r * nchunks <= 1):
            return None, None
        from dmlp_tpu.ops import summaries as osum
        if not osum.prune_enabled():
            return None, None
        ranges = []
        for rr in range(r):
            for t in range(nchunks):
                lo = rr * shard_rows + t * chunk_rows
                hi = min(lo + chunk_rows, (rr + 1) * shard_rows, n)
                ranges.append((lo, max(hi, lo)))
        with obs_span("sharded.prune_score", blocks=len(ranges)):
            summ = osum.build_summaries(inp.data_attrs, ranges)
            keep, stats = osum.prune_mask(inp.query_attrs, inp.ks, summ,
                                          staging=self._staging,
                                          precision=precision)
        return keep.reshape(r, nchunks), stats

    def _solve_chunked_extract(self, inp: KNNInput, routed: bool = True,
                               allow_prune: bool = False,
                               precision: str = "f32"):
        """Chunked staging + per-chunk extract folds over the mesh.

        The r3 mesh engines staged the full padded dataset in ONE
        device_put — on a transfer-bound link the end-to-end paid full
        staging serially, while the single-chip driver overlapped chunk
        i+1's transfer with chunk i's fold (engine.single._solve_extract).
        This driver brings that overlap to the mesh: each shard's row
        range is cut into the same ~chunk_rows pieces, chunk t carries
        every shard's t-th piece (one (R*chunk_rows, A) device_put sharded
        P("data", None)), and one fold dispatch per chunk keeps the
        running (R, Qpad, K) lists resident across the sweep — the
        reference's scatter phasing (engine.cpp:62-131 -> :233-257),
        overlapped instead of serialized. Global ids stay affine per
        (shard, chunk): id = rr * shard_rows + toff + j, which is exactly
        the extraction kernel's id contract. Returns None when the plan
        doesn't select the extraction kernel (caller falls back to the
        monolithic staging paths).

        ``routed`` enables the heterogeneous-k split (engine.single
        .hetk_split): wide-k outlier queries fold on the SAME staged
        chunks via the streaming-select mesh program while the bulk stays
        on the kernel; the return value is then a SEGMENT LIST
        [(top, qpad, idx, select), ...] instead of a (top, qpad) pair.
        candidates() passes routed=False (its single-tensor contract
        cannot carry two widths).
        """
        import time as _time

        from dmlp_tpu.engine.single import hetk_split, plan_chunks
        from dmlp_tpu.ops.pallas_distance import native_pallas_backend
        from dmlp_tpu.ops.pallas_extract import QUERY_TILE
        from dmlp_tpu.ops.pallas_extract import supports as ex_supports
        from dmlp_tpu.ops.topk import streaming_fallback

        cfg = self.config
        n = inp.params.num_data
        nq = inp.params.num_queries
        na = inp.params.num_attrs
        r, c = self.mesh.devices.shape
        if n == 0 or nq == 0:
            return None
        if cfg.resolve_select(round_up(max(-(-n // r), 1), 8)) != "extract":
            return None

        split = hetk_split(cfg, self._staging, inp.ks, n,
                           round_up(max(-(-n // r), 1), 8)) if routed \
            else None
        if split is None:
            bulk_idx = out_idx = None
            nqb, q_src, kmax = nq, inp.query_attrs, int(inp.ks.max())
        else:
            bulk_idx, out_idx = split
            nqb, q_src = len(bulk_idx), inp.query_attrs[bulk_idx]
            kmax = int(inp.ks[bulk_idx].max())

        granule = cfg.resolve_granule("extract")
        # data_block serves as the chunk-size hint, like the single-chip
        # extract driver (granule still rounds it to whole kernel blocks).
        shard_rows, nchunks, chunk_rows = plan_chunks(
            max(-(-n // r), 1), granule, cfg.data_block)
        qloc = round_up(max(-(-nqb // c), 1), QUERY_TILE)
        qpad = c * qloc
        k = resolve_kcap(cfg, kmax, "extract", r * shard_rows,
                         staging=self._staging)
        if not ex_supports(qloc, chunk_rows, na, k):
            return None
        impl = self._extract_impl("extract", qloc, chunk_rows, na, k)
        interpret = not native_pallas_backend()
        self._last_select = "extract"
        if split is not None:
            self.last_hetk = (int(bulk_idx.size), int(out_idx.size))

        t0 = _time.perf_counter()
        np_dtype = self._np_dtype()
        qsh = NamedSharding(self.mesh, P(QUERY_AXIS, None))
        csh = NamedSharding(self.mesh, P(DATA_AXIS, None))
        rsh = NamedSharding(self.mesh, P())
        q_attrs = np.zeros((qpad, na), np.float32)
        q_attrs[:nqb] = q_src
        q_dev = jax.device_put(q_attrs.astype(np_dtype, copy=False), qsh)
        lab_dev = jax.device_put(
            np.ascontiguousarray(inp.labels, np.int32), rsh)

        cd, ci = self._chunk_init_fn(r, qpad, k)()
        step = self._chunk_fold_fn(k, interpret, impl, precision)

        ostep = None
        if split is not None:
            select_out = streaming_fallback(cfg.use_pallas)
            ko = resolve_kcap(cfg, int(inp.ks[out_idx].max()), select_out,
                              r * shard_rows, staging=self._staging)
            qo_loc = round_up(max(-(-len(out_idx) // c), 1), 8)
            qo_pad = c * qo_loc
            qo = np.zeros((qo_pad, na), np.float32)
            qo[:len(out_idx)] = inp.query_attrs[out_idx]
            qo_dev = jax.device_put(qo.astype(np_dtype, copy=False), qsh)
            od, ol, oi = self._outlier_init_fn(r, qo_pad, ko)()
            ostep = self._outlier_fold_fn(ko, select_out)

        # Pruned two-stage solve: each shard prunes locally before its
        # fold (zero-filled piece + n_real = 0 via the live mask); a
        # chunk every shard pruned is never staged or dispatched at
        # all. ``None`` keep == dense scan, one compiled program either
        # way (the mask is a data input, not a cache key).
        keep_m, prune_stats = self._plan_prune_mesh(
            inp, r, shard_rows, nchunks, chunk_rows, allow_prune,
            precision)
        lsh = NamedSharding(self.mesh, P(DATA_AXIS))
        ones_live = jax.device_put(np.ones(r, np.int32), lsh)
        n_disp = nchunks if keep_m is None \
            else int(keep_m.any(axis=0).sum())
        item = np.dtype(np_dtype).itemsize
        scanned = 0
        first = True
        src = np.ascontiguousarray(inp.data_attrs, np.float32)
        throttle = ChunkThrottle()
        mi = MeasuredIters(self, "sharded.chunk_fold",
                           (qloc, chunk_rows, na, k), kernel=impl)
        from dmlp_tpu.ops.pallas_fused import variant_for
        with obs_span("sharded.enqueue_chunked", chunks=nchunks,
                      scheduled=n_disp, mesh=[r, c], kc=k, impl=impl,
                      variant=variant_for(impl, k, chunk_rows, qloc, na)):
            for t in range(nchunks):
                live_col = None if keep_m is None else keep_m[:, t]
                if live_col is not None and not live_col.any():
                    continue     # every shard pruned this chunk
                toff = t * chunk_rows
                # Staging buffer directly in the wire dtype: slice
                # assignment converts in place (one pass), instead of
                # f32-zeros + a full astype copy per chunk.
                a = np.zeros((r * chunk_rows, na), np_dtype)
                for rr in range(r):
                    if live_col is not None and not live_col[rr]:
                        # Pruned piece: stays zero, folds dead. NOTE on
                        # accounting: scanned_bytes counts CORPUS rows
                        # read from host DRAM — a partially-pruned
                        # chunk's device_put below still ships the full
                        # zero-filled buffer over the link, so only
                        # chunks EVERY shard pruned also save link
                        # traffic on the mesh path (the single-chip and
                        # serve paths save both; ops.summaries.note_scan
                        # documents the metric's meaning).
                        continue
                    lo = rr * shard_rows + toff
                    # Cap at the shard boundary too (see _chunk_fold_fn):
                    # the rows past it belong to — and are staged by —
                    # shard rr+1.
                    hi = min(lo + chunk_rows, (rr + 1) * shard_rows, n)
                    if hi > lo:
                        a[rr * chunk_rows: rr * chunk_rows + (hi - lo)] = \
                            src[lo:hi]
                        scanned += (hi - lo) * na * item
                a_dev = jax.device_put(a, csh)
                sc = jax.device_put(
                    np.asarray([n, toff, shard_rows], np.int32), rsh)
                lv = ones_live if live_col is None else jax.device_put(
                    np.asarray(live_col, np.int32), lsh)
                if first:
                    first = False
                    obs_counters.record_dispatch(
                        step, (cd, ci, a_dev, q_dev, sc, lv),
                        count=n_disp, site="sharded.chunk_fold")
                cd, ci, its = step(cd, ci, a_dev, q_dev, sc, lv)
                mi.add(its)
                if ostep is not None:
                    od, ol, oi = ostep(od, ol, oi, a_dev, qo_dev, lab_dev,
                                       sc, lv)
                throttle.tick(od if ostep is not None else cd)
                # Watermark tick while the staged chunk is still
                # referenced (no-op without a telemetry session).
                telemetry.sample_memory_now()
        mi.done()
        from dmlp_tpu.ops.summaries import note_scan
        note_scan(self, scanned_bytes=scanned,
                  dense_bytes=n * na * item,
                  blocks_total=(prune_stats or {}).get(
                      "blocks_total",
                      sum(1 for rr in range(r) for t in range(nchunks)
                          if min(rr * shard_rows + (t + 1) * chunk_rows,
                                 (rr + 1) * shard_rows, n)
                          > rr * shard_rows + t * chunk_rows)),
                  blocks_pruned=(prune_stats or {}).get(
                      "blocks_pruned", 0))
        self.last_phase_ms["enqueue"] = (_time.perf_counter() - t0) * 1e3

        # Collective-traffic accounting from the shapes actually merged
        # (obs.comms): one cross-shard merge per query-axis column.
        self.last_comms = engine_comms(self._merge_strategy, (r, c),
                                       qpad // c, k)
        merge_fn = self._chunk_merge_fn(k)
        obs_counters.record_dispatch(merge_fn, (cd, ci, lab_dev),
                                     site="sharded.chunk_merge")
        with obs_span("sharded.merge", mesh=[r, c], kc=k) as sp:
            top_b = merge_fn(cd, ci, lab_dev)
            sp.fence(top_b.dists)
        if split is None:
            return top_b, qpad
        self.last_comms = self.last_comms + engine_comms(
            self._merge_strategy, (r, c), qo_pad // c, ko)
        top_o = self._outlier_merge_fn(ko)(od, ol, oi)
        return [(top_b, qpad, bulk_idx, "extract"),
                (top_o, qo_pad, out_idx, select_out)]

    def candidates(self, inp: KNNInput):
        from dmlp_tpu.engine.single import staging_for_k
        kmax = int(inp.ks.max()) if inp.params.num_queries else 0
        with staging_for_k(self, kmax):
            return self._candidates(inp)

    def _candidates(self, inp: KNNInput):
        nq = inp.params.num_queries
        self.last_phase_ms = {}  # no stale phases if a path is skipped
        self.last_hetk = None    # routed=False below: no split ever fires
        self.last_comms = []     # no stale traffic either
        self._pending_iters = []
        self.last_extract_impl = None
        self.last_prune = None
        memwatch.note_engine_model(self, inp)
        # candidates() feeds the multi-host per-shard contract path,
        # whose consumers reason about PER-SHARD candidate horizons —
        # global-k pruning would thin the per-shard lists, so this
        # entry always scans densely.
        out = self._solve_chunked_extract(inp, routed=False)
        if out is not None:
            top, _ = out
        else:
            select, data_block, qgran, k = self._plan_local(inp)
            d_attrs, d_labels, d_ids, q_attrs = self._shard_inputs(
                inp, data_block, qgran)
            self._last_select = select  # run() gates the tie-overflow repair
            top = self._solve_merged(k, data_block, select, d_attrs,
                                     d_labels, d_ids, q_attrs)
        od, ol, oi = resilient_get((top.dists, top.labels, top.ids),
                                   site="sharded.fetch")
        out_np = (np.asarray(od, np.float64)[:nq], ol[:nq], oi[:nq])
        flush_measured_iters(self)  # post-fetch: a scalar readback
        return out_np

    def _solve_merged(self, k: int, data_block: int, select: str,
                      d_attrs, d_labels, d_ids, q_attrs,
                      precision: str = "f32"):
        """Dispatch the monolithic merged program, with obs hooks: the
        dispatch is recorded for cost-analysis counters and the merge's
        collective traffic is accounted from the dispatched shapes."""
        r, c = self.mesh.devices.shape
        impl = self._extract_impl(select, q_attrs.shape[0] // c,
                                  d_attrs.shape[0] // r,
                                  d_attrs.shape[1], k)
        fn = self._fn(k, data_block, select, impl,
                      precision if select == "extract" else "f32")
        args = (d_attrs, d_labels, d_ids, q_attrs)
        obs_counters.record_dispatch(fn, args, site="sharded.solve_merge")
        self.last_comms = engine_comms(self._merge_strategy, (r, c),
                                       q_attrs.shape[0] // c, k)
        def _op():
            rs_inject.fire("sharded.solve", which="merge")
            return fn(*args)

        with obs_span("sharded.solve_merge", select=select, mesh=[r, c],
                      kcap=k) as sp:
            # Re-dispatching the jitted mesh program on the same placed
            # arrays is idempotent — the retry wrapper's requirement.
            top, its = rs_retry.call_with_retry(_op, "sharded.solve")
            sp.fence(top.dists)
        self._queue_iters("sharded.solve_merge", select, its,
                          q_attrs.shape[0] // c, d_attrs.shape[0] // r,
                          d_attrs.shape[1], k, impl=impl)
        return top

    def _queue_iters(self, site: str, select: str, its,
                     qloc: int, shard_rows: int, na: int, k: int,
                     impl: str = "extract") -> None:
        """Queue a mesh program's per-shard kernel iters (summed over
        cells) for the post-fence measured-extraction-term flush; no-op
        for non-extract selects or without an installed probe. ``impl``
        tags the shape so the measured term is costed at the dispatched
        kernel's own resolved tiles (fused namespace when fused ran)."""
        if select != "extract":
            return
        mi = MeasuredIters(self, site, (qloc, shard_rows, na, k),
                           kernel=impl)
        mi.add(its)
        mi.done()

    def _solve_segments(self, inp: KNNInput):
        """Solve as (TopK, qpad, query_idx | None, select) segments — the
        mesh form of engine.single._solve_segments: one segment normally,
        two when the heterogeneous-k router splits wide-k outliers off
        the extraction kernel's bulk."""
        self.last_hetk = None
        self.last_phase_ms = {}
        self.last_comms = []
        self._pending_iters = []
        self.last_extract_impl = None
        self.last_prune = None
        # Pruning and the low-precision first pass ride the exact
        # contract path only: the f64 rescore + boundary repair are the
        # backstop both soundness margins lean on. The mesh engines
        # have no resilience ladder, so the config-resolved precision
        # (resolve_precision returns "f32" in fast mode) IS the active
        # one; _run widens its hazard eps to match.
        prec = self.config.resolve_precision()
        self.last_precision = {"active": prec, "configured": prec}
        out = self._solve_chunked_extract(inp,
                                          allow_prune=self.config.exact,
                                          precision=prec)
        if isinstance(out, list):
            return out
        if out is not None:
            top, qpad = out
            return [(top, qpad, None, self._last_select)]
        select, data_block, qgran, k = self._plan_local(inp)
        d_attrs, d_labels, d_ids, q_attrs = self._shard_inputs(
            inp, data_block, qgran)
        self._last_select = select
        top = self._solve_merged(k, data_block, select, d_attrs, d_labels,
                                 d_ids, q_attrs, precision=prec)
        return [(top, q_attrs.shape[0], None, select)]

    def solve_global(self, d_attrs, d_labels, d_ids, q_attrs, kmax: int):
        """Run the compiled sharded program on pre-placed global arrays.

        The multi-host feed path (parallel.distributed): each process
        contributes its local shard via make_global_dataset/queries; this
        method consumes the resulting jax.Arrays directly — no per-host
        full-dataset ingest. Shapes must already be mesh-uniform (data rows
        divisible by the data-axis size, query rows by the query-axis
        size). Returns the merged TopK (global, query-sharded).
        """
        select, data_block, k = self._plan_shard(d_attrs, q_attrs, kmax,
                                                 merged_width=True)
        r, c = self.mesh.devices.shape
        impl = self._extract_impl(select, q_attrs.shape[0] // c,
                                  d_attrs.shape[0] // r,
                                  d_attrs.shape[1], k)
        fn = self._fn(k, data_block, select, impl)

        def _op():
            rs_inject.fire("sharded.solve", which="global")
            return fn(d_attrs, d_labels, d_ids, q_attrs)

        top, its = rs_retry.call_with_retry(_op, "sharded.solve")
        self._queue_iters("sharded.solve_global", select, its,
                          q_attrs.shape[0] // c, d_attrs.shape[0] // r,
                          d_attrs.shape[1], k, impl=impl)
        return top

    def _plan_shard(self, d_attrs, q_attrs, kmax: int, merged_width: bool):
        """Per-shard blocking plan for pre-placed global arrays.

        Prefers the extraction kernel when the feed's (fixed) per-shard
        shapes support it; else the streaming select. ``merged_width``
        sizes the candidate width for the cross-shard merged output
        (cap R * shard_rows); per-shard outputs (solve_local_shards) cap
        at shard_rows. Sets _last_select.
        """
        from dmlp_tpu.ops.pallas_distance import _tile

        cfg = self.config
        r, c = self.mesh.devices.shape
        shard_rows = d_attrs.shape[0] // r
        cap = shard_rows * r if merged_width else shard_rows
        # The gspmd merged program (merge="auto") streams with the XLA
        # selects only: a Pallas dispatch inside a GSPMD-partitioned jit
        # would need its own partitioning rules — exactly the
        # hand-rolling that strategy exists to avoid (engine.auto).
        if self._merge_strategy != "gspmd" and cfg.data_block is None \
                and cfg.resolve_select(shard_rows) == "extract":
            from dmlp_tpu.ops.pallas_extract import supports as ex_supports
            k = resolve_kcap(cfg, kmax, "extract", cap,
                             staging=self._staging)
            if ex_supports(q_attrs.shape[0] // c, shard_rows,
                           d_attrs.shape[1], k):
                self._last_select = "extract"
                return "extract", shard_rows, k
        select = cfg.resolve_streaming_select(shard_rows)
        granule = cfg.resolve_granule(select)
        # _tile snaps to the largest granule-multiple divisor of shard_rows
        # (streaming_topk scans whole blocks, so the block must divide).
        data_block = _tile(shard_rows,
                           min(cfg.data_block or
                               cfg.resolve_data_block(select), shard_rows),
                           min(granule, shard_rows))
        k = resolve_kcap(cfg, kmax, select, cap,
                         staging=self._staging)
        self._last_select = select
        return select, data_block, k

    # -- per-shard program (no cross-shard merge) ---------------------------
    def _fn_local(self, k: int, data_block: int, select: str,
                  impl: str = "extract", precision: str = "f32"):
        """Compiled per-cell top-k with out_specs keeping BOTH mesh axes:
        output (R, Qpad, K) sharded P("data", "query", None). No collective
        runs inside the jit — the multi-host contract path rescores each
        data shard's candidates in float64 on the process that owns the
        shard, then merges on host (parallel.distributed), so the exact
        merge must not happen in f32 on device first."""
        key = ("local", k, data_block, select, impl, precision)
        if key not in self._fns:
            solve_shard = self._solve_shard_fn(k, data_block, select, impl,
                                               precision)

            def local(data_a, data_l, data_i, q_attrs):
                top, its = solve_shard(data_a, data_l, data_i, q_attrs)
                if select == "extract":
                    # The multi-host rescore reads kth/last POSITIONS of
                    # each per-shard list (tie-hazard check), so the
                    # extraction kernel's unsorted lists must be sorted
                    # here; the merged path's collectives re-sort anyway.
                    from dmlp_tpu.ops.topk import select_topk
                    top = select_topk(top.dists, top.labels, top.ids, k)
                return jax.tree.map(lambda t: t[None], top), its

            sharded = shard_map(
                local, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                          P(QUERY_AXIS, None)),
                out_specs=(P(DATA_AXIS, QUERY_AXIS, None),
                           P(DATA_AXIS, QUERY_AXIS)),
                check_vma=False)
            self._fns[key] = jax.jit(sharded)
        return self._fns[key]

    def solve_local_shards(self, d_attrs, d_labels, d_ids, q_attrs,
                           kmax: int):
        """Like solve_global, but returns per-shard candidate lists
        (TopK of shape (R, Qpad, K), sharded over both mesh axes)."""
        select, data_block, k = self._plan_shard(d_attrs, q_attrs, kmax,
                                                 merged_width=False)
        r, c = self.mesh.devices.shape
        impl = self._extract_impl(select, q_attrs.shape[0] // c,
                                  d_attrs.shape[0] // r,
                                  d_attrs.shape[1], k)
        fn = self._fn_local(k, data_block, select, impl)
        obs_counters.record_dispatch(fn, (d_attrs, d_labels, d_ids,
                                          q_attrs),
                                     site="sharded.solve_local_shards")

        def _op():
            rs_inject.fire("sharded.solve", which="local_shards")
            return fn(d_attrs, d_labels, d_ids, q_attrs)

        with obs_span("sharded.solve_local_shards", select=select,
                      mesh=[r, c], kcap=k):
            top, its = rs_retry.call_with_retry(_op, "sharded.solve")
        self._queue_iters("sharded.solve_local_shards", select, its,
                          q_attrs.shape[0] // c, d_attrs.shape[0] // r,
                          d_attrs.shape[1], k, impl=impl)
        return top

    def run(self, inp: KNNInput) -> List[QueryResult]:
        from dmlp_tpu.engine.single import staging_for_k
        kmax = int(inp.ks.max()) if inp.params.num_queries else 0
        with staging_for_k(self, kmax):
            return self._run(inp)

    def _run(self, inp: KNNInput) -> List[QueryResult]:
        import time as _time

        from dmlp_tpu.io.grammar import subset_queries

        n = inp.params.num_data
        memwatch.note_engine_model(self, inp)
        segments = self._solve_segments(inp)
        # Watermark tick at peak residency (solve enqueued, nothing
        # fetched); no-op without a telemetry session.
        telemetry.sample_memory_now()
        self.last_repairs = 0  # tie-overflow repair rate, for bench records
        merged: List[QueryResult] = [None] * inp.params.num_queries
        dn_max = None
        fetch_ms = final_ms = 0.0
        for top, _qpad, idx, select in segments:
            sub = inp if idx is None else subset_queries(inp, idx)
            nq = sub.params.num_queries
            # Like engine.single.run: "fetch" includes the wait for all
            # enqueued device work (staging + sharded solve + merge), not
            # just readback bytes.
            t0 = _time.perf_counter()
            with obs_span("sharded.fetch", select=select):
                od, ol, oi = resilient_get((top.dists, top.labels,
                                            top.ids), site="sharded.fetch")
                dists = np.asarray(od, np.float64)[:nq]
                labels = ol[:nq]
                ids = oi[:nq]
            fetch_ms += (_time.perf_counter() - t0) * 1e3
            t0 = _time.perf_counter()
            with obs_span("sharded.finalize", exact=self.config.exact):
                results = finalize_host(dists, labels, ids, sub.ks,
                                        sub.query_attrs, sub.data_attrs,
                                        exact=self.config.exact,
                                        query_ids=idx)
                if select in ("sort", "topk", "seg", "extract") \
                        and dists.shape[1] < n:
                    # Per-shard truncation surfaces on the merged lists:
                    # a point dropped by shard s has device dist > that
                    # shard's horizon, and the merged kcap-th <= any
                    # shard's kcap-th, so the same (eps-widened) boundary
                    # test covers both engines. width >= num_data means
                    # every real point is a candidate — nothing
                    # truncated. eps accounts for the staging dtype's
                    # non-monotone rounding (finalize.staging_eps; exact
                    # ties when f64-exact).
                    if dn_max is None:
                        dn_max = float(np.einsum(
                            "na,na->n", inp.data_attrs,
                            inp.data_attrs).max())
                    qn = np.einsum("qa,qa->q", sub.query_attrs,
                                   sub.query_attrs)
                    eps = staging_eps(
                        np.asarray(dists[:, -1], np.float64), qn, dn_max,
                        self._staging, inp.params.num_attrs)
                    prec = (self.last_precision or {}).get("active", "f32")
                    if prec == "bf16" and select == "extract":
                        # The bf16 first pass perturbs device distances
                        # beyond the staging model; the hazard test must
                        # not trust a boundary the low-precision dot
                        # could have reordered (finalize.lowp_eps).
                        eps = eps + lowp_eps("bf16", qn, dn_max)
                    suspects = np.nonzero(
                        boundary_overflow(dists, sub.ks, eps))[0]
                    if suspects.size:
                        repair_boundary_overflow(results, suspects, sub)
                        self.last_repairs += int(suspects.size)
                if idx is None:
                    merged = results
                else:
                    for local_i, orig in enumerate(idx):
                        merged[int(orig)] = results[local_i]
            final_ms += (_time.perf_counter() - t0) * 1e3
        self.last_phase_ms["fetch"] = fetch_ms
        self.last_phase_ms["finalize"] = final_ms
        flush_measured_iters(self)  # post-fence: a scalar readback
        return merged

    def _fn_full(self, k: int, data_block: int, select: str,
                 num_labels: int, impl: str = "extract",
                 precision: str = "f32"):
        """Compiled all-device pipeline: per-cell top-k -> cross-shard
        merge -> vote + report ordering, all query-sharded on device (the
        sharded analog of single._full_blocks)."""
        key = ("full", k, data_block, select, num_labels, impl, precision)
        if key not in self._fns:
            merge = self._merge_strategy
            solve_shard = self._solve_shard_fn(k, data_block, select, impl,
                                               precision)

            def local(data_a, data_l, data_i, q_attrs, ks):
                from dmlp_tpu.ops.vote import majority_vote, report_order

                # The extraction kernel's per-shard lists are unsorted;
                # both merges re-select with the composite sort (the
                # 1-member-axis ring case included), so report_order's
                # selection-order precondition holds either way.
                top, its = solve_shard(data_a, data_l, data_i, q_attrs)
                if merge == "allgather":
                    top = allgather_merge_topk(top, k, DATA_AXIS)
                else:
                    top = ring_allreduce_topk(top, k, DATA_AXIS)
                rd, rids, in_k = report_order(top, ks)
                valid = in_k & (top.ids >= 0)
                predicted = majority_vote(top.labels, valid, num_labels)
                return predicted, rids, rd, its

            sharded = shard_map(
                local, mesh=self.mesh,
                in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                          P(QUERY_AXIS, None), P(QUERY_AXIS)),
                out_specs=(P(QUERY_AXIS), P(QUERY_AXIS, None),
                           P(QUERY_AXIS, None),
                           P(DATA_AXIS, QUERY_AXIS)),
                check_vma=False)
            self._fns[key] = jax.jit(sharded)
        return self._fns[key]

    def run_device_full(self, inp: KNNInput) -> List[QueryResult]:
        """All-device pipeline over the mesh (vote + report order on the
        chips, f32 ordering; benchmark path — no float64 rescue).
        dtype="auto" never coarsens this path (engine.single
        .no_auto_coarsen): without the f64 rescore, the staging dtype IS
        the output ordering."""
        from dmlp_tpu.engine.single import no_auto_coarsen
        with no_auto_coarsen(self):
            return self._run_device_full(inp)

    def _run_device_full(self, inp: KNNInput) -> List[QueryResult]:
        from dmlp_tpu.io.grammar import subset_queries

        n = inp.params.num_data
        nq = inp.params.num_queries
        num_labels = int(inp.labels.max()) + 1 if n else 1
        ksh = NamedSharding(self.mesh, P(QUERY_AXIS))

        self.last_phase_ms = {}  # no stale phases if a path is skipped
        self.last_hetk = None
        self.last_comms = []
        self._pending_iters = []
        self.last_extract_impl = None
        self.last_prune = None
        memwatch.note_engine_model(self, inp)
        # Device-full output IS the f32 device ordering (no repair
        # backstop), so this benchmark path always scans densely.
        out = self._solve_chunked_extract(inp)
        if out is not None:
            from dmlp_tpu.engine.single import _device_epilogue
            segments = out if isinstance(out, list) \
                else [(out[0], out[1], None, self._last_select)]
            merged: List[QueryResult] = [None] * nq
            for top, qpad, idx, _select in segments:
                sub = inp if idx is None else subset_queries(inp, idx)
                nqs = sub.params.num_queries
                ks_pad = np.zeros(qpad, np.int32)
                ks_pad[:nqs] = sub.ks
                # Plain jit: inputs arrive query-sharded and XLA
                # partitions the (Q, K)-local vote/report accordingly.
                p, i, d = _device_epilogue(
                    top, jax.device_put(ks_pad, ksh),
                    num_labels=num_labels)
                p, i, d = resilient_get((p, i, d), site="sharded.fetch")
                preds = p[:nqs]
                rids = i[:nqs]
                rd = np.asarray(d, np.float64)[:nqs]
                gids = np.arange(nqs) if idx is None else idx
                for qi in range(nqs):
                    merged[int(gids[qi])] = QueryResult(
                        int(gids[qi]), int(sub.ks[qi]), int(preds[qi]),
                        rids[qi, : int(sub.ks[qi])].astype(np.int64),
                        rd[qi, : int(sub.ks[qi])])
            flush_measured_iters(self)
            return merged

        select, data_block, qgran, k = self._plan_local(inp)
        d_attrs, d_labels, d_ids, q_attrs = self._shard_inputs(
            inp, data_block, qgran)
        qpad = q_attrs.shape[0]
        self._last_select = select

        ks_pad = np.zeros(qpad, np.int32)
        ks_pad[:nq] = inp.ks
        ks_dev = jax.device_put(ks_pad, ksh)

        r, c = self.mesh.devices.shape
        impl = self._extract_impl(select, qpad // c,
                                  d_attrs.shape[0] // r,
                                  d_attrs.shape[1], k)
        fn_full = self._fn_full(k, data_block, select, num_labels, impl)
        full_args = (d_attrs, d_labels, d_ids, q_attrs, ks_dev)
        obs_counters.record_dispatch(fn_full, full_args,
                                     site="sharded.device_full")
        self.last_comms = engine_comms(self._merge_strategy, (r, c),
                                       qpad // c, k)
        with obs_span("sharded.device_full", select=select,
                      mesh=[r, c]) as sp:
            p, i, d, its = fn_full(*full_args)
            sp.fence(d)
        self._queue_iters("sharded.device_full", select, its,
                          qpad // c, d_attrs.shape[0] // r,
                          d_attrs.shape[1], k, impl=impl)
        p, i, d = resilient_get((p, i, d), site="sharded.fetch")
        preds = p[:nq]
        rids = i[:nq]
        rd = np.asarray(d, np.float64)[:nq]
        results = [QueryResult(qi, int(inp.ks[qi]), int(preds[qi]),
                               rids[qi, : int(inp.ks[qi])].astype(np.int64),
                               rd[qi, : int(inp.ks[qi])])
                   for qi in range(nq)]
        flush_measured_iters(self)
        return results


class RingEngine(ShardedEngine):
    """Ring-streaming engine: merge-top-k ring all-reduce over "data".

    O(k) accumulator per hop instead of an O(R*k) gather — the
    memory-bounded long-context analog (survey §5.7): the dataset axis plays
    the sequence axis, the running top-k plays the softmax running state of
    ring attention.
    """

    _merge_strategy = "ring"

    def __init__(self, config: EngineConfig = EngineConfig(mode="ring"),
                 mesh: Optional[Mesh] = None):
        super().__init__(config, mesh)
