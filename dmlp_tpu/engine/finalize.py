"""Host-side result finalization shared by all engines.

The device engines return, per query, a selection-ordered candidate list of
size K >= max-k (+ margin). This module turns those lists into final
``QueryResult``s: optional exact float64 rescoring (restoring the reference's
double-precision ordering, engine.cpp:12 / common.h:13, without paying f64 on
the MXU), the per-query k cut, the majority vote (engine.cpp:320-332), the
report sort (engine.cpp:334-338), and -1-sentinel padding (common.cpp:66).

Everything is vectorized NumPy over (Q, K) arrays — K is small (tens), so
this is a negligible epilogue next to the O(Q*N*A) device work.
"""

from __future__ import annotations

from typing import List

import numpy as np

from dmlp_tpu.io.report import QueryResult


def _row_lexsort(primary: np.ndarray, *descending_ints: np.ndarray) -> np.ndarray:
    """Row-wise argsort by (primary asc, then each int key desc), stable.

    Implemented as composed stable sorts, least-significant key first (the
    radix trick), all vectorized along axis 1.
    """
    idx = np.broadcast_to(np.arange(primary.shape[1]), primary.shape).copy()
    keys = [(-k).astype(np.int64) for k in reversed(descending_ints)] + [primary]
    for key in keys:  # least-significant first; stable sorts compose
        cur = np.take_along_axis(key, idx, axis=1)
        order = np.argsort(cur, axis=1, kind="stable")
        idx = np.take_along_axis(idx, order, axis=1)
    return idx


def _vote_batch(labels: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """Vectorized majority vote with tie -> larger label; -1 if none valid."""
    q = labels.shape[0]
    masked = np.where(valid, labels, -1)
    num_labels = int(masked.max()) + 1 if masked.size and masked.max() >= 0 else 0
    if num_labels == 0:
        return np.full(q, -1, np.int64)
    counts = np.zeros((q, num_labels), np.int64)
    rows = np.broadcast_to(np.arange(q)[:, None], labels.shape)
    sel = masked >= 0
    np.add.at(counts, (rows[sel], masked[sel]), 1)
    best = counts.max(axis=1)
    is_best = counts == best[:, None]
    predicted = num_labels - 1 - np.argmax(is_best[:, ::-1], axis=1)
    return np.where(best > 0, predicted, -1)


def rescore_f64(cand_ids: np.ndarray, query_attrs: np.ndarray,
                data_attrs: np.ndarray, block: int = 512) -> np.ndarray:
    """Exact float64 distances for candidate ids (difference form, like
    computeDistance at engine.cpp:12-18). ids < 0 map to +inf.

    ``block`` bounds the (block, K, A) gather temp. 512 measured 2.4x
    faster than 1024 at the wide-k shape (10240 x 4608 x 64: 36 s vs
    87 s — the 2.4 GB temps of block=1024 fall out of cache); 64-512
    are within noise of each other there and at narrow k the temps are
    tiny either way."""
    q, k = cand_ids.shape
    out = np.empty((q, k), np.float64)
    safe = np.clip(cand_ids, 0, data_attrs.shape[0] - 1)
    for q0 in range(0, q, block):
        q1 = min(q0 + block, q)
        gathered = data_attrs[safe[q0:q1]]                       # (b, K, A)
        diff = gathered - query_attrs[q0:q1, None, :]
        out[q0:q1] = np.einsum("qka,qka->qk", diff, diff)
    out[cand_ids < 0] = np.inf
    return out


# Calibrated eps-bound constants — THE single definition, shared by the
# host hazard test below and the device-side multi-pass floor
# (engine.single._mp_floor); a recalibration here propagates to both.
EPS_REL_BF16 = 2.0 ** -6
EPS_REL_F32 = 2.0 ** -21
EPS_CANCEL_COEF = 3.0 * 2.0 ** -22

#: Low-precision FIRST-PASS coefficients (the tentpole's ``lowp_eps``
#: bound): casting the streamed q/d tiles to the pass dtype perturbs
#: each operand by a relative half-ulp u (2^-8 for bfloat16's 7
#: explicit mantissa bits), so the f32-accumulated cross term errs by
#: at most (2u + u^2) * |q||d| <= (2u + u^2) * (qn + dn)/2 per dot
#: (AM-GM), i.e. the norm-expansion distance by (2u + u^2)(qn + dn) —
#: a bound on the MAGNITUDE scale, independent of the distance itself
#: (unlike staging_eps term 1, which shrinks with sqrt(dist)). The
#: coefficient folds the 2u, the second-order u^2, and a 2x safety
#: slack: 2^-6 = 8 * 2^-9 >= (2*2^-8 + 2^-16) * 2. f32 is the exact
#: pass (zero cast error — the f32 accumulation itself is already
#: covered by the EPS_CANCEL_COEF term everywhere this composes).
#: tests/test_precision.py fuzzes the bound with directed adversarial
#: magnitude-cancellation corpora. int8 has NO entry: an int8 pass
#: needs data-dependent quantization scales, so its bound cannot be a
#: static coefficient — the ROADMAP follow-on.
LOWP_COEF = {"f32": 0.0, "bf16": 2.0 ** -6}


def lowp_eps(precision: str, qn: np.ndarray, dn_max: float) -> np.ndarray:
    """Per-query bound on the distance perturbation a low-precision
    FIRST PASS (ops.pallas_extract with ``precision != "f32"``) can add
    on top of the staging/f32 terms: ``LOWP_COEF[precision] * (qn +
    dn_max)``. Composes ADDITIVELY with :func:`staging_eps` (the cast
    error of the pass dtype and the staging/accumulation errors act on
    the same computed distance, so their bounds sum) at every decision
    the low-precision distances feed: the truncation-hazard test, the
    prune thresholds, the MXU-gate bound, and the multi-pass floor.
    Zero for the exact "f32" pass. Raises KeyError on a precision with
    no static bound (int8 — see LOWP_COEF)."""
    coef = LOWP_COEF[precision]
    if not coef:
        return np.zeros_like(np.asarray(qn, np.float64))
    return coef * (np.asarray(qn, np.float64) + dn_max)


def staging_eps(last: np.ndarray, qn: np.ndarray, dn_max: float,
                staging: str, na: int) -> np.ndarray:
    """Per-query bound on the distance perturbation the device pipeline
    can introduce, for the truncation-hazard test. Two terms:

    1. ATTR ROUNDING — casting attrs to the staging dtype perturbs each
       computed distance by at most (first order, Cauchy-Schwarz over the
       per-attr terms)

           |d~ - d| <= 2 * u * sqrt(d) * sqrt(2 * (|q|^2 + |x|^2))

       with u the half-ulp relative rounding (2^-9 for bfloat16, 2^-24
       for float32).
    2. COMPUTATION — the norm-expansion form qn + dn - 2 q.x evaluates
       three terms of magnitude ~(qn + dn) in f32 and CANCELS them, so
       its rounding error scales with the MAGNITUDES, not the result:
       ~(na + 2) * u32 * (qn + dn). When true distances are tiny against
       the coordinate scale (clustered data), this term dwarfs term 1 —
       the fuzz case the original attr-only bound missed: near-duplicate
       points at coordinate scale ~5 have gaps ~1e-6 but f32 cancellation
       error ~1e-5, silently reordering candidates past the margin.

    Neither error is monotone across points, so two points' device
    distances can swap even without an exact device tie — an
    exact-equality hazard test is sound only for exact device arithmetic.
    Comparing the k-th candidate against a potentially missed point
    doubles both bounds; the constants fold the doubling, sqrt(2), a
    >= 1.4x second-order slack, and (term 2) u32 = 2^-22 covering the
    MXU's HIGHEST-precision 3-pass product error on top of f32
    accumulation. ``dn_max`` (max squared data-row norm, f64) bounds
    |x|^2 over every point, known or missed.
    """
    rel = EPS_REL_BF16 if staging == "bfloat16" else EPS_REL_F32
    scale = qn + dn_max
    return (rel * np.sqrt(np.maximum(last, 0.0) * scale)
            + EPS_CANCEL_COEF * (na + 2) * scale)


def boundary_hazard(kth: np.ndarray, last: np.ndarray,
                    eps: np.ndarray | float = 0.0) -> np.ndarray:
    """The (eps-widened) truncation-hazard predicate on the two boundary
    columns — THE single definition; boundary_overflow, the single-chip
    engine (which fetches only these columns), and the distributed
    rescore all evaluate this. +inf in the last slot means the candidate
    list wasn't even full of real points — nothing can have been
    truncated."""
    return np.isfinite(last) & (last <= kth + eps)


def boundary_overflow(device_dists: np.ndarray, ks: np.ndarray,
                      eps: np.ndarray | float = 0.0) -> np.ndarray:
    """Queries whose fast-path candidate set may have truncated a tie group.

    The "topk" selection keeps the K smallest device distances with ties
    broken by position, not by the reference's larger-id preference
    (dmlp_tpu.ops.topk). A query's true top-k can then be missing
    from the candidates only if >= K entries tie at or below its k-th
    distance — which implies its k-th candidate distance equals the K-th
    (last) one. That equality is the hazard test: exact (conservative — it
    can flag safe queries, never miss an unsafe one) and computable from the
    raw device distances alone. Flagged queries are recomputed exactly on
    host (engines call dmlp_tpu.golden on just those), so parity survives
    adversarial duplicate-heavy data on the fast path too.

    ``eps`` widens the test to ``last <= kth + eps`` for staging dtypes
    whose rounding perturbs distances non-monotonically (staging_eps): a
    true neighbor can then sit up to eps ABOVE the k-th device distance,
    so the list has provably captured the true top-k only when the
    candidate horizon (last) clears the k-th distance by more than eps.
    With eps = 0 this reduces to the exact-tie test.

    Args:
      device_dists: (Q, K) raw device candidate distances, selection order.
      ks: (Q,) per-query k.
      eps: scalar or (Q,) staging-dtype perturbation bound.

    Returns:
      (Q,) bool mask of suspect queries.
    """
    q, kcap = device_dists.shape
    if q == 0 or kcap == 0:
        return np.zeros(q, bool)
    last = device_dists[:, kcap - 1]
    kth = device_dists[np.arange(q), np.clip(np.asarray(ks) - 1, 0, kcap - 1)]
    return boundary_hazard(kth, last, eps)


def repair_boundary_overflow(results: List[QueryResult],
                             suspect_idx: np.ndarray, inp) -> None:
    """Recompute the flagged queries exactly (golden model) in place.

    ``suspect_idx`` holds local query indices (positions in ``results`` /
    ``inp`` row order); the repaired entries keep their original query ids.

    Repairs run through the vectorized oracle (golden.fast: BLAS coarse
    pass + exact f64 rescore + strict fallback), not the per-query strict
    model: staging-eps hazards can flag thousands of queries at once
    (bf16 on dense distance distributions), and the repair must stay a
    BLAS pass, not a Python loop over full-dataset solves.
    """
    from dmlp_tpu.golden.fast import knn_golden_fast
    from dmlp_tpu.io.grammar import subset_queries

    fixed_all = knn_golden_fast(subset_queries(inp, suspect_idx))
    for j, qi in enumerate(np.asarray(suspect_idx)):
        fixed = fixed_all[j]
        results[qi] = QueryResult(results[qi].query_id, fixed.k,
                                  fixed.predicted_label, fixed.neighbor_ids,
                                  fixed.neighbor_dists)


def finalize_host(cand_dists: np.ndarray | None, cand_labels: np.ndarray,
                  cand_ids: np.ndarray, ks: np.ndarray,
                  query_attrs: np.ndarray, data_attrs: np.ndarray,
                  exact: bool = True,
                  query_ids: np.ndarray | None = None) -> List[QueryResult]:
    """Candidate lists -> final per-query results.

    Args:
      cand_dists/labels/ids: (Q, K) device candidate lists (selection order).
        ``cand_dists`` may be None when ``exact`` (distances are rescored
        from the float64 originals anyway — engines then skip fetching the
        device distance matrix entirely).
      ks: (Q,) per-query k (K >= ks.max() required).
      query_attrs/data_attrs: float64 originals, used only when ``exact``.
      exact: rescore candidates in float64 and re-select (parity mode).
      query_ids: (Q,) global query ids; defaults to arange (single process).
    """
    q, kcap = cand_ids.shape
    ks = np.asarray(ks, np.int64)
    if q and kcap < ks.max():
        raise ValueError(f"candidate width {kcap} < max k {ks.max()}")
    cand_ids = np.asarray(cand_ids, np.int64)
    cand_labels = np.asarray(cand_labels, np.int64)
    d = rescore_f64(cand_ids, query_attrs, data_attrs) if exact \
        else np.asarray(cand_dists, np.float64)

    # Re-derive the selection order (dist asc, id desc — the measured
    # label-free oracle-binary comparator, golden.reference); after
    # float64 rescoring the device's f32 order may no longer be sorted.
    order = _row_lexsort(d, cand_ids)
    d = np.take_along_axis(d, order, axis=1)
    labels = np.take_along_axis(cand_labels, order, axis=1)
    ids = np.take_along_axis(cand_ids, order, axis=1)

    pos = np.arange(kcap)[None, :]
    in_k = pos < ks[:, None]
    valid = in_k & (ids >= 0)
    predicted = _vote_batch(labels, valid)

    # Report order == selection order under the measured label-free
    # comparator (one (dist asc, id desc) total order governs both): the
    # list is already sorted, and masking the beyond-k tail to (inf, -1)
    # preserves sortedness (the tail is contiguous at the end) — the
    # former second lexsort was an identity permutation (and measured
    # ~9.5 s at the 10240 x 4608 wide-k shape).
    rd = np.where(valid, d, np.inf)
    rids = np.where(valid, ids, -1)

    if query_ids is None:
        query_ids = np.arange(q, dtype=np.int64)
    results: List[QueryResult] = []
    for qi in range(q):
        k = int(ks[qi])
        results.append(QueryResult(int(query_ids[qi]), k, int(predicted[qi]),
                                   rids[qi, :k].copy(), rd[qi, :k].copy()))
    return results
