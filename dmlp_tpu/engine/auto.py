"""Compiler-sharded KNN engine: GSPMD chooses the collective schedule.

The sharded/ring engines hand-roll every collective — ``shard_map``
cells plus an explicit all-gather merge or ring ppermute reduction
(parallel.collectives). This engine expresses the SAME chunked
distance -> top-k solve as one pure ``jit`` program whose inputs carry
``NamedSharding(mesh, P("data"))`` / ``P("query")`` placements and whose
merge point is a ``jax.lax.with_sharding_constraint`` resharding
(data-partitioned per-shard candidate lists -> query-partitioned merged
lists): XLA's GSPMD partitioner picks the collective schedule the
hand-written engines spell out by hand (PAPERS.md arXiv 2204.06514 is
the method paper). The bench harness A/Bs the two per config
(``--auto-ab`` -> the gated ``auto/`` ledger family): where GSPMD
matches the hand-rolled layouts the record justifies deleting code,
where it loses it justifies keeping shard_map.

Correctness is inherited, not re-proven: the program returns merged
(dist, label, id) candidate lists in the engines' selection order, and
the UNCHANGED ShardedEngine ``_run`` pipeline (fetch -> float64
``finalize_host`` rescore -> eps-widened ``boundary_overflow`` repair)
takes it from there, so responses are byte-identical to the golden
oracle on every path the hand-rolled engines cover.

Composition with the config axes happens where they resolve — OUTSIDE
the jit (R2 discipline):

- prune (``$DMLP_TPU_PRUNE``): the host-side summary scoring of
  ``_plan_prune_mesh`` masks whole (shard, chunk) blocks before
  staging — pruned rows stage as sentinel (id = -1) zeros, which the
  streaming fold provably ignores. Like the mesh engines' monolithic
  path, the saving is host-DRAM scan bytes (ops.summaries.note_scan
  documents the link-bytes caveat: the padded device_put still ships
  the zero-filled rows).
- precision (``$DMLP_TPU_PRECISION``): a "bf16" first pass runs as
  bfloat16 STAGING (the streamed operands of the distance dot are
  bf16; accumulation stays f32 per ops.distance) — resolved before the
  solve, so the existing staging machinery supplies the widened
  resolve_kcap window and the staging_eps hazard test that keep the
  f64 rescore byte-exact. Fast mode never applies it (no repair
  backstop), same contract as everywhere else.
- fused (``$DMLP_TPU_FUSED``): the GSPMD program streams with the
  XLA selects (no Pallas dispatch inside the partitioned jit — a
  manually-tiled kernel would need its own partitioning rules, exactly
  the hand-rolling this engine exists to avoid), so the toggle cannot
  change its results.

No ANALYTIC comms model: the schedule is the compiler's, so
obs.comms.engine_comms returns the honest empty for the "gspmd" merge
strategy rather than asserting traffic this module never dispatched.
Since PR 20 the record is no longer empty, though — it is *derived*:
:meth:`AutoShardedEngine.comms_from_hlo` reads the compiled program's
collective schedule (obs.hlo) and populates ``last_comms`` with
``gspmd_*`` traffic records naming which collectives the partitioner
actually chose, on which mesh axis, and how many bytes they move. The
derivation lowers outside the timed region and only when introspection
is requested (CLI ``--hlo-report``, bench ``--auto-ab``), so the solve
path itself stays claim-free.
"""

from __future__ import annotations

import contextlib
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.sharded import ShardedEngine
from dmlp_tpu.engine.single import (fit_blocks, pad_dataset, resilient_get,
                                    resolve_kcap, round_up)
from dmlp_tpu.io.grammar import KNNInput
from dmlp_tpu.io.report import QueryResult
from dmlp_tpu.obs import counters as obs_counters
from dmlp_tpu.obs import memwatch, telemetry
from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.ops.topk import TopK, select_topk, streaming_topk
from dmlp_tpu.parallel.mesh import DATA_AXIS, QUERY_AXIS
from dmlp_tpu.resilience import inject as rs_inject
from dmlp_tpu.resilience import retry as rs_retry


class AutoShardedEngine(ShardedEngine):
    """GSPMD-partitioned engine over the same 2D ("data", "query") mesh.

    Subclasses :class:`~dmlp_tpu.engine.sharded.ShardedEngine` for the
    whole host-side contract (``run``/``_run`` fetch -> finalize ->
    boundary repair, ``candidates``, staging-dtype bookkeeping) and
    replaces only the device solve: no ``shard_map``, no explicit
    collective — one jit with pinned in/out shardings and a
    ``with_sharding_constraint`` merge point.
    """

    # Not a hand-rolled merge: obs.comms has no analytic model for a
    # compiler-chosen schedule; comms_from_hlo() derives the real one
    # from the compiled program on request.
    _merge_strategy = "gspmd"

    def __init__(self, config: EngineConfig = EngineConfig(mode="auto"),
                 mesh: Optional[Mesh] = None):
        super().__init__(config, mesh)
        axes = set(self.mesh.axis_names)
        missing = sorted({DATA_AXIS, QUERY_AXIS} - axes)
        if missing:
            # The sharding constraints below name these axes; GSPMD
            # would fail at trace time with an opaque error — fail at
            # construction with the actual contract instead.
            raise ValueError(
                f"auto engine mesh must declare axes "
                f"({DATA_AXIS!r}, {QUERY_AXIS!r}); got "
                f"{tuple(self.mesh.axis_names)} (missing {missing})")

    # -- precision composition (resolved OUTSIDE the jit) --------------------
    @contextlib.contextmanager
    def _precision_staging(self):
        """The auto engine's bf16 first pass IS bf16 staging: swap the
        wire/operand dtype for the solve so every existing margin
        (resolve_kcap's 96 + k/2 window, _run's staging_eps hazard
        test) applies unchanged. Only in exact mode (resolve_precision
        already returns "f32" in fast mode) and only when staging is
        not already bf16."""
        if self.config.resolve_precision() != "bf16" \
                or self._staging != "float32":
            yield
            return
        self._staging, self._dtype = "bfloat16", jnp.bfloat16
        try:
            yield
        finally:
            self._staging, self._dtype = "float32", jnp.float32

    def run(self, inp: KNNInput) -> List[QueryResult]:
        with self._precision_staging():
            return super().run(inp)

    # -- the compiled GSPMD program ------------------------------------------
    def _fn_auto(self, k: int, data_block: int, select: str):
        """One pure-jit solve: vmap the per-shard streaming fold over
        the data-sharded leading axis, then reshard the concatenated
        candidates to query-partitioned and re-select with the
        composite (dist asc, id desc) order. in/out shardings are
        pinned (check R902) so the partitioner sees the full placement
        contract instead of inferring it from the first dispatch."""
        key = ("auto", k, data_block, select)
        if key not in self._fns:
            mesh = self.mesh
            dsh3 = NamedSharding(mesh, P(DATA_AXIS, None, None))
            dsh2 = NamedSharding(mesh, P(DATA_AXIS, None))
            qsh = NamedSharding(mesh, P(QUERY_AXIS, None))
            use_pallas = self.config.use_pallas

            def solve(d_attrs, d_labels, d_ids, q_attrs):
                def cell(a, lab, ids):
                    return streaming_topk(q_attrs, a, lab, ids, k=k,
                                          data_block=data_block,
                                          select=select,
                                          use_pallas=use_pallas)

                # (R, shard_rows, A): the leading axis IS the mesh data
                # axis, so the per-shard folds stay local to their tile.
                tops = jax.vmap(cell)(d_attrs, d_labels, d_ids)
                # The merge point. Collapsing the shard axis into the
                # candidate axis and constraining the result onto the
                # query axis is the data->query reshard the hand-rolled
                # engines spell as allgather_merge_topk /
                # ring_allreduce_topk — here GSPMD schedules it.
                qpad = q_attrs.shape[0]
                md = jnp.moveaxis(tops.dists, 0, 1).reshape(qpad, -1)
                ml = jnp.moveaxis(tops.labels, 0, 1).reshape(qpad, -1)
                mi = jnp.moveaxis(tops.ids, 0, 1).reshape(qpad, -1)
                md = jax.lax.with_sharding_constraint(md, qsh)
                ml = jax.lax.with_sharding_constraint(ml, qsh)
                mi = jax.lax.with_sharding_constraint(mi, qsh)
                return select_topk(md, ml, mi, k)

            self._fns[key] = jax.jit(
                solve,
                in_shardings=(dsh3, dsh2, dsh2, qsh),
                out_shardings=TopK(qsh, qsh, qsh))
        return self._fns[key]

    # -- staging + solve ------------------------------------------------------
    def _solve_auto(self, inp: KNNInput, allow_prune: bool):
        """Stage (data-sharded 3D view + query-sharded queries), run the
        GSPMD program, return the single segment the inherited ``_run``
        finalizes. Pruning masks whole (shard, chunk) blocks on host
        before staging — sentinel rows fold as provable no-ops."""
        import time as _time

        cfg = self.config
        n = inp.params.num_data
        nq = inp.params.num_queries
        na = inp.params.num_attrs
        r, c = self.mesh.devices.shape

        kmax = int(inp.ks.max()) if nq else 1
        shard_rows_est = round_up(max(-(-n // r), 1), 8)
        select = cfg.resolve_streaming_select(shard_rows_est)
        data_block = min(cfg.data_block, shard_rows_est) \
            if cfg.data_block is not None else \
            fit_blocks(max(-(-n // r), 1), cfg.resolve_data_block(select),
                       granule=cfg.resolve_granule(select))
        self._last_select = select

        attrs, labels, ids = pad_dataset(inp, r * data_block, np.float32)
        shard_rows = attrs.shape[0] // r
        qpad = c * round_up(max(-(-nq // c), 1), 8)
        k = resolve_kcap(cfg, kmax, select, r * shard_rows,
                         staging=self._staging)

        # Prune stage 0+1 (host, outside the jit): the mesh block plan
        # at data_block granularity. A pruned block's rows stage as
        # sentinel zeros — never read from host DRAM, though the
        # monolithic device_put still ships them (see module docstring).
        nchunks = shard_rows // data_block
        keep_m, prune_stats = self._plan_prune_mesh(
            inp, r, shard_rows, nchunks, data_block, allow_prune,
            precision="f32")
        np_dtype = self._np_dtype()
        item = np.dtype(np_dtype).itemsize
        scanned = n * na * item
        if keep_m is not None:
            for rr in range(r):
                for t in range(nchunks):
                    if keep_m[rr, t]:
                        continue
                    lo = rr * shard_rows + t * data_block
                    hi = min(lo + data_block, (rr + 1) * shard_rows, n)
                    if hi > lo:
                        attrs[lo:hi] = 0
                        labels[lo:hi] = -1
                        ids[lo:hi] = -1
                        scanned -= (hi - lo) * na * item
        from dmlp_tpu.ops.summaries import note_scan
        note_scan(self, scanned_bytes=scanned,
                  dense_bytes=n * na * item,
                  blocks_total=(prune_stats or {}).get(
                      "blocks_total",
                      sum(1 for rr in range(r) for t in range(nchunks)
                          if min(rr * shard_rows + (t + 1) * data_block,
                                 (rr + 1) * shard_rows, n)
                          > rr * shard_rows + t * data_block)),
                  blocks_pruned=(prune_stats or {}).get(
                      "blocks_pruned", 0))

        t0 = _time.perf_counter()
        dsh3 = NamedSharding(self.mesh, P(DATA_AXIS, None, None))
        dsh2 = NamedSharding(self.mesh, P(DATA_AXIS, None))
        qsh = NamedSharding(self.mesh, P(QUERY_AXIS, None))
        q_attrs = np.zeros((qpad, na), np.float32)
        q_attrs[:nq] = inp.query_attrs
        with obs_span("auto.stage_enqueue",
                      mesh=list(self.mesh.devices.shape)):
            # One-hop staging straight into the jit's pinned shardings
            # (same rationale as ShardedEngine._shard_inputs_inner).
            args = (
                jax.device_put(
                    attrs.astype(np_dtype, copy=False).reshape(
                        r, shard_rows, na), dsh3),
                jax.device_put(labels.reshape(r, shard_rows), dsh2),
                jax.device_put(ids.reshape(r, shard_rows), dsh2),
                jax.device_put(q_attrs.astype(np_dtype, copy=False), qsh))
        self.last_phase_ms["stage_enqueue"] = \
            (_time.perf_counter() - t0) * 1e3

        fn = self._fn_auto(k, data_block, select)
        obs_counters.record_dispatch(fn, args, site="auto.solve")
        # Shape specs only (no buffers kept alive): comms_from_hlo()
        # re-lowers this signature post-solve to read the schedule.
        self._last_dispatch = (fn, jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args))

        def _op():
            rs_inject.fire("auto.solve", which="gspmd")
            return fn(*args)

        with obs_span("auto.solve", select=select,
                      mesh=[r, c], kcap=k) as sp:
            # Re-dispatching the jitted program on the same placed
            # arrays is idempotent — the retry wrapper's requirement.
            top = rs_retry.call_with_retry(_op, "auto.solve")
            sp.fence(top.dists)
        telemetry.sample_memory_now()
        return [(top, qpad, None, select)]

    # -- engine entry points --------------------------------------------------
    def _reset_solve_state(self) -> None:
        self.last_hetk = None        # no heterogeneous-k split: the
        # streaming selects take any k natively, so nothing routes
        self.last_phase_ms = {}
        self.last_comms = []         # compiler-chosen schedule: no
        # analytic traffic claim until comms_from_hlo() derives the
        # real one from the compiled program (module docstring)
        self._last_dispatch = None
        self._pending_iters = []
        self.last_extract_impl = None
        self.last_prune = None

    def comms_from_hlo(self):
        """Derive the REAL comms record from the compiled program.

        Lowers the last solve's dispatch signature (shape specs stored
        by ``_solve_auto``), reads its collective schedule via obs.hlo,
        and populates ``last_comms`` with ``gspmd_*`` CollectiveTraffic
        records (which collectives GSPMD chose, on which mesh axis, how
        many bytes). Returns the :class:`~dmlp_tpu.obs.hlo.HloReport`,
        or None when no solve ran or the signature cannot lower —
        introspection never raises into the solve path. Call it OUTSIDE
        the timed region: the AOT lower+compile is not free (the
        fingerprint cache dedupes repeat calls)."""
        from dmlp_tpu.obs import hlo as obs_hlo
        disp = getattr(self, "_last_dispatch", None)
        if disp is None:
            return None
        fn, specs = disp
        rep = obs_hlo.report_for_fn(fn, specs, label="auto.solve")
        if rep is None:
            return None
        mesh_axes = dict(zip(self.mesh.axis_names,
                             self.mesh.devices.shape))
        self.last_comms = obs_hlo.traffic_from_report(
            rep, mesh_axes=mesh_axes)
        return rep

    def _solve_segments(self, inp: KNNInput):
        self._reset_solve_state()
        # Precision resolves outside the jit; run() already swapped the
        # staging dtype when the bf16 first pass applies, so the ACTIVE
        # record is whatever the solve actually stages with.
        prec = self.config.resolve_precision()
        self.last_precision = {
            "active": "bf16" if (prec == "bf16"
                                 and self._staging == "bfloat16")
            else "f32",
            "configured": prec}
        return self._solve_auto(inp, allow_prune=self.config.exact)

    def _candidates(self, inp: KNNInput):
        nq = inp.params.num_queries
        self._reset_solve_state()
        memwatch.note_engine_model(self, inp)
        # Same dense-scan rationale as ShardedEngine._candidates: the
        # per-shard candidate-horizon consumers preclude global-k
        # pruning.
        [(top, _qpad, _idx, _select)] = self._solve_auto(
            inp, allow_prune=False)
        od, ol, oi = resilient_get((top.dists, top.labels, top.ids),
                                   site="auto.fetch")
        return (np.asarray(od, np.float64)[:nq], ol[:nq], oi[:nq])

    def solve_global(self, d_attrs, d_labels, d_ids, q_attrs, kmax: int):
        # engine.sharded._fn now carries a "gspmd" merged program (the
        # fleet's merge="auto" stream path uses it single-controller),
        # but the multi-host contract feed (parallel.distributed) has
        # never been qualified against it. Multi-host GSPMD is the
        # TPU-round follow-on (ROADMAP); fail loudly until then.
        raise NotImplementedError(
            "AutoShardedEngine has no multi-host contract path yet; "
            "use mode='sharded'/'ring' for parallel.distributed feeds")

    def solve_local_shards(self, d_attrs, d_labels, d_ids, q_attrs,
                           kmax: int):
        raise NotImplementedError(
            "AutoShardedEngine has no multi-host contract path yet; "
            "use mode='sharded'/'ring' for parallel.distributed feeds")

    def _run_device_full(self, inp: KNNInput) -> List[QueryResult]:
        from dmlp_tpu.engine.single import (_device_epilogue,
                                            flush_measured_iters)

        nq = inp.params.num_queries
        num_labels = int(inp.labels.max()) + 1 if inp.params.num_data else 1
        ksh = NamedSharding(self.mesh, P(QUERY_AXIS))
        self._reset_solve_state()
        memwatch.note_engine_model(self, inp)
        # Device-full output IS the device ordering — no repair
        # backstop, so no pruning (same contract as the mesh engines).
        [(top, qpad, _idx, _select)] = self._solve_auto(
            inp, allow_prune=False)
        ks_pad = np.zeros(qpad, np.int32)
        ks_pad[:nq] = inp.ks
        p, i, d = _device_epilogue(top, jax.device_put(ks_pad, ksh),
                                   num_labels=num_labels)
        p, i, d = resilient_get((p, i, d), site="auto.fetch")
        preds = p[:nq]
        rids = i[:nq]
        rd = np.asarray(d, np.float64)[:nq]
        results = [QueryResult(qi, int(inp.ks[qi]), int(preds[qi]),
                               rids[qi, : int(inp.ks[qi])].astype(np.int64),
                               rd[qi, : int(inp.ks[qi])])
                   for qi in range(nq)]
        flush_measured_iters(self)
        return results
