"""Single-chip KNN engine — the minimum end-to-end slice (survey §7 L1).

One jitted function does what the reference's whole MPI choreography does on
a grid of CPU ranks (engine.cpp:20-351): distances ride the MXU as a matmul
(dmlp_tpu.ops.distance), selection is an exact-tie-break sort
(dmlp_tpu.ops.topk), and queries/data stream in blocks so the (Q, N) distance
matrix never materializes. The scatter/bcast phases (engine.cpp:62-209)
vanish: one chip holds the (padded) arrays in HBM.

Two output paths:

- ``candidates()`` + host finalize (default, ``run()``): the device returns
  top-(kmax + margin) candidate lists; the host rescores them in float64 and
  applies vote/report semantics — checksum parity with the float64 golden
  model while the MXU does the O(Q*N*A) work in f32/bf16.
- ``run_device_full()``: vote + report ordering on-device too (benchmark
  path; no float64 rescue).
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.finalize import finalize_host, repair_boundary_overflow
from dmlp_tpu.io.grammar import KNNInput
from dmlp_tpu.io.report import QueryResult
from dmlp_tpu.ops.topk import TopK, init_topk, make_block_step, streaming_topk
from dmlp_tpu.ops.vote import majority_vote, report_order

# Per-chunk distance-tile budget for the pipelined driver (bytes). The live
# tile is (query_rows x chunk_rows) f32; chunk/query blocking keeps it under
# this so HBM never holds a Q x N matrix.
_TILE_BUDGET = 1 << 30


def round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def plan_chunks(n: int, granule: int, target: int | None) -> Tuple[int, int, int]:
    """Chunked-staging plan shared by the pipelined and extract drivers:
    (npad, nchunks, chunk_rows) — ~``target``-row chunks (default 51200,
    measured best on the tunneled v5e link: big enough that per-chunk merge
    work stays negligible, small enough that the first fold starts while
    later chunks are still in flight) of whole ``granule`` blocks covering
    ``n``. Large granules can make the final chunk all padding; drivers
    skip staging it."""
    npad = round_up(max(n, 1), granule)
    t = round_up(target or 51200, granule)
    nchunks = max(1, -(-npad // t))
    chunk_rows = round_up(-(-npad // nchunks), granule)
    return npad, nchunks, chunk_rows


def fit_blocks(n: int, target_block: int, granule: int = 8) -> int:
    """A data_block (multiple of ``granule``, <= ~target_block) whose
    round_up padding wastes < granule * nblocks rows of n.

    Plain round_up(n, target_block) can waste up to target_block - 1 rows
    (31% at n=200k, target=64k) — real compute, since padded rows still ride
    the matmul. Shrinking the block to ~n/nblocks keeps the scan length and
    the waste both minimal. The "seg" selection needs granule=128 (whole
    lane-width segments).
    """
    n = max(n, 1)
    nblocks = max(1, -(-n // max(target_block, granule)))
    return round_up(-(-n // nblocks), granule)


def resolve_kcap(cfg: EngineConfig, kmax: int, select: str, cap: int) -> int:
    """Device candidate-list width: kmax + margin, rounded to 8, clamped to
    [kmax, cap]. The fast selection paths get >= 8 slack beyond kmax even
    with margin 0: the tie-overflow detector compares the k-th and last
    candidate, which coincide without slack (degenerate all-repair)."""
    extra = cfg.margin if cfg.exact else 0
    if select in ("topk", "seg", "extract"):
        extra = max(extra, 8)
    return max(min(round_up(kmax + extra, 8), cap), kmax)


def pad_dataset(inp: KNNInput, multiple: int, dtype: np.dtype
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad (attrs, labels, ids) to a multiple of ``multiple`` rows.

    Sentinel rows carry label = -1 and id = -1; the distance kernel masks
    them to +inf (masked_pairwise_sq_l2). This replaces the reference's
    uneven remainder shards (engine.cpp:62-63) — XLA wants static, uniform
    shapes.

    ``dtype`` should be the host-side staging dtype: padding straight into
    float32 halves the memcpy and the host->device bytes relative to staging
    in the parser's float64 (the f64 originals stay available for the exact
    host rescore).
    """
    n = inp.params.num_data
    npad = round_up(max(n, 1), multiple)
    attrs = np.zeros((npad, inp.params.num_attrs), dtype)
    attrs[:n] = inp.data_attrs
    labels = np.full(npad, -1, np.int32)
    labels[:n] = inp.labels
    ids = np.full(npad, -1, np.int32)
    ids[:n] = np.arange(n, dtype=np.int32)
    return attrs, labels, ids


@functools.partial(jax.jit, static_argnames=("k", "select", "use_pallas"))
def _chunk_fold(carry: TopK, q_attrs, battrs, blabels, bids, *, k, select,
                use_pallas=False) -> TopK:
    """Fold one data chunk into the running top-k (pipelined driver step).

    One dispatch per chunk: the host enqueues chunk transfers and fold
    dispatches back-to-back, so the device DMAs chunk i+1 while computing
    chunk i — the async replacement for the reference's scatter-then-compute
    phasing (engine.cpp:62-131, :233-257), which matters here because the
    host->device link (not the MXU) bounds the solve.
    """
    step = make_block_step(select, k, use_pallas, carry.dists.dtype)
    return step(carry, q_attrs, battrs, blabels, bids)


@jax.jit
def _device_flags(dists, ks):
    """Per-query tie-overflow hazard flags, computed on device so the exact
    path never reads the (Q, K) distance matrix back over the link (see
    engine.finalize.boundary_overflow for the hazard derivation)."""
    kcap = dists.shape[1]
    last = dists[:, kcap - 1]
    kth = jnp.take_along_axis(
        dists, jnp.clip(ks[:, None] - 1, 0, kcap - 1), axis=1)[:, 0]
    return jnp.isfinite(last) & (last == kth)


@functools.partial(jax.jit, static_argnames=("k",))
def _extract_finalize(od, oi, glabels, *, k):
    """Extraction-kernel epilogue: gather labels from global ids and sort
    the (unordered) running lists into the golden selection order
    (dist asc, label desc, id desc) — a tiny (Q, K) composite sort."""
    from dmlp_tpu.ops.topk import select_topk
    n = glabels.shape[0]
    labels = jnp.where(oi >= 0, glabels[jnp.clip(oi, 0, max(n - 1, 0))], -1)
    return select_topk(od, labels, oi, k)


@functools.partial(jax.jit,
                   static_argnames=("k", "data_block", "select", "use_pallas"))
def _topk_blocks(data_attrs, data_labels, data_ids, q_blocks, *, k,
                 data_block, select, use_pallas=False):
    """All query blocks in one dispatch: ``lax.map`` keeps the live distance
    tile at (query_block x data_block) while avoiding per-block Python
    dispatch + per-block device->host readbacks (which dominate over a
    tunneled PJRT link)."""
    return jax.lax.map(
        lambda q: streaming_topk(q, data_attrs, data_labels, data_ids,
                                 k=k, data_block=data_block, select=select,
                                 use_pallas=use_pallas),
        q_blocks)


@functools.partial(jax.jit, static_argnames=("num_labels",))
def _device_epilogue(top: TopK, ks, *, num_labels):
    """Vote + report ordering on-device over (Q, K) candidate lists — the
    reference's result post-processing (engine.cpp:314-347) as a tiny
    epilogue jit shared by every device-full select path (including the
    flagship extraction kernel, whose lists _solve already sorts)."""
    rd, rids, in_k = report_order(top, ks)
    valid = in_k & (top.ids >= 0)
    predicted = majority_vote(top.labels, valid, num_labels)
    return predicted, rids, rd


class SingleChipEngine:
    """The one-chip engine (CPU backend in CI, TPU in production)."""

    def __init__(self, config: EngineConfig = EngineConfig()):
        self.config = config
        self._dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
        self.last_phase_ms: dict = {}

    def _prep(self, inp: KNNInput):
        cfg = self.config
        n = inp.params.num_data
        # The scan/device-full paths fold arbitrary-id blocks, so
        # "extract" remaps here (and the granule must match what runs —
        # the extract granule has no 1024-divisor for the seg producer).
        select = cfg.resolve_streaming_select(round_up(max(n, 1), 8))
        if cfg.data_block is not None:
            data_block = min(cfg.data_block, round_up(max(n, 1), 8))
        else:
            data_block = fit_blocks(n, cfg.resolve_data_block(select),
                                    granule=cfg.resolve_granule(select))
        attrs, labels, ids = pad_dataset(inp, data_block, np.float32)
        kmax = int(inp.ks.max()) if inp.params.num_queries else 1
        k = resolve_kcap(cfg, kmax, select, attrs.shape[0])
        d_attrs = jnp.asarray(attrs, self._dtype)
        self._last_select = select  # run() gates the tie-overflow repair on it
        return (d_attrs, jnp.asarray(labels), jnp.asarray(ids), k, data_block,
                select)

    def _solve_scan(self, inp: KNNInput) -> Tuple[TopK, int]:
        """Whole-dataset staging + one lax.map/scan dispatch ("sort" path)."""
        cfg = self.config
        d_attrs, d_labels, d_ids, k, data_block, select = self._prep(inp)
        nq = inp.params.num_queries
        qb = min(cfg.query_block, round_up(max(nq, 1), 8))
        qpad = round_up(max(nq, 1), qb)
        q_attrs = np.zeros((qpad, inp.params.num_attrs), np.float32)
        q_attrs[:nq] = inp.query_attrs
        q_blocks = jnp.asarray(
            q_attrs.reshape(qpad // qb, qb, -1), self._dtype)

        out: TopK = _topk_blocks(d_attrs, d_labels, d_ids, q_blocks,
                                 k=k, data_block=data_block, select=select,
                                 use_pallas=cfg.use_pallas)
        return TopK(out.dists.reshape(qpad, -1), out.labels.reshape(qpad, -1),
                    out.ids.reshape(qpad, -1)), qpad

    def _solve_pipelined(self, inp: KNNInput) -> Tuple[TopK, int]:
        """Chunked staging + one fold dispatch per chunk ("topk"/"seg").

        The dataset is staged in ~chunk_rows-row pieces, each followed by
        its fold dispatch; transfers and compute are enqueued back-to-back
        so the device DMAs chunk i+1 while folding chunk i. On a
        bandwidth-limited host link (tunneled PJRT, or a pod feeding over
        DCN) the solve then costs ~max(transfer, compute), not their sum.
        """
        import time as _time

        cfg = self.config
        n = inp.params.num_data
        na = inp.params.num_attrs
        nq = inp.params.num_queries
        # resolve_streaming_select: only reached when the extraction kernel
        # can't tile this shape (or select != extract in the first place)
        select = cfg.resolve_streaming_select(round_up(max(n, 1), 8))
        self._last_select = select
        granule = cfg.resolve_granule(select)

        t0 = _time.perf_counter()
        npad, nchunks, chunk_rows = plan_chunks(n, granule, cfg.data_block)

        # Query padding: multiples of 1024 keep the fused Pallas tiling
        # eligible (ops.pallas_distance.supports); 8 otherwise.
        qgran = 1024 if (cfg.use_pallas and select == "seg"
                         and nq > 1024) else 8
        qpad = round_up(max(nq, 1), qgran)
        # Bound the live (query_rows x chunk_rows) f32 tile by both the
        # configured query_block and the HBM tile budget.
        qsb = min(qpad, round_up(cfg.query_block, qgran))
        while qsb > qgran and qsb * chunk_rows * 4 > _TILE_BUDGET:
            qsb -= qgran
        nqb = -(-qpad // qsb)
        qpad = nqb * qsb

        kmax = int(inp.ks.max()) if nq else 1
        k = resolve_kcap(cfg, kmax, select, nchunks * chunk_rows)

        q_attrs = np.zeros((qpad, na), np.float32)
        q_attrs[:nq] = inp.query_attrs
        q_dev = [jnp.asarray(q_attrs[i * qsb:(i + 1) * qsb], self._dtype)
                 for i in range(nqb)]

        # Stage chunks (async puts) and enqueue their folds immediately.
        carries = [init_topk(qsb, k) for _ in range(nqb)]
        src_attrs = np.ascontiguousarray(inp.data_attrs, np.float32)
        for c in range(nchunks):
            lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
            a = np.zeros((chunk_rows, na), np.float32)
            lab = np.full(chunk_rows, -1, np.int32)
            ids = np.full(chunk_rows, -1, np.int32)
            if hi > lo:
                a[:hi - lo] = src_attrs[lo:hi]
                lab[:hi - lo] = inp.labels[lo:hi]
                ids[:hi - lo] = np.arange(lo, hi, dtype=np.int32)
            da = jnp.asarray(a, self._dtype)
            dl, di = jnp.asarray(lab), jnp.asarray(ids)
            for b in range(nqb):
                carries[b] = _chunk_fold(carries[b], q_dev[b], da, dl, di,
                                         k=k, select=select,
                                         use_pallas=cfg.use_pallas)
        self.last_phase_ms["enqueue"] = (_time.perf_counter() - t0) * 1e3

        if nqb == 1:
            return carries[0], qpad
        return TopK(*(jnp.concatenate(parts) for parts in
                      zip(*carries))), qpad

    def _solve_extract(self, inp: KNNInput) -> Tuple[TopK, int] | None:
        """Chunked staging + the fused extraction kernel (select="extract").

        Each ~50k-row chunk is staged asynchronously and folded into the
        running (Q, K) lists by ops.pallas_extract.extract_topk — the
        distance tile lives only in VMEM, so HBM holds just the chunk, the
        queries, and the lists. Chunk row ranges are contiguous, giving the
        kernel its trace-time-affine ids (id_base = chunk start). Returns
        None when the kernel can't tile this shape (caller falls back).
        """
        import time as _time

        from dmlp_tpu.ops.pallas_distance import native_pallas_backend
        from dmlp_tpu.ops.pallas_extract import extract_topk
        from dmlp_tpu.ops.pallas_extract import supports as extract_supports

        cfg = self.config
        n = inp.params.num_data
        na = inp.params.num_attrs
        nq = inp.params.num_queries
        if n == 0 or nq == 0:
            return None

        granule = cfg.resolve_granule("extract")
        t0 = _time.perf_counter()
        npad, nchunks, chunk_rows = plan_chunks(n, granule, cfg.data_block)
        # Queries pad to a whole query tile for the same reason data pads
        # to whole extraction blocks: an awkward qb (e.g. 8 * prime) would
        # force a degenerate 8-row query tile.
        from dmlp_tpu.ops.pallas_extract import QUERY_TILE
        qpad = round_up(nq, QUERY_TILE)
        kmax = int(inp.ks.max())
        k = resolve_kcap(cfg, kmax, "extract", nchunks * chunk_rows)
        if not extract_supports(qpad, chunk_rows, na, k):
            return None
        interpret = not native_pallas_backend()
        self._last_select = "extract"

        q_attrs = np.zeros((qpad, na), np.float32)
        q_attrs[:nq] = inp.query_attrs
        q_dev = jnp.asarray(q_attrs, self._dtype)
        src_attrs = np.ascontiguousarray(inp.data_attrs, np.float32)
        od = oi = None
        for c in range(nchunks):
            lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
            if lo >= n:
                break  # whole-block padding can leave an empty last chunk
            a = np.zeros((chunk_rows, na), np.float32)
            if hi > lo:
                a[:hi - lo] = src_attrs[lo:hi]
            da = jnp.asarray(a, self._dtype)
            od, oi, _iters = extract_topk(
                q_dev, da, od, oi, n_real=hi - lo, id_base=lo, kc=k,
                interpret=interpret)
        self.last_phase_ms["enqueue"] = (_time.perf_counter() - t0) * 1e3

        top = _extract_finalize(od, oi, jnp.asarray(inp.labels), k=k)
        return top, qpad

    def _solve(self, inp: KNNInput) -> Tuple[TopK, int]:
        self.last_phase_ms = {}  # no stale phases if a path is skipped
        select = self.config.resolve_select(
            round_up(max(inp.params.num_data, 1), 8))
        if select == "sort":
            return self._solve_scan(inp)
        if select == "extract":
            out = self._solve_extract(inp)
            if out is not None:
                return out
            # shape untileable for the extraction kernel — fall through to
            # the chunk-fold driver on the best remaining path
        return self._solve_pipelined(inp)

    def candidates(self, inp: KNNInput) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Device pass: (Q, K) selection-ordered candidate lists as NumPy."""
        out, qpad = self._solve(inp)
        nq = inp.params.num_queries
        dists = np.asarray(out.dists, np.float64)[:nq]
        labels = np.asarray(out.labels)[:nq]
        ids = np.asarray(out.ids)[:nq]
        return dists, labels, ids

    def run(self, inp: KNNInput) -> List[QueryResult]:
        """Full parity pipeline: device candidates + host float64 finalize.

        On the fast "topk"/"seg" selection paths, queries whose candidate
        set may have truncated a distance-tie group (boundary_overflow) are
        recomputed exactly — parity holds on either path.

        Readback is kept minimal: in exact mode only the candidate ids and
        the device-computed hazard flags cross the link (labels are
        re-derived from ids on host, distances are rescored in float64
        anyway); the (Q, K) f32 distance matrix is fetched only in fast
        mode, where it is the result.
        """
        import time as _time

        nq = inp.params.num_queries
        n = inp.params.num_data
        top, qpad = self._solve(inp)
        kcap = top.dists.shape[1]

        flags_dev = None
        if self._last_select in ("topk", "seg", "extract") and kcap < n:
            ks_pad = np.ones(qpad, np.int32)
            ks_pad[:nq] = inp.ks
            flags_dev = _device_flags(top.dists, jnp.asarray(ks_pad))

        t0 = _time.perf_counter()
        # NOTE: the "fetch" phase time includes the wait for all enqueued
        # device work (staging + solve), not just the readback bytes — the
        # enqueue phase above is host dispatch only. Don't read this table
        # as "readback costs X ms".
        fetch = ([] if self.config.exact else [top.dists]) + [top.ids] \
            + ([flags_dev] if flags_dev is not None else [])
        fetched = list(jax.device_get(fetch))
        dists = None if self.config.exact \
            else np.asarray(fetched.pop(0), np.float64)[:nq]
        ids = fetched.pop(0)[:nq]
        flags = fetched.pop(0)[:nq] if flags_dev is not None else None
        labels = np.where(ids >= 0,
                          inp.labels[np.clip(ids, 0, max(n - 1, 0))], -1) \
            if n else np.full_like(ids, -1)
        self.last_phase_ms["fetch"] = (_time.perf_counter() - t0) * 1e3

        t0 = _time.perf_counter()
        results = finalize_host(dists, labels, ids, inp.ks, inp.query_attrs,
                                inp.data_attrs, exact=self.config.exact)
        self.last_repairs = 0  # tie-overflow repair rate, for bench records
        if flags is not None:
            suspects = np.nonzero(flags)[0]
            if suspects.size:
                repair_boundary_overflow(results, suspects, inp)
                self.last_repairs = int(suspects.size)
        self.last_phase_ms["finalize"] = (_time.perf_counter() - t0) * 1e3
        return results

    def run_device_full(self, inp: KNNInput) -> List[QueryResult]:
        """All-device pipeline (vote + report order on TPU); f32 ordering.

        Runs the same ``_solve`` as ``run()`` — so the flagship extraction
        kernel (and the pipelined chunk overlap) serves this benchmark mode
        too — then votes and report-orders on device via the epilogue jit;
        only the final (Q, K) report lists cross the link.
        """
        nq = inp.params.num_queries
        num_labels = int(inp.labels.max()) + 1 if inp.params.num_data else 1
        top, qpad = self._solve(inp)
        ks_pad = np.zeros(qpad, np.int32)
        ks_pad[:nq] = inp.ks

        p, i, d = _device_epilogue(top, jnp.asarray(ks_pad),
                                   num_labels=num_labels)
        preds = np.asarray(p)[:nq]
        rids = np.asarray(i)[:nq]
        rd = np.asarray(d, np.float64)[:nq]
        return [QueryResult(qi, int(inp.ks[qi]), int(preds[qi]),
                            rids[qi, : int(inp.ks[qi])].astype(np.int64),
                            rd[qi, : int(inp.ks[qi])])
                for qi in range(nq)]
