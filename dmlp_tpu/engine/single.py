"""Single-chip KNN engine — the minimum end-to-end slice (survey §7 L1).

One jitted function does what the reference's whole MPI choreography does on
a grid of CPU ranks (engine.cpp:20-351): distances ride the MXU as a matmul
(dmlp_tpu.ops.distance), selection is an exact-tie-break sort
(dmlp_tpu.ops.topk), and queries/data stream in blocks so the (Q, N) distance
matrix never materializes. The scatter/bcast phases (engine.cpp:62-209)
vanish: one chip holds the (padded) arrays in HBM.

Two output paths:

- ``candidates()`` + host finalize (default, ``run()``): the device returns
  top-(kmax + margin) candidate lists; the host rescores them in float64 and
  applies vote/report semantics — checksum parity with the float64 golden
  model while the MXU does the O(Q*N*A) work in f32/bf16.
- ``run_device_full()``: vote + report ordering on-device too (benchmark
  path; no float64 rescue).
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.finalize import (boundary_hazard, finalize_host,
                                      lowp_eps, repair_boundary_overflow,
                                      staging_eps)
from dmlp_tpu.io.grammar import KNNInput, subset_queries
from dmlp_tpu.io.report import QueryResult
from dmlp_tpu.obs import counters as obs_counters
from dmlp_tpu.obs import memwatch, telemetry
from dmlp_tpu.obs.trace import span as obs_span
from dmlp_tpu.ops.topk import TopK, init_topk, make_block_step, streaming_topk
from dmlp_tpu.ops.vote import majority_vote, report_order
from dmlp_tpu.resilience import degrade as rs_degrade
from dmlp_tpu.resilience import inject as rs_inject
from dmlp_tpu.resilience import retry as rs_retry

# Per-chunk distance-tile budget for the pipelined driver (bytes). The live
# tile is (query_rows x chunk_rows) f32; chunk/query blocking keeps it under
# this so HBM never holds a Q x N matrix.
_TILE_BUDGET = 1 << 30

# Max staged-but-unfolded chunks in flight. The enqueue loop runs far
# ahead of device execution (staging, not the host, is the bottleneck),
# and every jnp.asarray allocates its device buffer immediately — without
# backpressure a dataset LARGER than HBM would stage itself to death
# before the first folds free their chunks. Blocking on the fold output
# W chunks back caps device residency at ~W chunks while still keeping
# the transfer pipe full (W * 51200 * 64 * 4B ~= 105 MB at the default
# chunk plan).
_CHUNK_WINDOW = 8


class ChunkThrottle:
    """Sliding-window backpressure for chunked staging loops: feed each
    chunk's fold output to tick(); it blocks on the output from
    _CHUNK_WINDOW chunks ago, so at most that many staged chunks (plus
    their folds) are ever in flight on device."""

    def __init__(self, window: int = _CHUNK_WINDOW):
        self._window = window
        self._pending: list = []

    def tick(self, fold_out) -> None:
        self._pending.append(fold_out)
        if len(self._pending) > self._window:
            jax.block_until_ready(self._pending.pop(0))


def round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def np_staging_dtype(staging: str):
    """Host wire dtype for a staging mode ("float32" | "bfloat16").

    The engines convert on HOST and stage with explicit
    ``jax.device_put``: the sanitizer's transfer guard
    (``--sanitize`` / dmlp_tpu.check.sanitize) disallows *implicit*
    transfers, and staging is the one transfer that is the engines'
    explicit job — ``jnp.asarray`` staging would trip the guard on TPU.
    """
    if staging == "bfloat16":
        import ml_dtypes
        return ml_dtypes.bfloat16
    return np.float32


def stage_put(arr: np.ndarray, staging: str = "float32"):
    """Explicit (async) host->device put in the staging wire dtype —
    the transfer-guard-proof spelling of ``jnp.asarray(arr, dtype)``.

    The one staging chokepoint every chunked driver feeds through, so
    it is a registered injection site (``single.stage_put``) and the
    put carries the transient-retry wrapper: re-staging the same host
    array is idempotent by construction. The fire rides INSIDE the
    retried op so an injected transient is consumed by attempt 1 and
    the retry's re-put lands."""
    host = np.asarray(arr, np_staging_dtype(staging))

    def _op():
        rs_inject.fire("single.stage_put")
        return jax.device_put(host)

    return rs_retry.call_with_retry(_op, "single.stage_put")


def resilient_get(values, site: str = "single.fetch"):
    """Fenced device readback (the fetch IS the fence) with fault
    injection + bounded transient retry — ``jax.device_get`` of
    already-enqueued values is idempotent, so a flaky readback retries
    without re-dispatching the solve. ``$DMLP_TPU_OP_TIMEOUT_S`` (off
    by default — the readback IS the solve fence, so its normal
    duration is the solve's) additionally bounds each attempt with a
    worker-thread deadline; the resulting ``OperationTimeout``
    classifies transient, so a slow-but-recoverable fetch retries and
    the ``timeouts`` counter records it."""
    deadline = float(os.environ.get("DMLP_TPU_OP_TIMEOUT_S", "0") or 0)

    def _get():
        rs_inject.fire(site)
        return jax.device_get(values)  # check: allow-host-sync

    def _op():
        # The deadline is part of the resilience layer: with the
        # DMLP_TPU_RESILIENCE=0 kill switch the wrapper must be a
        # direct call (no worker thread, no unretried OperationTimeout).
        if deadline > 0 and rs_retry.resilience_enabled():
            return rs_retry.call_with_timeout(_get, deadline, site=site)
        return _get()

    return rs_retry.call_with_retry(_op, site)


def plan_chunks(n: int, granule: int, target: int | None) -> Tuple[int, int, int]:
    """Chunked-staging plan shared by the pipelined and extract drivers:
    (npad, nchunks, chunk_rows) — ~``target``-row chunks (default 51200,
    measured best on the tunneled v5e link: big enough that per-chunk merge
    work stays negligible, small enough that the first fold starts while
    later chunks are still in flight) of whole ``granule`` blocks covering
    ``n``. Large granules can make the final chunk all padding; drivers
    skip staging it."""
    npad = round_up(max(n, 1), granule)
    t = round_up(target or 51200, granule)
    nchunks = max(1, -(-npad // t))
    chunk_rows = round_up(-(-npad // nchunks), granule)
    return npad, nchunks, chunk_rows


def fit_blocks(n: int, target_block: int, granule: int = 8) -> int:
    """A data_block (multiple of ``granule``, <= ~target_block) whose
    round_up padding wastes < granule * nblocks rows of n.

    Plain round_up(n, target_block) can waste up to target_block - 1 rows
    (31% at n=200k, target=64k) — real compute, since padded rows still ride
    the matmul. Shrinking the block to ~n/nblocks keeps the scan length and
    the waste both minimal. The "seg" selection needs granule=128 (whole
    lane-width segments).
    """
    n = max(n, 1)
    nblocks = max(1, -(-n // max(target_block, granule)))
    return round_up(-(-n // nblocks), granule)


def resolve_kcap(cfg: EngineConfig, kmax: int, select: str, cap: int,
                 staging: str = "float32",
                 precision: str | None = None) -> int:
    """Device candidate-list width: kmax + margin, rounded to 8, clamped to
    [kmax, cap]. The fast selection paths get >= 8 slack beyond kmax even
    with margin 0: the tie-overflow detector compares the k-th and last
    candidate, which coincide without slack (degenerate all-repair).

    bfloat16 staging deepens the margin with k (96 + k/2): its rounding
    reorders device distances non-monotonically by up to
    finalize.staging_eps, and the eps-aware hazard test only stays quiet
    (no oracle-repair fallback) when the candidate horizon clears the
    k-th distance by more than eps — deeper lists buy that clearance
    where distances grow dense. Measured at the 200k x 10k x 64 benchmark
    shape: a 32-slot window leaves 3453/10000 queries flagged, 64 slots
    71, 96 slots 0 — the constant is that measurement plus headroom; the
    (vectorized-oracle) repair stays as the sound backstop for inputs
    whose distance density outruns it.

    ``precision`` is the first-pass dot precision the window must clear
    (config.resolve_precision when None — the inflation is planned from
    the CONFIGURED precision, not the active rung: a bf16-sized window
    fed by an f32 pass is merely generous, never unsound, and planning
    it once keeps the window static across ladder steps). "bf16" reuses
    the bf16-staging depth (96 + k/2): the cast perturbs every distance
    by at most finalize.lowp_eps, the same coef * (qn + dn_max) shape
    as the staging cancellation term that margin was calibrated for."""
    if precision is None:
        precision = cfg.resolve_precision()
    extra = cfg.margin if cfg.exact else 0
    if select in ("sort", "topk", "seg", "extract"):
        extra = max(extra, 8)
    if precision == "bf16" and cfg.exact:
        extra = max(extra, 96 + kmax // 2)
    if staging == "bfloat16" and cfg.exact:
        extra = max(extra, 96 + kmax // 2)
    elif cfg.exact:
        # f32 staging: the cancellation eps (finalize.staging_eps term 2)
        # scales with qn + dn_max, not with k — at wide k the candidate
        # horizon sits in a DENSE part of the distance spectrum and a
        # constant 8-slot margin stops clearing it (measured at
        # 204800 x 1024 x 64, k=4096 on v5e: 809/1024 queries flagged;
        # k/8 extra slots -> 0 flagged, WIDEK_MP_r05). Slots are cheap;
        # oracle repairs are ~30 ms/query.
        extra = max(extra, kmax // 8)
    return max(min(round_up(kmax + extra, 8), cap), kmax)


def pad_dataset(inp: KNNInput, multiple: int, dtype: np.dtype
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad (attrs, labels, ids) to a multiple of ``multiple`` rows.

    Sentinel rows carry label = -1 and id = -1; the distance kernel masks
    them to +inf (masked_pairwise_sq_l2). This replaces the reference's
    uneven remainder shards (engine.cpp:62-63) — XLA wants static, uniform
    shapes.

    ``dtype`` should be the host-side staging dtype: padding straight into
    float32 halves the memcpy and the host->device bytes relative to staging
    in the parser's float64 (the f64 originals stay available for the exact
    host rescore).
    """
    n = inp.params.num_data
    npad = round_up(max(n, 1), multiple)
    attrs = np.zeros((npad, inp.params.num_attrs), dtype)
    attrs[:n] = inp.data_attrs
    labels = np.full(npad, -1, np.int32)
    labels[:n] = inp.labels
    ids = np.full(npad, -1, np.int32)
    ids[:n] = np.arange(n, dtype=np.int32)
    return attrs, labels, ids


def hetk_split(cfg: EngineConfig, staging: str, ks: np.ndarray,
               num_data: int, gate_rows: int):
    """Heterogeneous-k split plan: (bulk_idx, out_idx) or None.

    k is legal up to num_data (generate_input.py:19) but the extraction
    kernel's running lists cap at kc <= 512 (ops.pallas_extract.supports).
    Without routing, ONE huge-k query pushes every query off the flagship
    kernel onto the streaming select. The split keeps queries whose kcap
    fits on the kernel ("bulk") and streams only the wide-k outliers —
    each query is solved exactly once, on the best path its k admits.
    ``gate_rows`` is the row count the auto-select gate sees (whole
    dataset for the single-chip engine, one shard for the mesh engines).
    """
    if len(ks) == 0 or num_data == 0 or not cfg.use_pallas:
        return None
    if cfg.select not in ("auto", "extract"):
        return None
    if cfg.resolve_select(gate_rows) != "extract":
        return None
    # Largest per-query k whose candidate width still fits the kernel's
    # kc cap (the margin is k- and staging-dependent, resolve_kcap).
    k_fit = next((k for k in range(512, 0, -1)
                  if resolve_kcap(cfg, k, "extract", 1 << 30,
                                  staging) <= 512), 0)
    if k_fit == 0 or int(ks.max()) <= k_fit:
        return None      # everything fits: no routing needed
    bulk = np.nonzero(ks <= k_fit)[0]
    out = np.nonzero(ks > k_fit)[0]
    if bulk.size == 0:
        return None      # nothing the kernel could take
    return bulk, out


class MeasuredIters:
    """Lazy per-site accumulator for the extract/fused kernels'
    iteration diagnostics: ``add()`` chains a tiny on-device ``jnp.sum``
    per dispatch (no-op unless a cost probe is installed), ``done()``
    queues the site's device scalar on ``engine._pending_iters`` for the
    post-fence flush (engine._flush_measured_iters) — ONE copy of the
    protocol for the extract paths instead of one per path. ``kernel``
    ("extract" | "fused") rides along so the measured extraction term
    costs its iterations at the kernel's OWN resolved tiles (the fused
    tune-cache namespace can pin different ones)."""

    def __init__(self, engine, site: str,
                 shape: Tuple[int, int, int, int],
                 kernel: str = "extract"):
        self._on = obs_counters.active() is not None
        self._engine, self._site = engine, site
        self._shape, self._kernel = tuple(shape), kernel
        self._sum = None

    def add(self, iters) -> None:
        if self._on:
            s = jnp.sum(iters)
            self._sum = s if self._sum is None else self._sum + s

    def done(self) -> None:
        if self._sum is not None:
            self._engine._pending_iters.append(
                (self._site, self._sum, self._shape, self._kernel))


def flush_measured_iters(engine) -> None:
    """Read back an engine's queued extract-loop iters sums (the solve
    is already fenced by the result fetch, so this is a scalar readback,
    not a sync) and hand them to the installed cost probe — the
    MEASURED extraction term of obs.kernel_cost. No-op when nothing was
    queued (no probe, or a non-extract path ran). Shared by the
    single-chip engine and the mesh engines (both queue through
    MeasuredIters onto ``engine._pending_iters``)."""
    pend = getattr(engine, "_pending_iters", [])
    engine._pending_iters = []
    if not pend:
        return
    for site, s, shape, kernel in pend:
        try:
            obs_counters.record_measured_iters(  # check: allow-host-sync
                site, int(jax.device_get(s)), shape, kernel=kernel)
        except Exception:  # check: no-retry
            pass  # observability must never fail the solve


@contextlib.contextmanager
def no_auto_coarsen(engine):
    """Device-full output IS the device ordering (no f64 rescore or host
    repair licenses a coarser dtype there), so dtype="auto" resolves to
    float32 for the duration of a run_device_full; an EXPLICIT
    dtype="bfloat16" is honored — the caller asked for it."""
    if engine.config.dtype == "auto" and engine._staging == "bfloat16":
        engine._staging, engine._dtype = "float32", jnp.float32
        try:
            yield
        finally:
            engine._staging, engine._dtype = "bfloat16", jnp.bfloat16
    else:
        yield


# Widest kmax dtype="auto" may stage bf16 for. The bf16 kcap margin
# (96 + k/2, resolve_kcap) was calibrated inside the extraction kernel's
# window; far beyond it the margin stops clearing the bf16 eps on dense
# distance spectra — measured on v5e at 204800 x 1024 x 64, k=4096
# (WIDEK_MP_r05): EVERY query flags and the oracle repair (~32 s)
# swamps the 2x staging-transfer win bf16 buys. Auto therefore prefers
# exact-margin f32 staging for wide-k solves; an EXPLICIT
# dtype="bfloat16" is still honored.
_BF16_AUTO_K_CAP = 512


def staging_for_k(engine, kmax: int):
    """no_auto_coarsen-shaped context: swap dtype="auto" bf16 staging to
    float32 for the duration of a wide-k solve (kmax > _BF16_AUTO_K_CAP)."""
    if kmax > _BF16_AUTO_K_CAP:
        return no_auto_coarsen(engine)
    return contextlib.nullcontext()


def active_precision(engine) -> str:
    """First-pass dot precision THIS dispatch actually runs at.

    "bf16" only when all three hold: the config resolves to it
    (config.resolve_precision — ``$DMLP_TPU_PRECISION`` included), the
    solve is exact (the f64 rescore + boundary repair are the backstop
    that makes a lossy first pass sound; fast ordering has none), and
    the resilience ladder still sits on its top "lowp" rung — the first
    OOM step-down gives the low-precision pass (and, on the next plan,
    its inflated window) back before anything else. Resolved OUTSIDE
    every jit and passed as a static argument, so every compiled
    program keys on the result (R2 discipline). Candidate windows
    deliberately do NOT consult this: resolve_kcap plans from the
    CONFIGURED precision so the window stays static across rungs.

    Engines that freeze a precision PLAN at construction (the resident
    serving engines — their bucket kcaps and staged summary-eps
    constants derive from it) expose ``_precision_plan``; the active
    cast clamps to it, so flipping ``$DMLP_TPU_PRECISION`` to "bf16"
    under a server whose windows were planned f32 cannot run a lossy
    pass against uninflated windows. (The f32 flip under a bf16 plan
    is always safe: wider-than-needed windows only.)"""
    if getattr(engine, "_degrade_rung", "fused") != "lowp":
        return "f32"
    cfg = engine.config
    if not cfg.exact:
        return "f32"
    plan = getattr(engine, "_precision_plan", None)
    if plan is not None and plan != "bf16":
        return "f32"
    return cfg.resolve_precision()


@functools.partial(jax.jit,
                   static_argnames=("chunk_rows", "k", "select", "use_pallas"))
def _outlier_fold(carry: TopK, q_attrs, battrs, labels_all, lo, n_real, *,
                  chunk_rows, k, select, use_pallas=False) -> TopK:
    """Fold one already-staged data chunk into the huge-k outlier queries'
    running top-k (heterogeneous-k routing). The chunk's labels/ids are
    derived ON DEVICE (labels by dynamic_slice of the once-staged full
    label vector, ids from the chunk's row range) so the outlier path adds
    zero host->device attr traffic — it rides the exact same chunk arrays
    the extraction kernel consumes. ``lo``/``n_real`` are traced scalars:
    one compile serves every chunk."""
    blabels = jax.lax.dynamic_slice(labels_all, (lo,), (chunk_rows,))
    ri = lo + jnp.arange(chunk_rows, dtype=jnp.int32)
    bids = jnp.where(ri < n_real, ri, -1)
    step = make_block_step(select, k, use_pallas, carry.dists.dtype)
    return step(carry, q_attrs, battrs, blabels, bids)


@functools.partial(jax.jit, static_argnames=("k", "select", "use_pallas"))
def _chunk_fold(carry: TopK, q_attrs, battrs, blabels, bids, *, k, select,
                use_pallas=False) -> TopK:
    """Fold one data chunk into the running top-k (pipelined driver step).

    One dispatch per chunk: the host enqueues chunk transfers and fold
    dispatches back-to-back, so the device DMAs chunk i+1 while computing
    chunk i — the async replacement for the reference's scatter-then-compute
    phasing (engine.cpp:62-131, :233-257), which matters here because the
    host->device link (not the MXU) bounds the solve.
    """
    step = make_block_step(select, k, use_pallas, carry.dists.dtype)
    return step(carry, q_attrs, battrs, blabels, bids)


@jax.jit
def _boundary_cols(dists, ks):
    """(kth, last) candidate-distance columns, stacked (2, Q) — computed on
    device so the exact path never reads the (Q, K) distance matrix back
    over the link. The host applies the staging-eps hazard test to these
    two vectors (engine.finalize.boundary_overflow / staging_eps)."""
    kcap = dists.shape[1]
    last = dists[:, kcap - 1]
    kth = jnp.take_along_axis(
        dists, jnp.clip(ks[:, None] - 1, 0, kcap - 1), axis=1)[:, 0]
    return jnp.stack([kth, last])


@functools.partial(jax.jit, static_argnames=("k",))
def _extract_finalize(od, oi, glabels, *, k):
    """Extraction-kernel epilogue: gather labels from global ids and sort
    the (unordered) running lists into the golden selection order
    (dist asc, id desc) — a tiny (Q, K) composite sort."""
    from dmlp_tpu.ops.topk import select_topk
    n = glabels.shape[0]
    labels = jnp.where(oi >= 0, glabels[jnp.clip(oi, 0, max(n - 1, 0))], -1)
    return select_topk(od, labels, oi, k)


@functools.partial(jax.jit, static_argnames=("staging", "na", "precision"))
def _mp_floor(od, qn, dn_max, *, staging: str, na: int,
              precision: str = "f32"):
    """Next-pass floor, computed ON DEVICE so passes chain without a host
    readback (an inter-pass sync costs a full tunnel round trip per pass,
    measured ~1.3 s of serialization at 9 passes). Ports
    finalize.staging_eps: floor = max(od) - eps(max(od)); exhausted rows
    (max = inf) get floor = +inf so later passes yield empty lists.
    A "bf16" first pass deepens the eps by the finalize.lowp_eps term
    (the floor must clear the cast error too, or a later pass could
    skip a candidate the low-precision dot pushed below the boundary).
    Returns (floor (Q, 1) f32, fd (Q,) f32 for post-hoc stall checks)."""
    from dmlp_tpu.engine.finalize import (EPS_CANCEL_COEF, EPS_REL_BF16,
                                          EPS_REL_F32, LOWP_COEF)
    fd = jnp.max(od, axis=1)
    rel = EPS_REL_BF16 if staging == "bfloat16" else EPS_REL_F32
    scale = qn + dn_max
    eps = (rel * jnp.sqrt(jnp.maximum(fd, 0.0) * scale)
           + (EPS_CANCEL_COEF * (na + 2) + LOWP_COEF[precision]) * scale)
    floor = jnp.where(jnp.isfinite(fd), fd - eps, jnp.inf)
    return floor[:, None].astype(jnp.float32), fd


@functools.partial(jax.jit, static_argnames=("kcap",))
def _mp_merge(dists, ids, glabels, *, kcap):
    """Merge the multi-pass extraction slabs: (Q, P*kc) concatenated
    lists -> dedup by id (eps-overlapped floors re-extract boundary
    candidates on purpose; duplicates carry identical device distances,
    so id-identity is the whole test) -> gather labels -> composite-sort
    to the final (Q, kcap) selection order (dist asc, id desc). Also returns the per-row
    valid-candidate count for the driver's shortfall check."""
    from dmlp_tpu.ops.topk import select_topk
    order = jnp.argsort(ids, axis=1)
    sid = jnp.take_along_axis(ids, order, 1)
    sd = jnp.take_along_axis(dists, order, 1)
    dup = jnp.concatenate([jnp.zeros_like(sid[:, :1], bool),
                           sid[:, 1:] == sid[:, :-1]], axis=1)
    invalid = dup | (sid < 0)
    sd = jnp.where(invalid, jnp.inf, sd)
    sid = jnp.where(invalid, -1, sid)
    n = glabels.shape[0]
    lab = jnp.where(sid >= 0, glabels[jnp.clip(sid, 0, max(n - 1, 0))], -1)
    return select_topk(sd, lab, sid, kcap), jnp.sum(sid >= 0, axis=1)


@functools.partial(jax.jit,
                   static_argnames=("k", "data_block", "select", "use_pallas"))
def _topk_blocks(data_attrs, data_labels, data_ids, q_blocks, *, k,
                 data_block, select, use_pallas=False):
    """All query blocks in one dispatch: ``lax.map`` keeps the live distance
    tile at (query_block x data_block) while avoiding per-block Python
    dispatch + per-block device->host readbacks (which dominate over a
    tunneled PJRT link)."""
    return jax.lax.map(
        lambda q: streaming_topk(q, data_attrs, data_labels, data_ids,
                                 k=k, data_block=data_block, select=select,
                                 use_pallas=use_pallas),
        q_blocks)


@functools.partial(jax.jit, static_argnames=("num_labels",))
def _device_epilogue(top: TopK, ks, *, num_labels):
    """Vote + report ordering on-device over (Q, K) candidate lists — the
    reference's result post-processing (engine.cpp:314-347) as a tiny
    epilogue jit shared by every device-full select path (including the
    flagship extraction kernel, whose lists _solve already sorts)."""
    rd, rids, in_k = report_order(top, ks)
    valid = in_k & (top.ids >= 0)
    predicted = majority_vote(top.labels, valid, num_labels)
    return predicted, rids, rd


class SingleChipEngine:
    """The one-chip engine (CPU backend in CI, TPU in production)."""

    def __init__(self, config: EngineConfig = EngineConfig()):
        self.config = config
        self._staging = config.resolve_dtype()
        self._dtype = (jnp.bfloat16 if self._staging == "bfloat16"
                       else jnp.float32)
        self.last_phase_ms: dict = {}
        self.last_hetk = None  # (bulk, outlier) counts when routing split
        self.last_mp_passes = 0  # multi-pass extraction pass count
        # Which kernel the last extract-path solve dispatched
        # ("fused" | "extract" | None) — bench/artifacts report it.
        self.last_extract_impl = None
        # Degradation-ladder rung (resilience.degrade): "fused" (the
        # default) allows the fused megakernel; "tuned" drops to the
        # two-pass extraction kernel; "streaming" forces the chunk-fold
        # driver (no extract-kernel dispatch at all);
        # last_degrade_rung reports the rung the last run() settled on.
        self._degrade_rung = "fused"
        self.last_degrade_rung = "fused"
        self._mp_hazard = None   # its per-query loss flags (run() repairs)
        # (site, device iters-sum scalar, (qb, b, a, kc)) triples the
        # extract paths queue when a cost probe is installed; flushed to
        # obs.counters after the solve fence (measured extraction term).
        self._pending_iters: list = []
        # Pruned two-stage solve accounting (ops.summaries.note_scan):
        # blocks_total/blocks_pruned/scanned_bytes/dense_bytes of the
        # last solve — the bench A/B and the CLI metrics summary read
        # it. None until a chunked driver runs.
        self.last_prune = None
        # Analytic peak-HBM model of the last solve (obs.memwatch);
        # populated only while a telemetry session is active.
        self.last_mem_model = None
        # Low-precision first-pass record of the last run(): active/
        # configured precision + the window slots the bound inflation
        # added (bench A/B and the CLI metrics summary read it).
        self.last_precision = None

    def _staging_itemsize(self) -> int:
        return 2 if self._staging == "bfloat16" else 4

    def _plan_prune(self, inp: KNNInput, nchunks: int, chunk_rows: int):
        """Stage 0+1 of the pruned two-stage solve for a chunked
        driver: (survivor chunk schedule, plan stats | None). Active
        only on the resilience ladder's top ``lowp``/``prune`` rungs
        (run() enters at "lowp"; candidates()/run_device_full stay
        dense — fast ordering has no repair backstop), in exact mode,
        with the ``DMLP_TPU_PRUNE`` kill switch on, and when there is
        more than one block to choose between. On the "lowp" rung with
        precision resolving to "bf16" the prune thresholds widen by
        the finalize.lowp_eps cast bound — a block must stay pruned
        under the error the low-precision first pass could add. The
        schedule preserves natural chunk order, so ChunkThrottle
        backpressure and the affine-id contract are untouched — pruned
        blocks are simply never staged."""
        n = inp.params.num_data
        dense = list(range(nchunks))
        if (nchunks <= 1 or n == 0 or inp.params.num_queries == 0
                or self._degrade_rung not in ("lowp", "prune")
                or not self.config.exact):
            return dense, None
        from dmlp_tpu.ops import summaries as osum
        if not osum.prune_enabled():
            return dense, None
        ranges = [(c * chunk_rows, min((c + 1) * chunk_rows, n))
                  for c in range(nchunks)]
        with obs_span("single.prune_score", blocks=nchunks):
            summ = osum.build_summaries(inp.data_attrs, ranges)
            keep, stats = osum.prune_mask(inp.query_attrs, inp.ks, summ,
                                          staging=self._staging,
                                          precision=active_precision(self))
        schedule = [c for c in dense if keep[c]]
        if not schedule:       # belt: prune_mask guarantees a survivor
            return dense, None
        return schedule, stats

    def _prep(self, inp: KNNInput):
        cfg = self.config
        n = inp.params.num_data
        # The scan/device-full paths fold arbitrary-id blocks, so
        # "extract" remaps here (and the granule must match what runs —
        # the extract granule has no 1024-divisor for the seg producer).
        select = cfg.resolve_streaming_select(round_up(max(n, 1), 8))
        if cfg.data_block is not None:
            data_block = min(cfg.data_block, round_up(max(n, 1), 8))
        else:
            data_block = fit_blocks(n, cfg.resolve_data_block(select),
                                    granule=cfg.resolve_granule(select))
        attrs, labels, ids = pad_dataset(inp, data_block, np.float32)
        kmax = int(inp.ks.max()) if inp.params.num_queries else 1
        k = resolve_kcap(cfg, kmax, select, attrs.shape[0],
                         staging=self._staging)
        d_attrs = stage_put(attrs, self._staging)
        self._last_select = select  # run() gates the tie-overflow repair on it
        return (d_attrs, jax.device_put(labels), jax.device_put(ids), k,
                data_block, select)

    def _solve_scan(self, inp: KNNInput) -> Tuple[TopK, int]:
        """Whole-dataset staging + one lax.map/scan dispatch ("sort" path)."""
        cfg = self.config
        d_attrs, d_labels, d_ids, k, data_block, select = self._prep(inp)
        nq = inp.params.num_queries
        qb = min(cfg.query_block, round_up(max(nq, 1), 8))
        qpad = round_up(max(nq, 1), qb)
        q_attrs = np.zeros((qpad, inp.params.num_attrs), np.float32)
        q_attrs[:nq] = inp.query_attrs
        q_blocks = stage_put(
            q_attrs.reshape(qpad // qb, qb, -1), self._staging)

        statics = dict(k=k, data_block=data_block, select=select,
                       use_pallas=cfg.use_pallas)
        obs_counters.record_dispatch(
            _topk_blocks, (d_attrs, d_labels, d_ids, q_blocks),
            statics=statics, site="single.topk_blocks")
        with obs_span("single.solve_scan", select=select,
                      qpad=qpad) as sp:
            out: TopK = _topk_blocks(d_attrs, d_labels, d_ids, q_blocks,
                                     **statics)
            sp.fence(out.dists)
        from dmlp_tpu.ops.summaries import note_scan
        dense = inp.params.num_data * inp.params.num_attrs \
            * self._staging_itemsize()
        note_scan(self, scanned_bytes=dense, dense_bytes=dense,
                  blocks_total=1, blocks_pruned=0)
        return TopK(out.dists.reshape(qpad, -1), out.labels.reshape(qpad, -1),
                    out.ids.reshape(qpad, -1)), qpad

    def _solve_pipelined(self, inp: KNNInput) -> Tuple[TopK, int]:
        """Chunked staging + one fold dispatch per chunk ("topk"/"seg").

        The dataset is staged in ~chunk_rows-row pieces, each followed by
        its fold dispatch; transfers and compute are enqueued back-to-back
        so the device DMAs chunk i+1 while folding chunk i. On a
        bandwidth-limited host link (tunneled PJRT, or a pod feeding over
        DCN) the solve then costs ~max(transfer, compute), not their sum.
        """
        import time as _time

        cfg = self.config
        n = inp.params.num_data
        na = inp.params.num_attrs
        nq = inp.params.num_queries
        # resolve_streaming_select: only reached when the extraction kernel
        # can't tile this shape (or select != extract in the first place)
        select = cfg.resolve_streaming_select(round_up(max(n, 1), 8))
        self._last_select = select
        granule = cfg.resolve_granule(select)

        t0 = _time.perf_counter()
        npad, nchunks, chunk_rows = plan_chunks(n, granule, cfg.data_block)

        # Query padding: multiples of 1024 keep the fused Pallas tiling
        # eligible (ops.pallas_distance.supports); 8 otherwise.
        qgran = 1024 if (cfg.use_pallas and select == "seg"
                         and nq > 1024) else 8
        qpad = round_up(max(nq, 1), qgran)
        # Bound the live (query_rows x chunk_rows) f32 tile by both the
        # configured query_block and the HBM tile budget.
        qsb = min(qpad, round_up(cfg.query_block, qgran))
        while qsb > qgran and qsb * chunk_rows * 4 > _TILE_BUDGET:
            qsb -= qgran
        nqb = -(-qpad // qsb)
        qpad = nqb * qsb

        kmax = int(inp.ks.max()) if nq else 1
        k = resolve_kcap(cfg, kmax, select, nchunks * chunk_rows,
                         staging=self._staging)

        q_attrs = np.zeros((qpad, na), np.float32)
        q_attrs[:nq] = inp.query_attrs
        q_dev = [stage_put(q_attrs[i * qsb:(i + 1) * qsb], self._staging)
                 for i in range(nqb)]

        # Stage chunks (async puts) and enqueue their folds immediately,
        # under the sliding-window backpressure (ChunkThrottle). The
        # survivor schedule (pruned two-stage solve) composes here: a
        # pruned chunk is never staged, so its bytes never cross the
        # host->device link at all.
        schedule, prune_stats = self._plan_prune(inp, nchunks, chunk_rows)
        carries = [init_topk(qsb, k) for _ in range(nqb)]
        src_attrs = np.ascontiguousarray(inp.data_attrs, np.float32)
        throttle = ChunkThrottle()
        scanned = 0
        statics = dict(k=k, select=select, use_pallas=cfg.use_pallas)
        with obs_span("single.enqueue_pipelined", select=select,
                      chunks=nchunks, scheduled=len(schedule),
                      qblocks=nqb, k=k):
            for c in schedule:
                lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
                a = np.zeros((chunk_rows, na), np.float32)
                lab = np.full(chunk_rows, -1, np.int32)
                ids = np.full(chunk_rows, -1, np.int32)
                if hi > lo:
                    a[:hi - lo] = src_attrs[lo:hi]
                    lab[:hi - lo] = inp.labels[lo:hi]
                    ids[:hi - lo] = np.arange(lo, hi, dtype=np.int32)
                da = stage_put(a, self._staging)
                scanned += max(hi - lo, 0) * na * self._staging_itemsize()
                dl, di = jax.device_put(lab), jax.device_put(ids)
                if c == schedule[0]:
                    obs_counters.record_dispatch(
                        _chunk_fold, (carries[0], q_dev[0], da, dl, di),
                        statics=statics, count=len(schedule) * nqb,
                        site="single.chunk_fold")
                for b in range(nqb):
                    carries[b] = _chunk_fold(carries[b], q_dev[b], da, dl,
                                             di, **statics)
                throttle.tick(carries[-1].dists)
                # Watermark tick while the chunk is still referenced —
                # chunk arrays are loop-locals, so a post-loop sample
                # would miss the staging window (no-op unless a
                # telemetry session is active).
                telemetry.sample_memory_now()
        from dmlp_tpu.ops.summaries import note_scan
        note_scan(self, scanned_bytes=scanned,
                  dense_bytes=n * na * self._staging_itemsize(),
                  blocks_total=nchunks,
                  blocks_pruned=(prune_stats or {}).get(
                      "blocks_pruned", 0))
        self.last_phase_ms["enqueue"] = (_time.perf_counter() - t0) * 1e3

        if nqb == 1:
            return carries[0], qpad
        return TopK(*(jnp.concatenate(parts) for parts in
                      zip(*carries))), qpad

    def _solve_extract(self, inp: KNNInput) -> Tuple[TopK, int] | None:
        """Chunked staging + the fused extraction kernel (select="extract").

        Each ~50k-row chunk is staged asynchronously and folded into the
        running (Q, K) lists by ops.pallas_extract.extract_topk — the
        distance tile lives only in VMEM, so HBM holds just the chunk, the
        queries, and the lists. Chunk row ranges are contiguous, giving the
        kernel its trace-time-affine ids (id_base = chunk start). Returns
        None when the kernel can't tile this shape (caller falls back).
        """
        import time as _time

        from dmlp_tpu.ops import pallas_fused
        from dmlp_tpu.ops.pallas_distance import native_pallas_backend

        cfg = self.config
        n = inp.params.num_data
        na = inp.params.num_attrs
        nq = inp.params.num_queries
        if n == 0 or nq == 0:
            return None

        rs_inject.fire("single.extract_solve", rung=self._degrade_rung,
                       path="single")
        granule = cfg.resolve_granule("extract")
        t0 = _time.perf_counter()
        npad, nchunks, chunk_rows = plan_chunks(n, granule, cfg.data_block)
        # Queries pad to a whole query tile for the same reason data pads
        # to whole extraction blocks: an awkward qb (e.g. 8 * prime) would
        # force a degenerate 8-row query tile.
        from dmlp_tpu.ops.pallas_extract import QUERY_TILE
        qpad = round_up(nq, QUERY_TILE)
        kmax = int(inp.ks.max())
        k = resolve_kcap(cfg, kmax, "extract", nchunks * chunk_rows,
                         staging=self._staging)
        # Fused-vs-two-pass selection, resolved HERE (outside any jitted
        # body, lint R203): kern is a concrete Python callable whose own
        # jit keys on mxu_gate + the resolved tiles, so the choice is
        # part of the jit cache key by construction.
        kern, impl = pallas_fused.resolve_topk_kernel(
            qpad, chunk_rows, na, k, rung=self._degrade_rung)
        if kern is None:
            return None
        interpret = not native_pallas_backend()
        prec = active_precision(self)
        self._last_select = "extract"
        self.last_extract_impl = impl

        schedule, prune_stats = self._plan_prune(inp, nchunks, chunk_rows)
        live = [c for c in schedule if c * chunk_rows < n]
        q_attrs = np.zeros((qpad, na), np.float32)
        q_attrs[:nq] = inp.query_attrs
        q_dev = stage_put(q_attrs, self._staging)
        src_attrs = np.ascontiguousarray(inp.data_attrs, np.float32)
        od = oi = None
        scanned = 0
        mi = MeasuredIters(self, "single.extract_topk",
                           (qpad, chunk_rows, na, k), kernel=impl)
        throttle = ChunkThrottle()
        with obs_span("single.enqueue_extract", chunks=nchunks, kc=k,
                      impl=impl, scheduled=len(live),
                      variant=pallas_fused.variant_for(
                          impl, k, chunk_rows, qpad, na)):
            for c in live:    # survivor schedule; pruned blocks are
                # never staged — the beyond-HBM payoff is exactly that
                # their bytes never leave host DRAM
                lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
                a = np.zeros((chunk_rows, na), np.float32)
                if hi > lo:
                    a[:hi - lo] = src_attrs[lo:hi]
                da = stage_put(a, self._staging)
                scanned += (hi - lo) * na * self._staging_itemsize()
                if c == live[0]:
                    # Resolved via the analytic kernel model
                    # (obs.kernel_cost) — pallas_call has no XLA cost.
                    obs_counters.record_dispatch(
                        kern, (q_dev, da), statics=dict(kc=k,
                                                        precision=prec),
                        count=len(live),
                        site="single.extract_topk")
                od, oi, _iters = kern(
                    q_dev, da, od, oi, n_real=hi - lo, id_base=lo, kc=k,
                    interpret=interpret, precision=prec)
                mi.add(_iters)
                throttle.tick(od)
                telemetry.sample_memory_now()   # staging window live
        mi.done()
        from dmlp_tpu.ops.summaries import note_scan
        note_scan(self, scanned_bytes=scanned,
                  dense_bytes=n * na * self._staging_itemsize(),
                  blocks_total=min(nchunks, -(-n // chunk_rows)),
                  blocks_pruned=(prune_stats or {}).get(
                      "blocks_pruned", 0))
        self.last_phase_ms["enqueue"] = (_time.perf_counter() - t0) * 1e3

        top = _extract_finalize(od, oi, jax.device_put(inp.labels), k=k)
        return top, qpad

    # Multi-pass resident-dataset budget: every pass re-sweeps the staged
    # chunks, so they must stay device-resident (re-uploading P times would
    # be transfer-bound suicide on the tunneled link). 2 GiB staged attrs
    # leaves ample HBM for lists + scratch on a 16 GiB chip; bigger
    # datasets keep the streaming fallback.
    _MP_RESIDENT_BUDGET = 2 << 30
    _MP_MAX_PASSES = 16
    _MP_KC = 512  # slots per pass — the kernel's widest tuned window

    def _solve_extract_multipass(self, inp: KNNInput):
        """All-wide-k solve on the extraction kernel in P floor-raised
        passes (VERDICT r4 item 2).

        When EVERY query's k overflows the kernel's kc cap the router
        (hetk_split) has no bulk to keep and r4 dropped the whole input to
        the streaming selects — even though k is legal up to num_data
        (generate_input.py:19). Instead: stage the chunks once
        (device-resident), and sweep them P = ceil(kcap/512) times. Pass 1
        runs the plain kernel; pass p+1 masks candidates below that row's
        previous max MINUS the staging-eps margin (the kernel's new
        ``floor`` input), so each pass extracts the next ~512-wide slab of
        the top-k. The eps overlap deliberately re-extracts boundary
        candidates rather than risk losing a tie — _mp_merge dedups by id
        and composite-sorts to the final width.

        Correctness: the kernel guarantees every unextracted candidate
        sits at or above the pass's max, so the union is complete below
        the last pass's max minus eps. The two loss modes both flag for
        exact oracle repair (run() ORs _mp_hazard into the standard
        boundary test): STALL (a >512-wide tie plateau pins the floor; the
        pass adds nothing and fd stops rising) and SHORTFALL (eps-window
        duplicates ate enough slots that a row ends with fewer than
        min(k, n) distinct candidates).

        Returns a run()-compatible segment list, or None when the plan
        doesn't apply (k fits single-pass, kernel can't tile, dataset too
        big to keep resident, or P would exceed _MP_MAX_PASSES).
        """
        import time as _time

        from dmlp_tpu.ops import pallas_fused
        from dmlp_tpu.ops.pallas_distance import native_pallas_backend
        from dmlp_tpu.ops.pallas_extract import QUERY_TILE

        cfg = self.config
        n = inp.params.num_data
        na = inp.params.num_attrs
        nq = inp.params.num_queries
        if n == 0 or nq == 0 or not cfg.use_pallas:
            return None
        if cfg.select not in ("auto", "extract"):
            return None
        if cfg.resolve_select(round_up(max(n, 1), 8)) != "extract":
            return None
        kc = self._MP_KC
        kmax = int(inp.ks.max())
        if resolve_kcap(cfg, kmax, "extract", 1 << 30,
                        self._staging) <= kc:
            return None  # single-pass (or the hetk router) owns this k
        granule = cfg.resolve_granule("extract")
        npad, nchunks, chunk_rows = plan_chunks(n, granule, cfg.data_block)
        kcap = resolve_kcap(cfg, kmax, "extract", npad,
                            staging=self._staging)
        npasses = -(-kcap // kc)
        if npasses > self._MP_MAX_PASSES:
            return None
        itemsize = 2 if self._staging == "bfloat16" else 4
        if npad * na * itemsize > self._MP_RESIDENT_BUDGET:
            return None
        qpad = round_up(nq, QUERY_TILE)
        kern, impl = pallas_fused.resolve_topk_kernel(
            qpad, chunk_rows, na, kc, rung=self._degrade_rung)
        if kern is None:
            return None
        # ADVICE r5 (single.py:614): passes 2+ dispatch the kernel over
        # the FULL concatenated d_full array, not chunk_rows — today the
        # 128*ne divisibility and tile caps happen to carry from
        # chunk_rows to its multiples, but supports() resolves its
        # variant per row count and nothing guaranteed the carry-over.
        # Assert the invariant the whole-array sweep actually needs, so
        # future variant tuning fails loudly here instead of silently
        # mis-tiling every pass after the first. The fused/two-pass
        # selection resolves INDEPENDENTLY per row count (the fused
        # tune-cache namespace may pin a variant at one bucket only), so
        # pass 1 and the resident passes may legally run different
        # kernels — each is bit-identical, so the union is too.
        n_staged = min(nchunks, -(-n // chunk_rows))
        full_rows = n_staged * chunk_rows
        kern_full, impl_full = pallas_fused.resolve_topk_kernel(
            qpad, full_rows, na, kc, rung=self._degrade_rung)
        if kern_full is None:
            raise AssertionError(
                f"multi-pass extract: full-array sweep shape (qb={qpad}, "
                f"rows={full_rows}, a={na}, kc={kc}) is untileable even "
                f"though the per-chunk shape (rows={chunk_rows}) tiles — "
                "supports() invariants diverged between the chunked "
                "pass 1 and the resident passes 2+")
        interpret = not native_pallas_backend()
        prec = active_precision(self)
        self._last_select = "extract"
        self.last_extract_impl = impl
        rs_inject.fire("single.extract_solve", rung=self._degrade_rung,
                       path="multipass")

        t0 = _time.perf_counter()
        q_attrs = np.zeros((qpad, na), np.float32)
        q_attrs[:nq] = inp.query_attrs
        q_dev = stage_put(q_attrs, self._staging)
        src_attrs = np.ascontiguousarray(inp.data_attrs, np.float32)

        # Pass 1 overlaps with staging, like the single-pass driver; the
        # chunks stay resident for passes 2..P.
        chunks: List[Tuple] = []
        od = oi = None
        mi = MeasuredIters(self, "single.extract_mp_pass1",
                           (qpad, chunk_rows, na, kc), kernel=impl)
        throttle = ChunkThrottle()
        for c in range(nchunks):
            lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
            if lo >= n:
                break
            a = np.zeros((chunk_rows, na), np.float32)
            a[:hi - lo] = src_attrs[lo:hi]
            da = stage_put(a, self._staging)
            if c == 0:
                obs_counters.record_dispatch(
                    kern, (q_dev, da), statics=dict(kc=kc, precision=prec),
                    count=n_staged, site="single.extract_mp_pass1")
            chunks.append((da, lo, hi))
            od, oi, _iters = kern(q_dev, da, od, oi, n_real=hi - lo,
                                  id_base=lo, kc=kc,
                                  interpret=interpret, precision=prec)
            mi.add(_iters)
            throttle.tick(od)
        mi.done()
        ods, ois = [od], [oi]

        # Floors chain ON DEVICE (_mp_floor): every pass enqueues without
        # a host readback, so the whole P-pass sweep pipelines like the
        # single-pass chunk driver. Stall detection moves post-hoc: the
        # per-pass fd vectors come back in ONE readback at the end
        # (plateau rows waste their later passes on duplicate lists —
        # bounded by _MP_MAX_PASSES and caught below for exact repair).
        qn_host = np.zeros(qpad, np.float64)
        qn_host[:nq] = np.einsum("qa,qa->q", inp.query_attrs,
                                 inp.query_attrs)
        dn_max = float(np.einsum("na,na->n", inp.data_attrs,
                                 inp.data_attrs).max())
        qn_dev = jax.device_put(np.asarray(qn_host, np.float32))
        # Explicit device scalar: dn_max rides _mp_floor as a traced
        # arg, and the sanitizer's transfer guard disallows the implicit
        # python-float -> device conversion at the jit boundary.
        dn_dev = jax.device_put(np.float32(dn_max))
        # Passes 2..P sweep the RESIDENT dataset: one whole-array kernel
        # dispatch per pass (the kernel grids over blocks internally)
        # instead of nchunks dispatches — chunking only existed to
        # overlap pass 1 with staging, and per-dispatch overhead on a
        # tunneled link is ~0.25 s (36 -> 9 dispatches at the 204800,
        # 9-pass shape). The concat is one on-device copy (~dataset
        # bytes), well under the resident budget.
        d_full = chunks[0][0] if len(chunks) == 1 \
            else jnp.concatenate([c[0] for c in chunks], axis=0)
        telemetry.sample_memory_now()  # resident dataset ×2 peak (concat)
        del chunks  # free the duplicate once the concat is enqueued —
        # otherwise the dataset is HBM-resident TWICE for the whole sweep
        if npasses > 1:
            obs_counters.record_dispatch(
                kern_full, (q_dev, d_full),
                statics=dict(kc=kc, precision=prec),
                count=npasses - 1, site="single.extract_mp_resident")
        fds = []
        mir = MeasuredIters(self, "single.extract_mp_resident",
                            (qpad, full_rows, na, kc), kernel=impl_full)
        for _p in range(1, npasses):
            floor_dev, fd = _mp_floor(ods[-1], qn_dev, dn_dev,
                                      staging=self._staging, na=na,
                                      precision=prec)
            fds.append(fd)
            od, oi, _iters = kern_full(q_dev, d_full, n_real=n, id_base=0,
                                       kc=kc, interpret=interpret,
                                       floor=floor_dev, precision=prec)
            mir.add(_iters)
            throttle.tick(od)
            ods.append(od)
            ois.append(oi)
        mir.done()
        # Final pass's fd too: a plateau pinning the LAST boundary must
        # flag as well (its ties are the one loss the outer boundary test
        # can miss when kcap >= n).
        fds.append(_mp_floor(ods[-1], qn_dev, dn_dev,
                             staging=self._staging, na=na,
                             precision=prec)[1])
        self.last_phase_ms["enqueue"] = (_time.perf_counter() - t0) * 1e3
        self.last_mp_passes = len(ods)

        from dmlp_tpu.obs import trace as obs_trace
        obs_trace.instant("single.multipass_sweep", passes=len(ods),
                          kcap=kcap, chunks=n_staged)
        # The multipass plan keeps the dataset resident and re-sweeps
        # it; every block stays competitive against floor-raised passes,
        # so it scans densely by design (staged bytes counted once).
        from dmlp_tpu.ops.summaries import note_scan
        dense = n * na * self._staging_itemsize()
        note_scan(self, scanned_bytes=dense, dense_bytes=dense,
                  blocks_total=n_staged, blocks_pruned=0)
        top, valid = _mp_merge(jnp.concatenate(ods, axis=1),
                               jnp.concatenate(ois, axis=1),
                               jax.device_put(inp.labels), kcap=kcap)
        # One fence for everything: fd sequence (stall check), final
        # valid counts (shortfall check).
        fetched = resilient_get([valid] + fds)
        valid_h, fd_h = fetched[0], fetched[1:]
        stalled = np.zeros(qpad, bool)
        for prev, cur in zip(fd_h, fd_h[1:]):
            stalled |= np.isfinite(cur) & (cur <= prev)
        needed = np.minimum(inp.ks.astype(np.int64), n)
        shortfall = np.asarray(valid_h)[:nq] < needed
        self._mp_hazard = stalled[:nq] | shortfall
        return [(top, qpad, None, "extract")]

    def _flush_measured_iters(self) -> None:
        flush_measured_iters(self)

    def _solve(self, inp: KNNInput) -> Tuple[TopK, int]:
        self.last_phase_ms = {}  # no stale phases if a path is skipped
        self._pending_iters = []
        self.last_extract_impl = None
        self.last_prune = None   # no stale scan accounting either
        select = self.config.resolve_select(
            round_up(max(inp.params.num_data, 1), 8))
        if select == "sort":
            return self._solve_scan(inp)
        # The "streaming" degradation rung (resilience.degrade) forbids
        # extract-kernel dispatch: the chunk-fold driver below holds no
        # running-list kernel state and its live tile is one slab.
        if select == "extract" and self._degrade_rung != "streaming":
            out = self._solve_extract(inp)
            if out is not None:
                return out
            # shape untileable for the extraction kernel — fall through to
            # the chunk-fold driver on the best remaining path
        return self._solve_pipelined(inp)

    def _plan_hetk(self, inp: KNNInput):
        return hetk_split(self.config, self._staging, inp.ks,
                          inp.params.num_data,
                          round_up(max(inp.params.num_data, 1), 8))

    def _solve_extract_routed(self, inp: KNNInput, plan):
        """Split solve: extraction kernel for the bulk queries + streaming
        fold for the huge-k outliers, sharing one staging pass.

        Each data chunk is uploaded ONCE; the extract fold (bulk) and the
        outlier fold are enqueued back-to-back on the same device array,
        so the transfer-bound end-to-end cost stays that of the unsplit
        extract path. Returns a segment list for run()/run_device_full,
        or None when the bulk shape can't tile (caller falls back).
        """
        import time as _time

        from dmlp_tpu.ops import pallas_fused
        from dmlp_tpu.ops.pallas_distance import native_pallas_backend
        from dmlp_tpu.ops.pallas_extract import QUERY_TILE
        from dmlp_tpu.ops.topk import streaming_fallback

        bulk, outl = plan
        cfg = self.config
        n = inp.params.num_data
        na = inp.params.num_attrs

        granule = cfg.resolve_granule("extract")
        t0 = _time.perf_counter()
        npad, nchunks, chunk_rows = plan_chunks(n, granule, cfg.data_block)
        qpad_b = round_up(len(bulk), QUERY_TILE)
        kb = resolve_kcap(cfg, int(inp.ks[bulk].max()), "extract",
                          nchunks * chunk_rows, staging=self._staging)
        kern, impl = pallas_fused.resolve_topk_kernel(
            qpad_b, chunk_rows, na, kb, rung=self._degrade_rung)
        if kern is None:
            return None
        select_out = streaming_fallback(cfg.use_pallas)
        ko = resolve_kcap(cfg, int(inp.ks[outl].max()), select_out,
                          nchunks * chunk_rows, staging=self._staging)
        interpret = not native_pallas_backend()
        prec = active_precision(self)
        self._last_select = "extract"
        self.last_extract_impl = impl
        self.last_hetk = (int(bulk.size), int(outl.size))
        rs_inject.fire("single.extract_solve", rung=self._degrade_rung,
                       path="routed")

        qb_host = np.zeros((qpad_b, na), np.float32)
        qb_host[:len(bulk)] = inp.query_attrs[bulk]
        qb_dev = stage_put(qb_host, self._staging)
        qo_pad = round_up(len(outl), 8)
        qo_host = np.zeros((qo_pad, na), np.float32)
        qo_host[:len(outl)] = inp.query_attrs[outl]
        qo_dev = stage_put(qo_host, self._staging)
        labels_pad = np.full(nchunks * chunk_rows, -1, np.int32)
        labels_pad[:n] = inp.labels
        labels_dev = jax.device_put(labels_pad)

        # The prune plan covers BOTH query sets (bulk and outliers ride
        # the same per-query ks), so the shared staging sweep may only
        # skip a chunk no query of either segment can need.
        schedule, prune_stats = self._plan_prune(inp, nchunks, chunk_rows)
        live_sched = [c for c in schedule if c * chunk_rows < n]
        carry_o = init_topk(qo_pad, ko)
        src_attrs = np.ascontiguousarray(inp.data_attrs, np.float32)
        od = oi = None
        scanned = 0
        mi = MeasuredIters(self, "single.extract_bulk",
                           (qpad_b, chunk_rows, na, kb), kernel=impl)
        throttle = ChunkThrottle()
        for c in live_sched:
            lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
            a = np.zeros((chunk_rows, na), np.float32)
            if hi > lo:
                a[:hi - lo] = src_attrs[lo:hi]
            da = stage_put(a, self._staging)
            scanned += (hi - lo) * na * self._staging_itemsize()
            if c == live_sched[0]:
                obs_counters.record_dispatch(
                    kern, (qb_dev, da), statics=dict(kc=kb,
                                                     precision=prec),
                    count=len(live_sched),
                    site="single.extract_bulk")
            od, oi, _iters = kern(
                qb_dev, da, od, oi, n_real=hi - lo, id_base=lo, kc=kb,
                interpret=interpret, precision=prec)
            mi.add(_iters)
            carry_o = _outlier_fold(
                carry_o, qo_dev, da, labels_dev,
                jax.device_put(np.int32(lo)), jax.device_put(np.int32(n)),
                chunk_rows=chunk_rows, k=ko,
                select=select_out, use_pallas=cfg.use_pallas)
            throttle.tick(carry_o.dists)
            telemetry.sample_memory_now()   # staging window live
        mi.done()
        from dmlp_tpu.ops.summaries import note_scan
        note_scan(self, scanned_bytes=scanned,
                  dense_bytes=n * na * self._staging_itemsize(),
                  blocks_total=min(nchunks, -(-n // chunk_rows)),
                  blocks_pruned=(prune_stats or {}).get(
                      "blocks_pruned", 0))
        self.last_phase_ms["enqueue"] = (_time.perf_counter() - t0) * 1e3

        top_b = _extract_finalize(od, oi, jax.device_put(inp.labels),
                                  k=kb)
        return [(top_b, qpad_b, bulk, "extract"),
                (carry_o, qo_pad, outl, select_out)]

    def _solve_segments(self, inp: KNNInput, allow_multipass: bool = True):
        """Solve as a list of (TopK, qpad, query_idx | None, select)
        segments — one segment for homogeneous k, two when the
        heterogeneous-k router splits huge-k outliers off the extraction
        kernel's bulk. Queries in different segments are independent
        sub-problems; run()/run_device_full merge by original index.

        ``allow_multipass`` gates the all-wide-k multi-pass extraction:
        its loss modes (tie plateau / eps-window shortfall) are only made
        exact by run()'s host repair, so run_device_full — which has no
        repair — keeps the streaming fallback instead."""
        self.last_hetk = None
        self._mp_hazard = None
        self.last_mp_passes = 0
        self._pending_iters = []
        self.last_extract_impl = None
        self.last_prune = None
        # Both routed and multipass paths dispatch the extraction
        # kernel; the "streaming" rung skips straight to _solve, whose
        # own gate lands on the chunk-fold driver.
        streaming = self._degrade_rung == "streaming"
        plan = None if streaming else self._plan_hetk(inp)
        if plan is not None:
            self.last_phase_ms = {}
            segs = self._solve_extract_routed(inp, plan)
            if segs is not None:
                return segs
        if allow_multipass and not streaming:
            self.last_phase_ms = {}
            segs = self._solve_extract_multipass(inp)
            if segs is not None:
                return segs
        top, qpad = self._solve(inp)
        return [(top, qpad, None, self._last_select)]

    def candidates(self, inp: KNNInput) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Device pass: (Q, K) selection-ordered candidate lists as NumPy."""
        kmax = int(inp.ks.max()) if inp.params.num_queries else 0
        memwatch.note_engine_model(self, inp)
        with staging_for_k(self, kmax):
            out, qpad = self._solve(inp)
        telemetry.sample_memory_now()
        nq = inp.params.num_queries
        # Explicit fenced readback (the result fetch IS the fence); the
        # sanitizer's transfer guard allows device_get, never implicit
        # conversion.
        od, ol, oi = resilient_get((out.dists, out.labels, out.ids))
        dists = np.asarray(od, np.float64)[:nq]
        labels = ol[:nq]
        ids = oi[:nq]
        self._flush_measured_iters()
        return dists, labels, ids

    def run(self, inp: KNNInput) -> List[QueryResult]:
        """Full parity pipeline: device candidates + host float64 finalize.

        On the fast "topk"/"seg" selection paths, queries whose candidate
        set may have truncated a distance-tie group (boundary_overflow) are
        recomputed exactly — parity holds on either path.

        Readback is kept minimal: in exact mode only the candidate ids and
        the device-computed hazard flags cross the link (labels are
        re-derived from ids on host, distances are rescored in float64
        anyway); the (Q, K) f32 distance matrix is fetched only in fast
        mode, where it is the result.
        """
        kmax = int(inp.ks.max()) if inp.params.num_queries else 0
        with staging_for_k(self, kmax):
            # Degradation ladder (resilience.degrade): on device OOM —
            # injected or real — the solve steps tuned -> heuristic ->
            # streaming -> host-f64, every rung checksum-preserving.
            return rs_degrade.run_ladder(self, inp, self._run)

    def _run(self, inp: KNNInput) -> List[QueryResult]:
        import time as _time

        n = inp.params.num_data
        memwatch.note_engine_model(self, inp)
        segments = self._solve_segments(inp)
        # Watermark tick at peak residency: the solve is enqueued, the
        # staged chunks/carries are live, nothing is fetched yet (no-op
        # without a telemetry session).
        telemetry.sample_memory_now()
        # Precision record for metrics/bench: what the first pass ran
        # at, and how many window slots the bound inflation bought the
        # rescore (kcap minus what an f32-precision plan would have
        # sized — 0 whenever precision resolves to "f32").
        prec = active_precision(self)
        kcap0 = int(segments[0][0].dists.shape[1])
        kmax0 = int(inp.ks.max()) if inp.params.num_queries else 0
        self.last_precision = {
            "active": prec,
            "configured": self.config.resolve_precision(),
            "kcap": kcap0,
            "kcap_inflation": kcap0 - resolve_kcap(
                self.config, kmax0, self._last_select, kcap0,
                staging=self._staging, precision="f32"),
        }
        self.last_repairs = 0  # tie-overflow repair rate, for bench records
        self.last_comms = []   # one chip: no collectives (obs.comms)
        merged: List[QueryResult] = [None] * inp.params.num_queries
        # Max squared data-row norm (f64): scales the staging-dtype
        # perturbation bound of the hazard test — computed on first need
        # only (an O(N*A) host pass the kcap >= n case never uses).
        dn_max = None

        fetch_ms = final_ms = 0.0
        for top, qpad, idx, select in segments:
            sub = inp if idx is None else subset_queries(inp, idx)
            nq = sub.params.num_queries
            kcap = top.dists.shape[1]

            cols_dev = None
            if select in ("sort", "topk", "seg", "extract") and kcap < n:
                ks_pad = np.ones(qpad, np.int32)
                ks_pad[:nq] = sub.ks
                cols_dev = _boundary_cols(top.dists, jax.device_put(ks_pad))

            t0 = _time.perf_counter()
            # NOTE: the "fetch" phase time includes the wait for all
            # enqueued device work (staging + solve), not just the readback
            # bytes — and past _CHUNK_WINDOW chunks the enqueue phase
            # absorbs throttled transfer wait too. Don't read this table
            # as "readback costs X ms".
            fetch = ([] if self.config.exact else [top.dists]) + [top.ids] \
                + ([cols_dev] if cols_dev is not None else [])
            with obs_span("single.fetch", select=select, kcap=kcap):
                fetched = list(resilient_get(fetch))
            dists = None if self.config.exact \
                else np.asarray(fetched.pop(0), np.float64)[:nq]
            ids = fetched.pop(0)[:nq]
            flags = None
            if cols_dev is not None:
                kth, last = np.asarray(fetched.pop(0), np.float64)[:, :nq]
                if dn_max is None:
                    dn_max = float(np.einsum(
                        "na,na->n", inp.data_attrs, inp.data_attrs).max()) \
                        if n else 0.0
                qn = np.einsum("qa,qa->q", sub.query_attrs, sub.query_attrs)
                eps = staging_eps(last, qn, dn_max, self._staging,
                                  inp.params.num_attrs)
                if prec == "bf16" and select == "extract":
                    # The low-precision first pass perturbs device
                    # distances by up to lowp_eps ON TOP of the staging
                    # rounding; the hazard test must clear both.
                    # Streaming-fallback segments never cast, so their
                    # eps stays the staging bound alone.
                    eps = eps + lowp_eps("bf16", qn, dn_max)
                flags = boundary_hazard(kth, last, eps)
            # Multi-pass extraction's own loss detectors (stall/shortfall,
            # _solve_extract_multipass) join the standard boundary test.
            mp = getattr(self, "_mp_hazard", None)
            if mp is not None and idx is None:
                flags = mp if flags is None else (flags | mp)
            labels = np.where(ids >= 0,
                              inp.labels[np.clip(ids, 0, max(n - 1, 0))], -1) \
                if n else np.full_like(ids, -1)
            fetch_ms += (_time.perf_counter() - t0) * 1e3

            t0 = _time.perf_counter()
            with obs_span("single.finalize", exact=self.config.exact) as sp:
                results = finalize_host(dists, labels, ids, sub.ks,
                                        sub.query_attrs, sub.data_attrs,
                                        exact=self.config.exact,
                                        query_ids=idx)
                if flags is not None:
                    suspects = np.nonzero(flags)[0]
                    if suspects.size:
                        repair_boundary_overflow(results, suspects, sub)
                        self.last_repairs += int(suspects.size)
                        sp.set(repairs=int(suspects.size))
            if idx is None:
                merged = results
            else:
                for local_i, orig in enumerate(idx):
                    merged[int(orig)] = results[local_i]
            final_ms += (_time.perf_counter() - t0) * 1e3
        self.last_phase_ms["fetch"] = fetch_ms
        self.last_phase_ms["finalize"] = final_ms
        self._flush_measured_iters()
        return merged

    def run_device_full(self, inp: KNNInput) -> List[QueryResult]:
        """All-device pipeline (vote + report order on TPU); f32 ordering.

        Runs the same ``_solve`` as ``run()`` — so the flagship extraction
        kernel (and the pipelined chunk overlap) serves this benchmark mode
        too — then votes and report-orders on device via the epilogue jit;
        only the final (Q, K) report lists cross the link.
        """
        num_labels = int(inp.labels.max()) + 1 if inp.params.num_data else 1
        merged: List[QueryResult] = [None] * inp.params.num_queries
        self.last_comms = []   # one chip: no collectives (obs.comms)
        memwatch.note_engine_model(self, inp)
        with no_auto_coarsen(self):
            segments = self._solve_segments(inp, allow_multipass=False)
        telemetry.sample_memory_now()
        for top, qpad, idx, _select in segments:
            sub = inp if idx is None else subset_queries(inp, idx)
            nq = sub.params.num_queries
            ks_pad = np.zeros(qpad, np.int32)
            ks_pad[:nq] = sub.ks

            p, i, d = _device_epilogue(top, jax.device_put(ks_pad),
                                       num_labels=num_labels)
            p, i, d = resilient_get((p, i, d))
            preds = p[:nq]
            rids = i[:nq]
            rd = np.asarray(d, np.float64)[:nq]
            gids = np.arange(nq) if idx is None else idx
            for qi in range(nq):
                merged[int(gids[qi])] = QueryResult(
                    int(gids[qi]), int(sub.ks[qi]), int(preds[qi]),
                    rids[qi, : int(sub.ks[qi])].astype(np.int64),
                    rd[qi, : int(sub.ks[qi])])
        self._flush_measured_iters()
        return merged
