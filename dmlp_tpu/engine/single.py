"""Single-chip KNN engine — the minimum end-to-end slice (survey §7 L1).

One jitted function does what the reference's whole MPI choreography does on
a grid of CPU ranks (engine.cpp:20-351): distances ride the MXU as a matmul
(dmlp_tpu.ops.distance), selection is an exact-tie-break sort
(dmlp_tpu.ops.topk), and queries/data stream in blocks so the (Q, N) distance
matrix never materializes. The scatter/bcast phases (engine.cpp:62-209)
vanish: one chip holds the (padded) arrays in HBM.

Two output paths:

- ``candidates()`` + host finalize (default, ``run()``): the device returns
  top-(kmax + margin) candidate lists; the host rescores them in float64 and
  applies vote/report semantics — checksum parity with the float64 golden
  model while the MXU does the O(Q*N*A) work in f32/bf16.
- ``run_device_full()``: vote + report ordering on-device too (benchmark
  path; no float64 rescue).
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.finalize import finalize_host
from dmlp_tpu.io.grammar import KNNInput
from dmlp_tpu.io.report import QueryResult
from dmlp_tpu.ops.topk import TopK, streaming_topk
from dmlp_tpu.ops.vote import majority_vote, report_order


def round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def pad_dataset(inp: KNNInput, multiple: int, dtype: np.dtype
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad (attrs, labels, ids) to a multiple of ``multiple`` rows.

    Sentinel rows carry label = -1 and id = -1; the distance kernel masks
    them to +inf (masked_pairwise_sq_l2). This replaces the reference's
    uneven remainder shards (engine.cpp:62-63) — XLA wants static, uniform
    shapes.
    """
    n = inp.params.num_data
    npad = round_up(max(n, 1), multiple)
    attrs = np.zeros((npad, inp.params.num_attrs), dtype)
    attrs[:n] = inp.data_attrs
    labels = np.full(npad, -1, np.int32)
    labels[:n] = inp.labels
    ids = np.full(npad, -1, np.int32)
    ids[:n] = np.arange(n, dtype=np.int32)
    return attrs, labels, ids


@functools.partial(jax.jit, static_argnames=("k", "data_block"))
def _topk_block(data_attrs, data_labels, data_ids, q_attrs, *, k, data_block):
    return streaming_topk(q_attrs, data_attrs, data_labels, data_ids,
                          k=k, data_block=data_block)


@functools.partial(jax.jit, static_argnames=("k", "data_block", "num_labels"))
def _full_block(data_attrs, data_labels, data_ids, q_attrs, ks, *,
                k, data_block, num_labels):
    top = streaming_topk(q_attrs, data_attrs, data_labels, data_ids,
                         k=k, data_block=data_block)
    rd, rids, in_k = report_order(top, ks)
    valid = in_k & (top.ids >= 0)
    predicted = majority_vote(top.labels, valid, num_labels)
    return predicted, rids, rd


class SingleChipEngine:
    """The one-chip engine (CPU backend in CI, TPU in production)."""

    def __init__(self, config: EngineConfig = EngineConfig()):
        self.config = config
        self._dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32

    def _prep(self, inp: KNNInput):
        cfg = self.config
        n = inp.params.num_data
        data_block = min(cfg.data_block, round_up(max(n, 1), 8))
        attrs, labels, ids = pad_dataset(inp, data_block, np.float64)
        kmax = int(inp.ks.max()) if inp.params.num_queries else 1
        extra = cfg.margin if cfg.exact else 0
        k = min(round_up(kmax + extra, 8), attrs.shape[0])
        k = max(k, kmax)  # never below the widest query's k
        d_attrs = jnp.asarray(attrs, self._dtype)
        return d_attrs, jnp.asarray(labels), jnp.asarray(ids), k, data_block

    def candidates(self, inp: KNNInput) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Device pass: (Q, K) selection-ordered candidate lists as NumPy."""
        cfg = self.config
        d_attrs, d_labels, d_ids, k, data_block = self._prep(inp)
        nq = inp.params.num_queries
        qb = min(cfg.query_block, round_up(max(nq, 1), 8))
        qpad = round_up(max(nq, 1), qb)
        q_attrs = np.zeros((qpad, inp.params.num_attrs), np.float64)
        q_attrs[:nq] = inp.query_attrs

        outs: List[TopK] = []
        for q0 in range(0, qpad, qb):
            blk = jnp.asarray(q_attrs[q0:q0 + qb], self._dtype)
            outs.append(_topk_block(d_attrs, d_labels, d_ids, blk,
                                    k=k, data_block=data_block))
        dists = np.concatenate([np.asarray(o.dists, np.float64) for o in outs])[:nq]
        labels = np.concatenate([np.asarray(o.labels) for o in outs])[:nq]
        ids = np.concatenate([np.asarray(o.ids) for o in outs])[:nq]
        return dists, labels, ids

    def run(self, inp: KNNInput) -> List[QueryResult]:
        """Full parity pipeline: device candidates + host float64 finalize."""
        dists, labels, ids = self.candidates(inp)
        return finalize_host(dists, labels, ids, inp.ks, inp.query_attrs,
                             inp.data_attrs, exact=self.config.exact)

    def run_device_full(self, inp: KNNInput) -> List[QueryResult]:
        """All-device pipeline (vote + report order on TPU); f32 ordering."""
        cfg = self.config
        d_attrs, d_labels, d_ids, k, data_block = self._prep(inp)
        nq = inp.params.num_queries
        num_labels = int(inp.labels.max()) + 1 if inp.params.num_data else 1
        qb = min(cfg.query_block, round_up(max(nq, 1), 8))
        qpad = round_up(max(nq, 1), qb)
        q_attrs = np.zeros((qpad, inp.params.num_attrs), np.float64)
        q_attrs[:nq] = inp.query_attrs
        ks_pad = np.zeros(qpad, np.int32)
        ks_pad[:nq] = inp.ks

        preds, rids, rd = [], [], []
        for q0 in range(0, qpad, qb):
            p, i, d = _full_block(
                d_attrs, d_labels, d_ids,
                jnp.asarray(q_attrs[q0:q0 + qb], self._dtype),
                jnp.asarray(ks_pad[q0:q0 + qb]),
                k=k, data_block=data_block, num_labels=num_labels)
            preds.append(np.asarray(p)); rids.append(np.asarray(i)); rd.append(np.asarray(d, np.float64))
        preds = np.concatenate(preds)[:nq]
        rids = np.concatenate(rids)[:nq]
        rd = np.concatenate(rd)[:nq]
        return [QueryResult(qi, int(inp.ks[qi]), int(preds[qi]),
                            rids[qi, : int(inp.ks[qi])].astype(np.int64),
                            rd[qi, : int(inp.ks[qi])])
                for qi in range(nq)]
