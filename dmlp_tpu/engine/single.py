"""Single-chip KNN engine — the minimum end-to-end slice (survey §7 L1).

One jitted function does what the reference's whole MPI choreography does on
a grid of CPU ranks (engine.cpp:20-351): distances ride the MXU as a matmul
(dmlp_tpu.ops.distance), selection is an exact-tie-break sort
(dmlp_tpu.ops.topk), and queries/data stream in blocks so the (Q, N) distance
matrix never materializes. The scatter/bcast phases (engine.cpp:62-209)
vanish: one chip holds the (padded) arrays in HBM.

Two output paths:

- ``candidates()`` + host finalize (default, ``run()``): the device returns
  top-(kmax + margin) candidate lists; the host rescores them in float64 and
  applies vote/report semantics — checksum parity with the float64 golden
  model while the MXU does the O(Q*N*A) work in f32/bf16.
- ``run_device_full()``: vote + report ordering on-device too (benchmark
  path; no float64 rescue).
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.finalize import (boundary_overflow, finalize_host,
                                      repair_boundary_overflow)
from dmlp_tpu.io.grammar import KNNInput
from dmlp_tpu.io.report import QueryResult
from dmlp_tpu.ops.topk import TopK, streaming_topk
from dmlp_tpu.ops.vote import majority_vote, report_order


def round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def fit_blocks(n: int, target_block: int, granule: int = 8) -> int:
    """A data_block (multiple of ``granule``, <= ~target_block) whose
    round_up padding wastes < granule * nblocks rows of n.

    Plain round_up(n, target_block) can waste up to target_block - 1 rows
    (31% at n=200k, target=64k) — real compute, since padded rows still ride
    the matmul. Shrinking the block to ~n/nblocks keeps the scan length and
    the waste both minimal. The "seg" selection needs granule=128 (whole
    lane-width segments).
    """
    n = max(n, 1)
    nblocks = max(1, -(-n // max(target_block, granule)))
    return round_up(-(-n // nblocks), granule)


def pad_dataset(inp: KNNInput, multiple: int, dtype: np.dtype
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad (attrs, labels, ids) to a multiple of ``multiple`` rows.

    Sentinel rows carry label = -1 and id = -1; the distance kernel masks
    them to +inf (masked_pairwise_sq_l2). This replaces the reference's
    uneven remainder shards (engine.cpp:62-63) — XLA wants static, uniform
    shapes.

    ``dtype`` should be the host-side staging dtype: padding straight into
    float32 halves the memcpy and the host->device bytes relative to staging
    in the parser's float64 (the f64 originals stay available for the exact
    host rescore).
    """
    n = inp.params.num_data
    npad = round_up(max(n, 1), multiple)
    attrs = np.zeros((npad, inp.params.num_attrs), dtype)
    attrs[:n] = inp.data_attrs
    labels = np.full(npad, -1, np.int32)
    labels[:n] = inp.labels
    ids = np.full(npad, -1, np.int32)
    ids[:n] = np.arange(n, dtype=np.int32)
    return attrs, labels, ids


@functools.partial(jax.jit,
                   static_argnames=("k", "data_block", "select", "use_pallas"))
def _topk_blocks(data_attrs, data_labels, data_ids, q_blocks, *, k,
                 data_block, select, use_pallas=False):
    """All query blocks in one dispatch: ``lax.map`` keeps the live distance
    tile at (query_block x data_block) while avoiding per-block Python
    dispatch + per-block device->host readbacks (which dominate over a
    tunneled PJRT link)."""
    return jax.lax.map(
        lambda q: streaming_topk(q, data_attrs, data_labels, data_ids,
                                 k=k, data_block=data_block, select=select,
                                 use_pallas=use_pallas),
        q_blocks)


@functools.partial(jax.jit,
                   static_argnames=("k", "data_block", "num_labels", "select",
                                    "use_pallas"))
def _full_blocks(data_attrs, data_labels, data_ids, q_blocks, ks_blocks, *,
                 k, data_block, num_labels, select, use_pallas=False):
    def one(args):
        q_attrs, ks = args
        top = streaming_topk(q_attrs, data_attrs, data_labels, data_ids,
                             k=k, data_block=data_block, select=select,
                             use_pallas=use_pallas)
        rd, rids, in_k = report_order(top, ks)
        valid = in_k & (top.ids >= 0)
        predicted = majority_vote(top.labels, valid, num_labels)
        return predicted, rids, rd
    return jax.lax.map(one, (q_blocks, ks_blocks))


class SingleChipEngine:
    """The one-chip engine (CPU backend in CI, TPU in production)."""

    def __init__(self, config: EngineConfig = EngineConfig()):
        self.config = config
        self._dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32

    def _prep(self, inp: KNNInput):
        cfg = self.config
        n = inp.params.num_data
        select = cfg.resolve_select(round_up(max(n, 1), 8))
        if cfg.data_block is not None:
            data_block = min(cfg.data_block, round_up(max(n, 1), 8))
        else:
            data_block = fit_blocks(n, cfg.resolve_data_block(select),
                                    granule=cfg.resolve_granule(select))
        attrs, labels, ids = pad_dataset(inp, data_block, np.float32)
        kmax = int(inp.ks.max()) if inp.params.num_queries else 1
        extra = cfg.margin if cfg.exact else 0
        if select in ("topk", "seg"):
            # The tie-overflow detector needs ks < kcap slack: with zero
            # extra slots the k-th and last candidate coincide and every
            # query would be flagged (degenerate all-repair).
            extra = max(extra, 8)
        k = min(round_up(kmax + extra, 8), attrs.shape[0])
        k = max(k, kmax)  # never below the widest query's k
        d_attrs = jnp.asarray(attrs, self._dtype)
        self._last_select = select  # run() gates the tie-overflow repair on it
        return (d_attrs, jnp.asarray(labels), jnp.asarray(ids), k, data_block,
                select)

    def candidates(self, inp: KNNInput) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Device pass: (Q, K) selection-ordered candidate lists as NumPy."""
        cfg = self.config
        d_attrs, d_labels, d_ids, k, data_block, select = self._prep(inp)
        nq = inp.params.num_queries
        qb = min(cfg.query_block, round_up(max(nq, 1), 8))
        qpad = round_up(max(nq, 1), qb)
        q_attrs = np.zeros((qpad, inp.params.num_attrs), np.float32)
        q_attrs[:nq] = inp.query_attrs
        q_blocks = jnp.asarray(
            q_attrs.reshape(qpad // qb, qb, -1), self._dtype)

        out: TopK = _topk_blocks(d_attrs, d_labels, d_ids, q_blocks,
                                 k=k, data_block=data_block, select=select,
                                 use_pallas=cfg.use_pallas)
        dists = np.asarray(out.dists, np.float64).reshape(qpad, -1)[:nq]
        labels = np.asarray(out.labels).reshape(qpad, -1)[:nq]
        ids = np.asarray(out.ids).reshape(qpad, -1)[:nq]
        return dists, labels, ids

    def run(self, inp: KNNInput) -> List[QueryResult]:
        """Full parity pipeline: device candidates + host float64 finalize.

        On the fast "topk" selection path, queries whose candidate set may
        have truncated a distance-tie group (boundary_overflow) are
        recomputed exactly — parity holds on either path.
        """
        dists, labels, ids = self.candidates(inp)
        results = finalize_host(dists, labels, ids, inp.ks, inp.query_attrs,
                                inp.data_attrs, exact=self.config.exact)
        if self._last_select in ("topk", "seg") \
                and dists.shape[1] < inp.params.num_data:
            # (width >= num_data means every real point is a candidate —
            # nothing can have been truncated.)
            suspects = np.nonzero(boundary_overflow(dists, inp.ks))[0]
            if suspects.size:
                repair_boundary_overflow(results, suspects, inp)
        return results

    def run_device_full(self, inp: KNNInput) -> List[QueryResult]:
        """All-device pipeline (vote + report order on TPU); f32 ordering."""
        cfg = self.config
        d_attrs, d_labels, d_ids, k, data_block, select = self._prep(inp)
        nq = inp.params.num_queries
        num_labels = int(inp.labels.max()) + 1 if inp.params.num_data else 1
        qb = min(cfg.query_block, round_up(max(nq, 1), 8))
        qpad = round_up(max(nq, 1), qb)
        q_attrs = np.zeros((qpad, inp.params.num_attrs), np.float32)
        q_attrs[:nq] = inp.query_attrs
        ks_pad = np.zeros(qpad, np.int32)
        ks_pad[:nq] = inp.ks

        nb = qpad // qb
        p, i, d = _full_blocks(
            d_attrs, d_labels, d_ids,
            jnp.asarray(q_attrs.reshape(nb, qb, -1), self._dtype),
            jnp.asarray(ks_pad.reshape(nb, qb)),
            k=k, data_block=data_block, num_labels=num_labels,
            select=select, use_pallas=cfg.use_pallas)
        preds = np.asarray(p).reshape(qpad)[:nq]
        rids = np.asarray(i).reshape(qpad, -1)[:nq]
        rd = np.asarray(d, np.float64).reshape(qpad, -1)[:nq]
        return [QueryResult(qi, int(inp.ks[qi]), int(preds[qi]),
                            rids[qi, : int(inp.ks[qi])].astype(np.int64),
                            rd[qi, : int(inp.ks[qi])])
                for qi in range(nq)]
