from dmlp_tpu.engine.single import SingleChipEngine  # noqa: F401
from dmlp_tpu.engine.sharded import RingEngine, ShardedEngine  # noqa: F401
from dmlp_tpu.engine.auto import AutoShardedEngine  # noqa: F401
from dmlp_tpu.engine.finalize import finalize_host  # noqa: F401
