"""Findings baseline: pin accepted pre-existing findings, fail new ones.

``check_baseline.json`` stores fingerprints (rule, path, scope, key) —
no line numbers, so baselined findings survive unrelated edits — with
multiplicity. The diff is a multiset comparison:

- a finding whose fingerprint has remaining baseline budget is
  *baselined* (reported, never fails);
- a finding without budget is *new* (fails ``make check``);
- unspent baseline entries are *stale* (reported so the baseline gets
  pruned as fixes land; never fail).

The goal state is an EMPTY baseline — the file exists so adopting the
analyzer never requires fixing the world in one PR, not to let
findings rot.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Tuple

from dmlp_tpu.check.findings import Finding

BASELINE_SCHEMA = 1
DEFAULT_NAME = "check_baseline.json"


def load_baseline(path: str) -> Counter:
    """Fingerprint multiset from a baseline file; empty if absent."""
    if not os.path.exists(path):
        return Counter()
    with open(path) as f:
        data = json.load(f)
    if data.get("baseline_schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: baseline_schema {data.get('baseline_schema')!r} "
            f"!= {BASELINE_SCHEMA}")
    out: Counter = Counter()
    for e in data.get("findings", []):
        fp = (e["rule"], e["path"], e.get("scope", ""), e["key"])
        out[fp] += int(e.get("count", 1))
    return out


def save_baseline(path: str, findings: List[Finding]) -> dict:
    counts: Counter = Counter(f.fingerprint() for f in findings)
    data = {
        "baseline_schema": BASELINE_SCHEMA,
        "findings": [
            {"rule": r, "path": p, "scope": s, "key": k, "count": n}
            for (r, p, s, k), n in sorted(counts.items())
        ],
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return data


def diff_baseline(findings: List[Finding], baseline: Counter
                  ) -> Tuple[List[Finding], List[Finding],
                             Dict[Tuple[str, str, str, str], int]]:
    """(new, baselined, stale): findings split against the baseline
    multiset; ``stale`` maps unspent fingerprints to leftover counts."""
    budget = Counter(baseline)
    new: List[Finding] = []
    matched: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget[fp] > 0:
            budget[fp] -= 1
            matched.append(f)
        else:
            new.append(f)
    stale = {fp: n for fp, n in budget.items() if n > 0}
    return new, matched, stale
