"""Runtime sanitizer: the dynamic half of the host-sync contract.

``DMLP_TPU_SANITIZE=1`` (or ``--sanitize`` on the CLIs) wraps solves in

- ``jax.transfer_guard("disallow")`` — implicit transfers raise.
  Explicit ``jax.device_put``/``jax.device_get`` stay allowed, which is
  exactly the R3 (hostsync) discipline: every intentional staging /
  readback in the engines is explicit, every implicit ``float()`` /
  ``.item()`` / array conversion of a device value is a bug. What the
  static pass wants annotated is what the guard rejects un-annotated.
- ``jax.checking_leaks()`` — tracer leaks out of jitted scopes raise.
- ``jax.debug_nans`` (train only) — NaN-producing steps raise at the
  op, not 200 steps later in the loss curve.

Backend note: on this container's CPU backend the guard catches scalar
conversions (``float``/``.item``) but zero-copy ``np.asarray`` views
pass; on TPU every implicit device->host readback is a real transfer
and raises. The engines therefore route ALL intentional readbacks
through explicit ``jax.device_get`` so a sanitized solve behaves
identically on both.
"""

from __future__ import annotations

import contextlib
import os
from typing import Mapping, Optional

ENV_VAR = "DMLP_TPU_SANITIZE"
_TRUTHY = ("1", "true", "on", "yes")


def sanitize_enabled(environ: Optional[Mapping[str, str]] = None) -> bool:
    env = os.environ if environ is None else environ
    return str(env.get(ENV_VAR, "")).strip().lower() in _TRUTHY


@contextlib.contextmanager
def sanitized(train: bool = False):
    """Context under which implicit transfers and tracer leaks raise
    (plus NaN checks when ``train``). Output of a clean program is
    byte-identical — the guards only turn silent hazards into errors.

    Solve mode guards ALL directions (``jax.transfer_guard("disallow")``
    — the engines' chunk pipelines must be explicit end to end).
    Train mode guards host<->device only: the jitted step re-places
    state leaves across shardings (e.g. the scalar step counter on
    first dispatch), and those device->device moves are GSPMD's
    legitimate job, not host-sync leaks."""
    import jax
    with contextlib.ExitStack() as stack:
        if train:
            stack.enter_context(
                jax.transfer_guard_host_to_device("disallow"))
            stack.enter_context(
                jax.transfer_guard_device_to_host("disallow"))
            stack.enter_context(jax.debug_nans(True))
        else:
            stack.enter_context(jax.transfer_guard("disallow"))
        stack.enter_context(jax.checking_leaks())
        yield


def maybe_sanitized(train: bool = False, force: bool = False,
                    environ: Optional[Mapping[str, str]] = None):
    """``sanitized()`` when ``force`` or $DMLP_TPU_SANITIZE is truthy,
    else a null context — the one-liner the CLIs wrap their solve in."""
    if force or sanitize_enabled(environ):
        return sanitized(train=train)
    return contextlib.nullcontext()
