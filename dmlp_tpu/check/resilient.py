"""R5 — silent-swallow hazards in resilience-wrapped paths.

The resilience layer's whole contract is that recovery is *visible*:
every retry, degradation, and rollback is counted and traced. A bare
``except Exception`` handler that neither re-raises nor carries the
explicit ``# check: no-retry`` annotation defeats that contract — it
can eat an :class:`InjectedTransientError` or a real RESOURCE_EXHAUSTED
before the retry/ladder machinery ever classifies it, turning a
recoverable fault into a silently wrong or silently degraded run.

Scope: modules inside ``dmlp_tpu/resilience/``, ``dmlp_tpu/serve/``,
and ``dmlp_tpu/fleet/``
(the serving daemon's per-request error paths swallow by design and
must say so), plus any module that imports ``dmlp_tpu.resilience``
(i.e. paths actually wrapped by the layer). A handler is compliant
when it catches something narrower than
``Exception``/``BaseException``, re-raises (any ``raise`` in its body),
or is annotated ``# check: no-retry`` — the annotation documents "this
swallow is deliberate and out of the retry path" (observability
best-effort blocks, already-killed-process cleanup).

- **R501** broad ``except Exception`` handler in a resilience-wrapped
  module without a re-raise or a ``# check: no-retry`` annotation.
"""

from __future__ import annotations

import ast

from dmlp_tpu.check.common import ModuleInfo
from dmlp_tpu.check.findings import Finding

ALLOW = "no-retry"

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    """Catches Exception/BaseException (bare ``except:`` is R002's)."""
    t = handler.type
    if t is None:
        return False
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        name = node.id if isinstance(node, ast.Name) else (
            node.attr if isinstance(node, ast.Attribute) else None)
        if name in _BROAD:
            return True
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    """Any ``raise`` in the handler body (bare or transforming) means
    the error is propagated, not swallowed. A ``raise`` inside a
    function merely *defined* in the handler does not count — defining
    a raiser is not raising."""
    stack: list = list(handler.body)
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


def in_resilient_scope(mod: ModuleInfo) -> bool:
    rel = mod.relpath.replace("\\", "/")
    if rel.startswith(("dmlp_tpu/resilience/", "dmlp_tpu/serve/",
                       "dmlp_tpu/fleet/")):
        return True
    return any(src.startswith("dmlp_tpu.resilience")
               for src in mod.imports.values())


class ResilientRule:
    def run(self, mod: ModuleInfo, add) -> None:
        if not in_resilient_scope(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _reraises(node):
                continue
            if mod.allowed(node, ALLOW):
                continue
            add(Finding(
                "R501", mod.relpath, node.lineno, node.col_offset,
                mod.scope_of(node), "broad-except-swallow",
                "broad `except Exception` in a resilience-wrapped path "
                "swallows retryable/classifiable errors — re-raise, "
                "narrow the type, or annotate `# check: no-retry` if "
                "the swallow is deliberate"))
