"""R7 — concurrency discipline for the threaded serving/telemetry surface.

PRs 9-10 made dmlp_tpu a multithreaded online system: the serving
daemon's ThreadingTCPServer handlers, the MicroBatcher consumer thread,
the telemetry Sampler/export threads, heartbeat and retry-timeout
workers all share mutable state under ad-hoc locks. None of that
discipline was machine-checked — R1 enforces collective-axis contracts,
but a lock-order inversion or an unguarded field ships silently. This
family is the static half (the runtime half is :mod:`.racecheck`):

- **R701 — lock-order inversions.** The analyzer infers every lock the
  package creates (``self._lock = threading.Lock()`` / module-level
  ``Lock()``/``RLock()``/``Condition()``), builds the package-wide
  acquisition graph (``with self._lock:`` nesting plus ``acquire()``/
  ``release()`` regions, propagated one call-graph fixpoint through
  resolvable calls), and flags every acquisition edge that sits on a
  cycle — two locks taken in opposite orders anywhere in the package is
  a latent deadlock, even if no single run interleaves it. A nested
  re-acquisition of the same non-reentrant ``Lock`` is the degenerate
  self-cycle and flags too.
- **R702 — guarded-field discipline.** Per class, a field written under
  one of the class's locks anywhere (outside ``__init__``) is *guarded*;
  every other non-``__init__`` access that does not hold one of its
  guard locks flags, and so does ``return self._field`` of a guarded
  mutable (list/dict/set/deque) — handing out a reference exports the
  race to every caller.
- **R703 — blocking calls under a lock.** ``time.sleep``, socket sends/
  receives, ``urlopen``, subprocess waits, ``Thread.join``,
  ``Event.wait`` (on anything but the held lock), queue gets, and jax
  dispatch/readback (``jax.device_get``, ``block_until_ready``,
  ``.item()``) made while holding an inferred lock — directly or
  through a resolvable call chain — stall every thread contending for
  that lock (an injected straggler delay under the admission path's
  queue lock would freeze the whole daemon).
- **R704 — thread lifecycle.** A ``threading.Thread`` started without
  ``daemon=True`` and without a reachable ``join()`` on its binding
  wedges interpreter shutdown; every thread needs a stop path or an
  explicit daemon declaration.

Escape hatch: ``# check: allow-concurrency`` waives the family at a
site, ``# check: allow-concurrency=R70x`` one rule — every in-tree use
must state the invariant that makes the pattern safe (mirroring
``allow-host-sync``).

Known limits (deliberate; the runtime sanitizer covers the remainder):
call resolution is name/annotation-based (``self.attr.m()`` resolves
through ``__init__`` constructor assignments and parameter/variable
annotations; unresolvable receivers are skipped, never guessed), and
held-lock state does not flow into closures defined under a lock.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Any, Dict, List, Optional, Set, Tuple

from dmlp_tpu.check.common import ModuleInfo, call_name, dotted
from dmlp_tpu.check.findings import Finding

ALLOW = "allow-concurrency"

#: canonical factory -> lock kind ("lock" is non-reentrant)
LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "cond",
}
#: canonical factory -> special receiver type marker
SPECIAL_FACTORIES = {
    "threading.Event": "@event",
    "threading.Thread": "@thread",
    "subprocess.Popen": "@proc",
    "queue.Queue": "@queue",
    "queue.SimpleQueue": "@queue",
}

#: canonical dotted names that block the calling thread outright
BLOCKING_DOTTED = {
    "time.sleep", "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "subprocess.call",
    "socket.create_connection", "urllib.request.urlopen",
    "jax.device_get", "jax.block_until_ready",
}
#: attribute leaves that block regardless of receiver type
BLOCKING_LEAVES = {"sendall", "recv", "accept", "communicate",
                   "block_until_ready", "device_get", "urlopen",
                   "create_connection"}
#: attribute leaves that block only on typed receivers
_RECV_BLOCKING = {
    ("@event", "wait"), ("@thread", "join"), ("@proc", "wait"),
    ("@queue", "get"), ("@queue", "join"),
}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_MUTABLE_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                  ast.DictComp, ast.SetComp)
_MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "deque",
                  "collections.deque", "defaultdict",
                  "collections.defaultdict", "OrderedDict",
                  "collections.OrderedDict"}


def _canon(mod: ModuleInfo, name: Optional[str]) -> Optional[str]:
    """Canonicalize a dotted name through the module's import table
    (``rs_inject.fire`` -> ``dmlp_tpu.resilience.inject.fire``)."""
    if not name:
        return None
    head, _, rest = name.partition(".")
    src = mod.imports.get(head)
    if src:
        return f"{src}.{rest}" if rest else src
    return name


def _ann_class(mod: ModuleInfo, ann: Optional[ast.AST]) -> Optional[str]:
    """Class dotted path from an annotation, unwrapping Optional[...] /
    ``X | None`` / string annotations."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        base = dotted(ann.value)
        if base and base.rsplit(".", 1)[-1] == "Optional":
            inner = ann.slice
            return _ann_class(mod, inner)
        return None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        for side in (ann.left, ann.right):
            got = _ann_class(mod, side)
            if got:
                return got
        return None
    name = dotted(ann)
    if name in ("None", "bool", "int", "float", "str", "bytes"):
        return None
    return _canon(mod, name)


@dataclasses.dataclass
class _Event:
    """One interesting occurrence inside a function body, with the
    lockrefs held at that point. ``kind`` is "acquire" | "call" |
    "blocking"; ``target``: lockref / canonical call name / blocking
    descriptor. ``node`` is present only in live (run-time) scans."""

    kind: str
    target: str
    line: int
    held: Tuple[str, ...]
    node: Optional[ast.AST] = None


@dataclasses.dataclass
class _FieldAccess:
    field: str
    write: bool
    held: Tuple[str, ...]
    in_init: bool
    line: int
    escape: bool = False
    node: Optional[ast.AST] = None


@dataclasses.dataclass
class _ThreadSite:
    line: int
    daemon: bool
    binding: Optional[str]     # "self.x" / local name / None
    node: Optional[ast.AST] = None


class ModuleConcScan:
    """Everything R7 needs from one module: lock definitions, typed
    names, per-function event streams with held-lock context, per-class
    field accesses, and thread-construction sites. Used both to build
    the cacheable cross-module facts and (re-run live) to place
    findings."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        # local lockref -> kind; lockrefs: "Class.attr" | ":name" |
        # "fnkey:<name>" for function-locals
        self.locks: Dict[str, str] = {}
        # typed names: module globals / class attrs / special markers
        self.module_types: Dict[str, str] = {}
        self.class_attr_types: Dict[str, Dict[str, str]] = {}
        self.classes: List[str] = []
        # fnkey ("Class.method" | "fn") -> event list
        self.functions: Dict[str, List[_Event]] = {}
        self.fn_defs: Dict[str, ast.AST] = {}
        # class -> list of field accesses / mutable fields
        self.class_fields: Dict[str, List[_FieldAccess]] = {}
        self.mutable_fields: Dict[str, Set[str]] = {}
        self.thread_sites: List[_ThreadSite] = []
        self._scan()

    # -- prepass: lock/type tables -------------------------------------------
    def _factory_kind(self, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        name = _canon(self.mod, call_name(value))
        if name in LOCK_FACTORIES:
            return LOCK_FACTORIES[name]
        leaf = (name or "").rsplit(".", 1)[-1]
        if f"threading.{leaf}" in LOCK_FACTORIES \
                and leaf in ("Lock", "RLock", "Condition"):
            return LOCK_FACTORIES[f"threading.{leaf}"]
        return None

    def _special_type(self, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        name = _canon(self.mod, call_name(value))
        if name in SPECIAL_FACTORIES:
            return SPECIAL_FACTORIES[name]
        leaf = (name or "").rsplit(".", 1)[-1]
        for canon, marker in SPECIAL_FACTORIES.items():
            if canon.endswith("." + leaf):
                return marker
        return None

    def _value_type(self, value: ast.AST, env: Dict[str, str]
                    ) -> Optional[str]:
        """Type marker for an assignment RHS: special factory marker,
        constructed class's dotted path, or an alias's known type."""
        special = self._special_type(value)
        if special:
            return special
        if isinstance(value, ast.Call):
            name = _canon(self.mod, call_name(value))
            if name:
                leaf = name.rsplit(".", 1)[-1]
                if leaf[:1].isupper():         # constructor by convention
                    return name
            return None
        if isinstance(value, ast.Name):
            return env.get(value.id) or self.module_types.get(value.id)
        if isinstance(value, ast.Attribute):
            d = dotted(value)
            if d and d.startswith("self."):
                cls = env.get("@class")
                if cls:
                    return self.class_attr_types.get(cls, {}).get(d[5:])
        return None

    def _scan(self):
        mod = self.mod
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                kind = self._factory_kind(stmt.value)
                if kind:
                    self.locks[f":{name}"] = kind
                    continue
                t = self._value_type(stmt.value, {})
                if t:
                    self.module_types[name] = t
                # blocking alias: `_sleep = time.sleep`
                src = _canon(mod, dotted(stmt.value)) \
                    if isinstance(stmt.value, (ast.Attribute, ast.Name)) \
                    else None
                if src in BLOCKING_DOTTED:
                    self.module_types[name] = f"@blocking:{src}"
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                cls = _ann_class(mod, stmt.annotation)
                if cls:
                    self.module_types[stmt.target.id] = cls
        # classes: collect lock attrs + attr types from every method
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class_decls(node)
        # function event streams
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, _FUNC_NODES):
                        self._scan_function(sub, cls=node.name)
            elif isinstance(node, _FUNC_NODES):
                self._scan_function(node, cls=None)

    def _scan_class_decls(self, cnode: ast.ClassDef):
        cls = cnode.name
        self.classes.append(cls)
        attrs = self.class_attr_types.setdefault(cls, {})
        self.mutable_fields.setdefault(cls, set())
        for fn in cnode.body:
            if not isinstance(fn, _FUNC_NODES):
                continue
            params = {a.arg: _ann_class(self.mod, a.annotation)
                      for a in fn.args.args + fn.args.kwonlyargs}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    d = dotted(tgt)
                    if not d or not d.startswith("self.") \
                            or d.count(".") != 1:
                        continue
                    attr = d[5:]
                    kind = self._factory_kind(node.value)
                    if kind:
                        self.locks[f"{cls}.{attr}"] = kind
                        continue
                    t = self._value_type(node.value, {"@class": cls})
                    if t is None and isinstance(node.value, ast.Name):
                        t = params.get(node.value.id)
                    if t and attr not in attrs:
                        attrs[attr] = t
                    if fn.name == "__init__":
                        v = node.value
                        is_mut = isinstance(v, _MUTABLE_NODES) or (
                            isinstance(v, ast.Call)
                            and (_canon(self.mod, call_name(v))
                                 in _MUTABLE_CALLS
                                 or (call_name(v) or "").rsplit(
                                     ".", 1)[-1] in _MUTABLE_CALLS))
                        if is_mut:
                            self.mutable_fields[cls].add(attr)

    # -- lock-expression resolution ------------------------------------------
    def _lockref_of(self, expr: ast.AST, cls: Optional[str], fnkey: str,
                    env: Dict[str, str]) -> Optional[str]:
        d = dotted(expr)
        if d is None:
            return None
        if d.startswith("self.") and cls is not None and d.count(".") == 1:
            ref = f"{cls}.{d[5:]}"
            return ref if ref in self.locks else None
        if "." not in d:
            if f":{d}" in self.locks:
                return f":{d}"
            local = f"{fnkey}:{d}"
            return local if local in self.locks else None
        # typed receiver: x.attr where x's class is known
        head, _, attr = d.rpartition(".")
        recv_t = self._recv_type(head, cls, env)
        if recv_t and not recv_t.startswith("@"):
            return f"@ext:{recv_t}.{attr}"
        return None

    def _recv_type(self, head: str, cls: Optional[str],
                   env: Dict[str, str]) -> Optional[str]:
        if head == "self" and cls:
            return f"@local_class:{cls}"
        if head.startswith("self.") and cls and head.count(".") == 1:
            return self.class_attr_types.get(cls, {}).get(head[5:])
        if "." not in head:
            return env.get(head) or self.module_types.get(head)
        return None

    # -- function body walk ---------------------------------------------------
    def _scan_function(self, fn: ast.AST, cls: Optional[str]):
        fnkey = f"{cls}.{fn.name}" if cls else fn.name
        if fnkey in self.functions:      # duplicate def: first wins
            return
        events: List[_Event] = []
        self.functions[fnkey] = events
        self.fn_defs[fnkey] = fn
        env: Dict[str, str] = {"@class": cls or ""}
        for a in fn.args.args + fn.args.kwonlyargs:
            t = _ann_class(self.mod, a.annotation)
            if t:
                env[a.arg] = t
        accesses = (self.class_fields.setdefault(cls, [])
                    if cls else None)
        in_init = fn.name == "__init__"
        self._walk(list(fn.body), held=[], fnkey=fnkey, cls=cls, env=env,
                   events=events, accesses=accesses, in_init=in_init)

    def _walk(self, stmts, held: List[str], fnkey: str,
              cls: Optional[str], env: Dict[str, str],
              events: List[_Event], accesses, in_init: bool):
        for st in stmts:
            if isinstance(st, _FUNC_NODES + (ast.ClassDef,)):
                # a closure defined here runs LATER: its body gets a
                # fresh (empty) held context
                if isinstance(st, _FUNC_NODES):
                    self._walk(list(st.body), [], fnkey, cls, env,
                               events, accesses, in_init=False)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in st.items:
                    ref = self._lockref_of(item.context_expr, cls, fnkey,
                                           env)
                    self._exprs(item.context_expr, held, fnkey, cls, env,
                                events, accesses, in_init)
                    if ref is not None:
                        events.append(_Event("acquire", ref,
                                             item.context_expr.lineno,
                                             tuple(held),
                                             item.context_expr))
                        held.append(ref)
                        pushed += 1
                self._walk(list(st.body), held, fnkey, cls, env, events,
                           accesses, in_init)
                for _ in range(pushed):
                    held.pop()
                continue
            if isinstance(st, ast.Assign):
                # function-local lock / typed binding
                kind = self._factory_kind(st.value)
                if kind and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name):
                    self.locks[f"{fnkey}:{st.targets[0].id}"] = kind
                t = self._value_type(st.value, env)
                if t and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name):
                    env[st.targets[0].id] = t
                self._maybe_thread_site(st.value, st.targets, cls)
                self._exprs(st, held, fnkey, cls, env, events, accesses,
                            in_init)
                continue
            if isinstance(st, ast.Return):
                self._exprs(st, held, fnkey, cls, env, events, accesses,
                            in_init)
                if accesses is not None and st.value is not None:
                    d = dotted(st.value)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        accesses.append(_FieldAccess(
                            d[5:], False, tuple(held), in_init,
                            st.lineno, escape=True, node=st))
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                self._exprs(st.iter, held, fnkey, cls, env, events,
                            accesses, in_init)
                self._target_writes(st.target, held, accesses, in_init)
                self._walk(list(st.body) + list(st.orelse), held, fnkey,
                           cls, env, events, accesses, in_init)
                continue
            if isinstance(st, (ast.If, ast.While)):
                self._exprs(st.test, held, fnkey, cls, env, events,
                            accesses, in_init)
                self._walk(list(st.body) + list(st.orelse), held, fnkey,
                           cls, env, events, accesses, in_init)
                continue
            if isinstance(st, ast.Try):
                body = list(st.body) + list(st.orelse) + list(st.finalbody)
                for h in st.handlers:
                    body += list(h.body)
                self._walk(body, held, fnkey, cls, env, events, accesses,
                           in_init)
                continue
            if isinstance(st, ast.Expr):
                # bare acquire()/release() region tracking
                call = st.value if isinstance(st.value, ast.Call) else None
                leaf = None
                if call is not None and isinstance(call.func,
                                                   ast.Attribute):
                    leaf = call.func.attr
                if leaf in ("acquire", "release") and call is not None:
                    ref = self._lockref_of(call.func.value, cls, fnkey,
                                           env)
                    if ref is not None:
                        if leaf == "acquire":
                            events.append(_Event(
                                "acquire", ref, st.lineno, tuple(held),
                                call))
                            held.append(ref)
                        elif ref in held:
                            held.remove(ref)
                        continue
                self._exprs(st, held, fnkey, cls, env, events, accesses,
                            in_init)
                continue
            self._maybe_thread_site(getattr(st, "value", None), [], cls)
            self._exprs(st, held, fnkey, cls, env, events, accesses,
                        in_init)

    def _target_writes(self, target: ast.AST, held, accesses, in_init):
        if accesses is None:
            return
        for sub in ast.walk(target):
            d = dotted(sub) if isinstance(sub, ast.Attribute) else None
            if d and d.startswith("self.") and d.count(".") == 1:
                accesses.append(_FieldAccess(
                    d[5:], True, tuple(held), in_init, sub.lineno,
                    node=sub))

    def _maybe_thread_site(self, value, targets, cls: Optional[str]):
        """Record ``threading.Thread(...)`` constructions (R704)."""
        calls = []
        if isinstance(value, ast.Call):
            calls.append((value, targets))
        for call, tgts in calls:
            inner = call
            # `threading.Thread(...).start()` — unwrap the chain
            if isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Call):
                inner = call.func.value
            if self._special_type(inner) != "@thread":
                continue
            daemon = any(
                kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in inner.keywords)
            binding = None
            for tgt in (tgts or []):
                d = dotted(tgt)
                if d:
                    binding = d
            self.thread_sites.append(_ThreadSite(
                inner.lineno, daemon, binding, node=inner))

    # -- expression-level events ----------------------------------------------
    def _blocking_desc(self, call: ast.Call, cls: Optional[str],
                       env: Dict[str, str],
                       held: List[str], fnkey: str) -> Optional[str]:
        name = call_name(call)
        canon = _canon(self.mod, name)
        if canon in BLOCKING_DOTTED:
            return canon
        if name and "." not in name:
            t = env.get(name) or self.module_types.get(name)
            if t and t.startswith("@blocking:"):
                return t[len("@blocking:"):]
        if isinstance(call.func, ast.Attribute):
            leaf = call.func.attr
            if leaf == "item" and not call.args and not call.keywords:
                return ".item()"
            if leaf in BLOCKING_LEAVES:
                return f".{leaf}()"
            recv = call.func.value
            d = dotted(recv)
            recv_t = None
            if d is not None:
                if d.startswith("self.") and cls and d.count(".") == 1:
                    attr = d[5:]
                    if f"{cls}.{attr}" in self.locks:
                        recv_t = f"@lockobj:{cls}.{attr}"
                    else:
                        recv_t = self.class_attr_types.get(
                            cls, {}).get(attr)
                elif "." not in d:
                    if f":{d}" in self.locks or f"{fnkey}:{d}" in self.locks:
                        recv_t = "@lockobj:" + (
                            f":{d}" if f":{d}" in self.locks
                            else f"{fnkey}:{d}")
                    else:
                        recv_t = env.get(d) or self.module_types.get(d)
            if recv_t:
                if recv_t.startswith("@lockobj:"):
                    # cond.wait on the HELD lock releases it: legal.
                    ref = recv_t[len("@lockobj:"):]
                    if leaf == "wait" and ref not in held:
                        return f"{d}.wait()"
                    return None
                for marker, bleaf in _RECV_BLOCKING:
                    if recv_t == marker and leaf == bleaf:
                        return f"{d}.{leaf}()"
        return None

    def _exprs(self, node: ast.AST, held: List[str], fnkey: str,
               cls: Optional[str], env: Dict[str, str],
               events: List[_Event], accesses, in_init: bool):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                desc = self._blocking_desc(sub, cls, env, held, fnkey)
                if desc is not None:
                    events.append(_Event("blocking", desc, sub.lineno,
                                         tuple(held), sub))
                    continue
                target = self._call_target(sub, cls, fnkey, env)
                if target is not None:
                    events.append(_Event("call", target, sub.lineno,
                                         tuple(held), sub))
            elif accesses is not None and isinstance(sub, ast.Attribute):
                d = dotted(sub)
                if d and d.startswith("self.") and d.count(".") == 1 \
                        and isinstance(sub.ctx, (ast.Store, ast.Del)):
                    accesses.append(_FieldAccess(
                        d[5:], True, tuple(held), in_init, sub.lineno,
                        node=sub))
                elif d and d.startswith("self.") and d.count(".") == 1:
                    accesses.append(_FieldAccess(
                        d[5:], False, tuple(held), in_init, sub.lineno,
                        node=sub))

    def _call_target(self, call: ast.Call, cls: Optional[str],
                     fnkey: str, env: Dict[str, str]) -> Optional[str]:
        """Partially-resolved callee: ``local:<fnkey>`` for same-module
        defs, ``ext:<dotted>`` for import/annotation-resolved targets,
        None when the receiver cannot be typed."""
        name = call_name(call)
        if name is None:
            return None
        if "." not in name:
            if name in self.functions or any(
                    isinstance(n, _FUNC_NODES) and n.name == name
                    for n in self.mod.tree.body):
                return f"local:{name}"
            src = self.mod.imports.get(name)
            if src:
                return f"ext:{src}"
            if name in self.module_types \
                    and not self.module_types[name].startswith("@"):
                # ClassName(...) constructor of a typed name
                return None
            if name[:1].isupper():
                canon = _canon(self.mod, name)
                if canon and canon != name:
                    return f"ext:{canon}.__init__"
                return f"local:{name}.__init__"
            return None
        head, _, leaf = name.rpartition(".")
        if head == "self" and cls:
            return f"local:{cls}.{leaf}"
        recv_t = self._recv_type(head, cls, env)
        if recv_t:
            if recv_t.startswith("@local_class:"):
                return f"local:{recv_t.split(':', 1)[1]}.{leaf}"
            if not recv_t.startswith("@"):
                if "." not in recv_t and recv_t in self.classes:
                    return f"local:{recv_t}.{leaf}"
                return f"ext:{recv_t}.{leaf}"
            return None
        canon = _canon(self.mod, name)
        if canon and canon != name:
            return f"ext:{canon}"
        return None

    # -- cacheable facts ------------------------------------------------------
    def facts(self) -> Dict[str, Any]:
        """JSON-safe cross-module facts (no AST nodes — and no line
        numbers: facts must be stable under pure line shifts so a
        comment edit in a lock-bearing file does not invalidate every
        OTHER file's cached verdict). Duplicate events collapse."""
        fns: Dict[str, List[List[Any]]] = {}
        for fnkey, evs in self.functions.items():
            seen = set()
            rows = []
            for e in evs:
                sig = (e.kind, e.target, e.held)
                if sig in seen:
                    continue
                seen.add(sig)
                rows.append([e.kind, e.target, list(e.held)])
            fns[fnkey] = rows
        return {
            "locks": dict(self.locks),
            "classes": list(self.classes),
            "functions": fns,
        }


# -- global graph -------------------------------------------------------------

def _module_dotted(relpath: str) -> str:
    rel = relpath.replace("\\", "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[:-len("/__init__")]
    return rel.replace("/", ".")


class ConcurrencyGraph:
    """Package-wide lock graph built from per-module facts: resolves
    cross-module call targets, closes may-acquire/may-block over the
    call graph, and computes the cyclic (inversion) edge set."""

    def __init__(self, fact_pairs: List[Tuple[str, Dict[str, Any]]]):
        self.lock_kinds: Dict[str, str] = {}
        self.class_home: Dict[str, str] = {}       # dotted class -> relpath
        #: fnref -> [(kind, target, held lockrefs)] (line-free facts)
        self.fn_events: Dict[str, List[Tuple[str, str, List[str]]]] = {}
        self._mod_by_dotted: Dict[str, str] = {}
        for rel, facts in fact_pairs:
            md = _module_dotted(rel)
            self._mod_by_dotted[md] = rel
            for ref, kind in facts.get("locks", {}).items():
                self.lock_kinds[self._g(rel, ref)] = kind
            for cls in facts.get("classes", []):
                self.class_home[f"{md}.{cls}"] = rel
            for fnkey, evs in facts.get("functions", {}).items():
                self.fn_events[f"{rel}::{fnkey}"] = [
                    (k, t, h) for k, t, h in evs]
        self._close()

    @staticmethod
    def _g(rel: str, ref: str) -> str:
        return f"{rel}::{ref}"

    def resolve_lock(self, rel: str, ref: str) -> Optional[str]:
        if ref.startswith("@ext:"):
            dotted_attr = ref[len("@ext:"):]
            cls_path, _, attr = dotted_attr.rpartition(".")
            home = self.class_home.get(cls_path)
            if home is None:
                return None
            cls = cls_path.rsplit(".", 1)[-1]
            g = self._g(home, f"{cls}.{attr}")
            return g if g in self.lock_kinds else None
        g = self._g(rel, ref)
        return g if g in self.lock_kinds else None

    def resolve_call(self, rel: str, target: str) -> Optional[str]:
        kind, _, name = target.partition(":")
        if kind == "local":
            ref = f"{rel}::{name}"
            return ref if ref in self.fn_events else None
        # ext: dotted — try module fn, then class method/constructor
        parts = name.split(".")
        for split in range(len(parts) - 1, 0, -1):
            mod_dotted = ".".join(parts[:split])
            rest = ".".join(parts[split:])
            home = self._mod_by_dotted.get(mod_dotted)
            if home is None:
                continue
            ref = f"{home}::{rest}"
            if ref in self.fn_events:
                return ref
        # class path: a.b.Class.m -> module a.b, fnkey Class.m handled
        # above; constructor a.b.Class -> Class.__init__
        home = None
        cls_path = name
        if cls_path in self.class_home:
            home = self.class_home[cls_path]
            cls = cls_path.rsplit(".", 1)[-1]
            ref = f"{home}::{cls}.__init__"
            return ref if ref in self.fn_events else None
        return None

    def _close(self):
        """Fixpoint may-acquire / may-block over the resolved call
        graph, then the edge set + cycle detection."""
        self.may_acquire: Dict[str, Set[str]] = {}
        self.may_block: Dict[str, Set[str]] = {}
        calls: Dict[str, Set[str]] = {}
        for fnref, evs in self.fn_events.items():
            rel = fnref.split("::", 1)[0]
            acq, blk, outs = set(), set(), set()
            for k, t, _h in evs:
                if k == "acquire":
                    g = self.resolve_lock(rel, t)
                    if g:
                        acq.add(g)
                elif k == "blocking":
                    blk.add(t)
                elif k == "call":
                    c = self.resolve_call(rel, t)
                    if c:
                        outs.add(c)
            self.may_acquire[fnref] = acq
            self.may_block[fnref] = blk
            calls[fnref] = outs
        for _ in range(20):                      # fixpoint (shallow)
            changed = False
            for fnref, outs in calls.items():
                for c in outs:
                    na = self.may_acquire[c] - self.may_acquire[fnref]
                    if na:
                        self.may_acquire[fnref] |= na
                        changed = True
                    nb = self.may_block[c] - self.may_block[fnref]
                    if nb:
                        self.may_block[fnref] |= nb
                        changed = True
            if not changed:
                break
        # edges: held lock -> acquired lock, with one example function
        # as the counter-site (facts carry no line numbers — stability
        # under line shifts is what keeps the cache per-file)
        self.edge_sites: Dict[Tuple[str, str], str] = {}
        self.self_edges: Dict[str, str] = {}
        for fnref, evs in self.fn_events.items():
            rel = fnref.split("::", 1)[0]
            for k, t, h in evs:
                helds = [self.resolve_lock(rel, x) for x in h]
                helds = [x for x in helds if x]
                if not helds:
                    continue
                acquired: Set[str] = set()
                if k == "acquire":
                    g = self.resolve_lock(rel, t)
                    if g:
                        acquired.add(g)
                elif k == "call":
                    c = self.resolve_call(rel, t)
                    if c:
                        acquired |= self.may_acquire[c]
                for m in acquired:
                    for hl in helds:
                        if hl == m:
                            if self.lock_kinds.get(m) == "lock" \
                                    and k == "acquire":
                                self.self_edges.setdefault(m, fnref)
                            continue
                        self.edge_sites.setdefault((hl, m), fnref)
        self.cyclic_edges = self._cyclic(set(self.edge_sites))

    @staticmethod
    def _cyclic(edges: Set[Tuple[str, str]]) -> Set[Tuple[str, str]]:
        """Edges inside a strongly connected component of size >= 2."""
        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        comp: Dict[str, int] = {}
        counter = [0]
        ncomp = [0]

        def strongconnect(v0: str):
            work = [(v0, iter(sorted(adj[v0])))]
            index[v0] = low[v0] = counter[0]
            counter[0] += 1
            stack.append(v0)
            on.add(v0)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    if w in on:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp[w] = ncomp[0]
                        if w == v:
                            break
                    ncomp[0] += 1

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        sizes: Dict[int, int] = {}
        for v, c in comp.items():
            sizes[c] = sizes.get(c, 0) + 1
        return {(a, b) for a, b in edges
                if comp.get(a) == comp.get(b) and sizes.get(comp.get(a),
                                                            0) >= 2}


def _short(lockref: str) -> str:
    """Human form of a global lockref for messages."""
    rel, _, ref = lockref.partition("::")
    return f"{rel}:{ref.lstrip(':')}"


class ConcurrencyRule:
    """R701-R704 over each module, against the package-wide graph."""

    def __init__(self, graph: ConcurrencyGraph):
        self.graph = graph

    # -- driver ---------------------------------------------------------------
    def run(self, mod: ModuleInfo, add) -> None:
        scan = ModuleConcScan(mod)
        rel = mod.relpath
        g = self.graph
        for fnkey, events in scan.functions.items():
            for e in events:
                helds = [(x, g.resolve_lock(rel, x)) for x in e.held]
                helds = [(loc, glo) for loc, glo in helds if glo]
                if not helds:
                    continue
                if e.kind == "blocking":
                    self._r703(mod, e, e.target, helds, add,
                               via=None)
                elif e.kind == "call":
                    target = g.resolve_call(rel, e.target)
                    if target is None:
                        continue
                    blocks = g.may_block.get(target, set())
                    if blocks:
                        self._r703(mod, e, sorted(blocks)[0], helds, add,
                                   via=target)
                    for m in sorted(g.may_acquire.get(target, set())):
                        self._r701(mod, e, helds, m, add, via=target)
                elif e.kind == "acquire":
                    m = g.resolve_lock(rel, e.target)
                    if m is None:
                        continue
                    if any(glo == m for _loc, glo in helds) \
                            and g.lock_kinds.get(m) == "lock":
                        if not mod.allowed_value(e.node, ALLOW, "R701"):
                            add(Finding(
                                "R701", rel, e.line,
                                getattr(e.node, "col_offset", 0),
                                mod.scope_of(e.node), f"self:{m}",
                                f"nested acquisition of non-reentrant "
                                f"lock {_short(m)} — guaranteed "
                                f"self-deadlock (use RLock or "
                                f"restructure)"))
                        continue
                    self._r701(mod, e, helds, m, add, via=None)
        self._r702(mod, scan, add)
        self._r704(mod, scan, add)

    def _r701(self, mod, e: _Event, helds, m: str, add, via):
        for _loc, hl in helds:
            if hl == m:
                continue
            if (hl, m) in self.graph.cyclic_edges:
                counter = self.graph.edge_sites.get((m, hl))
                where = (f" (reverse order in {counter})"
                         if counter else "")
                via_s = f" via {via.split('::')[-1]}()" if via else ""
                if mod.allowed_value(e.node, ALLOW, "R701"):
                    continue
                add(Finding(
                    "R701", mod.relpath, e.line,
                    getattr(e.node, "col_offset", 0),
                    mod.scope_of(e.node), f"{hl}->{m}",
                    f"acquiring {_short(m)}{via_s} while holding "
                    f"{_short(hl)} inverts the package's lock order"
                    f"{where} — potential deadlock"))

    def _r703(self, mod, e: _Event, desc: str, helds, add, via):
        if mod.allowed_value(e.node, ALLOW, "R703"):
            return
        hl = helds[-1][1]
        via_s = f" via {via.split('::')[-1]}()" if via else ""
        add(Finding(
            "R703", mod.relpath, e.line,
            getattr(e.node, "col_offset", 0), mod.scope_of(e.node),
            f"block:{desc}",
            f"blocking call {desc}{via_s} while holding {_short(hl)} — "
            f"every thread contending for the lock stalls behind it; "
            f"move the call outside the guard or annotate "
            f"`# check: allow-concurrency=R703` with the invariant"))

    # -- R702 -----------------------------------------------------------------
    def _r702(self, mod: ModuleInfo, scan: ModuleConcScan, add) -> None:
        for cls, accesses in scan.class_fields.items():
            class_locks = {r for r in scan.locks
                           if r.startswith(f"{cls}.")}
            if not class_locks:
                continue
            guards: Dict[str, Set[str]] = {}
            for a in accesses:
                if a.write and not a.in_init and a.held:
                    locks_held = {h for h in a.held if h in scan.locks}
                    if locks_held:
                        guards.setdefault(a.field, set()).update(
                            locks_held)
            for a in accesses:
                if a.in_init or a.field not in guards:
                    continue
                if a.field in scan.locks or f"{cls}.{a.field}" \
                        in scan.locks:
                    continue
                gset = guards[a.field]
                if a.escape and a.field in scan.mutable_fields.get(
                        cls, set()):
                    if not mod.allowed_value(a.node, ALLOW, "R702"):
                        add(Finding(
                            "R702", mod.relpath, a.line,
                            getattr(a.node, "col_offset", 0),
                            mod.scope_of(a.node),
                            f"escape:{cls}.{a.field}",
                            f"returning guarded mutable self."
                            f"{a.field} by reference escapes the "
                            f"{'/'.join(sorted(gset))} guard — return "
                            f"a copy (list(...)/dict(...))"))
                    continue
                if set(a.held) & gset:
                    continue
                if mod.allowed_value(a.node, ALLOW, "R702"):
                    continue
                kind = "write" if a.write else "read"
                add(Finding(
                    "R702", mod.relpath, a.line,
                    getattr(a.node, "col_offset", 0),
                    mod.scope_of(a.node),
                    f"{kind}:{cls}.{a.field}",
                    f"{kind} of self.{a.field} outside its guard "
                    f"{'/'.join(sorted(gset))} (every other write "
                    f"holds it) — take the lock, or annotate "
                    f"`# check: allow-concurrency=R702` with the "
                    f"invariant that makes the race benign"))

    # -- R704 -----------------------------------------------------------------
    def _r704(self, mod: ModuleInfo, scan: ModuleConcScan, add) -> None:
        src = mod.source
        for site in scan.thread_sites:
            if site.daemon:
                continue
            if site.binding:
                leaf = site.binding.rsplit(".", 1)[-1]
                if f"{leaf}.join(" in src:
                    continue
            if mod.allowed_value(site.node, ALLOW, "R704"):
                continue
            add(Finding(
                "R704", mod.relpath, site.line,
                getattr(site.node, "col_offset", 0),
                mod.scope_of(site.node), "thread-lifecycle",
                "thread started without daemon=True and without a "
                "reachable join()/stop path — it can wedge interpreter "
                "shutdown; declare daemon=True or keep a joined handle"))


def module_conc_facts(mod: ModuleInfo) -> Dict[str, Any]:
    """The cacheable per-file R7 facts (locks + event streams)."""
    return ModuleConcScan(mod).facts()
