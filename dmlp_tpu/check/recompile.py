"""R2 — recompilation and stale-trace hazards around ``jax.jit``.

The family exists because of a real review bug (PR 3): the extract
kernel's measured-variant resolution originally ran *inside* the jitted
body, so a mid-process tuner sweep changed the cache but the jit kept
replaying the trace baked with the old variant. The fix — resolve
outside, make the concrete variant part of the jit cache key — is now a
lint (R203), together with its relatives:

- **R201** non-hashable (mutable) default arguments on jitted
  functions: jax hashes static arguments; a ``[]``/``{}`` default
  either crashes or, worse, silently aliases across traces.
- **R202** f-string construction inside traced bodies: trace-time
  string building is a smell that host state (names, config reprs) is
  leaking into the traced program — except in ``raise``/``assert``
  error paths, which run once at trace time and abort.
- **R203** variant/config resolution (``resolve_*``,
  ``lookup_variant``) inside traced bodies — the PR 3 bug class.
- **R204** keyword-only parameters with obviously-static names
  (``select``, ``use_pallas``, ``kc`` ...) missing from
  ``static_argnames``: tracing them as arrays either fails or bakes a
  silent recompile per value.
- **R205** traced bodies closing over module-level mutable literals:
  jit reads them at trace time only; later mutation is silently
  ignored — the closed-over-mutable variant of the stale-cache bug.
"""

from __future__ import annotations

import ast
from typing import Set

from dmlp_tpu.check.common import ModuleInfo, call_name
from dmlp_tpu.check.findings import Finding

#: resolution calls that must happen OUTSIDE traced bodies (R203)
RESOLUTION_FNS = {
    "resolve_variant", "_resolve_variant", "lookup_variant",
    "resolve_select", "resolve_streaming_select", "resolve_dtype",
    "resolve_granule", "resolve_data_block", "resolve_kcap",
    # the fused-megakernel selection surface (ops.pallas_fused): which
    # kernel runs — and the env kill switch that flips it — must be
    # baked into the jit cache key, never read inside a traced body
    "resolve_topk_kernel", "fused_enabled", "variant_for",
}

#: keyword-only parameter names that are plainly Python-level config —
#: if one of these is traced (not in static_argnames) the jit either
#: fails or recompiles per value (R204). Names that are legitimately
#: traced arrays (n_real, id_base, floor, carries, ...) are NOT listed.
OBVIOUSLY_STATIC = {
    "select", "use_pallas", "interpret", "schedule", "staging",
    "k", "kc", "data_block", "chunk_rows", "query_block", "granule",
    "num_labels", "n_micro", "n_stages", "n_classes", "n_experts",
    "n_virtual", "ne", "unroll", "tile_q", "tile_n", "block_skip",
    "fresh", "capacity", "merge", "mode", "dtype", "na",
}

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, _MUTABLE_LITERALS):
        return True
    return isinstance(node, ast.Call) \
        and call_name(node) in ("list", "dict", "set", "bytearray")


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Parameter names + assigned names inside ``fn`` (shadow check)."""
    out = {a.arg for a in fn.args.posonlyargs + fn.args.args
           + fn.args.kwonlyargs}
    if fn.args.vararg:
        out.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        out.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
    return out


def _in_error_path(mod: ModuleInfo, node: ast.AST) -> bool:
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.Raise, ast.Assert)):
            return True
        cur = mod.parents.get(cur)
    return False


class RecompileRule:
    def run(self, mod: ModuleInfo, add) -> None:
        traced = mod.traced_def_nodes()
        for fn, info in traced:
            if mod.allowed(fn, "allow-recompile"):
                continue
            scope = (mod.scope_of(fn) + "." + fn.name).lstrip(".")
            for d in list(fn.args.defaults) + [
                    d for d in fn.args.kw_defaults if d is not None]:
                if _is_mutable_default(d):
                    add(Finding(
                        "R201", mod.relpath, d.lineno, d.col_offset,
                        scope, "mutable-default",
                        f"jitted function {fn.name} has a mutable "
                        f"(non-hashable) default argument"))
            if info.kind == "jit" and info.static_argnames:
                for a in fn.args.kwonlyargs:
                    if a.arg in OBVIOUSLY_STATIC \
                            and a.arg not in info.static_argnames:
                        add(Finding(
                            "R204", mod.relpath, a.lineno, a.col_offset,
                            scope, f"static:{a.arg}",
                            f"keyword-only param {a.arg!r} of jitted "
                            f"{fn.name} looks static but is missing "
                            f"from static_argnames"))
            self._body_checks(mod, fn, scope, add)
            self._closure_check(mod, fn, scope, add)

    def _body_checks(self, mod: ModuleInfo, fn, scope: str, add) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.JoinedStr) \
                    and not isinstance(mod.parents.get(node),
                                       ast.FormattedValue) \
                    and not _in_error_path(mod, node) \
                    and not mod.allowed(node, "allow-recompile"):
                add(Finding(
                    "R202", mod.relpath, node.lineno, node.col_offset,
                    scope, "fstring",
                    f"f-string built inside traced body {fn.name} — "
                    f"host state leaking into the trace"))
            if isinstance(node, ast.Call):
                name = call_name(node)
                leaf = name.rsplit(".", 1)[-1] if name else None
                if leaf in RESOLUTION_FNS \
                        and not mod.allowed(node, "allow-recompile"):
                    add(Finding(
                        "R203", mod.relpath, node.lineno,
                        node.col_offset, scope, f"resolve:{leaf}",
                        f"{leaf}() runs inside traced body {fn.name}; "
                        f"hoist it out so the resolved value is part "
                        f"of the jit cache key (PR 3 stale-trace bug)"))

    def _closure_check(self, mod: ModuleInfo, fn, scope: str, add) -> None:
        if not mod.mutable_globals:
            return
        local = _local_bindings(fn)
        seen: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in mod.mutable_globals \
                    and node.id not in local and node.id not in seen \
                    and not mod.allowed(node, "allow-recompile"):
                seen.add(node.id)
                add(Finding(
                    "R205", mod.relpath, node.lineno, node.col_offset,
                    scope, f"closure:{node.id}",
                    f"traced body {fn.name} closes over module-level "
                    f"mutable {node.id!r}: jit reads it at trace time "
                    f"only, later mutation is silently ignored"))
