"""Finding records and their baseline fingerprints.

A finding's *fingerprint* deliberately excludes line/column numbers:
baselined findings must survive unrelated edits above them in the file,
so the stable identity is (rule, path, enclosing scope, detail key) —
the same convention ruff/mypy baselines use. Two identical violations in
the same scope share a fingerprint; the baseline stores multiplicity.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

#: rule id -> one-line description, the ``--list-rules`` catalog.
RULES = {
    # R0 — generic hygiene (the conservative ruff subset; make lint)
    "R001": "unused import (ruff F401)",
    "R002": "bare `except:` swallows everything (ruff E722)",
    "R003": "mutable default argument (ruff B006)",
    "R004": "f-string without placeholders (ruff F541)",
    # R1 — collective-axis contract
    "R101": "collective names a mesh axis no *_AXIS constant declares",
    "R102": "collective axis absent from the enclosing shard_map specs",
    "R103": "collective call site has no analytic comms-model annotation",
    "R104": "comms-model annotation names a function obs/comms.py lacks",
    "R105": "engine kernel dispatch site lacks a MeasuredIters/"
            "_queue_iters probe (extraction term degrades to modeled)",
    "R106": "dispatched kernel has no obs/kernel_cost analytic model"
            " (counters silently under-count the dispatch)",
    # R2 — recompilation hazards
    "R201": "non-hashable default argument on a jit-compiled function",
    "R202": "f-string construction inside a traced (jit/shard_map) body",
    "R203": "variant/config resolution inside a traced body (stale-cache"
            " reuse: the resolved value must be part of the jit key)",
    "R204": "keyword-only param of a jitted function missing from"
            " static_argnames",
    "R205": "traced body closes over a module-level mutable",
    # R3 — host-sync hazards (engine/, ops/, parallel/ hot paths)
    "R301": ".item() forces a device sync",
    "R302": "jax.device_get readback (annotate fenced sites with"
            " `# check: allow-host-sync`)",
    "R303": "float()/int()/bool() on a device-producing expression",
    "R304": "np.asarray/np.array on a device-producing expression"
            " (implicit transfer; use jax.device_get)",
    "R305": "branching on a traced value inside a jit body",
    # R4 — compat-bypass (everywhere but utils/compat.py)
    "R401": "direct shard_map spelling (use utils.compat.shard_map)",
    "R402": "direct jax.lax.axis_size (use utils.compat.axis_size)",
    "R403": "direct Pallas CompilerParams (use"
            " utils.compat.tpu_compiler_params)",
    "R404": "hard-coded host memory-kind string (use"
            " utils.compat.host_memory_kind)",
    # R5 — resilience-path silent swallowing
    "R501": "broad `except Exception` in a resilience-wrapped path"
            " without re-raise or `# check: no-retry` annotation",
    # R6 — telemetry metric-name contract (obs.telemetry registry)
    "R601": "registry metric name is not a literal snake_case dotted"
            " string (dynamic names fork unbounded series)",
    "R602": "metric name registered with conflicting kinds"
            " (counter vs gauge vs histogram)",
    # R7 — concurrency discipline (threaded serving/telemetry surface)
    "R701": "lock-order inversion: two locks acquired in opposite"
            " orders across the package (potential deadlock)",
    "R702": "guarded field accessed outside its lock (or a guarded"
            " mutable escapes by reference)",
    "R703": "blocking call (sleep, socket/subprocess wait, jax"
            " readback, thread join) while holding a lock",
    "R704": "thread started without a join/stop path or a daemon"
            " declaration",
    # R8 — low-precision MXU contract (ops/pallas_*.py)
    "R801": "dot/dot_general without explicit preferred_element_type"
            " (accumulator follows operand dtype; bf16 accumulation"
            " voids the lowp_eps exactness bound)",
    "R802": "sub-f32 operand cast without a `# check: lowp-eps=<fn>`"
            " annotation naming its analytic error bound",
    "R803": "lowp-eps annotation names a function engine/finalize.py"
            " does not define",
    # R9 — compiler-sharded (GSPMD) surface contract (engine/auto.py)
    "R901": "PartitionSpec names a mesh axis no *_AXIS constant"
            " declares (GSPMD silently replicates instead of sharding)",
    "R902": "jit in engine/auto.py without pinned in_shardings/"
            "out_shardings (the partitioner must see the full"
            " placement contract, not infer it from the first"
            " dispatch)",
    "R903": "with_sharding_constraint spec resolves to a mesh axis no"
            " *_AXIS constant declares (the constraint silently"
            " replicates — same failure mode as R901, caught at the"
            " constraint site through variable-held shardings)",
    # R10 — compiled-program introspection contract (obs/hlo.py)
    "R1001": "comms-model annotation names a model the obs/hlo.py"
             " reconcile table (MODEL_COLLECTIVE_KINDS) does not map"
             " to an HLO collective kind — the HLO-vs-model reconcile"
             " silently skips the site",
}

#: rule id -> allowlist directive that silences it at a call site.
ALLOW_DIRECTIVES = {
    "R0": "allow-hygiene",
    "R1": "allow-collective",
    "R2": "allow-recompile",
    "R3": "allow-host-sync",
    "R4": "allow-compat",
    "R5": "no-retry",
    "R6": "allow-metric-name",
    "R7": "allow-concurrency",
    "R8": "allow-lowprec",
    "R9": "allow-auto-shard",
    "R10": "allow-hlo-model",
}

#: every directive that SUPPRESSES a finding (for ``--stale-allows``):
#: the family allowlists plus the R1 traffic waiver. A directive of one
#: of these kinds that no longer silences anything is stale and should
#: be pruned. (``comms-model=``/``noqa`` are annotations, not
#: suppressions — never reported stale here.) ``allow-concurrency``
#: also matches its rule-scoped form ``allow-concurrency=R70x``.
SUPPRESSION_DIRECTIVES = tuple(sorted(
    set(ALLOW_DIRECTIVES.values()) | {"no-traffic"}))


def is_suppression_directive(directive: str) -> bool:
    base = directive.split("=", 1)[0]
    return base in SUPPRESSION_DIRECTIVES


def family(rule: str) -> str:
    """"R103" -> "R1"; "R1001" -> "R10". Every rule id is its family
    plus a 2-digit index, so the family is the id minus the last two
    digits (``[:2]`` would misfile R10xx under R1)."""
    return rule[:-2]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site.

    ``key`` is the stable detail used for fingerprinting (no line
    numbers — see module docstring); ``message`` is the human line.
    """

    rule: str
    path: str       # repo-relative, '/'-separated
    line: int
    col: int
    scope: str      # dotted enclosing def/class qualname ('' = module)
    key: str
    message: str

    @property
    def family(self) -> str:
        return family(self.rule)

    def fingerprint(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.scope, self.key)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["family"] = self.family
        return d

    def render(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{where}: {self.rule}{scope}: {self.message}"
