"""R10 — the compiled-program introspection contract.

R1's traffic rules (R103/R104) make every byte-moving collective in
engine/, parallel/, train/ carry a ``# check: comms-model=<fn>``
annotation naming its analytic model in obs/comms.py. PR 20's
HLO-derived ledger (obs/hlo.py) reconciles those models against the
bytes the compiled program actually schedules — but only for models its
``MODEL_COLLECTIVE_KINDS`` table maps to an HLO collective kind. An
annotation naming a model the table lacks passes R104 (the function
exists) yet reconciles NOTHING: the HLO-vs-model comparison silently
skips the site, which is exactly the silent-gap failure mode the
introspection exists to close.

- **R1001**: every ``comms-model=`` annotation in the traffic scope
  must name a key of ``obs/hlo.py``'s ``MODEL_COLLECTIVE_KINDS``. When
  a model is genuinely un-reconcilable (no HLO twin), map it in the
  table or waive the site with ``# check: allow-hlo-model``.

The table keys ride the package facts like the R104 comms-model set
(installed-package fallback for single-file fixture runs, folded into
the merged digest); when the table is unknown the rule stays silent
rather than flagging everything.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from dmlp_tpu.check.common import ModuleInfo
from dmlp_tpu.check.findings import Finding

ALLOW = "allow-hlo-model"

#: directories whose comms-model annotations must reconcile — the same
#: scope whose collectives R103 forces to carry them
HLO_SCOPE = ("dmlp_tpu/engine/", "dmlp_tpu/parallel/", "dmlp_tpu/train/")

_PREFIX = "comms-model="


def _stmt_at(mod: ModuleInfo, line: int) -> Optional[ast.stmt]:
    """The innermost statement whose span covers ``line`` (directives
    land on code lines, so one normally exists; None for e.g. an
    annotation inside a docstring)."""
    best: Optional[ast.stmt] = None
    best_span = None
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.stmt):
            continue
        lo = getattr(node, "lineno", 0)
        hi = getattr(node, "end_lineno", 0) or lo
        if not (lo <= line <= hi):
            continue
        span = hi - lo
        if best_span is None or span <= best_span:
            best, best_span = node, span
    return best


class HloIntroRule:
    """One instance runs over the whole package; the reconcile table
    comes from the merged PackageFacts (same plumbing R104 uses for
    the obs/comms.py def set)."""

    def __init__(self, facts):
        self.hlo_models = facts.hlo_models   # None = unknown: silent

    def run(self, mod: ModuleInfo, add) -> None:
        if self.hlo_models is None:
            return
        rel = mod.relpath.replace("\\", "/")
        if not any(rel.startswith(p) or f"/{p}" in rel
                   for p in HLO_SCOPE):
            return
        for line in sorted(mod.directives):
            models: List[str] = []
            for d in sorted(mod.directives[line]):
                if d.startswith(_PREFIX):
                    models.extend(x for x in d[len(_PREFIX):].split(",")
                                  if x)
            for m in models:
                if m in self.hlo_models:
                    continue
                stmt = _stmt_at(mod, line)
                if stmt is not None \
                        and mod.allowed_value(stmt, ALLOW, "R1001"):
                    continue
                add(Finding(
                    "R1001", mod.relpath, line, 0,
                    mod.scope_of(stmt) if stmt is not None else "",
                    f"comms-model:{m}",
                    f"comms-model annotation names {m!r}, which "
                    f"obs/hlo.py's MODEL_COLLECTIVE_KINDS does not map "
                    f"to an HLO collective kind — the HLO-vs-model "
                    f"reconcile silently skips this site (map it or "
                    f"annotate `# check: allow-hlo-model`)"))
