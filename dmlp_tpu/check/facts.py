"""Per-file cross-module facts — the analyzer's cacheable interface.

The cross-module rule families (R1 collectives, R105/R106 dispatch
cost, R6 metric names, R7 concurrency) need package-wide context. Before
the fingerprint cache they dug it straight out of every parsed
:class:`~dmlp_tpu.check.common.ModuleInfo`; now each file reduces to a
small JSON-safe *facts* dict (:func:`module_facts` — a pure function of
that one file's AST), and :class:`PackageFacts` merges the per-file
dicts into the tables the rules consume. The split is what makes
per-file caching sound: a file's findings depend only on (its own
content, the merged facts), so the cache key is (content hash, facts
digest) — see :mod:`dmlp_tpu.check.cache`.
"""

from __future__ import annotations

import ast
import hashlib
import json
from typing import Any, Dict, List, Optional, Set, Tuple

from dmlp_tpu.check.common import ModuleInfo

FACTS_SCHEMA = 1


def module_facts(mod: ModuleInfo) -> Dict[str, Any]:
    """JSON-safe cross-module facts for one file (content-only: no
    paths inside, so a moved file keeps its facts)."""
    from dmlp_tpu.check.concurrency import module_conc_facts
    from dmlp_tpu.check.metricnames import registration_facts
    axis_consts = {n: v for n, v in mod.str_consts.items()
                   if n.endswith("_AXIS")}
    axis_helpers: Dict[str, int] = {}
    for name, node in mod.defs.items():
        args = node.args.posonlyargs + node.args.args
        for i, a in enumerate(args):
            if a.arg == "axis_name":
                axis_helpers[name] = i
    return {
        "facts_schema": FACTS_SCHEMA,
        "axis_consts": axis_consts,
        "defs": sorted(mod.defs),
        "axis_helpers": axis_helpers,
        "metric_sites": registration_facts(mod),
        "modeled_kernels": _modeled_from_tree(mod.tree),
        "hlo_model_keys": _hlo_table_keys(mod.tree),
        "concurrency": module_conc_facts(mod),
    }


def _hlo_table_keys(tree: ast.AST) -> List[str]:
    """String keys of a module-level ``MODEL_COLLECTIVE_KINDS`` dict
    literal — only meaningful for obs/hlo.py (the R10 reconcile table),
    but harmless elsewhere. Covers plain and annotated assignment."""
    for stmt in getattr(tree, "body", []):
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            target = stmt.targets[0].id
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            target = stmt.target.id
        if target != "MODEL_COLLECTIVE_KINDS" \
                or not isinstance(stmt.value, ast.Dict):
            continue
        return sorted(k.value for k in stmt.value.keys
                      if isinstance(k, ast.Constant)
                      and isinstance(k.value, str))
    return []


def _modeled_from_tree(tree: ast.AST) -> List[str]:
    """Kernel names keyed by ``id(pallas_x.kernel)`` in a model table —
    only meaningful for obs/kernel_cost.py, but harmless elsewhere."""
    from dmlp_tpu.check.common import call_name
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for key in node.keys:
            if isinstance(key, ast.Call) and call_name(key) == "id" \
                    and key.args and isinstance(key.args[0],
                                                ast.Attribute):
                names.add(key.args[0].attr)
    return sorted(names)


class PackageFacts:
    """Merged package-wide context, built from (relpath, facts) pairs."""

    def __init__(self, pairs: List[Tuple[str, Dict[str, Any]]]):
        from dmlp_tpu.check.concurrency import ConcurrencyGraph
        self.pairs = sorted(pairs)
        self.axis_consts: Dict[str, str] = {}
        self.declared: Set[str] = set()
        self.comms_models: Set[str] = set()
        self.axis_helpers: Dict[str, int] = {}
        modeled: Set[str] = set()
        saw_kernel_cost = False
        eps_fns: Set[str] = set()
        saw_finalize = False
        hlo_keys: Set[str] = set()
        saw_hlo = False
        metric_sites: List[Tuple[str, int, str, str]] = []
        conc_pairs: List[Tuple[str, Dict[str, Any]]] = []
        for rel, facts in self.pairs:
            rel_n = rel.replace("\\", "/")
            for name, val in facts.get("axis_consts", {}).items():
                self.axis_consts[name] = val
                self.declared.add(val)
            if rel_n.endswith("obs/comms.py"):
                self.comms_models.update(facts.get("defs", []))
            for name, idx in facts.get("axis_helpers", {}).items():
                self.axis_helpers[name] = idx
            if rel_n.endswith("obs/kernel_cost.py"):
                saw_kernel_cost = True
                modeled.update(facts.get("modeled_kernels", []))
            if rel_n.endswith("engine/finalize.py"):
                saw_finalize = True
                eps_fns.update(n for n in facts.get("defs", [])
                               if "eps" in n)
            if rel_n.endswith("obs/hlo.py"):
                saw_hlo = True
                hlo_keys.update(facts.get("hlo_model_keys", []))
            for seq, (name, kind) in enumerate(
                    facts.get("metric_sites", [])):
                metric_sites.append((rel, seq, name, kind))
            conc_pairs.append((rel, facts.get("concurrency", {})))
        #: literal metric name -> (kind, relpath) of its first
        #: (path, document-order)-ranked registration (the R602
        #: table). No line numbers anywhere in the facts: a pure line
        #: shift in a metric-registering file must not change the
        #: merged digest (and with it invalidate EVERY file's cached
        #: verdict) — same rule the concurrency facts follow.
        self.metric_first: Dict[str, Tuple[str, str]] = {}
        for rel, seq, name, kind in sorted(
                metric_sites, key=lambda s: (s[0], s[1])):
            self.metric_first.setdefault(name, (kind, rel))
        #: kernel model table; None = unknown (R106 stays silent). When
        #: the analyzed set has no obs/kernel_cost.py (single-file
        #: fixture runs), fall back to the installed package's copy —
        #: context the per-file pairs don't carry, so it must ride in
        #: the digest too (else an explicit-target cached run would
        #: replay stale R106 verdicts after a kernel_cost.py edit).
        self._fallback_models: Optional[List[str]] = None
        if saw_kernel_cost:
            self.modeled_kernels: Optional[Set[str]] = modeled or None
        else:
            self.modeled_kernels = _installed_modeled_kernels()
            self._fallback_models = sorted(self.modeled_kernels or [])
        #: eps-bound function names defined by engine/finalize.py; the
        #: R803 validation table. Same installed-package fallback (and
        #: same fold-into-digest obligation) as the kernel model table.
        self._fallback_eps: Optional[List[str]] = None
        if saw_finalize:
            self.eps_models: Optional[Set[str]] = eps_fns or None
        else:
            self.eps_models = _installed_eps_models()
            self._fallback_eps = sorted(self.eps_models or [])
        #: the obs/hlo.py MODEL_COLLECTIVE_KINDS keys — the R1001
        #: validation table; None = unknown (the rule stays silent).
        #: Same installed-package fallback + digest obligation as above.
        self._fallback_hlo: Optional[List[str]] = None
        if saw_hlo:
            self.hlo_models: Optional[Set[str]] = hlo_keys or None
        else:
            self.hlo_models = _installed_hlo_models()
            self._fallback_hlo = sorted(self.hlo_models or [])
        self.concurrency = ConcurrencyGraph(conc_pairs)

    def digest(self) -> str:
        """Stable digest of the merged facts inputs — part of every
        per-file findings cache key (a change to any file's FACTS
        invalidates every file's findings; a facts-neutral edit only
        invalidates the edited file)."""
        blob = json.dumps([self.pairs, self._fallback_models,
                           self._fallback_eps, self._fallback_hlo],
                          sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()


def _installed_modeled_kernels() -> Optional[Set[str]]:
    import os
    try:
        from dmlp_tpu.check.analyzer import package_root
        path = os.path.join(package_root(), "obs", "kernel_cost.py")
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    names = set(_modeled_from_tree(tree))
    return names or None


def _installed_hlo_models() -> Optional[Set[str]]:
    import os
    try:
        from dmlp_tpu.check.analyzer import package_root
        path = os.path.join(package_root(), "obs", "hlo.py")
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    names = set(_hlo_table_keys(tree))
    return names or None


def _installed_eps_models() -> Optional[Set[str]]:
    import os
    try:
        from dmlp_tpu.check.analyzer import package_root
        path = os.path.join(package_root(), "engine", "finalize.py")
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    names = {n.name for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and "eps" in n.name}
    return names or None


def build_package_facts(modules: List[ModuleInfo]) -> PackageFacts:
    """The no-cache path: facts straight from parsed modules."""
    return PackageFacts([(m.relpath, module_facts(m)) for m in modules])
