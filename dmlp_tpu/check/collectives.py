"""R1 — the collective-axis contract.

Three checks over every ``jax.lax`` collective call site (and every call
into a package helper with an ``axis_name`` parameter):

- **R101**: the axis argument must resolve to a mesh axis some
  ``*_AXIS`` constant in the package declares (parallel/mesh.py,
  train/sharding.py, ...). String typos and undeclared axes are the
  classic silent-wrong-program bug — psum over a nonexistent axis fails
  only at trace time, on the mesh, with an opaque error.
- **R102**: when the call sits lexically inside a function that this
  module shard_maps, the resolved axis must appear in that shard_map's
  in/out PartitionSpecs — a collective over an axis the specs never
  mention is either dead replication or a wrong-mesh bug.
- **R103/R104**: traffic-bearing collectives in ``engine/``,
  ``parallel/``, ``train/`` must carry a
  ``# check: comms-model=<fn>[,<fn>]`` annotation naming their analytic
  traffic model in ``obs/comms.py`` (or ``# check: no-traffic`` with a
  reason in prose). This is the static half of the analytic-vs-traced
  reconcile: a new collective without a model, or a model function that
  was renamed away, fails ``make check`` instead of silently skewing
  every comms record.

Axis arguments resolve through: string literals, ``*_AXIS`` constants
(local or imported), and function parameters — parameter-passed axes
are checked at each *call site* of the helper instead (depth-limited),
so ``ring_allreduce_topk(..., DATA_AXIS)`` validates where the axis is
actually chosen.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from dmlp_tpu.check.common import ModuleInfo, call_name
from dmlp_tpu.check.findings import Finding

#: collective -> positional index of its axis-name argument
AXIS_ARG = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "all_to_all": 1, "psum_scatter": 1, "axis_index": 0,
}
#: collectives that move bytes (axis_index only reads the coordinate)
TRAFFIC = {"psum", "pmean", "pmax", "pmin", "ppermute", "all_gather",
           "all_to_all", "psum_scatter"}
#: directories whose collectives must map to an obs/comms.py model
TRAFFIC_SCOPE = ("dmlp_tpu/engine/", "dmlp_tpu/parallel/",
                 "dmlp_tpu/train/")

_LAX_PREFIXES = ("jax.lax.", "lax.")


def collective_kind(call: ast.Call) -> Optional[str]:
    name = call_name(call)
    if name is None:
        return None
    for pref in _LAX_PREFIXES:
        if name.startswith(pref) and name[len(pref):] in AXIS_ARG:
            return name[len(pref):]
    return None


def _axis_arg_expr(call: ast.Call, kind: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis"):
            return kw.value
    idx = AXIS_ARG[kind]
    if len(call.args) > idx:
        return call.args[idx]
    return None


def resolve_axis(expr: ast.AST, mod: ModuleInfo,
                 axis_consts: Dict[str, str]) -> object:
    """A string axis, a list of them (tuple axes), the marker
    ``("param", name)`` for function parameters, or None (opaque)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            r = resolve_axis(e, mod, axis_consts)
            if not isinstance(r, str):
                return None
            out.append(r)
        return out
    if isinstance(expr, ast.Name):
        if expr.id in mod.str_consts:
            return mod.str_consts[expr.id]
        src = mod.imports.get(expr.id, "")
        leaf = src.rsplit(".", 1)[-1] if src else expr.id
        if leaf in axis_consts:
            return axis_consts[leaf]
        return ("param", expr.id)
    return None


class CollectiveRule:
    """One instance runs over the whole package: the cross-module
    context (declared axes, obs/comms.py model names, axis-helper
    signatures) comes from the merged PackageFacts."""

    def __init__(self, facts):
        self.axis_consts: Dict[str, str] = facts.axis_consts
        self.declared: Set[str] = facts.declared
        self.comms_models: Set[str] = facts.comms_models
        self.axis_helpers: Dict[str, int] = facts.axis_helpers

    # -- per-module ----------------------------------------------------------
    def run(self, mod: ModuleInfo, add) -> None:
        specs_by_def = self._shard_map_specs(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = collective_kind(node)
            if kind is not None:
                self._check_site(mod, node, kind,
                                 _axis_arg_expr(node, kind),
                                 specs_by_def, add)
                continue
            # calls into package axis helpers: the axis is chosen HERE
            name = call_name(node)
            leaf = name.rsplit(".", 1)[-1] if name else None
            if leaf in self.axis_helpers:
                idx = self.axis_helpers[leaf]
                expr = None
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        expr = kw.value
                if expr is None and len(node.args) > idx:
                    expr = node.args[idx]
                if expr is not None:
                    self._check_axis_value(mod, node, f"{leaf}(axis_name)",
                                           expr, specs_by_def, add,
                                           helper=True)

    def _check_site(self, mod: ModuleInfo, node: ast.Call, kind: str,
                    axis_expr, specs_by_def, add) -> None:
        if axis_expr is not None:
            self._check_axis_value(mod, node, kind, axis_expr,
                                   specs_by_def, add)
        if kind in TRAFFIC:
            self._check_traffic(mod, node, kind, add)

    def _check_axis_value(self, mod: ModuleInfo, node: ast.AST, what: str,
                          expr, specs_by_def, add, helper: bool = False
                          ) -> None:
        resolved = resolve_axis(expr, mod, self.axis_consts)
        if resolved is None or (isinstance(resolved, tuple)
                                and resolved[0] == "param"):
            # Parameter-passed axes validate at the helper's call sites
            # (this function IS that check when ``helper``); opaque
            # expressions are not guessed at.
            return
        axes = resolved if isinstance(resolved, list) else [resolved]
        if mod.allowed(node, "allow-collective"):
            return
        for ax in axes:
            if ax not in self.declared:
                add(Finding(
                    "R101", mod.relpath, node.lineno, node.col_offset,
                    mod.scope_of(node), f"{what}:{ax}",
                    f"{what} names mesh axis {ax!r}, which no *_AXIS "
                    f"constant declares (declared: "
                    f"{sorted(self.declared)})"))
                continue
            spec_axes = self._enclosing_spec_axes(mod, node, specs_by_def)
            if spec_axes is not None and ax not in spec_axes:
                add(Finding(
                    "R102", mod.relpath, node.lineno, node.col_offset,
                    mod.scope_of(node), f"{what}:{ax}",
                    f"{what} uses axis {ax!r} but the enclosing "
                    f"shard_map specs only mention "
                    f"{sorted(spec_axes)}"))

    def _check_traffic(self, mod: ModuleInfo, node: ast.Call, kind: str,
                       add) -> None:
        rel = mod.relpath.replace("\\", "/")
        if not any(rel.startswith(p) or f"/{p}" in rel
                   for p in TRAFFIC_SCOPE):
            return
        if mod.allowed(node, "no-traffic") \
                or mod.allowed(node, "allow-collective"):
            return
        models: List[str] = []
        for v in mod.directive_values(node, "comms-model"):
            models.extend(x for x in v.split(",") if x)
        if not models:
            add(Finding(
                "R103", mod.relpath, node.lineno, node.col_offset,
                mod.scope_of(node), kind,
                f"{kind} moves bytes but carries no `# check: "
                f"comms-model=<fn>` annotation naming its analytic "
                f"model in obs/comms.py (or `# check: no-traffic`)"))
            return
        for m in models:
            if m not in self.comms_models:
                add(Finding(
                    "R104", mod.relpath, node.lineno, node.col_offset,
                    mod.scope_of(node), f"{kind}:{m}",
                    f"comms-model annotation names {m!r}, but "
                    f"obs/comms.py defines no such function"))

    # -- shard_map spec plumbing --------------------------------------------
    def _shard_map_specs(self, mod: ModuleInfo) -> Dict[str, Set[str]]:
        """def name -> set of axis names its shard_map specs mention."""
        out: Dict[str, Set[str]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None or name.split(".")[-1] != "shard_map":
                continue
            target = None
            if node.args and isinstance(node.args[0], ast.Name):
                target = node.args[0].id
            axes: Set[str] = set()
            for kw in node.keywords:
                if kw.arg in ("in_specs", "out_specs"):
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str):
                            axes.add(sub.value)
                        elif isinstance(sub, ast.Name):
                            r = resolve_axis(sub, mod, self.axis_consts)
                            if isinstance(r, str):
                                axes.add(r)
            if target and axes:
                out[target] = out.get(target, set()) | axes
        return out

    def _enclosing_spec_axes(self, mod: ModuleInfo, node: ast.AST,
                             specs_by_def) -> Optional[Set[str]]:
        for fn in mod.enclosing_funcs(node):
            if fn.name in specs_by_def:
                return specs_by_def[fn.name]
        return None
