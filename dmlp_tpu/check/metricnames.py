"""R6 — metric-name contract for the telemetry registry.

The telemetry registry (obs.telemetry) is get-or-create by name: any
call site can "declare" a metric, so two failure modes are one typo
away — a DYNAMIC name (f-string, concatenation, variable) silently
forks a metric family per interpolation (unbounded cardinality, and the
scrape's series names become unpredictable), and the SAME literal name
registered under two different kinds corrupts both users (the registry
raises at runtime, but only on the execution path that collides). Both
are statically decidable, so they fail ``make check`` instead:

- **R601** — a ``registry.counter/gauge/histogram(...)`` name argument
  that is not a literal snake_case dotted string
  (``telemetry.NAME_RE``: ``span.latency_ms``, ``mem.device
  .bytes_in_use``). The one deliberate dynamic-registration seam (the
  span-name bridge in obs.telemetry) carries the explicit
  ``# check: allow-metric-name`` annotation.
- **R602** — one literal name registered with conflicting kinds across
  the package (counter vs gauge vs histogram); flagged at every site
  disagreeing with the first (path, line)-ordered registration.

Scope: any call ``<recv>.counter|gauge|histogram(...)`` whose receiver
is a registry — a name ending in ``registry``/``REGISTRY`` or a call of
``telemetry.registry()``. Labels stay dynamic on purpose: bounded
cardinality is the label's job, the NAME is the contract.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from dmlp_tpu.check.common import ModuleInfo, call_name, dotted
from dmlp_tpu.check.findings import Finding

ALLOW = "allow-metric-name"

_REG_METHODS = ("counter", "gauge", "histogram")

# Mirrors obs.telemetry.NAME_RE without importing it (the checker must
# analyze a tree whose package may not import cleanly).
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)*$")


def _is_registry_recv(node: ast.AST) -> bool:
    """Does this expression denote the telemetry registry? Covers the
    module global (``REGISTRY``), locals/attributes named ``registry``,
    and the accessor call ``telemetry.registry()``."""
    name = dotted(node)
    if name and name.split(".")[-1].lower() == "registry":
        return True
    if isinstance(node, ast.Call):
        cn = call_name(node)
        return bool(cn and cn.split(".")[-1] == "registry")
    return False


def _registration_sites(mod: ModuleInfo
                        ) -> List[Tuple[ast.Call, str, object]]:
    """(call node, kind, name arg | None) for every registry
    registration call in one module."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in _REG_METHODS:
            continue
        if not _is_registry_recv(node.func.value):
            continue
        arg = node.args[0] if node.args else None
        out.append((node, node.func.attr, arg))
    return out


def registration_facts(mod: ModuleInfo) -> List[List[str]]:
    """Cacheable per-file facts: ``[name, kind]`` in document order
    for every literal registration (the cross-module R602 input). No
    line numbers — facts must survive pure line shifts so one comment
    edit does not invalidate every file's cached verdict."""
    out = []
    for node, kind, arg in _registration_sites(mod):
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append([arg.value, kind])
    return out


class MetricNameRule:
    """Cross-module rule: the package-wide first-registration table
    (PackageFacts.metric_first) lets R602 see kind conflicts across
    files."""

    def __init__(self, facts):
        # literal name -> (kind, relpath) of its FIRST
        # (path, document-order)-ranked registration
        self._first: Dict[str, Tuple[str, str]] = facts.metric_first

    def run(self, mod: ModuleInfo, add) -> None:
        for node, kind, arg in _registration_sites(mod):
            literal = (arg.value
                       if isinstance(arg, ast.Constant)
                       and isinstance(arg.value, str) else None)
            if literal is None or not _NAME_RE.match(literal):
                if mod.allowed(node, ALLOW):
                    continue
                what = ("dynamic (non-literal)" if literal is None
                        else f"non-snake-case {literal!r}")
                add(Finding(
                    "R601", mod.relpath, node.lineno, node.col_offset,
                    mod.scope_of(node), f"{kind}:{what}",
                    f"registry.{kind}(...) metric name must be a "
                    f"literal snake_case dotted string — {what} names "
                    "fork unbounded series / unpredictable scrape "
                    "names (use a label for the dynamic part, or "
                    "annotate `# check: allow-metric-name` for a "
                    "deliberate seam)"))
                continue
            first = self._first.get(literal)
            if first is not None and first[0] != kind:
                add(Finding(
                    "R602", mod.relpath, node.lineno, node.col_offset,
                    mod.scope_of(node), f"{literal}:{kind}vs{first[0]}",
                    f"metric {literal!r} registered here as {kind} but "
                    f"as {first[0]} in {first[1]} — one "
                    "name, one kind (the registry raises at runtime "
                    "only on the colliding path)"))
