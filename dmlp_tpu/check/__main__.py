"""``python -m dmlp_tpu.check`` — run the static analysis suite.

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = usage error. ``--json`` keeps stdout pure JSON (narration goes to
stderr), matching the ``check_trace --json`` convention so CI can pipe
the verdict.

Usage::

    python -m dmlp_tpu.check                      # R1-R7 over the package
    python -m dmlp_tpu.check --families R0        # hygiene only (make lint)
    python -m dmlp_tpu.check --json               # machine output
    python -m dmlp_tpu.check --write-baseline     # accept current findings
    python -m dmlp_tpu.check --stale-allows       # dead allow-directives
    python -m dmlp_tpu.check --no-cache ...       # bypass ~/.cache
    python -m dmlp_tpu.check path/to/file.py ...  # explicit targets

Analysis results are cached per file content hash under
``~/.cache/dmlp_tpu/check`` ($DMLP_TPU_CHECK_CACHE overrides) so
re-runs only re-analyze changed files; ``--no-cache`` opts out.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from dmlp_tpu.check.analyzer import (ALL_FAMILIES, DEFAULT_FAMILIES,
                                     analyze_paths,
                                     analyze_paths_tracking,
                                     package_root, repo_root,
                                     stale_allow_directives)
from dmlp_tpu.check.baseline import (DEFAULT_NAME, diff_baseline,
                                     load_baseline, save_baseline)
from dmlp_tpu.check.cache import CheckCache
from dmlp_tpu.check.findings import RULES


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="dmlp_tpu.check",
                                description=__doc__)
    p.add_argument("paths", nargs="*",
                   help="files/dirs to analyze (default: the dmlp_tpu "
                        "package)")
    p.add_argument("--families", default=None, metavar="R1,R2,...",
                   help=f"rule families to run (default "
                        f"{','.join(DEFAULT_FAMILIES)}; all: "
                        f"{','.join(ALL_FAMILIES)})")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default <repo>/{DEFAULT_NAME} "
                        f"when analyzing the package)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: every finding is new")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--json", action="store_true",
                   help="pure-JSON verdict on stdout, narration on "
                        "stderr")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the per-file fingerprint cache")
    p.add_argument("--stale-allows", action="store_true",
                   help="report `# check: allow-*`/no-retry/"
                        "no-traffic directives that no longer "
                        "suppress any finding (exit 1 if any)")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    families = None
    if args.families:
        families = [f.strip() for f in args.families.split(",") if f.strip()]
        bad = [f for f in families if f not in ALL_FAMILIES]
        if bad:
            p.error(f"unknown families {bad}; valid: "
                    f"{','.join(ALL_FAMILIES)}")

    import os
    paths = args.paths or [package_root()]
    baseline_path = args.baseline
    if baseline_path is None and not args.paths:
        cand = os.path.join(repo_root(), DEFAULT_NAME)
        if os.path.exists(cand):
            baseline_path = cand

    if args.stale_allows:
        # Stale detection needs directive-use tracking from an actual
        # rule run over EVERY family (cached verdicts carry no use
        # info), so this mode always analyzes fresh.
        _findings, modules = analyze_paths_tracking(
            paths, list(ALL_FAMILIES))
        stale_dirs = stale_allow_directives(modules)
        if args.json:
            json.dump({"check_schema": 1, "mode": "stale-allows",
                       "paths": paths,
                       "stale_allows": [
                           {"path": pa, "line": ln, "directive": d}
                           for pa, ln, d in stale_dirs],
                       "ok": not stale_dirs}, sys.stdout, indent=2)
            sys.stdout.write("\n")
            out = sys.stderr
        else:
            out = sys.stdout
        for pa, ln, d in stale_dirs:
            print(f"STALE-ALLOW {pa}:{ln}: `# check: {d}` no longer "
                  f"suppresses any finding — remove it", file=out)
        print(f"dmlp_tpu.check --stale-allows: {len(stale_dirs)} stale "
              f"directive(s)", file=out)
        return 1 if stale_dirs else 0

    cache = CheckCache(enabled=not args.no_cache)
    findings = analyze_paths(paths, families, cache=cache)

    if args.write_baseline:
        out = baseline_path or os.path.join(repo_root(), DEFAULT_NAME)
        save_baseline(out, findings)
        print(f"wrote {len(findings)} finding(s) to {out}",
              file=sys.stderr)
        return 0

    baseline = load_baseline(baseline_path) \
        if baseline_path and not args.no_baseline else {}
    new, matched, stale = diff_baseline(findings, baseline)

    err = sys.stderr
    if args.json:
        verdict = {
            "check_schema": 1,
            "families": list(families or DEFAULT_FAMILIES),
            "paths": paths,
            "baseline": baseline_path,
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in new],
            "baselined": len(matched),
            "stale_baseline": [
                {"rule": r, "path": pa, "scope": s, "key": k, "count": n}
                for (r, pa, s, k), n in sorted(stale.items())],
            "cache": {"enabled": cache.enabled, "hits": cache.hits,
                      "misses": cache.misses},
            "ok": not new,
        }
        json.dump(verdict, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        err = sys.stdout
    for f in new:
        print(f"NEW  {f.render()}", file=err)
    for f in matched:
        print(f"BASE {f.render()}", file=err)
    for (r, pa, s, k), n in sorted(stale.items()):
        print(f"STALE baseline entry {r} {pa} [{s}] {k} x{n} — fixed? "
              f"prune it", file=err)
    print(f"dmlp_tpu.check: {len(findings)} finding(s): {len(new)} new, "
          f"{len(matched)} baselined, {len(stale)} stale baseline "
          f"entr{'y' if len(stale) == 1 else 'ies'}", file=err)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
