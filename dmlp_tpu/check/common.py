"""Shared AST machinery for the rule modules.

One :class:`ModuleInfo` per source file carries everything every rule
family needs — the parsed tree, parent links, ``# check:`` directives by
line, import/constant tables, and the set of function defs that are
*traced* (jit-decorated, or passed into ``jax.jit``/``shard_map``) — so
each rule module stays a thin visitor over pre-digested facts.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

CHECK_COMMENT = "# check:"


def parse_directives(source: str) -> Dict[int, Set[str]]:
    """``# check: <d1> <d2>`` comments by 1-based line.

    A directive silences findings on its own line; a *standalone*
    comment line (nothing but the comment) also covers the next
    non-comment line, so multi-line calls can carry their annotation
    above the statement.
    """
    out: Dict[int, Set[str]] = {}
    carry: Set[str] = set()
    for i, raw in enumerate(source.splitlines(), start=1):
        line = raw.strip()
        ds: Set[str] = set()
        pos = raw.find(CHECK_COMMENT)
        # Only real comments count: a '# check:' inside a string literal
        # has code (an opening quote) before the '#' on the line — the
        # cheap test below is "comment starts the stripped line or is
        # preceded by code"; string false-positives only ADD allow
        # directives, never hide real code, so the cheap test is enough.
        if pos >= 0:
            ds = set(raw[pos + len(CHECK_COMMENT):].split())
        if line.startswith("#"):
            carry |= ds
            continue
        if ds or carry:
            out[i] = ds | carry
        carry = set()
    return out


def dotted(node: ast.AST) -> Optional[str]:
    """``jax.lax.psum`` for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def is_docstring(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    """Is this Constant-str the docstring expression of its scope?"""
    p = parents.get(node)
    if not (isinstance(p, ast.Expr) and isinstance(node, ast.Constant)
            and isinstance(node.value, str)):
        return False
    gp = parents.get(p)
    return isinstance(gp, (ast.Module, ast.FunctionDef,
                           ast.AsyncFunctionDef, ast.ClassDef)) \
        and gp.body and gp.body[0] is p


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class JitInfo:
    """How a def is traced: 'jit' (decorated / wrapped in jax.jit) or
    'shard_map' (passed to the compat/jax shard_map), plus the
    static_argnames its jit wrapper pins (empty for shard_map)."""

    kind: str
    static_argnames: Set[str] = dataclasses.field(default_factory=set)


class ModuleInfo:
    """Parsed + pre-digested facts about one source file."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.directives = parse_directives(source)
        #: (line, directive) pairs that suppressed (or would suppress)
        #: a finding this run — consumed by ``--stale-allows``
        self.used_allows: Set[Tuple[int, str]] = set()
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.scopes: Dict[ast.AST, str] = {}
        self._link(self.tree, None, [])
        # import name -> dotted module/source ("np" -> "numpy",
        # "shard_map" -> "dmlp_tpu.utils.compat.shard_map")
        self.imports: Dict[str, str] = {}
        # module-level NAME = "literal" string constants
        self.str_consts: Dict[str, str] = {}
        # module-level names bound to mutable literals ([], {}, set())
        self.mutable_globals: Set[str] = set()
        # name -> wrapped function name for f = functools.partial(g, ...)
        self.partial_aliases: Dict[str, str] = {}
        self._scan_module_level()
        # def name -> JitInfo for traced defs (jit/shard_map)
        self.traced: Dict[str, JitInfo] = {}
        self.defs: Dict[str, ast.AST] = {}
        self._scan_traced()

    # -- structure ----------------------------------------------------------
    def _link(self, node: ast.AST, parent, scope: List[str]):
        if parent is not None:
            self.parents[node] = parent
        self.scopes[node] = ".".join(scope)
        push = isinstance(node, _FUNC_NODES + (ast.ClassDef,))
        for child in ast.iter_child_nodes(node):
            self._link(child, node,
                       scope + [node.name] if push else scope)

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(node, "")

    def enclosing_funcs(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of enclosing function defs."""
        out = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def _directive_lines(self, node: ast.AST) -> set:
        """Lines whose directives govern ``node``: the node's own span
        and its statement's first line. Line-above annotations are
        handled by parse_directives' standalone-comment carry (the
        directive lands ON the next code line) — consulting
        ``lineno - 1`` directly would let a TRAILING directive on one
        statement silently cover the next one too."""
        lines = {getattr(node, "lineno", 0),
                 getattr(node, "end_lineno", 0) or 0}
        stmt = self.parents.get(node)
        while stmt is not None and not isinstance(stmt, ast.stmt):
            stmt = self.parents.get(stmt)
        if stmt is not None:
            lines.add(stmt.lineno)
        return lines

    def allowed(self, node: ast.AST, directive: str) -> bool:
        """Is ``node`` governed by ``directive``? A match is recorded in
        :attr:`used_allows` — rules only consult this at would-be
        finding sites, so the recorded set is exactly the directives
        that still suppress something (the ``--stale-allows`` feed)."""
        hit = False
        for ln in self._directive_lines(node):
            if directive in self.directives.get(ln, ()):
                self.used_allows.add((ln, directive))
                hit = True
        return hit

    def allowed_value(self, node: ast.AST, prefix: str,
                      value: str) -> bool:
        """Directive match for the ``<prefix>=<value>`` form (e.g.
        ``allow-concurrency=R703``), also accepting the bare
        ``<prefix>`` as a family-wide waiver. Matches are recorded for
        stale-allow tracking like :meth:`allowed`."""
        if self.allowed(node, prefix):
            return True
        scoped = f"{prefix}={value}"
        hit = False
        for ln in self._directive_lines(node):
            if scoped in self.directives.get(ln, ()):
                self.used_allows.add((ln, scoped))
                hit = True
        return hit

    def directive_values(self, node: ast.AST, prefix: str) -> List[str]:
        """Values of ``<prefix>=<value>`` directives governing ``node``."""
        lines = self._directive_lines(node)
        vals = []
        for ln in sorted(lines):
            for d in self.directives.get(ln, ()):
                if d.startswith(prefix + "="):
                    vals.append(d[len(prefix) + 1:])
        return vals

    # -- module-level tables -------------------------------------------------
    def _scan_module_level(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    self.imports[a.asname or a.name] = f"{mod}.{a.name}"
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                v = stmt.value
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    self.str_consts[name] = v.value
                if isinstance(v, (ast.List, ast.Dict, ast.Set)) or (
                        isinstance(v, ast.Call)
                        and call_name(v) in ("list", "dict", "set")):
                    self.mutable_globals.add(name)

    # -- traced-def discovery ------------------------------------------------
    def _is_jit_expr(self, node: ast.AST) -> bool:
        """Does this expression denote jax.jit (or a partial of it)?"""
        name = dotted(node)
        if name in ("jax.jit", "jit"):
            return True
        if isinstance(node, ast.Call) \
                and call_name(node) in ("functools.partial", "partial"):
            return node.args and self._is_jit_expr(node.args[0])
        return False

    def jit_static_argnames(self, node: ast.AST) -> Set[str]:
        """static_argnames from a partial(jax.jit, ...) / jax.jit(...)."""
        out: Set[str] = set()
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str):
                            out.add(sub.value)
        return out

    def _mark(self, name: str, info: JitInfo):
        name = self.partial_aliases.get(name, name)
        prev = self.traced.get(name)
        if prev is None or (prev.kind != "jit" and info.kind == "jit"):
            self.traced[name] = info

    def _scan_traced(self):
        for node in ast.walk(self.tree):
            if isinstance(node, _FUNC_NODES):
                self.defs.setdefault(node.name, node)
                for dec in node.decorator_list:
                    if self._is_jit_expr(dec):
                        self._mark(node.name, JitInfo(
                            "jit", self.jit_static_argnames(dec)))
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and call_name(node.value) in ("functools.partial",
                                                  "partial") \
                    and node.value.args \
                    and isinstance(node.value.args[0], ast.Name) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self.partial_aliases[node.targets[0].id] = \
                    node.value.args[0].id
        # second pass: functions fed to jax.jit(...) / shard_map(...)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in ("jax.jit", "jit") and node.args \
                    and isinstance(node.args[0], ast.Name):
                self._mark(node.args[0].id, JitInfo(
                    "jit", self.jit_static_argnames(node)))
            if name is not None and name.split(".")[-1] == "shard_map":
                target = None
                if node.args and isinstance(node.args[0], ast.Name):
                    target = node.args[0].id
                elif node.args and isinstance(node.args[0], ast.Call):
                    inner = node.args[0]
                    if call_name(inner) in ("functools.partial", "partial") \
                            and inner.args \
                            and isinstance(inner.args[0], ast.Name):
                        target = inner.args[0].id
                if target:
                    self._mark(target, JitInfo("shard_map"))

    def traced_def_nodes(self) -> List[Tuple[ast.AST, JitInfo]]:
        """(def node, JitInfo) for every traced def, including defs
        lexically nested inside a traced def (their bodies trace too)."""
        out = []
        roots = []
        for node in ast.walk(self.tree):
            if isinstance(node, _FUNC_NODES) and node.name in self.traced:
                roots.append((node, self.traced[node.name]))
        seen = set()
        for root, info in roots:
            for sub in ast.walk(root):
                if isinstance(sub, _FUNC_NODES) and id(sub) not in seen:
                    seen.add(id(sub))
                    out.append((sub, info if sub is root
                                else JitInfo(info.kind)))
        return out
