"""JAX-aware static analysis for the dmlp_tpu tree (``python -m
dmlp_tpu.check``).

The repo's three hand-rolled shard_map engines, Pallas kernels, compat
shims, and analytic comms models must stay mutually consistent — and the
bug classes that have actually bitten it (variant resolution traced
inside jit, jax API drift, analytic comms accounting silently diverging
from the collectives in the code) are exactly the ones a domain-specific
checker catches before runtime. This package is that checker: an
AST-based pass (stdlib ``ast``, no dependencies) over the whole package
enforcing repo-specific rule families:

- **R1 collective-axis contract** (:mod:`.collectives`): every
  ``psum``/``ppermute``/``all_gather``/``all_to_all``/``axis_index``
  call site must name a mesh axis declared by an ``*_AXIS`` constant
  (parallel/mesh.py, train/sharding.py, ...), consistent with the
  enclosing ``shard_map`` specs; and every traffic-bearing collective in
  engine/parallel/train code must be mapped to an analytic model in
  ``obs/comms.py`` via a ``# check: comms-model=<fn>`` annotation.
- **R2 recompilation hazards** (:mod:`.recompile`): mutable defaults on
  jitted functions, f-strings and variant/config resolution inside
  traced bodies (the PR 3 review bug, now a lint), keyword-only params
  missing from ``static_argnames``, closures over module-level mutables.
- **R3 host-sync hazards** (:mod:`.hostsync`): ``.item()``,
  ``jax.device_get``, ``float()``/``int()``/``np.asarray`` on
  device-producing expressions, and traced-value branches inside
  ``engine/``, ``ops/``, ``parallel/`` hot paths, with a
  ``# check: allow-host-sync`` allowlist for the fenced readbacks that
  are intentional.
- **R4 compat-bypass** (:mod:`.compatrule`): direct use of drifting jax
  APIs (``shard_map`` spellings, ``axis_size``, Pallas
  ``CompilerParams``, host memory-kind strings) anywhere outside
  ``utils/compat.py``.
- **R5 resilience swallowing** (:mod:`.resilient`): broad ``except
  Exception`` without re-raise or ``# check: no-retry`` in the
  resilience/serving error paths.
- **R6 metric-name contract** (:mod:`.metricnames`): literal
  snake_case registry names, one kind per name package-wide.
- **R7 concurrency discipline** (:mod:`.concurrency`): lock-order
  inversions over the inferred package lock graph, guarded-field
  accesses outside their lock (incl. mutable reference escapes),
  blocking calls under a lock, and thread-lifecycle holes — the
  threaded serving/telemetry surface's contracts, machine-enforced.
- **R0 hygiene** (:mod:`.hygiene`): the conservative ruff subset
  (unused imports, bare except, mutable default args, pointless
  f-strings) so ``make lint`` has teeth even on containers without
  ruff installed (the pyproject ``[tool.ruff]`` config mirrors it).

Cross-module context flows through the cacheable facts layer
(:mod:`.facts`), and verdicts are cached per file content hash
(:mod:`.cache`) so re-runs only re-analyze changed files;
``--stale-allows`` reports allow-directives that no longer suppress
anything. Accepted pre-existing findings are pinned in
``check_baseline.json`` (:mod:`.baseline`); any NEW finding fails
``make check``. The runtime side lives in :mod:`.sanitize`
(``DMLP_TPU_SANITIZE=1`` / ``--sanitize`` wraps solves in
``jax.transfer_guard("disallow")`` + ``jax.checking_leaks()`` — the
hot path is provably free of implicit host syncs at runtime too) and
:mod:`.racecheck` (``DMLP_TPU_RACECHECK=1``: instrumented lock
factories record real acquisition orders, catching actual inversions
and blocking-under-lock as they happen — ``make race-smoke``).
"""

from dmlp_tpu.check.analyzer import analyze_package, analyze_paths
from dmlp_tpu.check.baseline import diff_baseline, load_baseline, save_baseline
from dmlp_tpu.check.findings import Finding
from dmlp_tpu.check.sanitize import sanitize_enabled, sanitized

__all__ = [
    "Finding", "analyze_package", "analyze_paths", "load_baseline",
    "save_baseline", "diff_baseline", "sanitize_enabled", "sanitized",
]
