"""R0 — generic hygiene: the conservative ruff subset, reimplemented.

This container ships no ruff; the committed ``pyproject.toml``
``[tool.ruff]`` config selects exactly these rules for environments
that have it, and ``make lint`` falls back to this family so the gate
has teeth either way:

- **R001** unused imports (F401) — skipped in ``__init__.py`` files,
  whose imports are re-exports by convention;
- **R002** bare ``except:`` (E722);
- **R003** mutable default arguments (B006) — jitted functions get the
  sharper R201 from the recompile family instead;
- **R004** f-strings without placeholders (F541).
"""

from __future__ import annotations

import ast
from typing import Set

from dmlp_tpu.check.common import ModuleInfo, call_name
from dmlp_tpu.check.findings import Finding

ALLOW = "allow-hygiene"

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _used_names(mod: ModuleInfo) -> Set[str]:
    used: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Assign):
            # __all__ strings are uses (re-export surface)
            t = node.targets[0] if len(node.targets) == 1 else None
            if isinstance(t, ast.Name) and t.id == "__all__":
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        used.add(sub.value)
    return used


def _noqa(mod: ModuleInfo, node: ast.AST) -> bool:
    """Honor ruff/flake8 ``# noqa`` on the statement's lines — the two
    allowlist dialects must agree or every re-export needs both."""
    lines = mod.source.splitlines()
    for ln in {getattr(node, "lineno", 0),
               getattr(node, "end_lineno", 0) or 0}:
        if 0 < ln <= len(lines) and "# noqa" in lines[ln - 1]:
            return True
    return False


class HygieneRule:
    def run(self, mod: ModuleInfo, add) -> None:
        self._unused_imports(mod, add)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None \
                    and not mod.allowed(node, ALLOW):
                add(Finding(
                    "R002", mod.relpath, node.lineno, node.col_offset,
                    mod.scope_of(node), "bare-except",
                    "bare `except:` catches SystemExit/KeyboardInterrupt"
                    " too — name the exceptions"))
            elif isinstance(node, ast.JoinedStr) \
                    and not isinstance(mod.parents.get(node),
                                       ast.FormattedValue) \
                    and not any(isinstance(v, ast.FormattedValue)
                                for v in node.values) \
                    and not mod.allowed(node, ALLOW):
                add(Finding(
                    "R004", mod.relpath, node.lineno, node.col_offset,
                    mod.scope_of(node), "fstring-no-placeholder",
                    "f-string without placeholders — drop the prefix"))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    and node.name not in mod.traced:
                scope = (mod.scope_of(node) + "." + node.name).lstrip(".")
                for d in list(node.args.defaults) + [
                        d for d in node.args.kw_defaults if d is not None]:
                    mutable = isinstance(d, _MUTABLE_LITERALS) or (
                        isinstance(d, ast.Call) and call_name(d) in
                        ("list", "dict", "set", "bytearray"))
                    if mutable and not mod.allowed(d, ALLOW):
                        add(Finding(
                            "R003", mod.relpath, d.lineno, d.col_offset,
                            scope, "mutable-default",
                            f"mutable default argument on {node.name} "
                            f"is shared across calls"))

    def _unused_imports(self, mod: ModuleInfo, add) -> None:
        if mod.relpath.replace("\\", "/").endswith("__init__.py"):
            return
        used = _used_names(mod)
        for node in ast.walk(mod.tree):
            aliases = []
            if isinstance(node, ast.Import):
                aliases = [(a, (a.asname or a.name.split(".")[0]))
                           for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                aliases = [(a, (a.asname or a.name)) for a in node.names
                           if a.name != "*"]
            for alias, bound in aliases:
                if bound not in used and not mod.allowed(node, ALLOW) \
                        and not _noqa(mod, node):
                    add(Finding(
                        "R001", mod.relpath, node.lineno,
                        node.col_offset, mod.scope_of(node),
                        f"unused:{bound}",
                        f"import {bound!r} is never used"))
