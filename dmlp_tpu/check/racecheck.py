"""Runtime race sanitizer — the dynamic half of check family R7.

``DMLP_TPU_RACECHECK=1`` (or an explicit :func:`install`) wraps the
``threading.Lock`` / ``RLock`` / ``Condition`` factories so every lock
created afterwards is *tracked*: each acquisition records the
per-thread held stack and feeds a process-global acquisition-order
graph. Two violation classes are detected as they happen:

- **inversion** — lock B acquired while holding A after some earlier
  acquisition (any thread) took A while holding B. This is the runtime
  proof of check rule R701: the static rule flags *potential* cycles,
  this records the orders a real run actually exhibited.
- **blocking_under_lock** — an instrumented blocking primitive
  (``time.sleep``, ``threading.Thread.join``) entered while the calling
  thread holds any tracked lock (runtime R703).

Lock identity is the **creation site** (``file:line`` of the factory
call), so every instance of ``Registry._lock`` shares one node — the
same granularity the static analyzer reasons at, which keeps the order
graph finite and the reports readable.

The instrumentation is for the ``tools/race_stress.py`` harness and
``make race-smoke`` — NOT for production serving: acquire/release pay a
dict update each. :func:`report` returns the verdict;
``DMLP_TPU_RACECHECK_OUT=<path>`` makes the serving daemon write it at
drain. ``install`` also retrofits the already-created process-global
telemetry locks (REGISTRY, session slot) when obs.telemetry was
imported first, so registry edges are visible even in in-process
harnesses.

Caveat (documented, deliberate): a wrapped lock fed into
``threading.Condition(lock=...)`` uses the stdlib's acquire/release
fallback, so tracked Conditions must not rely on re-entrant waiter
internals — the tree's Conditions are all created standalone AFTER
install, which wraps their inner RLock transparently.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

RACECHECK_ENV = "DMLP_TPU_RACECHECK"
RACECHECK_OUT_ENV = "DMLP_TPU_RACECHECK_OUT"

_state_lock = threading.Lock()     # guards the graph/violation tables
_installed = False
_orig: Dict[str, Any] = {}
#: (held_site, acquired_site) -> first (file:line, thread name) seen
_edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
_violations: List[Dict[str, Any]] = []
_locks_created = 0
_tls = threading.local()


def enabled() -> bool:
    return _installed


def _held() -> List[Tuple[str, Any]]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _caller_site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _record_violation(kind: str, **data) -> None:
    v = {"kind": kind, "thread": threading.current_thread().name,
         **data}
    with _state_lock:
        _violations.append(v)


class _TrackedLock:
    """Wrapper over a real Lock/RLock: order-graph bookkeeping around
    the native primitive. Exposes the lock protocol (acquire/release/
    context manager/locked) so it drops into Condition and `with`."""

    __slots__ = ("_inner", "site", "kind")

    def __init__(self, inner, site: str, kind: str):
        self._inner = inner
        self.site = site
        self.kind = kind

    # -- bookkeeping -----------------------------------------------------------
    def _before_acquire(self, acquire_site: str) -> None:
        held = _held()
        me = self.site
        for held_site, _obj in held:
            if held_site == me:
                continue
            with _state_lock:
                _edges.setdefault(
                    (held_site, me),
                    (acquire_site, threading.current_thread().name))
                rev = _edges.get((me, held_site))
            if rev is not None:
                _record_violation(
                    "inversion", held=held_site, acquiring=me,
                    site=acquire_site, reverse_site=rev[0],
                    reverse_thread=rev[1])

    # -- lock protocol ---------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        site = _caller_site()
        if blocking:
            self._before_acquire(site)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held().append((self.site, self))
        return got

    def release(self):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                del held[i]
                break
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # Condition(lock=...) compatibility passthroughs when present.
    def _is_owned(self):
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<TrackedLock {self.kind} @{self.site}>"


def _wrap_factory(kind: str):
    orig = _orig[kind]

    def factory(*args, **kwargs):
        global _locks_created
        inner = orig(*args, **kwargs)
        site = f"{kind}@{_caller_site()}"
        with _state_lock:
            _locks_created += 1
        return _TrackedLock(inner, site, kind)

    return factory


class _TrackedCondition:
    """Condition wrapper: acquisition tracking on the outer lock,
    held-stack handoff around wait() (which releases the lock)."""

    def __init__(self, inner, site: str):
        self._inner = inner
        self.site = site
        self.kind = "Condition"

    def acquire(self, *a, **kw):
        site = _caller_site()
        _TrackedLock._before_acquire(self, site)   # shared bookkeeping
        got = self._inner.acquire(*a, **kw)
        if got:
            _held().append((self.site, self))
        return got

    def release(self):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                del held[i]
                break
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout: Optional[float] = None):
        # wait() releases the condition's lock for its duration: pop it
        # from the held stack so a sleep inside another thread's guard
        # is not misattributed to this one.
        held = _held()
        idx = next((i for i in range(len(held) - 1, -1, -1)
                    if held[i][1] is self), None)
        if idx is not None:
            entry = held.pop(idx)
        try:
            return self._inner.wait(timeout)
        finally:
            if idx is not None:
                held.append(entry)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        result = predicate()
        while not result:
            left = None if deadline is None \
                else deadline - time.monotonic()
            if left is not None and left <= 0:
                break
            self.wait(left)
            result = predicate()
        return result

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()

    def __repr__(self):
        return f"<TrackedCondition @{self.site}>"


def _condition_factory(lock=None):
    # The stdlib Condition would otherwise build its inner RLock
    # through the PATCHED threading.RLock — one shared creation site
    # (threading.py) for every condition, which would alias all
    # conditions to one graph node and fabricate inversions. Hand it a
    # raw primitive; the wrapper is the tracked surface.
    if lock is None:
        lock = _orig["RLock"]()
    elif isinstance(lock, _TrackedLock):
        lock = lock._inner
    inner = _orig["Condition"](lock)
    site = f"Condition@{_caller_site()}"
    with _state_lock:
        global _locks_created
        _locks_created += 1
    return _TrackedCondition(inner, site)


def _blocking_wrapper(name: str, orig):
    def wrapped(*args, **kwargs):
        held = _held()
        if held:
            _record_violation(
                "blocking_under_lock", call=name,
                held=[site for site, _obj in held],
                site=_caller_site())
        return orig(*args, **kwargs)
    wrapped.__name__ = getattr(orig, "__name__", name)
    return wrapped


def _thread_join_wrapper(orig):
    def join(self, timeout: Optional[float] = None):
        held = _held()
        if held:
            _record_violation(
                "blocking_under_lock", call="Thread.join",
                held=[site for site, _obj in held],
                site=_caller_site())
        return orig(self, timeout)
    return join


def install() -> bool:
    """Idempotently instrument the lock factories + blocking
    primitives; returns True when active after the call. Also swaps
    the pre-existing process-global telemetry locks if obs.telemetry
    was imported before install."""
    global _installed
    if _installed:
        return True
    _orig["Lock"] = threading.Lock
    _orig["RLock"] = threading.RLock
    _orig["Condition"] = threading.Condition
    _orig["time.sleep"] = time.sleep
    _orig["Thread.join"] = threading.Thread.join
    threading.Lock = _wrap_factory("Lock")
    threading.RLock = _wrap_factory("RLock")
    threading.Condition = _condition_factory
    time.sleep = _blocking_wrapper("time.sleep", _orig["time.sleep"])
    threading.Thread.join = _thread_join_wrapper(_orig["Thread.join"])
    _installed = True
    _retrofit_telemetry()
    return True


def _retrofit_telemetry() -> None:
    """Wrap the known module-level locks created at import time
    (obs.telemetry's REGISTRY table + session slot locks,
    resilience.stats' degradation-list lock) so their edges show up
    even when those modules were imported before install()."""
    tm = sys.modules.get("dmlp_tpu.obs.telemetry")
    if tm is not None:
        reg = getattr(tm, "REGISTRY", None)
        if reg is not None and not isinstance(
                getattr(reg, "_lock", None), _TrackedLock):
            reg._lock = _TrackedLock(reg._lock,
                                     "Lock@telemetry.REGISTRY", "Lock")
        slot = getattr(tm, "_session_lock", None)
        if slot is not None and not isinstance(slot, _TrackedLock):
            tm._session_lock = _TrackedLock(
                slot, "Lock@telemetry._session_lock", "Lock")
    st = sys.modules.get("dmlp_tpu.resilience.stats")
    if st is not None:
        lk = getattr(st, "_lock", None)
        if lk is not None and not isinstance(lk, _TrackedLock):
            st._lock = _TrackedLock(lk, "Lock@resilience.stats._lock",
                                    "Lock")


def uninstall() -> None:
    """Restore the native factories (tracked locks already handed out
    keep working — they wrap real primitives)."""
    global _installed
    if not _installed:
        return
    threading.Lock = _orig["Lock"]
    threading.RLock = _orig["RLock"]
    threading.Condition = _orig["Condition"]
    time.sleep = _orig["time.sleep"]
    threading.Thread.join = _orig["Thread.join"]
    _installed = False


def reset() -> None:
    """Clear the order graph and violation log (harness phases)."""
    global _locks_created
    with _state_lock:
        _edges.clear()
        _violations.clear()
        _locks_created = 0


def report() -> Dict[str, Any]:
    with _state_lock:
        return {
            "racecheck_schema": 1,
            "installed": _installed,
            "locks_created": _locks_created,
            "edges": len(_edges),
            "violations": list(_violations),
            "inversions": sum(1 for v in _violations
                              if v["kind"] == "inversion"),
            "blocking_under_lock": sum(
                1 for v in _violations
                if v["kind"] == "blocking_under_lock"),
            "ok": not _violations,
        }


def write_report_if_requested() -> Optional[str]:
    """Write the report to ``$DMLP_TPU_RACECHECK_OUT`` (the daemon's
    drain hook); returns the path written, or None."""
    path = os.environ.get(RACECHECK_OUT_ENV)
    if not path or not _installed:
        return None
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report(), f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def install_from_env() -> bool:
    """The entry-point hook: install iff ``DMLP_TPU_RACECHECK=1``."""
    if os.environ.get(RACECHECK_ENV) == "1":
        return install()
    return False
