"""R3 — host-sync hazards on the hot paths (``engine/``, ``ops/``,
``parallel/``).

A single stray ``.item()`` or implicit ``np.asarray`` readback in the
enqueue loop serializes the whole chunk pipeline against the device (on
a tunneled PJRT link: a full round trip per chunk). The rule flags the
sync primitives themselves plus implicit conversions of
device-producing expressions, with a light forward taint pass per
function:

- seeds: ``jnp.*`` / ``jax.lax.*`` calls, calls of this module's jitted
  functions, and calls of known device-producing ops
  (``extract_topk``, ``streaming_topk``, ...);
- propagation: assignment targets whose right side contains a tainted
  name or a seed call become tainted (tuple unpacking included).

Intentional, fenced readbacks are part of the design (the result fetch
IS a readback) — they carry ``# check: allow-host-sync`` and, for
runtime enforcement, go through the *explicit* ``jax.device_get``,
which the ``--sanitize`` transfer guard permits while implicit
conversions raise. Static rule and runtime guard agree by
construction: what R3 wants annotated is exactly what
``jax.transfer_guard("disallow")`` would reject un-annotated.

Known limit (documented, deliberate): taint is per-function and
syntactic, so a device value returned through ``self._solve(...)`` is
not tracked across the method boundary. The runtime sanitizer covers
that remainder — between them the static pass catches the cheap 95%
at zero runtime cost and the guard catches the rest under ``make
check``'s sanitized smoke.
"""

from __future__ import annotations

import ast
from typing import Set

from dmlp_tpu.check.common import ModuleInfo, call_name
from dmlp_tpu.check.findings import Finding

#: path fragments that make a module a hot path for this family —
#: serve/ joined when the resident engine's gate-stats readback turned
#: out to carry a dead allowlist (the serving solve loop is exactly as
#: sync-sensitive as the batch engines); fleet/ joined with the
#: mesh-resident serving engine (its fold loop is the same hot path)
HOT_DIRS = ("dmlp_tpu/engine/", "dmlp_tpu/ops/", "dmlp_tpu/parallel/",
            "dmlp_tpu/serve/", "dmlp_tpu/fleet/")

#: call prefixes whose results live on device (taint seeds)
DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "lax.")
#: known device-producing functions by leaf name (imported from ops/)
DEVICE_PRODUCERS = {
    "extract_topk", "streaming_topk", "init_topk", "select_topk",
    "merge_topk", "device_put", "allgather_merge_topk",
    "ring_allreduce_topk", "masked_pairwise_sq_l2", "pallas_distance",
}
#: conversions that force an implicit device->host transfer
_CONVERTERS = {"float": "R303", "int": "R303", "bool": "R303",
               "np.asarray": "R304", "np.array": "R304",
               "numpy.asarray": "R304", "numpy.array": "R304"}

ALLOW = "allow-host-sync"


def in_scope(relpath: str) -> bool:
    rel = relpath.replace("\\", "/")
    return any(rel.startswith(d) or f"/{d}" in rel for d in HOT_DIRS)


def _is_device_call(node: ast.Call, jit_names: Set[str]) -> bool:
    name = call_name(node)
    if name is None:
        return False
    if any(name.startswith(p) for p in DEVICE_PREFIXES):
        return True
    leaf = name.rsplit(".", 1)[-1]
    return leaf in DEVICE_PRODUCERS or name in jit_names


def _contains_device_expr(node: ast.AST, tainted: Set[str],
                          jit_names: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _is_device_call(sub, jit_names):
            return True
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                and sub.id in tainted:
            return True
    return False


def _taint_targets(target: ast.AST, tainted: Set[str]) -> None:
    for sub in ast.walk(target):
        if isinstance(sub, ast.Name):
            tainted.add(sub.id)


#: wrappers _launders looks through to find the converting call
_TRANSPARENT = {"list", "tuple", "sorted", "reversed"}
#: resilient_get (engine.single) is the retry-wrapped jax.device_get —
#: its one annotated device_get site is the fence, so its RESULT is a
#: host value exactly like a direct device_get's.
_LAUNDERING = set(_CONVERTERS) | {"jax.device_get", "device_get",
                                  "resilient_get",
                                  "np.ascontiguousarray",
                                  "numpy.ascontiguousarray", "str"}


def _launders(expr: ast.AST) -> bool:
    """Does this RHS produce a HOST value even from device inputs?
    ``np.asarray(x)[:n]``, ``list(jax.device_get(...))``, ``x is None``
    — conversions and identity tests launder taint; flagging their
    *results* downstream would double-count the one real sync."""
    while isinstance(expr, (ast.Subscript, ast.Starred)):
        expr = expr.value
    if isinstance(expr, ast.Compare) \
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in expr.ops):
        return True
    if isinstance(expr, ast.Call):
        name = call_name(expr) or ""
        if name in _LAUNDERING:
            return True
        if name in _TRANSPARENT and expr.args:
            return _launders(expr.args[0])
    return False


def _is_none_test(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Compare) \
        and all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops)


class HostSyncRule:
    def run(self, mod: ModuleInfo, add) -> None:
        if not in_scope(mod.relpath):
            return
        jit_names = {n for n, info in mod.traced.items()
                     if info.kind == "jit"}
        traced_defs = {id(fn) for fn, _ in mod.traced_def_nodes()}
        funcs = [n for n in ast.walk(mod.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            self._run_function(mod, fn, jit_names,
                               id(fn) in traced_defs, add)

    def _run_function(self, mod: ModuleInfo, fn, jit_names: Set[str],
                      is_traced: bool, add) -> None:
        """One forward pass in STATEMENT order: each statement is checked
        against the taint state as of its execution point, then updates
        it — so a laundering rebind (``x = jax.device_get(x)``) clears
        ``x`` for everything after it but not before. Loop-carried taint
        (a use textually before its loop-body def) is the documented
        miss of the single pass."""
        scope = (mod.scope_of(fn) + "." + fn.name).lstrip(".")
        tainted: Set[str] = set()

        def untaint(target: ast.AST) -> None:
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name):
                    tainted.discard(sub.id)

        def check_exprs(node: ast.AST) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    self._check_call(mod, sub, scope, tainted, jit_names,
                                     add)

        def visit(stmts) -> None:
            for st in stmts:
                if isinstance(st, ast.Assign):
                    check_exprs(st.value)
                    self._update(st.targets, st.value, tainted,
                                 jit_names, untaint)
                elif isinstance(st, ast.AnnAssign) \
                        and st.value is not None:
                    check_exprs(st.value)
                    self._update([st.target], st.value, tainted,
                                 jit_names, untaint)
                elif isinstance(st, ast.AugAssign):
                    check_exprs(st.value)
                    if _contains_device_expr(st.value, tainted,
                                             jit_names) \
                            and not _launders(st.value):
                        _taint_targets(st.target, tainted)
                elif isinstance(st, ast.For):
                    check_exprs(st.iter)
                    if _contains_device_expr(st.iter, tainted,
                                             jit_names):
                        _taint_targets(st.target, tainted)
                    visit(st.body)
                    visit(st.orelse)
                elif isinstance(st, (ast.If, ast.While)):
                    check_exprs(st.test)
                    if is_traced and not _is_none_test(st.test) \
                            and _contains_device_expr(st.test, tainted,
                                                      jit_names) \
                            and not mod.allowed(st, ALLOW):
                        add(Finding(
                            "R305", mod.relpath, st.lineno,
                            st.col_offset, scope, "traced-branch",
                            "Python branch on a traced value inside a "
                            "jit body — concretization error or silent "
                            "trace-time constant"))
                    visit(st.body)
                    visit(st.orelse)
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    for item in st.items:
                        check_exprs(item.context_expr)
                    visit(st.body)
                elif isinstance(st, ast.Try):
                    visit(st.body)
                    for h in st.handlers:
                        visit(h.body)
                    visit(st.orelse)
                    visit(st.finalbody)
                elif isinstance(st, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    pass  # nested defs run as their own functions
                else:
                    check_exprs(st)

        visit(fn.body)

    @staticmethod
    def _update(targets, value, tainted: Set[str], jit_names: Set[str],
                untaint) -> None:
        if _launders(value):
            for t in targets:
                untaint(t)
        elif _contains_device_expr(value, tainted, jit_names):
            for t in targets:
                _taint_targets(t, tainted)
        else:
            for t in targets:
                untaint(t)

    def _check_call(self, mod: ModuleInfo, node: ast.Call, scope: str,
                    tainted: Set[str], jit_names: Set[str], add) -> None:
        name = call_name(node)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args \
                and not mod.allowed(node, ALLOW):
            add(Finding(
                "R301", mod.relpath, node.lineno, node.col_offset,
                scope, "item", ".item() forces a blocking device sync"))
            return
        if name in ("jax.device_get", "device_get"):
            if not mod.allowed(node, ALLOW):
                add(Finding(
                    "R302", mod.relpath, node.lineno, node.col_offset,
                    scope, "device_get",
                    "jax.device_get readback — if this fence is "
                    "intentional, annotate `# check: allow-host-sync`"))
            return
        rule = _CONVERTERS.get(name or "")
        if rule and node.args \
                and _contains_device_expr(node.args[0], tainted,
                                          jit_names) \
                and not mod.allowed(node, ALLOW):
            add(Finding(
                rule, mod.relpath, node.lineno, node.col_offset, scope,
                f"convert:{name}",
                f"{name}() on a device-producing expression forces an "
                f"implicit transfer; fence it explicitly with "
                f"jax.device_get (and annotate) if intentional"))
