"""Per-file AST fingerprint cache for the analyzer.

``make check`` re-runs constantly; almost nothing changes between runs.
This cache keys everything by **content hash** (sha256 of the file
bytes), under ``~/.cache/dmlp_tpu/check/`` (``$DMLP_TPU_CHECK_CACHE``
overrides; ``--no-cache`` bypasses). Two levels, both sound:

- **facts** (:func:`dmlp_tpu.check.facts.module_facts`) are a pure
  function of one file's content → cached per content hash. Unchanged
  files never re-parse.
- **findings** for a file depend on (its content, its repo-relative
  path, the merged package facts, the rule families, the checker's own
  source). The cache key is exactly that tuple — so an edit that
  changes a file's *facts* (a new lock, a renamed comms model)
  invalidates everyone, while a facts-neutral edit (the common case)
  re-analyzes only the edited file.

The checker's own source digest rides in every key: editing a rule
module invalidates the world, so a stale cache can never mask a new
rule. Entries are one JSON file per content hash; corrupt or
foreign-schema entries are treated as misses, never errors.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional

CACHE_SCHEMA = 1
CACHE_ENV = "DMLP_TPU_CHECK_CACHE"
#: cap of findings-variant entries kept per file (distinct ctx/family
#: combinations); oldest-insertion beyond it are dropped on save
_MAX_VARIANTS = 8


def cache_dir() -> str:
    env = os.environ.get(CACHE_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "dmlp_tpu",
                        "check")


_checker_digest_memo: Optional[str] = None


def checker_digest() -> str:
    """Digest of the check package's own sources — rule edits must
    invalidate every cached verdict."""
    global _checker_digest_memo
    if _checker_digest_memo is not None:
        return _checker_digest_memo
    h = hashlib.sha256()
    pkg = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(pkg)):
        if fn.endswith(".py"):
            with open(os.path.join(pkg, fn), "rb") as f:
                h.update(fn.encode())
                h.update(f.read())
    _checker_digest_memo = h.hexdigest()
    return _checker_digest_memo


def content_sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class CheckCache:
    """One run's view of the on-disk cache. ``enabled=False`` turns
    every operation into a no-op (the ``--no-cache`` path reuses the
    same object shape)."""

    def __init__(self, directory: Optional[str] = None,
                 enabled: bool = True):
        self.dir = directory or cache_dir()
        self.enabled = enabled
        self.hits = 0
        self.misses = 0
        self._loaded: Dict[str, Dict[str, Any]] = {}
        self._dirty: Dict[str, bool] = {}

    # -- entry IO -------------------------------------------------------------
    def _path(self, sha: str) -> str:
        return os.path.join(self.dir, f"{sha}.json")

    def _entry(self, sha: str) -> Dict[str, Any]:
        if sha in self._loaded:
            return self._loaded[sha]
        entry: Dict[str, Any] = {"cache_schema": CACHE_SCHEMA,
                                 "checker": checker_digest(),
                                 "facts": None, "findings": {}}
        if self.enabled:
            try:
                with open(self._path(sha), encoding="utf-8") as f:
                    got = json.load(f)
                if got.get("cache_schema") == CACHE_SCHEMA \
                        and got.get("checker") == checker_digest():
                    entry = got
            except (OSError, ValueError):
                pass                     # corrupt entry == miss
        self._loaded[sha] = entry
        return entry

    def _save(self, sha: str) -> None:
        if not self.enabled or not self._dirty.get(sha):
            return
        entry = self._loaded[sha]
        findings = entry.get("findings", {})
        if len(findings) > _MAX_VARIANTS:
            for key in list(findings)[:len(findings) - _MAX_VARIANTS]:
                del findings[key]
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = self._path(sha) + f".tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(entry, f, sort_keys=True)
            os.replace(tmp, self._path(sha))
            self._dirty[sha] = False
        except OSError:
            pass                         # cache is best-effort only

    # -- facts ----------------------------------------------------------------
    def get_facts(self, sha: str) -> Optional[Dict[str, Any]]:
        if not self.enabled:
            return None
        return self._entry(sha).get("facts")

    def put_facts(self, sha: str, facts: Dict[str, Any]) -> None:
        if not self.enabled:
            return
        entry = self._entry(sha)
        if entry.get("facts") != facts:
            entry["facts"] = facts
            self._dirty[sha] = True

    # -- findings -------------------------------------------------------------
    @staticmethod
    def findings_key(relpath: str, ctx_digest: str,
                     families_key: str) -> str:
        return f"{relpath}|{families_key}|{ctx_digest}"

    def get_findings(self, sha: str, key: str
                     ) -> Optional[List[Dict[str, Any]]]:
        if not self.enabled:
            return None
        got = self._entry(sha).get("findings", {}).get(key)
        if got is not None:
            self.hits += 1
        else:
            self.misses += 1
        return got

    def put_findings(self, sha: str, key: str,
                     findings: List[Dict[str, Any]]) -> None:
        if not self.enabled:
            return
        entry = self._entry(sha)
        entry.setdefault("findings", {})[key] = findings
        self._dirty[sha] = True

    def flush(self) -> None:
        for sha in list(self._dirty):
            self._save(sha)
