"""Analysis driver: walk sources, build cross-module facts, run rules.

The collective rule needs package-wide context (declared ``*_AXIS``
constants, ``obs/comms.py`` model names, axis-helper signatures), so
analysis is two-phase: parse everything into :class:`ModuleInfo`, then
run each family over each module. Unparseable files become a synthetic
``R000`` finding rather than a crash — a syntax error in the tree is a
finding, not an excuse to skip the gate.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence

from dmlp_tpu.check.common import ModuleInfo
from dmlp_tpu.check.findings import Finding

ALL_FAMILIES = ("R0", "R1", "R2", "R3", "R4", "R5", "R6")
#: families make check enforces by default; R0 rides in `make lint`
DEFAULT_FAMILIES = ("R1", "R2", "R3", "R4", "R5", "R6")


def package_root() -> str:
    """Absolute path of the installed ``dmlp_tpu`` package directory."""
    import dmlp_tpu
    return os.path.dirname(os.path.abspath(dmlp_tpu.__file__))


def repo_root() -> str:
    return os.path.dirname(package_root())


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _relpath(path: str, root: str) -> str:
    ap = os.path.abspath(path)
    root = os.path.abspath(root)
    if ap.startswith(root + os.sep):
        return os.path.relpath(ap, root).replace(os.sep, "/")
    return os.path.basename(ap)


def load_modules(paths: Sequence[str], root: Optional[str] = None
                 ) -> tuple:
    """(modules, parse_findings) for every .py under ``paths``."""
    root = root or repo_root()
    modules: List[ModuleInfo] = []
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        rel = _relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            modules.append(ModuleInfo(path, rel, source))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(Finding(
                "R000", rel, getattr(e, "lineno", 0) or 0, 0, "",
                "unparseable", f"cannot analyze: {e}"))
    return modules, findings


def analyze_modules(modules: List[ModuleInfo],
                    families: Optional[Sequence[str]] = None
                    ) -> List[Finding]:
    from dmlp_tpu.check.collectives import CollectiveRule
    from dmlp_tpu.check.compatrule import CompatRule
    from dmlp_tpu.check.dispatchcost import DispatchCostRule
    from dmlp_tpu.check.hostsync import HostSyncRule
    from dmlp_tpu.check.hygiene import HygieneRule
    from dmlp_tpu.check.metricnames import MetricNameRule
    from dmlp_tpu.check.recompile import RecompileRule
    from dmlp_tpu.check.resilient import ResilientRule

    fams = set(families or DEFAULT_FAMILIES)
    findings: List[Finding] = []
    add = findings.append
    rules = []
    if "R0" in fams:
        rules.append(HygieneRule())
    if "R1" in fams:
        rules.append(CollectiveRule(modules))
        rules.append(DispatchCostRule(modules))
    if "R2" in fams:
        rules.append(RecompileRule())
    if "R3" in fams:
        rules.append(HostSyncRule())
    if "R4" in fams:
        rules.append(CompatRule())
    if "R5" in fams:
        rules.append(ResilientRule())
    if "R6" in fams:
        rules.append(MetricNameRule(modules))
    for mod in modules:
        for rule in rules:
            rule.run(mod, add)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_paths(paths: Sequence[str],
                  families: Optional[Sequence[str]] = None,
                  root: Optional[str] = None) -> List[Finding]:
    modules, parse_findings = load_modules(paths, root=root)
    return parse_findings + analyze_modules(modules, families)


def analyze_package(families: Optional[Sequence[str]] = None
                    ) -> List[Finding]:
    """Analyze the whole installed ``dmlp_tpu`` package."""
    return analyze_paths([package_root()], families)
