"""Analysis driver: walk sources, build cross-module facts, run rules.

Cross-module context (declared ``*_AXIS`` constants, ``obs/comms.py``
model names, metric registrations, the package lock graph) is needed by
several families, so analysis is phased: reduce every file to its
cacheable *facts* (:mod:`.facts`), merge them into one
:class:`~dmlp_tpu.check.facts.PackageFacts`, then run each family over
each module. Unparseable files become a synthetic ``R000`` finding
rather than a crash — a syntax error in the tree is a finding, not an
excuse to skip the gate.

With a :class:`~dmlp_tpu.check.cache.CheckCache` (the CLI default),
both phases key off content hashes: unchanged files load facts without
re-parsing, and files whose (content, merged-facts digest, families)
triple is cached skip rule execution entirely — ``make check`` re-runs
only re-analyze what changed.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence, Tuple

from dmlp_tpu.check.cache import CheckCache, content_sha
from dmlp_tpu.check.common import ModuleInfo
from dmlp_tpu.check.facts import PackageFacts, module_facts
from dmlp_tpu.check.findings import Finding

ALL_FAMILIES = ("R0", "R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8",
                "R9", "R10")
#: families make check enforces by default; R0 rides in `make lint`
DEFAULT_FAMILIES = ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9",
                    "R10")


def package_root() -> str:
    """Absolute path of the installed ``dmlp_tpu`` package directory."""
    import dmlp_tpu
    return os.path.dirname(os.path.abspath(dmlp_tpu.__file__))


def repo_root() -> str:
    return os.path.dirname(package_root())


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _relpath(path: str, root: str) -> str:
    ap = os.path.abspath(path)
    root = os.path.abspath(root)
    if ap.startswith(root + os.sep):
        return os.path.relpath(ap, root).replace(os.sep, "/")
    return os.path.basename(ap)


def load_modules(paths: Sequence[str], root: Optional[str] = None
                 ) -> tuple:
    """(modules, parse_findings) for every .py under ``paths``."""
    root = root or repo_root()
    modules: List[ModuleInfo] = []
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        rel = _relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            modules.append(ModuleInfo(path, rel, source))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(Finding(
                "R000", rel, getattr(e, "lineno", 0) or 0, 0, "",
                "unparseable", f"cannot analyze: {e}"))
    return modules, findings


def build_rules(facts: PackageFacts,
                families: Optional[Sequence[str]] = None) -> list:
    from dmlp_tpu.check.autoshard import AutoShardRule
    from dmlp_tpu.check.compatrule import CompatRule
    from dmlp_tpu.check.concurrency import ConcurrencyRule
    from dmlp_tpu.check.collectives import CollectiveRule
    from dmlp_tpu.check.dispatchcost import DispatchCostRule
    from dmlp_tpu.check.hlointro import HloIntroRule
    from dmlp_tpu.check.hostsync import HostSyncRule
    from dmlp_tpu.check.hygiene import HygieneRule
    from dmlp_tpu.check.lowprec import LowPrecRule
    from dmlp_tpu.check.metricnames import MetricNameRule
    from dmlp_tpu.check.recompile import RecompileRule
    from dmlp_tpu.check.resilient import ResilientRule

    fams = set(families or DEFAULT_FAMILIES)
    rules = []
    if "R0" in fams:
        rules.append(HygieneRule())
    if "R1" in fams:
        rules.append(CollectiveRule(facts))
        rules.append(DispatchCostRule(facts))
    if "R2" in fams:
        rules.append(RecompileRule())
    if "R3" in fams:
        rules.append(HostSyncRule())
    if "R4" in fams:
        rules.append(CompatRule())
    if "R5" in fams:
        rules.append(ResilientRule())
    if "R6" in fams:
        rules.append(MetricNameRule(facts))
    if "R7" in fams:
        rules.append(ConcurrencyRule(facts.concurrency))
    if "R8" in fams:
        rules.append(LowPrecRule(facts))
    if "R9" in fams:
        rules.append(AutoShardRule(facts))
    if "R10" in fams:
        rules.append(HloIntroRule(facts))
    return rules


def analyze_modules(modules: List[ModuleInfo],
                    families: Optional[Sequence[str]] = None,
                    facts: Optional[PackageFacts] = None
                    ) -> List[Finding]:
    if facts is None:
        facts = PackageFacts([(m.relpath, module_facts(m))
                              for m in modules])
    rules = build_rules(facts, families)
    findings: List[Finding] = []
    add = findings.append
    for mod in modules:
        for rule in rules:
            rule.run(mod, add)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_paths(paths: Sequence[str],
                  families: Optional[Sequence[str]] = None,
                  root: Optional[str] = None,
                  cache: Optional[CheckCache] = None) -> List[Finding]:
    if cache is not None and cache.enabled:
        findings, _mods = _analyze_cached(paths, families, root, cache)
        return findings
    modules, parse_findings = load_modules(paths, root=root)
    return parse_findings + analyze_modules(modules, families)


def analyze_paths_tracking(paths: Sequence[str],
                           families: Optional[Sequence[str]] = None,
                           root: Optional[str] = None
                           ) -> Tuple[List[Finding], List[ModuleInfo]]:
    """Uncached analysis that also returns the analyzed modules (their
    ``used_allows`` sets feed ``--stale-allows``)."""
    modules, parse_findings = load_modules(paths, root=root)
    return parse_findings + analyze_modules(modules, families), modules


def _analyze_cached(paths, families, root, cache: CheckCache):
    """The fingerprint-cached driver (see module docstring)."""
    root = root or repo_root()
    files: List[Tuple[str, str, bytes]] = []   # (path, rel, raw)
    parse_findings: List[Finding] = []
    for path in _iter_py_files(paths):
        rel = _relpath(path, root)
        try:
            with open(path, "rb") as f:
                files.append((path, rel, f.read()))
        except OSError as e:
            parse_findings.append(Finding(
                "R000", rel, 0, 0, "", "unparseable",
                f"cannot analyze: {e}"))
    shas = {rel: content_sha(raw) for _p, rel, raw in files}
    modules: dict = {}
    fact_pairs: List[Tuple[str, dict]] = []
    for path, rel, raw in files:
        facts = cache.get_facts(shas[rel])
        if facts is None:
            mod = _parse(path, rel, raw, parse_findings)
            if mod is None:
                continue
            modules[rel] = mod
            facts = module_facts(mod)
            cache.put_facts(shas[rel], facts)
        fact_pairs.append((rel, facts))
    merged = PackageFacts(fact_pairs)
    ctx = merged.digest()
    fam_key = ",".join(families or DEFAULT_FAMILIES)
    rules = None
    findings: List[Finding] = list(parse_findings)
    for path, rel, raw in files:
        if rel not in shas or not any(r == rel for r, _f in fact_pairs):
            continue                       # unparseable: R000 already out
        key = CheckCache.findings_key(rel, ctx, fam_key)
        cached = cache.get_findings(shas[rel], key)
        if cached is not None:
            findings.extend(Finding(**{k: e[k] for k in (
                "rule", "path", "line", "col", "scope", "key",
                "message")}) for e in cached)
            continue
        mod = modules.get(rel) or _parse(path, rel, raw, parse_findings)
        if mod is None:
            continue
        if rules is None:
            rules = build_rules(merged, families)
        mod_findings: List[Finding] = []
        for rule in rules:
            rule.run(mod, mod_findings.append)
        cache.put_findings(shas[rel], key,
                           [f.to_dict() for f in mod_findings])
        findings.extend(mod_findings)
    cache.flush()
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, modules


def _parse(path, rel, raw, parse_findings) -> Optional[ModuleInfo]:
    try:
        return ModuleInfo(path, rel, raw.decode("utf-8"))
    except (SyntaxError, UnicodeDecodeError) as e:
        parse_findings.append(Finding(
            "R000", rel, getattr(e, "lineno", 0) or 0, 0, "",
            "unparseable", f"cannot analyze: {e}"))
        return None


def analyze_package(families: Optional[Sequence[str]] = None
                    ) -> List[Finding]:
    """Analyze the whole installed ``dmlp_tpu`` package."""
    return analyze_paths([package_root()], families)


def stale_allow_directives(modules: List[ModuleInfo]
                           ) -> List[Tuple[str, int, str]]:
    """``(relpath, line, directive)`` for every suppression directive
    that silenced nothing in the run that analyzed ``modules`` (run ALL
    families first, or live directives for unrun families report
    stale)."""
    import re
    from dmlp_tpu.check.findings import is_suppression_directive
    # Prose in docstrings/messages mentions directives ("annotate
    # `# check: no-retry`") and parse_directives deliberately picks
    # those up (extra allows are harmless for suppression). For STALE
    # reporting they would be noise, so only well-formed bare tokens
    # count — the backticks/punctuation prose drags along fail this.
    token_re = re.compile(r"^[a-z][a-z-]*(=[A-Za-z0-9]+)?$")
    out: List[Tuple[str, int, str]] = []
    for mod in modules:
        for line, directives in sorted(mod.directives.items()):
            for d in sorted(directives):
                if not token_re.match(d) \
                        or not is_suppression_directive(d):
                    continue
                if (line, d) not in mod.used_allows:
                    out.append((mod.relpath, line, d))
    return out
