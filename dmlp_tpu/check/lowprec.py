"""R8 — low-precision MXU contract in the Pallas kernel bodies.

The low-precision first pass (``precision="bf16"``) is exact only
because of a two-part contract: every matmul keeps its accumulator in
f32 (``preferred_element_type=jnp.float32`` — a bf16 accumulator would
void the :func:`~dmlp_tpu.engine.finalize.lowp_eps` bound entirely),
and every site that casts streamed operands below f32 declares *which*
analytic error bound covers the cast, so the resolver/prune window
inflation can be audited from the kernel source alone. Both halves are
mechanical to check and silent to violate — a missing
``preferred_element_type`` still returns plausible neighbours, just no
longer byte-identical ones — which is exactly the profile a static
rule should carry instead of a fuzz test alone.

Scope: the Pallas kernel modules, ``dmlp_tpu/ops/pallas_*.py``.

- **R801** ``dot``/``dot_general`` call without an explicit
  ``preferred_element_type`` keyword (accumulator dtype left to the
  backend default, which follows the *operand* dtype — bf16 operands
  would get a low-precision accumulator).
- **R802** cast of an operand to a sub-f32 dtype (``bfloat16``,
  ``float16``, ``int8``, …) without a governing
  ``# check: lowp-eps=<fn>`` annotation naming the bound that covers
  it.
- **R803** a ``lowp-eps=<fn>`` annotation naming a function
  ``engine/finalize.py`` does not define — the declared bound must
  exist, or the annotation documents nothing.

``# check: allow-lowprec`` waives a site (e.g. a deliberately lossy
diagnostic kernel outside the exactness contract).
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Optional

from dmlp_tpu.check.common import ModuleInfo, call_name, dotted
from dmlp_tpu.check.facts import PackageFacts
from dmlp_tpu.check.findings import Finding

ALLOW = "allow-lowprec"
EPS_DIRECTIVE = "lowp-eps"

#: dtype name suffixes that count as "below f32" when cast to. int4/
#: fp8 spellings are included pre-emptively: the int8 first pass is a
#: roadmap follow-on and its cast sites must land already annotated.
_LOW_DTYPES = ("bfloat16", "float16", "half", "int8", "uint8", "int4",
               "float8_e4m3fn", "float8_e5m2")

#: call names (last dotted segment) that hit the MXU and therefore
#: need an explicit accumulator dtype.
_DOT_CALLS = ("dot", "dot_general")


def in_lowprec_scope(mod: ModuleInfo) -> bool:
    rel = mod.relpath.replace("\\", "/")
    return fnmatch.fnmatch(rel, "*dmlp_tpu/ops/pallas_*.py") \
        or fnmatch.fnmatch(rel, "dmlp_tpu/ops/pallas_*.py")


def _dtype_name(node: ast.AST) -> Optional[str]:
    """``jnp.bfloat16`` -> "bfloat16"; ``"bfloat16"`` -> itself."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = dotted(node)
    if name is not None:
        return name.split(".")[-1]
    return None


def _low_cast_dtype(call: ast.Call) -> Optional[str]:
    """The sub-f32 dtype a call casts to, else None.

    Recognizes ``x.astype(dt)`` and ``lax.convert_element_type(x, dt)``
    (positional or ``new_dtype=``) — the two spellings the kernels use.
    """
    name = call_name(call)
    if name is None:
        return None
    tail = name.split(".")[-1]
    cand: Optional[ast.AST] = None
    if tail == "astype" and call.args:
        cand = call.args[0]
    elif tail == "convert_element_type":
        cand = call.args[1] if len(call.args) > 1 else None
        for kw in call.keywords:
            if kw.arg == "new_dtype":
                cand = kw.value
    if cand is None:
        return None
    dt = _dtype_name(cand)
    if dt is not None and dt in _LOW_DTYPES:
        return dt
    return None


class LowPrecRule:
    def __init__(self, facts: PackageFacts):
        self._eps_fns = facts.eps_models

    def run(self, mod: ModuleInfo, add) -> None:
        if not in_lowprec_scope(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            tail = name.split(".")[-1]
            if tail in _DOT_CALLS:
                self._check_dot(mod, node, name, add)
            dt = _low_cast_dtype(node)
            if dt is not None:
                self._check_cast(mod, node, dt, add)

    def _check_dot(self, mod: ModuleInfo, node: ast.Call, name: str,
                   add) -> None:
        if any(kw.arg == "preferred_element_type"
               for kw in node.keywords):
            return
        if mod.allowed(node, ALLOW):
            return
        add(Finding(
            "R801", mod.relpath, node.lineno, node.col_offset,
            mod.scope_of(node), f"dot-no-acc-dtype:{name}",
            f"`{name}` without an explicit `preferred_element_type` — "
            "the accumulator dtype follows the operand dtype, so a "
            "bf16 first pass would accumulate in bf16 and void the "
            "lowp_eps exactness bound; pin `preferred_element_type="
            "jnp.float32`"))

    def _check_cast(self, mod: ModuleInfo, node: ast.Call, dt: str,
                    add) -> None:
        declared = mod.directive_values(node, EPS_DIRECTIVE)
        if not declared:
            if mod.allowed(node, ALLOW):
                return
            add(Finding(
                "R802", mod.relpath, node.lineno, node.col_offset,
                mod.scope_of(node), f"lowp-cast-unbounded:{dt}",
                f"operand cast to `{dt}` without a `# check: "
                "lowp-eps=<fn>` annotation naming the analytic bound "
                "that covers the precision loss (engine/finalize.py)"))
            return
        known = self._eps_fns
        if known is None:
            return          # finalize.py facts unavailable: stay silent
        for fn in declared:
            if fn in known:
                continue
            if mod.allowed(node, ALLOW):
                continue
            add(Finding(
                "R803", mod.relpath, node.lineno, node.col_offset,
                mod.scope_of(node), f"lowp-eps-unknown:{fn}",
                f"`lowp-eps={fn}` names a bound engine/finalize.py "
                "does not define — the annotation must reference a "
                "real eps function so the inflation it promises can "
                "be audited"))
