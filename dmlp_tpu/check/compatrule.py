"""R4 — compat-bypass: drifting jax APIs used outside ``utils/compat.py``.

PR 2's ``utils/compat.py`` pins every jax API this tree has already
been burned by (shard_map spelling + ``check_vma``/``check_rep``,
``axis_size``, Pallas ``CompilerParams`` rename, host memory kinds).
The shims only work if they stay the single point of drift — one direct
``jax.experimental.shard_map`` import in a new engine quietly re-breaks
the floor jax version. This family makes the funnel mandatory:

- **R401** any shard_map spelling that is not
  ``utils.compat.shard_map``;
- **R402** ``jax.lax.axis_size`` (compat maps it to the ``psum(1,...)``
  constant on older jax);
- **R403** ``CompilerParams``/``TPUCompilerParams`` attributes (compat
  handles the rename and drops unknown kwargs);
- **R404** hard-coded ``"pinned_host"``/``"unpinned_host"`` memory-kind
  strings (compat resolves what this backend actually exposes).
"""

from __future__ import annotations

import ast

from dmlp_tpu.check.common import ModuleInfo, dotted, is_docstring
from dmlp_tpu.check.findings import Finding

ALLOW = "allow-compat"
_EXEMPT_SUFFIX = "utils/compat.py"


def exempt(relpath: str) -> bool:
    rel = relpath.replace("\\", "/")
    # compat.py is the one legitimate user; the checker itself names the
    # patterns it hunts (this file's own strings must not self-flag).
    return rel.endswith(_EXEMPT_SUFFIX) or "dmlp_tpu/check/" in rel \
        or rel.startswith("dmlp_tpu/check/")


class CompatRule:
    def run(self, mod: ModuleInfo, add) -> None:
        if exempt(mod.relpath):
            return

        def flag(rule, node, key, msg):
            if not mod.allowed(node, ALLOW):
                add(Finding(rule, mod.relpath, node.lineno,
                            node.col_offset, mod.scope_of(node), key,
                            msg))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                m = node.module or ""
                if m.startswith("jax.experimental.shard_map"):
                    flag("R401", node, "import-shard_map",
                         "import from jax.experimental.shard_map — "
                         "use dmlp_tpu.utils.compat.shard_map")
                if m == "jax.lax" and any(a.name == "axis_size"
                                          for a in node.names):
                    flag("R402", node, "import-axis_size",
                         "import of jax.lax.axis_size — use "
                         "dmlp_tpu.utils.compat.axis_size")
                if m == "jax" and any(a.name == "shard_map"
                                      for a in node.names):
                    flag("R401", node, "import-shard_map",
                         "import of jax.shard_map — use "
                         "dmlp_tpu.utils.compat.shard_map")
            elif isinstance(node, ast.Attribute):
                name = dotted(node)
                if name in ("jax.shard_map",
                            "jax.experimental.shard_map.shard_map"):
                    flag("R401", node, "attr-shard_map",
                         f"direct {name} — use "
                         "dmlp_tpu.utils.compat.shard_map")
                elif name == "jax.lax.axis_size":
                    flag("R402", node, "attr-axis_size",
                         "direct jax.lax.axis_size — use "
                         "dmlp_tpu.utils.compat.axis_size")
                elif node.attr in ("CompilerParams", "TPUCompilerParams"):
                    flag("R403", node, f"attr-{node.attr}",
                         f"direct Pallas {node.attr} — use "
                         "dmlp_tpu.utils.compat.tpu_compiler_params "
                         "(handles the rename + unknown kwargs)")
            elif isinstance(node, ast.Constant) \
                    and node.value in ("pinned_host", "unpinned_host") \
                    and not is_docstring(node, mod.parents):
                flag("R404", node, f"memkind-{node.value}",
                     f"hard-coded memory kind {node.value!r} — use "
                     "dmlp_tpu.utils.compat.host_memory_kind() "
                     "(older XLA:CPU only exposes unpinned_host)")
