"""R9 — the compiler-sharded (GSPMD) surface contract.

R1 guards the hand-rolled collectives; this family extends the same
axis discipline to the declarative sharding surface the auto engine
(engine/auto.py) introduced:

- **R901**: every axis a ``PartitionSpec`` (usually spelled ``P``)
  names — and hence every ``NamedSharding`` / ``with_sharding_constraint``
  built from it — must resolve to a mesh axis some ``*_AXIS`` constant
  in the package declares. A typo'd axis in a sharding spec is worse
  than R101's psum case: GSPMD silently replicates instead of sharding,
  so the program is CORRECT and slow — nothing ever fails.
- **R902**: ``jax.jit`` calls in ``engine/auto.py`` must pin BOTH
  ``in_shardings`` and ``out_shardings`` (or carry ``# check:
  allow-auto-shard``): the auto engine's whole claim is that the partitioner
  sees the full placement contract, not whatever it infers from the
  first dispatch's committed layouts — an unpinned jit there can
  benchmark a different (resharding-on-entry) program than the one the
  A/B record names.
- **R903**: a ``with_sharding_constraint`` whose sharding arrives
  through a local variable (``qsh = NamedSharding(mesh, P(...))``)
  must resolve, through that binding, to declared ``*_AXIS`` axes.
  R901 checks the ``P(...)`` construction; R903 closes the variable
  indirection at the constraint site. Names that don't resolve to a
  single consistent NamedSharding binding are skipped, not guessed at;
  inline ``P``/``NamedSharding`` args are R901's job (no double
  report).

Axis expressions resolve exactly like R1 (``check.collectives
.resolve_axis``): string literals, ``*_AXIS`` constants (local or
imported), tuples of those; function parameters and opaque expressions
are skipped, not guessed at. ``None`` spec entries are replication, not
axes.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set

from dmlp_tpu.check.collectives import resolve_axis
from dmlp_tpu.check.common import ModuleInfo, call_name
from dmlp_tpu.check.findings import Finding

ALLOW = "allow-auto-shard"

#: the one file whose jits carry the R902 pinning contract
AUTO_ENGINE_PATH = "dmlp_tpu/engine/auto.py"


def _is_pspec_call(call: ast.Call, mod: ModuleInfo) -> bool:
    """Is this a PartitionSpec construction? Covers the canonical
    ``P`` alias by resolving the name through the module's imports
    (``from jax.sharding import PartitionSpec as P``)."""
    name = call_name(call)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    if leaf == "PartitionSpec":
        return True
    src = mod.imports.get(leaf, "")
    return src.rsplit(".", 1)[-1] == "PartitionSpec"


def _is_jit_call(call: ast.Call) -> bool:
    name = call_name(call)
    return name in ("jax.jit", "jit")


def _is_named_sharding_call(call: ast.Call, mod: ModuleInfo) -> bool:
    name = call_name(call)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    if leaf == "NamedSharding":
        return True
    src = mod.imports.get(leaf, "")
    return src.rsplit(".", 1)[-1] == "NamedSharding"


def _is_wsc_call(call: ast.Call) -> bool:
    name = call_name(call)
    return name is not None \
        and name.rsplit(".", 1)[-1] == "with_sharding_constraint"


class AutoShardRule:
    """One instance runs over the whole package; declared axes come
    from the merged PackageFacts (same source R1 reads)."""

    def __init__(self, facts):
        self.axis_consts: Dict[str, str] = facts.axis_consts
        self.declared: Set[str] = facts.declared

    def run(self, mod: ModuleInfo, add) -> None:
        sharding_vars = None    # built lazily: most files have no wsc
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_pspec_call(node, mod):
                self._check_spec_axes(mod, node, add)
            elif _is_jit_call(node) \
                    and mod.relpath.replace("\\", "/") == AUTO_ENGINE_PATH:
                self._check_jit_pinning(mod, node, add)
            if _is_wsc_call(node):
                if sharding_vars is None:
                    sharding_vars = self._sharding_vars(mod)
                self._check_constraint(mod, node, sharding_vars, add)

    def _check_spec_axes(self, mod: ModuleInfo, node: ast.Call,
                         add) -> None:
        for arg in node.args:
            if isinstance(arg, ast.Constant) and arg.value is None:
                continue    # replication entry, not an axis
            resolved = resolve_axis(arg, mod, self.axis_consts)
            if resolved is None or (isinstance(resolved, tuple)
                                    and resolved[0] == "param"):
                continue    # opaque / parameter-passed: not guessed at
            axes = resolved if isinstance(resolved, list) else [resolved]
            for ax in axes:
                if ax in self.declared:
                    continue
                if mod.allowed_value(node, ALLOW, "R901"):
                    continue
                add(Finding(
                    "R901", mod.relpath, node.lineno, node.col_offset,
                    mod.scope_of(node), f"pspec:{ax}",
                    f"PartitionSpec names mesh axis {ax!r}, which no "
                    f"*_AXIS constant declares (declared: "
                    f"{sorted(self.declared)}) — GSPMD would silently "
                    f"replicate instead of sharding"))

    def _check_jit_pinning(self, mod: ModuleInfo, node: ast.Call,
                           add) -> None:
        kwargs = {kw.arg for kw in node.keywords}
        missing = sorted({"in_shardings", "out_shardings"} - kwargs)
        if not missing:
            return
        if mod.allowed_value(node, ALLOW, "R902"):
            return
        add(Finding(
            "R902", mod.relpath, node.lineno, node.col_offset,
            mod.scope_of(node), f"jit:{','.join(missing)}",
            f"jit in the auto engine must pin in_shardings/"
            f"out_shardings (missing {missing}) or carry "
            f"`# check: allow-auto-shard` — an unpinned jit lets "
            f"the partitioner infer placements from the first dispatch"))

    # -- R903: variable-held shardings at constraint sites ------------------
    def _sharding_vars(self, mod: ModuleInfo):
        """name -> set of axes from every ``name = NamedSharding(...,
        P(...))`` binding in the module, or None for names also bound
        to something else (opaque: the reaching binding is unknown).
        Same-name bindings in different functions merge — each binding's
        axes must be declared anyway, so the union checks them all."""
        out: Dict[str, Optional[Set[str]]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            name = node.targets[0].id
            v = node.value
            if not (isinstance(v, ast.Call)
                    and _is_named_sharding_call(v, mod)):
                out[name] = None     # not (only) a sharding binding
                continue
            axes = self._spec_call_axes(mod, v)
            if axes is None:
                out[name] = None         # spec unresolvable: opaque
            elif out.get(name, set()) is not None:
                out[name] = out.get(name) or set()
                out[name].update(axes)
        return out

    def _spec_call_axes(self, mod: ModuleInfo,
                        ns_call: ast.Call) -> Optional[Set[str]]:
        """Resolved axis names of the PartitionSpec inside one
        NamedSharding construction; None when any entry is opaque."""
        spec = None
        for arg in list(ns_call.args) + [kw.value for kw in
                                         ns_call.keywords
                                         if kw.arg == "spec"]:
            if isinstance(arg, ast.Call) and _is_pspec_call(arg, mod):
                spec = arg
        if spec is None:
            return None
        axes: Set[str] = set()
        for arg in spec.args:
            if isinstance(arg, ast.Constant) and arg.value is None:
                continue    # replication entry, not an axis
            resolved = resolve_axis(arg, mod, self.axis_consts)
            if resolved is None or (isinstance(resolved, tuple)
                                    and resolved[0] == "param"):
                return None
            axes.update(resolved if isinstance(resolved, list)
                        else [resolved])
        return axes

    def _check_constraint(self, mod: ModuleInfo, node: ast.Call,
                          sharding_vars, add) -> None:
        spec_arg = node.args[1] if len(node.args) >= 2 else None
        if spec_arg is None:
            for kw in node.keywords:
                if kw.arg == "shardings":
                    spec_arg = kw.value
        # Only variable indirection: inline P(...)/NamedSharding(...)
        # constructions are R901's finding site already.
        if not isinstance(spec_arg, ast.Name):
            return
        axes = sharding_vars.get(spec_arg.id)
        if axes is None:
            return              # unknown or opaque binding: not guessed at
        for ax in sorted(axes):
            if ax in self.declared:
                continue
            if mod.allowed_value(node, ALLOW, "R903"):
                continue
            add(Finding(
                "R903", mod.relpath, node.lineno, node.col_offset,
                mod.scope_of(node), f"wsc:{ax}",
                f"with_sharding_constraint spec ({spec_arg.id}) "
                f"resolves to mesh axis {ax!r}, which no *_AXIS "
                f"constant declares (declared: "
                f"{sorted(self.declared)}) — the constraint silently "
                f"replicates"))
