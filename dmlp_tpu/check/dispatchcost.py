"""R1 extension — kernel-dispatch cost coverage (R105/R106).

The measured-extraction-term contract (ISSUE 3/8): every Pallas top-k
kernel dispatch in the engines is (a) recorded for the analytic cost
counters (``obs_counters.record_dispatch`` resolving through
``obs.kernel_cost.analytic_cost`` — pallas_call has no XLA cost
analysis) and (b) paired with a ``MeasuredIters`` probe so the
extraction term stays ``measured``, not modeled. Both halves drift
silently: a new kernel (the fused megakernel) dispatched without a
model skews every counters record low, and a dispatch loop without a
probe quietly downgrades ``extraction_term`` for that path. These rules
are the static half:

- **R105**: a ``record_dispatch`` of a top-k kernel (a direct
  ``ops.pallas_*`` import, or a variable bound from
  ``resolve_topk_kernel``) whose enclosing function neither constructs
  a ``MeasuredIters`` probe nor queues through ``_queue_iters`` — the
  dispatch site would report a modeled (lower-bound) extraction term.
- **R106**: a ``record_dispatch`` whose kernel argument resolves to a
  ``dmlp_tpu.ops`` function with NO entry in the
  ``obs.kernel_cost.analytic_cost`` model table (parsed statically from
  kernel_cost.py — renaming a kernel away from its model, or adding a
  kernel without one, fails ``make check`` instead of silently
  under-counting FLOPs/HBM bytes).

Both scope to ``engine/`` modules (the hot-path dispatch sites; tools
and tests measure what they please) and honor the R1 family's
``# check: allow-collective`` directive.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from dmlp_tpu.check.common import ModuleInfo, call_name
from dmlp_tpu.check.findings import Finding

#: functions whose return value IS a top-k kernel callable — names bound
#: from their result are kernel variables for R105/R106 purposes
KERNEL_RESOLVERS = {"resolve_topk_kernel"}

#: the probe protocol: an enclosing function satisfies R105 when it
#: constructs the accumulator itself or routes through the shared queue
PROBE_CALLS = {"MeasuredIters", "_queue_iters"}


def _modeled_kernels(modules: List[ModuleInfo]) -> Optional[Set[str]]:
    """Kernel function names registered in ``analytic_cost``'s model
    table — the analyzed copy when obs/kernel_cost.py is part of this
    run, else the installed package's file (fixture runs analyze a
    single temp file). None when neither parses: R106 then stays silent
    rather than flagging every dispatch. (Kept on the ModuleInfo
    signature for introspection/tests; the analysis driver routes the
    same extraction through the cacheable facts layer.)"""
    from dmlp_tpu.check.facts import (_installed_modeled_kernels,
                                      _modeled_from_tree)
    mod = next((m for m in modules
                if m.relpath.endswith("obs/kernel_cost.py")), None)
    if mod is None:
        return _installed_modeled_kernels()
    return set(_modeled_from_tree(mod.tree)) or None


class DispatchCostRule:
    """R105/R106 over every engine-module ``record_dispatch`` site."""

    def __init__(self, facts):
        self._modeled = facts.modeled_kernels

    # -- per-module tables ---------------------------------------------------
    def _ops_kernels(self, mod: ModuleInfo) -> dict:
        """local name -> kernel function name, for names imported from
        dmlp_tpu.ops (relative spellings included)."""
        out = {}
        for local, src in mod.imports.items():
            parts = src.split(".")
            if "ops" in parts[:-1]:
                out[local] = parts[-1]
        return out

    @staticmethod
    def _kernel_vars(fn: ast.AST) -> Set[str]:
        """Names bound (incl. tuple-unpacked) from a KERNEL_RESOLVERS
        call anywhere in ``fn`` — e.g. ``kern, impl =
        pallas_fused.resolve_topk_kernel(...)`` binds ``kern``."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            name = call_name(node.value)
            leaf = name.rsplit(".", 1)[-1] if name else None
            if leaf not in KERNEL_RESOLVERS:
                continue
            for tgt in node.targets:
                elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                if elts and isinstance(elts[0], ast.Name):
                    out.add(elts[0].id)
        return out

    @staticmethod
    def _has_probe(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                leaf = name.rsplit(".", 1)[-1] if name else None
                if leaf in PROBE_CALLS:
                    return True
        return False

    # -- driver --------------------------------------------------------------
    def run(self, mod: ModuleInfo, add) -> None:
        if "engine/" not in mod.relpath:
            return
        ops_kernels = self._ops_kernels(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            leaf = name.rsplit(".", 1)[-1] if name else None
            if leaf != "record_dispatch" or not node.args \
                    or not isinstance(node.args[0], ast.Name):
                continue
            arg = node.args[0].id
            encl = mod.enclosing_funcs(node)
            fn = encl[0] if encl else None
            kernel_vars = self._kernel_vars(fn) if fn is not None \
                else set()
            is_kernel = arg in ops_kernels or arg in kernel_vars
            if not is_kernel or mod.allowed(node, "allow-collective"):
                continue
            scope = mod.scope_of(node)
            if fn is not None and not self._has_probe(fn):
                add(Finding(
                    "R105", mod.relpath, node.lineno, node.col_offset,
                    scope, f"probe:{arg}",
                    f"kernel dispatch site records {arg!r} but "
                    f"{fn.name} threads no MeasuredIters/_queue_iters "
                    f"probe — the extraction term degrades to modeled"))
            if arg in ops_kernels and self._modeled is not None \
                    and ops_kernels[arg] not in self._modeled:
                add(Finding(
                    "R106", mod.relpath, node.lineno, node.col_offset,
                    scope, f"model:{ops_kernels[arg]}",
                    f"dispatched kernel {ops_kernels[arg]!r} has no "
                    f"entry in obs.kernel_cost.analytic_cost — its "
                    f"counters would silently fall through to XLA "
                    f"cost analysis (absent for pallas_call)"))
