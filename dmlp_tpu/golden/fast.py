"""Fast exact golden oracle — BLAS coarse pass + difference-form refinement.

The strict oracle (golden.reference.knn_golden) computes every distance in
the difference form and lexsorts full rows: exact, but O(Q*N*A) elementwise
f64 plus O(Q * N log N) sorting — hours at benchmark scale. This module
produces *identical results* orders of magnitude faster:

1. coarse distances via f64 dgemm (|q|^2 + |d|^2 - 2 q.d);
2. top-(kmax + margin) candidates per query by coarse value (argpartition);
3. exact difference-form rescore of just the candidates;
4. per-query safety check: the exact k-th distance must clear the coarse
   selection boundary by more than the norm+matmul error bound, else that
   query falls back to the strict full-row path.

The fallback makes the result exact regardless of the bound's tightness —
the bound only decides how often the slow path runs (measure-zero for
continuous data, possible for adversarial duplicates).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from dmlp_tpu.engine.finalize import finalize_host
from dmlp_tpu.golden.reference import finalize_query
from dmlp_tpu.io.grammar import KNNInput
from dmlp_tpu.io.report import QueryResult


def _strict_row(inp: KNNInput, qi: int, data: np.ndarray,
                labels: np.ndarray, ids: np.ndarray) -> QueryResult:
    """Exact full-row solve for one query (the knn_golden inner loop)."""
    diff = data - inp.query_attrs[qi][None, :]
    drow = np.einsum("na,na->n", diff, diff)
    return finalize_query(drow, labels, ids, int(inp.ks[qi]), qi)


def knn_golden_fast(inp: KNNInput, margin: int = 64,
                    query_block: int = 1024,
                    stats: Optional[dict] = None) -> List[QueryResult]:
    """Same results as knn_golden(inp) (float64), benchmark-scale fast.

    ``stats``, if given, receives {"fallbacks": <count of queries routed
    to the strict full-row path>} so the safety valve's cost is observable.
    """
    nd, nq = inp.params.num_data, inp.params.num_queries
    data = inp.data_attrs.astype(np.float64)
    labels = inp.labels.astype(np.int64)
    ids = np.arange(nd, dtype=np.int64)
    dn = np.einsum("na,na->n", data, data)
    kmax = int(inp.ks.max()) if nq else 1
    kcand = min(nd, kmax + margin)
    # Error bound of the norm+matmul form relative to the difference form:
    # cancellation of terms of magnitude ~(|q|^2 + |d|^2). A couple of
    # hundred ulps is far beyond the real accumulation error for A ~ 10^2.
    eps = np.finfo(np.float64).eps

    results: List[QueryResult] = [None] * nq  # type: ignore[list-item]
    fallbacks = 0
    for q0 in range(0, nq, query_block):
        q1 = min(q0 + query_block, nq)
        q = inp.query_attrs[q0:q1].astype(np.float64)
        qn = np.einsum("qa,qa->q", q, q)
        # In-place epilogue on the dgemm output: the broadcast expression
        # form allocates ~4 (Qb, N) f64 temporaries, which measured ~10x
        # the dgemm itself at benchmark scale (page faults on fresh GBs).
        coarse = q @ data.T
        coarse *= -2.0
        coarse += qn[:, None]
        coarse += dn[None, :]

        if kcand < nd:
            cand = np.argpartition(coarse, kcand - 1, axis=1)[:, :kcand]
        else:
            cand = np.broadcast_to(ids[None, :], (q1 - q0, nd))
        # Exact difference-form rescore of the candidates only.
        diff = data[cand] - q[:, None, :]
        exact = np.einsum("qka,qka->qk", diff, diff)

        ks_blk = inp.ks[q0:q1].astype(np.int64)
        if kcand < nd:
            coarse_cand = np.take_along_axis(coarse, cand, axis=1)
            # The bound must cover the points the coarse pass EXCLUDED
            # (their coarse value could be understated by up to the
            # rounding error of the norm+matmul form), and an excluded
            # point's |d|^2 can exceed every candidate's — so it uses the
            # global max norm, not dn[cand] (ADVICE r1: the candidate-norm
            # bound did not strictly prove exactness for adversarial
            # large-norm excluded points).
            err_q = 256.0 * eps * (qn + (dn.max() if nd else 0.0) + 1.0)
            # Safety (vectorized): the k-th exact distance must clear the
            # coarse selection boundary by the error bound, else that
            # query's candidates may be wrong -> strict full-row fallback.
            kth_exact = np.take_along_axis(
                np.sort(exact, axis=1),
                np.minimum(ks_blk, kcand)[:, None] - 1, axis=1)[:, 0]
            boundary = coarse_cand.max(axis=1)
            ok = kth_exact < boundary - err_q
        else:
            ok = np.ones(q1 - q0, bool)

        # Batched finalize over the whole query block (VERDICT r3 item 6:
        # the per-query Python finalize loop dominated oracle time at
        # benchmark scale — 182 s on harness config 4). finalize_host is
        # the engines' own vectorized implementation of the identical
        # contract; oracle honesty is anchored by the strict per-query
        # fallback below and the fast-vs-strict differential tests
        # (tests/test_golden_fast.py), which diff this path against
        # knn_golden's independent per-query code.
        cand_l, cand_i, exact_f = labels[cand], cand, exact
        if kcand < int(ks_blk.max(initial=0)):
            # k may legally exceed num_data (sentinel padding); widen the
            # candidate lists so finalize_host can pad with (-1, +inf).
            padw = int(ks_blk.max()) - kcand
            shape = (q1 - q0, padw)
            exact_f = np.concatenate([exact, np.full(shape, np.inf)], axis=1)
            cand_l = np.concatenate(
                [cand_l, np.full(shape, -1, np.int64)], axis=1)
            cand_i = np.concatenate(
                [cand_i, np.full(shape, -1, np.int64)], axis=1)
        blk = finalize_host(exact_f, cand_l, cand_i, ks_blk,
                            inp.query_attrs, inp.data_attrs, exact=False,
                            query_ids=np.arange(q0, q1, dtype=np.int64))
        results[q0:q1] = blk
        for row in np.nonzero(~ok)[0]:
            results[q0 + row] = _strict_row(inp, q0 + row, data, labels, ids)
            fallbacks += 1
    if stats is not None:
        stats["fallbacks"] = fallbacks
    return results
