from dmlp_tpu.golden.reference import knn_golden, solve_text  # noqa: F401
