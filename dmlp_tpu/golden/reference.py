"""Pure-NumPy golden KNN model — the portable differential-testing oracle.

The reference verifies engines against four stripped x86/MPI oracle binaries
(benchmarks/bench_1..4, survey §4). Build round 5 ran those binaries in this
container (isolated-singleton Open MPI; tools/capture_oracle.sh) and MEASURED
their semantics on tie-adversarial inputs, so this oracle implements the
binaries' observed contract, not the author engine.cpp's:

- squared Euclidean distance, float64, difference form (engine.cpp:12-18);
- k-selection comparator: distance asc, tie -> **larger id** first —
  LABEL-FREE. The author's engine.cpp breaks selection ties by larger label
  (engine.cpp:251-254), but the actual oracle binaries bench_1/2/3 match
  the label-free order exactly on 300/300 tie-adversarial fuzz cases
  (TIE_SEMANTICS_r05.json), while the label-aware order mismatched 18% of
  cases in the discovery census; bench_4 disagrees with its own siblings
  on ties — id-ASC report order — so the majority semantics is the
  contract;
- majority vote over the selected k with tie -> **larger label**
  (engine.cpp:326-332; confirmed on the binaries with crafted vote-tie
  inputs);
- report order: distance asc, tie -> **larger id** first (engine.cpp:334-338;
  identical to the selection order — one comparator governs both);
- pad with the id = -1 sentinel when fewer than k candidates exist
  (common.cpp:66); padded entries carry dist = +inf and do not vote.

On tie-free inputs — every graded benchmark input; continuous draws tie with
probability ~0 — the label-free and label-aware orders coincide, which is
why all 21,000 captured benchmark checksums match either way
(oracle_capture/ORACLE_GOLDEN.json). Known defects of the author's engine
are deliberately not inherited (survey §7 quirks Q1-Q3: wrong merge offsets
for heterogeneous k, zero-padding of short shards, duplicated report loop).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from dmlp_tpu.io.grammar import KNNInput, parse_input_text
from dmlp_tpu.io.report import QueryResult, format_results


def _select_order(dists: np.ndarray, labels: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Indices sorting by the selection total order (dist asc, id desc).

    Labels play no role in selection — measured, not assumed: build round
    5 ran the actual oracle binaries (isolated-singleton Open MPI) on
    tie-adversarial inputs and bench_1/2/3 match this label-free order
    exactly (0/300 mismatches; TIE_SEMANTICS_r05.json), while the
    author's engine.cpp label-aware comparator (engine.cpp:251-254)
    mismatched 18% in the discovery census. (bench_4 orders report ties
    id-ASC — inconsistent with its own siblings; see the artifact.)
    ``labels`` stays in the signature for call-site symmetry."""
    del labels
    return np.lexsort((-ids, dists))


def vote(labels: np.ndarray) -> int:
    """Majority vote with tie -> larger label (engine.cpp:320-332).

    Returns -1 for an empty candidate set (the C++ ``predicted_label``
    initializer at engine.cpp:326).
    """
    if labels.size == 0:
        return -1
    uniq, counts = np.unique(labels, return_counts=True)
    best = counts.max()
    return int(uniq[counts == best].max())


def finalize_query(drow: np.ndarray, labels: np.ndarray, ids: np.ndarray,
                   k: int, qi: int) -> QueryResult:
    """Candidate distances for one query -> its final QueryResult.

    THE definition of the output contract, shared by the strict and fast
    oracles: select by (dist asc, id desc), vote (tie -> larger
    label), report order (dist asc, id desc), pad to k with the id = -1 /
    dist = +inf sentinel (common.cpp:66). ``drow``/``labels``/``ids`` may be
    the full dataset row or any candidate subset that contains the true
    top-k.
    """
    order = _select_order(drow, labels, ids)[: min(k, drow.shape[0])]
    sel_d, sel_l, sel_i = drow[order], labels[order], ids[order]
    predicted = vote(sel_l)
    # Selection order IS the report order under the measured label-free
    # comparator (one (dist asc, id desc) total order governs both) —
    # no second sort.
    out_ids, out_dists = sel_i, sel_d
    if out_ids.size < k:
        pad = k - out_ids.size
        out_ids = np.concatenate([out_ids, np.full(pad, -1, np.int64)])
        out_dists = np.concatenate([out_dists, np.full(pad, np.inf)])
    return QueryResult(qi, k, predicted, out_ids.astype(np.int64),
                       out_dists.astype(np.float64))


def knn_golden(inp: KNNInput, dtype=np.float64,
               query_block: int = 256) -> List[QueryResult]:
    """Solve a problem instance exactly; returns per-query results in id order.

    ``dtype`` controls the distance arithmetic (float64 = reference parity;
    float32 mirrors the on-device engines for like-for-like differential
    tests). Queries are processed in blocks so the (Q, N) distance matrix is
    never fully materialized.
    """
    nd = inp.params.num_data
    nq = inp.params.num_queries
    data = inp.data_attrs.astype(dtype)
    queries = inp.query_attrs.astype(dtype)
    labels = inp.labels.astype(np.int64)
    ids = np.arange(nd, dtype=np.int64)

    results: List[QueryResult] = []
    data_block = 8192  # bounds the (qb, nb, A) diff tensor
    for q0 in range(0, nq, query_block):
        q1 = min(q0 + query_block, nq)
        # Difference form, like computeDistance (engine.cpp:12-18) — exact in
        # the working dtype, unlike the norm+matmul form the device uses.
        # Blocked over data too so the diff tensor stays bounded.
        dists = np.empty((q1 - q0, nd), dtype)
        for n0 in range(0, nd, data_block):
            n1 = min(n0 + data_block, nd)
            diff = queries[q0:q1, None, :] - data[None, n0:n1, :]
            dists[:, n0:n1] = np.einsum("qna,qna->qn", diff, diff)
        for qi in range(q0, q1):
            results.append(finalize_query(dists[qi - q0], labels, ids,
                                          int(inp.ks[qi]), qi))
    return results


def solve_text(text: str, dtype=np.float64, debug: bool = False,
               inp: Optional[KNNInput] = None) -> str:
    """End-to-end oracle: input grammar text -> stdout channel text."""
    if inp is None:
        inp = parse_input_text(text)
    return format_results(knn_golden(inp, dtype=dtype), debug=debug)
