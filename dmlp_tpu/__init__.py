"""dmlp_tpu — a TPU-native distributed machine-learning framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
``Distributed-Machine-Learning-Project`` (a 2-node MPI program: a distributed
brute-force k-nearest-neighbors classifier on a 2D Cartesian process grid,
validated by order-sensitive FNV-1a checksums and wall-clock benchmarks;
see ``/root/reference/engine.cpp``, ``common.cpp``, ``run_bench.sh``).

Instead of translating the MPI choreography (Cart grids, Scatterv/Bcast/Gather),
the framework expresses the same computation TPU-first:

- the brute-force distance computation (reference ``engine.cpp:12-18,239-246``,
  a scalar O(Q*N*A) loop) becomes one MXU matmul via
  ``|q - d|^2 = |q|^2 + |d|^2 - 2 q.d``  (:mod:`dmlp_tpu.ops.distance`);
- the 2D process grid + row/col sub-communicators (``engine.cpp:40-57``)
  become a ``jax.sharding.Mesh(("data", "query"))`` with ``shard_map``
  (:mod:`dmlp_tpu.engine.sharded`);
- the partial-top-k + root merge (``engine.cpp:249-256,289-308``) becomes
  either an ``all_gather``-merge or a ring ``ppermute`` stream with a running
  top-k (:mod:`dmlp_tpu.engine.ring`) — the long-context analog;
- the checksum/report contract (``common.cpp:57-79``) is reproduced exactly
  (:mod:`dmlp_tpu.io.checksum`, :mod:`dmlp_tpu.io.report`);
- the training north star (data-parallel ``train_step`` with ``psum`` gradient
  sync, samples/sec/chip + MFU metrics) lives in :mod:`dmlp_tpu.train`.

Package layout::

    dmlp_tpu/
      io/        input grammar, checksum, report, seeded data generation,
                 native (C++) host parser bindings
      golden/    pure-NumPy oracle (portable replacement for the x86
                 benchmark binaries, which cannot run here)
      ops/       distance / top-k / vote kernels (+ pallas/ TPU kernels)
      engine/    single-chip, 2D-sharded, and ring-streaming KNN engines
      parallel/  mesh construction, collective helpers, multi-host init
      models/    KNN model facade + MLP classifier (training extension)
      train/     jitted train_step (DP psum / TP sharding), metrics, checkpoint
      utils/     timing (the "Time taken:" contract), profiling, logging
      bench/     benchmark harness (run_bench.sh equivalent)
"""

__version__ = "0.1.0"

from dmlp_tpu.config import EngineConfig  # noqa: F401
