"""Pallas TPU kernel: fused masked distance tile + per-segment minima.

The "seg" selection (ops.topk.step_seg) needs two views of each distance
tile: the tile itself (to gather candidate columns from) and the minimum of
every 128-column segment (to pick the candidate segments). Computed with
stock XLA ops the segment-min pass re-reads the whole tile from HBM —
measured on TPU v5e that second pass costs more than the matmul that
produced the tile. This kernel produces both outputs in one pass: the MXU
computes the cross-term block, the VPU applies the norm expansion
``|q-d|^2 = |q|^2 + |d|^2 - 2 q.d`` + sentinel masking and reduces the
segment minima while the block is still in VMEM.

Grid: (Qb/TQ, B/TN) tiles; every tile is read/written exactly once.
Requires TN % 128 == 0 (whole lane-width segments). On non-TPU backends the
kernel runs in interpreter mode, so CPU tests exercise the identical code.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dmlp_tpu.utils.compat import tpu_compiler_params

SEG = 128  # candidate-segment width = one TPU lane row

_TQ = 1024  # query rows per tile (also the segmin lane dim -> 128-multiple)
_TN = 1024  # data columns per tile (8 segments -> valid sublane count)


def _tile(n: int, target: int, granule: int) -> int:
    """Largest granule-multiple divisor of n that is <= target (n itself if
    none exists — n is then a single tile, valid as a full-dimension block)."""
    t = min(target, n)
    t -= t % granule
    while t >= granule:
        if n % t == 0:
            return t
        t -= granule
    return n


def supports(qb: int, b: int, a: int) -> bool:
    """Shapes the kernel can tile within Mosaic's constraints + VMEM.

    The transposed segmin output needs tn/SEG sublanes divisible by 8
    (tn % 1024 == 0) unless one tile spans all of b; query tiles must be a
    multiple of 8 (engines pad to 8) and either divide into 128-multiples
    or fit a single full-dim tile small enough for VMEM. The VMEM budget
    covers the double-buffered dist, q, and d blocks (q/d scale with the
    attribute count, so wide-attribute inputs are gated out too).
    """
    if b % SEG != 0 or qb % 8 != 0:
        return False
    tn = _tile(b, _TN, 8 * SEG)
    tq = _tile(qb, _TQ, SEG)
    blocks_bytes = (tq * tn + tq * a + tn * a) * 4
    return 2 * blocks_bytes <= 12 * 2**20  # double-buffered


def _kernel(q_ref, d_ref, qn_ref, dn_ref, ids_ref, dist_ref, segmin_ref,
            *, precision: str = "f32"):
    # HIGHEST precision: default truncates f32 to bf16 on the MXU (1e-2
    # relative distance error measured on v5e — breaks neighbor selection).
    # A "bf16" FIRST PASS casts the operands instead (one MXU pass, f32
    # accumulation kept): every emitted distance then errs by at most
    # engine.finalize.lowp_eps, which the caller must fold into any
    # window/threshold decision fed by this tile.
    q = q_ref[:]
    d = d_ref[:]
    if precision == "bf16":
        q = q.astype(jnp.bfloat16)  # check: lowp-eps=lowp_eps
        d = d.astype(jnp.bfloat16)  # check: lowp-eps=lowp_eps
    cross = jax.lax.dot_general(
        q, d, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)
    dist = qn_ref[:] + dn_ref[:] - 2.0 * cross
    dist = jnp.maximum(dist, 0.0)
    dist = jnp.where(ids_ref[:] < 0, jnp.inf, dist)
    dist_ref[:] = dist
    tq, tn = dist.shape
    # Segment minima are emitted transposed, (segments, queries): the
    # (tn/SEG, tq) block satisfies Mosaic's (8, 128) tiling where the
    # natural (tq, tn/SEG) layout's tiny lane dimension would not.
    segmin_ref[:] = dist.reshape(tq, tn // SEG, SEG).min(axis=-1).T


@functools.partial(jax.jit, static_argnames=("interpret", "precision"))
def fused_dist_segmin(q_attrs: jax.Array, d_attrs: jax.Array,
                      data_ids: jax.Array, interpret: bool = False,
                      precision: str = "f32"):
    """(queries (Qb, A), data (B, A), ids (B,)) -> (dist (Qb, B) f32,
    segmin (Qb, B/SEG) f32). Sentinel columns (id < 0) give +inf.

    Qb must divide by 8 and B by SEG; A is unconstrained (one MXU pass).
    ``precision`` ("f32" | "bf16", static — resolve OUTSIDE any jit)
    picks the first-pass dot dtype; bf16 distances carry the
    engine.finalize.lowp_eps bound.
    """
    if precision not in ("f32", "bf16"):
        raise ValueError(f"unsupported first-pass precision {precision!r}")
    qb, a = q_attrs.shape
    b = d_attrs.shape[0]
    if not supports(qb, b, a):
        # ValueError, not assert: must fail loudly under ``python -O`` too.
        raise ValueError(f"untileable shape (qb={qb}, b={b}, a={a}); "
                         "gate on supports() first")
    tq = _tile(qb, _TQ, SEG)
    tn = _tile(b, _TN, 8 * SEG)

    q32 = q_attrs.astype(jnp.float32)
    d32 = d_attrs.astype(jnp.float32)
    qn = jnp.sum(q32 * q32, axis=-1, keepdims=True)          # (Qb, 1)
    dn = jnp.sum(d32 * d32, axis=-1)[None, :]                # (1, B)
    ids2 = data_ids[None, :]                                 # (1, B)

    grid = (qb // tq, b // tn)
    dist, segmin_t = pl.pallas_call(
        functools.partial(_kernel, precision=precision),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tq, a), lambda i, j: (i, 0)),
            pl.BlockSpec((tn, a), lambda i, j: (j, 0)),
            pl.BlockSpec((tq, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tn), lambda i, j: (0, j)),
            pl.BlockSpec((1, tn), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((tq, tn), lambda i, j: (i, j)),
            pl.BlockSpec((tn // SEG, tq), lambda i, j: (j, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((qb, b), jnp.float32),
            jax.ShapeDtypeStruct((b // SEG, qb), jnp.float32),
        ],
        # HIGHEST-precision dot needs headroom past the default 16M scoped
        # limit at the full (1024, 1024) tile.
        compiler_params=tpu_compiler_params(vmem_limit_bytes=32 * 2**20),
        interpret=interpret,
    )(q32, d32, qn, dn, ids2)
    return dist, segmin_t.T


@functools.lru_cache(maxsize=1)
def native_pallas_backend() -> bool:
    """True when Pallas compiles natively here (else use interpret mode).

    Decided by actually compiling + running a trivial kernel once (cached),
    not by matching the platform name: tunneled/experimental PJRT platforms
    (e.g. the 'axon' TPU tunnel) report surprising names, and a name check
    silently disabled the fused path for a whole benchmark round.
    """
    try:
        def probe(x_ref, o_ref):
            o_ref[:] = x_ref[:] + 1.0

        x = jnp.zeros((8, 128), jnp.float32)
        out = pl.pallas_call(
            probe, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))(x)
        # One-time cached probe readback.  # check: allow-host-sync
        return bool(jax.device_get(out)[0, 0] == 1.0)
    except Exception:
        return False
