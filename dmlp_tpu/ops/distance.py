"""Pairwise squared-L2 distance as an MXU matmul.

The reference computes distances with a scalar loop per (query, point) pair
(computeDistance, engine.cpp:12-18) — O(Q*N*A) multiply-adds on a CPU. On
TPU the same arithmetic is one batched matmul via the expansion

    |q - d|^2 = |q|^2 + |d|^2 - 2 <q, d>

so the O(Q*N*A) term rides the systolic array and the norms are O((Q+N)*A)
vector ops that XLA fuses into the epilogue. The norm+matmul form loses a few
ulps to cancellation relative to the difference form; strict-parity runs
rescore the few surviving candidates on host in float64
(see dmlp_tpu.engine.single), so the MXU keeps the heavy work either way.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pairwise_sq_l2(queries: jax.Array, data: jax.Array,
                   accum_dtype=jnp.float32) -> jax.Array:
    """Squared Euclidean distances between all (query, data) pairs.

    Args:
      queries: (Q, A) query attributes.
      data: (N, A) data-point attributes.
      accum_dtype: matmul accumulation dtype (preferred_element_type);
        float32 keeps MXU accumulation full-precision even for bf16 inputs.

    Returns:
      (Q, N) squared distances in ``accum_dtype``, clamped at 0 (the exact
      value is non-negative; cancellation in the expansion can produce tiny
      negatives).
    """
    qn = jnp.sum(jnp.square(queries.astype(accum_dtype)), axis=-1)
    dn = jnp.sum(jnp.square(data.astype(accum_dtype)), axis=-1)
    # precision=HIGHEST is load-bearing: at DEFAULT the TPU MXU truncates
    # f32 operands to bf16, measured 1e-2 max relative distance error on
    # v5e — far beyond what the exact-rescore margin can absorb, i.e.
    # wrong neighbor sets, not just reordered ones. HIGHEST (full f32,
    # bf16_6x passes) measured 1.5e-6 at no wall-clock cost (the matmul
    # is HBM-bound here). bf16 inputs are unaffected (accumulation is
    # f32 via preferred_element_type either way).
    cross = jax.lax.dot_general(
        queries, data,
        dimension_numbers=(((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=accum_dtype)
    return jnp.maximum(qn[:, None] + dn[None, :] - 2.0 * cross, 0.0)


def masked_pairwise_sq_l2(queries: jax.Array, data: jax.Array,
                          data_ids: jax.Array,
                          accum_dtype=jnp.float32) -> jax.Array:
    """Like :func:`pairwise_sq_l2` but padded points get +inf distance.

    Padding replaces the reference's uneven-remainder shards
    (engine.cpp:62-63,136-137): XLA wants uniform shapes, so shards are
    padded to a common size and padded slots — marked by the id = -1
    sentinel — are pushed to the end of any distance ordering with +inf.
    """
    d = pairwise_sq_l2(queries, data, accum_dtype)
    return jnp.where(data_ids[None, :] < 0, jnp.inf, d)
