"""Per-block coarse summaries + sound bound-based scan pruning.

Stage 0/1 of the pruned two-stage solve (ROADMAP "Beyond-HBM corpus").
The fused megakernel (PR 8) made the hot path one HBM pass, but every
solve still *scans the entire corpus*: on a beyond-HBM corpus the
host->device streaming of never-competitive chunks dominates wall
clock. This module proves most blocks cannot enter the top-k *before
their bytes ever move*:

- **Stage 0 (build)** — :func:`build_summaries`: per extract-chunk-
  aligned block, the row-norm band [min |x|, max |x|] and the
  per-attribute coordinate bounding box [lo_a, hi_a]. O(n*a) once at
  staging (batch) or ingest (serve), O(blocks * a) to keep — tiny
  next to the corpus, so serving keeps them device-resident
  (:func:`stage_summaries`) while the corpus itself may live in host
  DRAM.
- **Stage 1 (prune)** — :func:`prune_mask` (host f64, the batch
  engines) / :func:`score_blocks` (jitted f32 over the resident
  summaries, the serving engine): a sound per-(query, block) distance
  LOWER bound — ``max(norm-band, box)`` with
  ``|q - x|^2 >= (|q| - |x|)^2`` and the kd-tree box gap — is compared
  against a per-query UPPER bound on the k-th-best distance, obtained
  by accumulating per-block *upper* bounds (farthest box corner ∩
  norm sum) in ascending order until >= k real rows are covered: at
  least k points provably sit within that radius, so it dominates the
  true k-th distance. A block is pruned only when its lower bound
  clears the threshold by MORE than the staging-eps margin
  (:func:`dmlp_tpu.engine.finalize.staging_eps` — the same calibrated
  bound the exact pipeline already trusts for truncation hazards),
  which covers every staging-dtype/f32 perturbation on either side of
  the comparison. Soundness over threshold-tightness: a pruned block
  provably holds no row of any query's true float64 top-k (strict
  inequality, so (dist asc, id desc) tie-breaks cannot resurrect one),
  hence the survivors-only exact stage — candidates -> f64 finalize ->
  boundary repair, all unchanged — stays byte-identical to the dense
  scan and to the golden oracle.

The threshold accumulation subsumes single-seed-block seeding (the
minimum over any one block's upper bound is one term of the running
min); the serving engine still reports its cross-request winner
histogram's hottest block as ``seed_block`` so operators can see which
block anchors the threshold.

Kill switch: ``DMLP_TPU_PRUNE=0`` disables pruning everywhere
(mirroring ``DMLP_TPU_FUSED``); the engines additionally gate on the
resilience ladder's top ``lowp``/``prune`` rungs (resilience.degrade)
and on exact mode — fast mode's output IS the device ordering and has no
repair backstop, so it always scans densely.

The scoring pass has its own tune-cache namespace (``prune_score``,
:data:`PRUNE_KERNEL`): :func:`resolve_score_variant` reads a measured
entry for the block-chunk tiling when one exists and otherwise uses
the deterministic default, exactly the extract/fused resolution
contract. Import-light: jax loads only when the device scorer is
actually used.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dmlp_tpu.engine.finalize import lowp_eps, staging_eps

#: tune-cache namespace of the block-scoring pass (dmlp_tpu.tune)
PRUNE_KERNEL = "prune_score"

#: sub-block pieces per block (median split on the max-spread
#: attribute). Whole-block boxes go VACUOUS on uniform corpora — every
#: block's box is the full cube, every box gap is 0, every norm band
#: straddles the query norm — so block-level pruning is geometrically
#: impossible there. Two pieces make each box a half-cube: queries in
#: the other half see a strictly positive gap, and the per-piece
#: (count, upper-bound) entries sharpen the k-th threshold
#: accumulation. 2 (not 4/8) keeps the summary footprint ~3x the
#: whole-block one while already breaking the vacuous regime; the
#: device scorer (score_blocks) deliberately stays whole-block — the
#: serving micro-batch path is latency-bound on the scoring jit, and
#: whole-block bounds are a sound (merely looser) fallback.
PIECES = 2

#: default host-scoring block chunk (blocks per vectorized slab) when
#: no measured prune_score variant pins one: bounds the (Q, chunk, A)
#: f64 temp at ~tens of MB for bench-scale query counts
_SCORE_BLOCK_CHUNK = 128


def prune_enabled() -> bool:
    """The prune-path kill switch ($DMLP_TPU_PRUNE=0 disables) — read
    per call so tests and operators can flip it without re-imports."""
    return os.environ.get("DMLP_TPU_PRUNE", "1") != "0"


def resolve_score_variant(n_blocks: int, a: int) -> dict:
    """Scoring-pass tiling: the measured ``prune_score`` tune-cache
    entry when one exists (its ``tile_q`` is the host block-chunk),
    else the deterministic default — an absent cache is bit-identical
    CI, the shared resolution contract of every tuned kernel."""
    from dmlp_tpu.tune import lookup_variant
    cached = lookup_variant(8, n_blocks, a=a, kernel=PRUNE_KERNEL)
    if cached is not None:
        return dict(cached)
    return {"tile_q": _SCORE_BLOCK_CHUNK, "ne": 1, "unroll": 1}


@dataclasses.dataclass
class BlockSummaries:
    """Coarse per-block summaries over contiguous global row ranges.

    ``ranges[b] = (lo, hi)`` is block b's real-row span (hi <= n; empty
    blocks carry count 0 and can never survive pruning). Norms are L2
    (not squared); boxes are closed per-attribute intervals. All f64 —
    the bounds must dominate the golden model's float64 distances.
    """

    ranges: List[Tuple[int, int]]
    counts: np.ndarray        # (B,)   int64 real rows per block
    nmin: np.ndarray          # (B,)   f64 min row norm (+inf if empty)
    nmax: np.ndarray          # (B,)   f64 max row norm (-inf if empty)
    lo: np.ndarray            # (B, A) f64 box lower (+inf if empty)
    hi: np.ndarray            # (B, A) f64 box upper (-inf if empty)
    # Optional 2-piece split summaries (PIECES; None = whole-block
    # only, the pre-split format — every consumer falls back):
    pcounts: Optional[np.ndarray] = None  # (B, P)    int64 rows/piece
    pnmin: Optional[np.ndarray] = None    # (B, P)    f64 min piece norm
    pnmax: Optional[np.ndarray] = None    # (B, P)    f64 max piece norm
    plo: Optional[np.ndarray] = None      # (B, P, A) f64 piece box lower
    phi: Optional[np.ndarray] = None      # (B, P, A) f64 piece box upper
    # Per-block norm median (L2, not squared) + the EXACT count of rows
    # at or below it — a disjoint (near-half, farther-half) norm split
    # that tightens the k-th threshold independently of the box split:
    nq50: Optional[np.ndarray] = None     # (B,) f64 (+inf if empty)
    nq50_cnt: Optional[np.ndarray] = None  # (B,) int64 rows with
    #                                        norm <= nq50

    @property
    def n_blocks(self) -> int:
        return len(self.ranges)

    @property
    def nbytes(self) -> int:
        base = (self.counts.nbytes + self.nmin.nbytes + self.nmax.nbytes
                + self.lo.nbytes + self.hi.nbytes)
        for extra in (self.pcounts, self.pnmin, self.pnmax, self.plo,
                      self.phi, self.nq50, self.nq50_cnt):
            if extra is not None:
                base += extra.nbytes
        return base


def summarize_rows(rows: np.ndarray, na: int):
    """(count, nmin, nmax, lo, hi) of one block's real rows — the ONE
    reduction both the full build and the per-block ingest rebuild run,
    so they cannot drift."""
    m = rows.shape[0]
    if m == 0:
        return 0, np.inf, -np.inf, np.full(na, np.inf), np.full(na, -np.inf)
    r = np.asarray(rows, np.float64)
    norms = np.sqrt(np.einsum("ia,ia->i", r, r))
    return (m, float(norms.min()), float(norms.max()),
            r.min(axis=0), r.max(axis=0))


def split_rows(rows: np.ndarray, na: int):
    """Piece-level summaries of one block: a median split on the
    max-spread attribute (the kd-tree step that costs one O(m) pass),
    plus the norm median and its EXACT cover count.

    Returns ``(pieces, nq50, nq50_cnt)`` where ``pieces`` is a PIECES-
    list of summarize_rows tuples. Any partition of the rows is sound
    (piece bounds only ever describe real rows of the piece), so the
    degenerate split — every row equal on the chosen attribute — just
    halves by position. Empty blocks yield empty pieces."""
    r = np.asarray(rows, np.float64)
    m = r.shape[0]
    if m == 0:
        empty = summarize_rows(r, na)
        return [empty] * PIECES, np.inf, 0
    norms = np.sqrt(np.einsum("ia,ia->i", r, r))
    nq50 = float(np.quantile(norms, 0.5))
    nq50_cnt = int((norms <= nq50).sum())
    spread = r.max(axis=0) - r.min(axis=0)
    ax = int(np.argmax(spread))
    left = r[:, ax] <= float(np.median(r[:, ax]))
    if left.all() or not left.any():
        left = np.arange(m) < (m // 2)
    pieces = [summarize_rows(r[left], na), summarize_rows(r[~left], na)]
    return pieces, nq50, nq50_cnt


def build_summaries(attrs: np.ndarray,
                    ranges: Sequence[Tuple[int, int]],
                    pieces: int = PIECES) -> BlockSummaries:
    """Stage 0: summaries for ``attrs`` over ``ranges`` (one O(n*a)
    pass; blocks whose span is empty or past the data end count 0).
    ``pieces`` <= 1 builds the whole-block-only format (pre-split
    consumers, and A/B baselines for the split's win).

    ``attrs`` is NOT cast wholesale: a beyond-HBM corpus is held f32 on
    host precisely because an f64 copy would double host memory
    (tools/capacity_beyond_hbm.py), so only the per-block slice inside
    summarize_rows pays the f64 conversion — O(block_rows * a) extra,
    never O(n * a)."""
    attrs = np.asarray(attrs)
    n, na = attrs.shape if attrs.ndim == 2 else (0, 1)
    nb = len(ranges)
    counts = np.zeros(nb, np.int64)
    nmin = np.full(nb, np.inf)
    nmax = np.full(nb, -np.inf)
    lo = np.full((nb, na), np.inf)
    hi = np.full((nb, na), -np.inf)
    split = pieces > 1
    pcounts = np.zeros((nb, PIECES), np.int64) if split else None
    pnmin = np.full((nb, PIECES), np.inf) if split else None
    pnmax = np.full((nb, PIECES), -np.inf) if split else None
    plo = np.full((nb, PIECES, na), np.inf) if split else None
    phi = np.full((nb, PIECES, na), -np.inf) if split else None
    nq50 = np.full(nb, np.inf) if split else None
    nq50_cnt = np.zeros(nb, np.int64) if split else None
    for b, (blo, bhi) in enumerate(ranges):
        blo, bhi = max(blo, 0), min(bhi, n)
        rows = attrs[blo:bhi]
        counts[b], nmin[b], nmax[b], lo[b], hi[b] = summarize_rows(
            rows, na)
        if split:
            pc, nq50[b], nq50_cnt[b] = split_rows(rows, na)
            for p, (cm, cn, cx, cl, ch) in enumerate(pc):
                pcounts[b, p], pnmin[b, p], pnmax[b, p] = cm, cn, cx
                plo[b, p], phi[b, p] = cl, ch
    return BlockSummaries(list((int(a), int(b)) for a, b in ranges),
                          counts, nmin, nmax, lo, hi,
                          pcounts, pnmin, pnmax, plo, phi,
                          nq50, nq50_cnt)


def update_block(summ: BlockSummaries, b: int, rows: np.ndarray,
                 lo_hi: Optional[Tuple[int, int]] = None) -> None:
    """Rebuild exactly block ``b`` from its CURRENT real rows (the
    serving ingest path: a ``dynamic_update_slice`` row append must
    invalidate/rebuild the touched blocks' summaries — a stale summary
    is silent unsoundness, the one failure mode pruning cannot repair
    after the fact). Piece summaries (when the format carries them)
    rebuild in the same call, for the same reason."""
    if lo_hi is not None:
        summ.ranges[b] = (int(lo_hi[0]), int(lo_hi[1]))
    na = summ.lo.shape[1]
    rows = np.asarray(rows, np.float64)
    (summ.counts[b], summ.nmin[b], summ.nmax[b],
     summ.lo[b], summ.hi[b]) = summarize_rows(rows, na)
    if summ.pcounts is not None:
        pc, summ.nq50[b], summ.nq50_cnt[b] = split_rows(rows, na)
        for p, (cm, cn, cx, cl, ch) in enumerate(pc):
            summ.pcounts[b, p], summ.pnmin[b, p], summ.pnmax[b, p] = \
                cm, cn, cx
            summ.plo[b, p], summ.phi[b, p] = cl, ch


def block_bounds(queries: np.ndarray, summ: BlockSummaries,
                 block_chunk: Optional[int] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-(query, block) distance bounds, f64: ``lb[q, b]`` a LOWER
    bound on the squared distance from query q to ANY real row of
    block b (max of the norm-band and box-gap bounds), ``ub[q, b]`` an
    UPPER bound on the squared distance to EVERY real row (min of the
    farthest-box-corner and norm-sum bounds; +inf for empty blocks).
    Chunked over blocks so the (Q, chunk, A) temp stays bounded."""
    q = np.asarray(queries, np.float64)
    nq, na = q.shape
    nb = summ.n_blocks
    qnorm = np.sqrt(np.einsum("qa,qa->q", q, q))
    lb = np.empty((nq, nb))
    ub = np.empty((nq, nb))
    chunk = block_chunk or resolve_score_variant(nb, na)["tile_q"]
    for b0 in range(0, nb, chunk):
        b1 = min(b0 + chunk, nb)
        nmin, nmax = summ.nmin[b0:b1], summ.nmax[b0:b1]
        band = np.maximum(nmin[None, :] - qnorm[:, None],
                          qnorm[:, None] - nmax[None, :])
        lbn = np.square(np.maximum(band, 0.0))
        dlo = summ.lo[None, b0:b1] - q[:, None, :]
        dhi = q[:, None, :] - summ.hi[None, b0:b1]
        gap = np.maximum(np.maximum(dlo, dhi), 0.0)
        lbb = np.einsum("qba,qba->qb", gap, gap)
        lb[:, b0:b1] = np.maximum(lbn, lbb)
        far = np.maximum(np.abs(q[:, None, :] - summ.lo[None, b0:b1]),
                         np.abs(q[:, None, :] - summ.hi[None, b0:b1]))
        ubb = np.einsum("qba,qba->qb", far, far)
        ub[:, b0:b1] = np.minimum(
            ubb, np.square(qnorm[:, None] + nmax[None, :]))
    empty = summ.counts <= 0
    lb[:, empty] = np.inf
    ub[:, empty] = np.inf
    return lb, ub


def piece_bounds(queries: np.ndarray, summ: BlockSummaries,
                 block_chunk: Optional[int] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-(query, block, piece) bounds, f64: the block_bounds formulas
    over the PIECE norm bands / boxes. ``plb[q, b, p]`` lower-bounds
    the squared distance to any real row of piece p, ``pub`` upper-
    bounds it to every row (+inf for empty pieces). On a uniform
    corpus the whole-block gap is identically 0 while the half-cube
    piece gap is positive for every query in the other half — the
    non-vacuity the split buys. Requires the split format
    (``summ.pcounts is not None``)."""
    q = np.asarray(queries, np.float64)
    nq_, na = q.shape
    nb = summ.n_blocks
    npieces = summ.pcounts.shape[1]
    qnorm = np.sqrt(np.einsum("qa,qa->q", q, q))
    plb = np.empty((nq_, nb, npieces))
    pub = np.empty((nq_, nb, npieces))
    # Same chunking as block_bounds, halved: the (Q, chunk, P, A) temp
    # is P times the whole-block slab.
    chunk = block_chunk or max(
        1, resolve_score_variant(nb, na)["tile_q"] // npieces)
    for b0 in range(0, nb, chunk):
        b1 = min(b0 + chunk, nb)
        nmin, nmax = summ.pnmin[b0:b1], summ.pnmax[b0:b1]   # (c, P)
        band = np.maximum(nmin[None] - qnorm[:, None, None],
                          qnorm[:, None, None] - nmax[None])
        lbn = np.square(np.maximum(band, 0.0))
        dlo = summ.plo[None, b0:b1] - q[:, None, None, :]
        dhi = q[:, None, None, :] - summ.phi[None, b0:b1]
        gap = np.maximum(np.maximum(dlo, dhi), 0.0)
        lbb = np.einsum("qbpa,qbpa->qbp", gap, gap)
        plb[:, b0:b1] = np.maximum(lbn, lbb)
        far = np.maximum(
            np.abs(q[:, None, None, :] - summ.plo[None, b0:b1]),
            np.abs(q[:, None, None, :] - summ.phi[None, b0:b1]))
        ubb = np.einsum("qbpa,qbpa->qbp", far, far)
        pub[:, b0:b1] = np.minimum(
            ubb, np.square(qnorm[:, None, None] + nmax[None]))
    emptyp = summ.pcounts <= 0
    plb[:, emptyp] = np.inf
    pub[:, emptyp] = np.inf
    return plb, pub


def kth_thresholds(ub: np.ndarray, counts: np.ndarray,
                   ks: np.ndarray) -> np.ndarray:
    """Per-query upper bound on the true k-th-best squared distance:
    accumulate block upper bounds ascending until >= k real rows are
    covered — at least k points then provably sit within the last
    accumulated bound. +inf when the corpus holds fewer than k rows
    (nothing may be pruned: every real point is in the top-k)."""
    ks = np.asarray(ks, np.int64)
    order = np.argsort(ub, axis=1, kind="stable")
    sub = np.take_along_axis(ub, order, axis=1)
    csum = np.cumsum(np.asarray(counts, np.int64)[order], axis=1)
    reached = csum >= ks[:, None]
    idx = np.argmax(reached, axis=1)
    thr = np.take_along_axis(sub, idx[:, None], axis=1)[:, 0]
    return np.where(reached.any(axis=1), thr, np.inf)


def prune_mask(queries: np.ndarray, ks: np.ndarray,
               summ: BlockSummaries, *, staging: str = "float32",
               precision: str = "f32") -> Tuple[np.ndarray, Dict]:
    """Stage 1 on host (f64): the survivor mask over ``summ``'s blocks
    for this query batch, plus a stats record.

    Block b is pruned iff for EVERY query q
    ``lb(q, b) > thr(q) + eps(q)`` — strictly above the k-th-best
    upper bound widened by the staging-eps margin
    (engine.finalize.staging_eps, evaluated at the threshold), which
    dominates both the f64 rounding of the bound arithmetic and the
    staging-dtype/f32 perturbation of any distance the exact stage
    will later compare. A "bf16" first pass (engine "lowp" rung)
    additionally widens eps by the finalize.lowp_eps cast bound: the
    survivor scan's device distances then err by cast + staging, and a
    pruned block must clear both. By construction at least one block
    survives per query with a finite threshold (the block/piece
    anchoring the threshold bounds itself), so a schedule is never
    empty.

    With the split format, three INDEPENDENTLY sound k-th thresholds
    combine by elementwise min — block-level, per-piece, and the
    per-block norm split ((nq50_cnt rows within (|q| + nq50)^2, the
    rest within the block ub); each accumulates DISJOINT row groups,
    which the accumulation requires (overlapping groups would double-
    count coverage) — and the block lower bound sharpens to the max of
    the whole-box bound and the min over its pieces' bounds.
    """
    q = np.asarray(queries, np.float64)
    na = q.shape[1]
    lb, ub = block_bounds(q, summ)
    thr = kth_thresholds(ub, summ.counts, ks)
    plb = None
    if summ.pcounts is not None:
        plb, pub = piece_bounds(q, summ)
        lb = np.maximum(lb, plb.min(axis=2))
        thr = np.minimum(thr, kth_thresholds(
            pub.reshape(len(q), -1), summ.pcounts.reshape(-1), ks))
        qnorm = np.sqrt(np.einsum("qa,qa->q", q, q))
        near = np.square(qnorm[:, None] + summ.nq50[None, :])
        thr = np.minimum(thr, kth_thresholds(
            np.concatenate([near, ub], axis=1),
            np.concatenate([summ.nq50_cnt,
                            summ.counts - summ.nq50_cnt]), ks))
    live = summ.counts > 0
    dn_max = float(np.square(summ.nmax[live]).max()) if live.any() else 0.0
    qn = np.einsum("qa,qa->q", q, q)
    eps = staging_eps(thr, qn, dn_max, staging, na) \
        + lowp_eps(precision, qn, dn_max)
    keep = lb <= (thr + eps)[:, None]
    survivors = live & keep.any(axis=0)
    total = int(live.sum())
    pruned = int(total - int((survivors & live).sum()))
    stats = {
        "blocks_total": total,
        "blocks_pruned": pruned,
        "pruned_fraction": round(pruned / total, 6) if total else 0.0,
        "summary_bytes": int(summ.nbytes),
    }
    if plb is not None:
        # Non-vacuity meter of the split: fraction of (query, live
        # piece) pairs whose lower bound is strictly positive. On a
        # uniform corpus the whole-block version of this is provably
        # 0.0 (full-cube boxes, straddled norm bands); the half-cube
        # pieces keep it > 0, which tests/test_prune assert.
        livep = (summ.pcounts > 0).reshape(-1)
        flat = plb.reshape(len(q), -1)[:, livep]
        stats["lb_positive_fraction"] = (
            round(float((flat > 0.0).mean()), 6) if flat.size else 0.0)
    return survivors, stats


# -- device scoring (the serving engine's resident-summary pass) --------------

def stage_summaries(summ: BlockSummaries):
    """Stage conservative f32 copies of the summaries to device (tiny:
    O(blocks * a)). Directed rounding keeps the cast sound: box lows
    and norm minima round DOWN, box highs and norm maxima round UP, so
    the f32 box/band always CONTAINS the f64 one — the device lower
    bounds can only get looser, never unsound; the residual f32
    arithmetic error of the scorer itself is the eps margin's job."""
    import jax

    def _dir(x, up: bool):
        x32 = np.asarray(x, np.float32)
        back = x32.astype(np.float64)
        bad = (back < x) if up else (back > x)
        adj = np.nextafter(x32, np.float32(np.inf if up else -np.inf))
        return np.where(bad, adj, x32).astype(np.float32)

    live = summ.counts > 0
    dn_max = float(np.square(summ.nmax[live]).max()) if live.any() else 0.0
    return {
        "counts": jax.device_put(np.asarray(summ.counts, np.int32)),
        "nmin": jax.device_put(_dir(summ.nmin, up=False)),
        "nmax": jax.device_put(_dir(summ.nmax, up=True)),
        "lo": jax.device_put(_dir(summ.lo, up=False)),
        "hi": jax.device_put(_dir(summ.hi, up=True)),
        "dn_max": jax.device_put(_dir(np.float64(dn_max), up=True)),
    }


_score_jit = None


def score_blocks(q, qvalid, ks, counts, nmin, nmax, lo, hi, dn_max,
                 eps_rel, eps_cancel):
    """Stage 1 on device (jitted, f32): the survivor mask over the
    RESIDENT summaries for one padded micro-batch — the serving
    engine's per-request scoring pass, compiled once per (qpad,
    blocks) bucket shape. Same bound/threshold/eps structure as
    :func:`prune_mask`; ``qvalid`` masks bucket-padding queries out of
    the survivor union, ``eps_rel`` / ``eps_cancel`` are the
    staging-eps constants pre-scaled on host (rel and
    EPS_CANCEL_COEF * (na + 2)). Returns the (B,) bool survivor mask.
    """
    global _score_jit
    if _score_jit is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _score(q, qvalid, ks, counts, nmin, nmax, lo, hi, dn_max,
                   eps_rel, eps_cancel):
            q32 = q.astype(jnp.float32)
            qn = jnp.einsum("qa,qa->q", q32, q32)
            qnorm = jnp.sqrt(qn)
            band = jnp.maximum(nmin[None, :] - qnorm[:, None],
                               qnorm[:, None] - nmax[None, :])
            lbn = jnp.square(jnp.maximum(band, 0.0))
            gap = jnp.maximum(jnp.maximum(lo[None] - q32[:, None, :],
                                          q32[:, None, :] - hi[None]),
                              0.0)
            lbb = jnp.einsum("qba,qba->qb", gap, gap)
            far = jnp.maximum(jnp.abs(q32[:, None, :] - lo[None]),
                              jnp.abs(q32[:, None, :] - hi[None]))
            ubb = jnp.einsum("qba,qba->qb", far, far)
            ub = jnp.minimum(ubb,
                             jnp.square(qnorm[:, None] + nmax[None, :]))
            empty = counts <= 0
            ub = jnp.where(empty[None, :], jnp.inf, ub)
            lb = jnp.where(empty[None, :], jnp.inf,
                           jnp.maximum(lbn, lbb))
            order = jnp.argsort(ub, axis=1)
            sub = jnp.take_along_axis(ub, order, axis=1)
            csum = jnp.cumsum(counts[order], axis=1)
            reached = csum >= ks[:, None]
            idx = jnp.argmax(reached, axis=1)
            thr = jnp.where(
                reached.any(axis=1),
                jnp.take_along_axis(sub, idx[:, None], axis=1)[:, 0],
                jnp.inf)
            scale = qn + dn_max
            eps = (eps_rel * jnp.sqrt(jnp.maximum(thr, 0.0) * scale)
                   + eps_cancel * scale)
            keep = qvalid[:, None] & (lb <= (thr + eps)[:, None])
            return keep.any(axis=0) & ~empty

        _score_jit = _score
    return _score_jit(q, qvalid, ks, counts, nmin, nmax, lo, hi,
                      dn_max, eps_rel, eps_cancel)


# -- scan accounting (shared by every chunked driver) -------------------------

def note_scan(engine, *, scanned_bytes: int, dense_bytes: int,
              blocks_total: int, blocks_pruned: int) -> None:
    """Fold one solve's scanned-bytes accounting into
    ``engine.last_prune`` and the live telemetry registry — the
    ledgered counters the A/B harness and the OpenMetrics scrape read
    (``scan.bytes_streamed`` / ``prune.blocks_pruned`` /
    ``prune.gated_fraction``). Dense solves record too (blocks_pruned
    0), so the pruned-vs-dense byte ratio is computable from either
    arm's artifact.

    ``scanned_bytes`` counts CORPUS rows read from host memory for
    scanning. On the single-chip and serve paths that equals the
    host->device traffic saved (pruned chunks are never staged); on
    the mesh path a partially-pruned chunk still ships its fixed-shape
    sharded buffer (zero-filled pieces included) — only chunks every
    shard pruned skip the link there, so mesh scanned_bytes measures
    host DRAM reads, not wire bytes."""
    from dmlp_tpu.obs import telemetry
    rec = engine.last_prune if isinstance(
        getattr(engine, "last_prune", None), dict) else {}
    rec.update(blocks_total=int(blocks_total),
               blocks_pruned=int(blocks_pruned),
               scanned_bytes=int(scanned_bytes),
               dense_bytes=int(dense_bytes))
    rec["pruned_fraction"] = (round(blocks_pruned / blocks_total, 6)
                              if blocks_total else 0.0)
    engine.last_prune = rec
    try:
        reg = telemetry.registry()
        reg.counter("scan.bytes_streamed").inc(int(scanned_bytes))
        reg.counter("prune.blocks_total").inc(int(blocks_total))
        reg.counter("prune.blocks_pruned").inc(int(blocks_pruned))
        reg.gauge("prune.gated_fraction").set(rec["pruned_fraction"])
    except Exception:  # observability never fails a solve (ops/ is
        pass           # outside the R501 resilience scope: no directive)
