"""Exact-tie-break k-selection and blockwise merge.

The correctness contract is order-sensitive (checksums, survey §4). The
MEASURED oracle-binary comparator (r5: the binaries ran in-container and
were fuzzed on tie-adversarial inputs, golden.reference docstring /
TIE_SEMANTICS_r05.json) breaks both selection and report ties to the
**larger id**, label-free. ``jax.lax.top_k`` breaks ties by lowest index,
so it cannot express this; instead selection is a multi-operand
``jax.lax.sort`` over the composite key

    (distance asc, id desc)

— a strict total order (see dmlp_tpu.golden.reference).
Totality is what makes blockwise selection exact: top-k of a union equals
top-k of concatenated per-block top-k's, so the same primitive implements the
local select (engine.cpp:249-256), the root merge (engine.cpp:300-307), the
sharded all-gather merge, and the ring running merge.
"""

from __future__ import annotations

from typing import NamedTuple

import functools

import jax
import jax.numpy as jnp


def streaming_fallback(use_pallas: bool) -> str:
    """The array-ids selection strategy used wherever the extraction
    kernel cannot run (it needs affine per-block ids): the fused seg
    producer with Pallas, plain lax.top_k without. Single definition —
    config.resolve_streaming_select and streaming_topk both use it."""
    return "seg" if use_pallas else "topk"


class TopK(NamedTuple):
    """Per-query candidate lists, sorted by the selection order.

    Shapes are (..., k). Padding entries carry dist=+inf, label=-1, id=-1.
    """

    dists: jax.Array   # float
    labels: jax.Array  # int32
    ids: jax.Array     # int32


def select_topk(dists: jax.Array, labels: jax.Array, ids: jax.Array,
                k: int) -> TopK:
    """Select the k best (dist asc, id desc) along the last axis — the
    MEASURED oracle-binary comparator (label-free; golden.reference
    docstring / TIE_SEMANTICS_r05.json), identical to the report order.

    ``labels``/``ids`` broadcast against ``dists`` (e.g. (N,) vs (Q, N)).
    If k exceeds the axis size, results are padded with (+inf, -1, -1).
    """
    labels = jnp.broadcast_to(labels, dists.shape)
    ids = jnp.broadcast_to(ids, dists.shape)
    n = dists.shape[-1]
    if k > n:
        pad = k - n
        shape = dists.shape[:-1] + (pad,)
        dists = jnp.concatenate(
            [dists, jnp.full(shape, jnp.inf, dists.dtype)], axis=-1)
        labels = jnp.concatenate(
            [labels, jnp.full(shape, -1, labels.dtype)], axis=-1)
        ids = jnp.concatenate([ids, jnp.full(shape, -1, ids.dtype)], axis=-1)
    # Ascending lexicographic sort on (dist, -id): exactly the selection
    # total order; labels ride as payload. num_keys=2 (was 3 when
    # selection was label-aware) keeps everything int32/f32 (no x64).
    sd, _, sl, si = jax.lax.sort(
        (dists, -ids, labels, ids), num_keys=2, dimension=-1)
    return TopK(sd[..., :k], sl[..., :k], si[..., :k])


def merge_topk(a: TopK, b: TopK, k: int) -> TopK:
    """Merge two candidate lists into the k best — the root-merge analog
    (engine.cpp:289-308), also the ring engine's running-reduction step."""
    return select_topk(
        jnp.concatenate([a.dists, b.dists], axis=-1),
        jnp.concatenate([a.labels, b.labels], axis=-1),
        jnp.concatenate([a.ids, b.ids], axis=-1),
        k)


def streaming_topk(query_attrs: jax.Array, data_attrs: jax.Array,
                   data_labels: jax.Array, data_ids: jax.Array, k: int,
                   data_block: int, accum_dtype=jnp.float32,
                   select: str = "sort", use_pallas: bool = False) -> TopK:
    """Top-k nearest data points per query, streaming over data blocks.

    Computes (Qb x data_block) distance tiles one block at a time and folds
    each into a running top-k, so peak memory is O(Qb * (data_block + k))
    instead of O(Qb * N) — the blockwise-partial-reduce shape the reference
    implements across ranks (survey §5.7), here as a ``lax.scan`` on one chip
    (and reused per-shard by the distributed engines).

    ``data_attrs`` must be padded to a multiple of ``data_block`` with
    sentinel rows (id = -1); real N may be smaller.

    ``select`` picks the per-step merge: "sort" is the strict total order
    (reference tie semantics on device); "topk" is a ``lax.top_k`` partial
    reduce — ~4x faster on TPU, exact by distance, but distance ties keep
    the lowest *position* instead of the reference's larger-id
    preference. That matters only when a tie group straddles the candidate
    boundary k: the kept candidates may then exclude the preferred ones, a
    loss no downstream rescore can undo. Engines detect that hazard on host
    (dmlp_tpu.engine.finalize.boundary_overflow) and recompute affected
    queries exactly, so either path yields golden parity.
    """
    n = data_attrs.shape[0]
    assert n % data_block == 0, "pad data to a multiple of data_block first"
    nblocks = n // data_block
    qb = query_attrs.shape[0]

    if select == "extract":
        # The extraction kernel needs affine ids; this generic streaming
        # fold gets arbitrary id arrays, so apply the shared array-ids
        # fallback policy (config.resolve_streaming_select delegates to
        # the same function — one definition, no drift).
        select = streaming_fallback(use_pallas)

    blocks = (data_attrs.reshape(nblocks, data_block, -1),
              data_labels.reshape(nblocks, data_block),
              data_ids.reshape(nblocks, data_block))

    init = init_topk(qb, k, accum_dtype)
    if select == "seg" and (data_block % 128 != 0 or data_block < 256):
        select = "topk"  # seg needs whole 128-lane segments to pay off
    step = make_block_step(select, k, use_pallas, accum_dtype)

    out, _ = jax.lax.scan(
        lambda carry, blk: (step(carry, query_attrs, *blk), None),
        init, blocks)
    return out


@functools.partial(jax.jit, static_argnames=("qb", "k", "accum_dtype"))
def init_topk(qb: int, k: int, accum_dtype=jnp.float32) -> TopK:
    """Empty running top-k carry: all slots (+inf, -1, -1).

    Jitted (all-static args, one cached constant program per shape) so
    the eager chunk drivers can build carries under the sanitizer's
    transfer guard — eager ``jnp.full`` materializes its fill value via
    an implicit host->device transfer, which ``--sanitize`` disallows.
    """
    return TopK(
        jnp.full((qb, k), jnp.inf, accum_dtype),
        jnp.full((qb, k), -1, jnp.int32),
        jnp.full((qb, k), -1, jnp.int32))


def make_block_step(select: str, k: int, use_pallas: bool = False,
                    accum_dtype=jnp.float32):
    """One running-top-k fold step: (carry, queries, block) -> carry.

    Shared by the in-jit ``lax.scan`` (streaming_topk) and the pipelined
    per-chunk driver (engine.single), which dispatches one step per data
    chunk so host->device chunk transfers overlap the previous chunk's
    compute — the TPU-native replacement for the reference's synchronous
    Scatterv-then-compute phasing (engine.cpp:62-131 then :233-257).
    """
    from dmlp_tpu.ops.distance import masked_pairwise_sq_l2

    def step_sort(carry: TopK, query_attrs, battrs, blabels, bids):
        tile = masked_pairwise_sq_l2(query_attrs, battrs, bids, accum_dtype)
        cand = TopK(tile,
                    jnp.broadcast_to(blabels[None, :], tile.shape),
                    jnp.broadcast_to(bids[None, :], tile.shape))
        return merge_topk(carry, cand, k)

    def merge_cand(carry_, cand_d, cand_l, cand_i):
        """top_k over carry + candidate columns -> (Qb, k) TopK."""
        alld = jnp.concatenate([carry_.dists, cand_d], axis=-1)
        negd, idx = jax.lax.top_k(-alld, k)
        from_carry = idx < k
        cidx = jnp.minimum(idx, k - 1)
        bidx = jnp.maximum(idx - k, 0)
        labels_ = jnp.where(
            from_carry, jnp.take_along_axis(carry_.labels, cidx, axis=-1),
            jnp.take_along_axis(cand_l, bidx, axis=-1))
        ids_ = jnp.where(
            from_carry, jnp.take_along_axis(carry_.ids, cidx, axis=-1),
            jnp.take_along_axis(cand_i, bidx, axis=-1))
        return TopK(-negd, labels_, ids_)

    def step_seg(carry: TopK, query_attrs, battrs, blabels, bids):
        """Segment-min threshold selection (select="seg").

        Exact tile top-k with ~B/128 of the sort work: reduce the tile to
        per-128-column segment minima, pick the S = k+16 smallest-min
        segments (every true tile-top-k point lives in a segment whose min
        is <= the k-th smallest segment min T — if one didn't, >= k segments
        with min < its distance would each contribute a closer point), and
        run the real top_k on just the gathered S*128 candidates. When the
        S-th selected min still ties T (more eligible segments may exist
        beyond S — duplicate-heavy data), a lax.cond falls back to the full
        top_k for that step, so the result is always the exact per-tile
        top-k by distance.
        """
        from dmlp_tpu.ops.pallas_distance import (fused_dist_segmin,
                                                  native_pallas_backend,
                                                  supports)
        if use_pallas and supports(query_attrs.shape[0], battrs.shape[0],
                                   battrs.shape[1]):
            tile, segmin = fused_dist_segmin(
                query_attrs, battrs, bids,
                interpret=not native_pallas_backend())
        else:
            tile = masked_pairwise_sq_l2(query_attrs, battrs, bids,
                                         accum_dtype)
            segmin = None
        qb_, bcols = tile.shape
        nseg = bcols // 128
        s = min(nseg, k + 16)

        if segmin is None:
            segmin = tile.reshape(qb_, nseg, 128).min(axis=-1)
        neg_sel, seg_idx = jax.lax.top_k(-segmin, s)      # (Qb, S)
        sel_min = -neg_sel                                 # asc by segment min
        t = sel_min[:, min(k, s) - 1]
        hazard = (s < nseg) & jnp.any(
            jnp.isfinite(sel_min[:, -1]) & (sel_min[:, -1] <= t))

        def full(args):
            carry_, tile_, blabels_, bids_, _ = args
            return merge_cand(carry_, tile_,
                              jnp.broadcast_to(blabels_[None, :], tile_.shape),
                              jnp.broadcast_to(bids_[None, :], tile_.shape))

        def seg(args):
            carry_, tile_, blabels_, bids_, seg_idx_ = args
            # Gather whole 128-lane segments along the segment axis —
            # contiguous lanes, ~4x faster on TPU than a flat-index gather.
            # (A one-hot matmul gather measured ~8 ms faster at r3 but needs
            # a clamped tile copy + materialized one-hot at HIGHEST
            # precision — +12 GB peak HBM at the big-chunk shape — so the
            # plain gather wins overall.)
            t3 = tile_.reshape(qb_, nseg, 128)
            cand_d = jnp.take_along_axis(
                t3, seg_idx_[:, :, None], axis=1).reshape(qb_, s * 128)
            cand_l = blabels_.reshape(nseg, 128)[seg_idx_].reshape(
                qb_, s * 128)
            cand_i = bids_.reshape(nseg, 128)[seg_idx_].reshape(qb_, s * 128)
            return merge_cand(carry_, cand_d, cand_l, cand_i)

        if s == nseg:
            return full((carry, tile, blabels, bids, seg_idx))
        return jax.lax.cond(hazard, full, seg,
                            (carry, tile, blabels, bids, seg_idx))

    def step_topk(carry: TopK, query_attrs, battrs, blabels, bids):
        tile = masked_pairwise_sq_l2(query_attrs, battrs, bids, accum_dtype)
        alld = jnp.concatenate([carry.dists, tile], axis=-1)
        negd, idx = jax.lax.top_k(-alld, k)
        # Entry idx < k came from the carry, else from the block — gather
        # metadata from whichever side without materializing (Qb, B) labels.
        from_carry = idx < k
        cidx = jnp.minimum(idx, k - 1)
        bidx = jnp.maximum(idx - k, 0)
        new_labels = jnp.where(
            from_carry, jnp.take_along_axis(carry.labels, cidx, axis=-1),
            blabels[bidx])
        new_ids = jnp.where(
            from_carry, jnp.take_along_axis(carry.ids, cidx, axis=-1),
            bids[bidx])
        return TopK(-negd, new_labels, new_ids)

    if select not in ("sort", "topk", "seg"):
        raise ValueError(f"unknown select {select!r}")
    return {"sort": step_sort, "topk": step_topk, "seg": step_seg}[select]
