"""Device-side majority vote and report ordering.

Mirrors the reference's result post-processing (engine.cpp:314-347): a
majority label vote with tie -> larger label (:320-332) and the final
(distance asc, tie -> larger id) report sort (:334-338), both as jittable
batched ops so the full pipeline can stay on-device (the CLI parity path
instead finalizes on host in float64 — see dmlp_tpu.engine.single).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from dmlp_tpu.ops.topk import TopK


def majority_vote(labels: jax.Array, valid: jax.Array,
                  num_labels: int) -> jax.Array:
    """Majority vote per query with tie -> larger label.

    Args:
      labels: (Q, K) candidate labels (selection-ordered top-k lists).
      valid: (Q, K) bool — which candidates participate (first k_q real
        entries; padding/sentinel entries are False).
      num_labels: static upper bound (all labels < num_labels).

    Returns:
      (Q,) int32 predicted labels; -1 where no candidate is valid
      (the C++ initializer at engine.cpp:326).
    """
    onehot = jax.nn.one_hot(labels, num_labels, dtype=jnp.int32)
    counts = jnp.sum(onehot * valid[..., None].astype(jnp.int32), axis=-2)
    # argmax on the label-reversed counts finds, among maximal counts, the
    # largest label (argmax returns the first maximum).
    rev = counts[..., ::-1]
    predicted = num_labels - 1 - jnp.argmax(rev, axis=-1).astype(jnp.int32)
    any_valid = jnp.max(counts, axis=-1) > 0
    return jnp.where(any_valid, predicted, -1)


def report_order(topk: TopK, ks: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Mask each query's list to its own k and sort for reporting.

    ``topk`` lists are selection-ordered (dist asc, id desc), so
    the first k_q entries *are* query q's top-k_q; entries beyond k_q are
    invalidated (dist=+inf, id=-1) and the list re-sorted by the report order
    (dist asc, id desc). Returns (dists, ids, valid) with valid marking the
    first k_q slots of the report — the slots ``reportResult`` would print
    (padded slots print the -1 sentinel, common.cpp:66).
    """
    q, kmax = topk.ids.shape
    in_k = jnp.arange(kmax, dtype=ks.dtype)[None, :] < ks[:, None]
    d = jnp.where(in_k, topk.dists, jnp.inf)
    ids = jnp.where(in_k, topk.ids, -1)
    sd, _, sids = jax.lax.sort((d, -ids, ids), num_keys=2, dimension=-1)
    return sd, sids, in_k
