"""Pallas TPU kernel: fused distance + running top-k by iterative extraction.

The round-2 solve wrote every (Q, B) distance tile to HBM (8.4 GB at the
benchmark shape) and selected from it with segment-min + gather + lax.top_k
— measured on v5e the selection pipeline costs ~15x the distance matmul
(tools/profile_amortized.py). This kernel is the VERDICT-prescribed fix:
selection happens in VMEM while the distance block is still resident, so
the tile never exists in HBM at all.

Algorithm (replaces the reference's per-rank hot loop + nth_element,
engine.cpp:233-257, with a threshold-gated extraction):

- Grid (Qb/tq, B/tn); the (tq, kc) running top-k lives in the revisited
  output block (VMEM-resident across the data-block sweep, flash-attention
  style accumulator).
- Per data block: one MXU pass computes the (tq, tn) distance block into a
  VMEM scratch via the norm expansion |q-d|^2 = |q|^2 + |d|^2 - 2 q.d.
- A while-loop then extracts candidates: each iteration finds the minimum
  of each quarter of the block (4 candidates per row per pass), inserts
  those that beat the row's current k-th best (its threshold T = max of the
  running list) into the running list, and masks them out of the block.
  The loop ends when no row improved — for blocks that arrive after the
  running lists are warm, the expected number of iterations is ~1 + k*tn/N,
  so almost all blocks cost one scan, not a sort.
- Threshold-gated block skipping (ISSUE 3): before the loop, one cheap
  VPU reduction computes each row's block minimum; when no row's block
  min strictly beats its current threshold T (the same strict ``m < T``
  the extraction uses), the while-loop is skipped entirely (0 recorded
  iterations). A warm no-improve block then costs one (tq, tn) min pass
  instead of a full extraction round (ne argmin/insert/mask passes) —
  output-identical, because the skipped round could not have inserted
  anything. (This differs from the per-pass ``pl.when`` predication that
  measured SLOWER inside the loop — the gate is a single reduction
  before the loop, not predication of every pass.)

Variant selection (tile_q / tile_n / ne / unroll) resolves through the
measured autotuner cache (dmlp_tpu.tune) when an entry exists for this
(device kind, shape bucket, kc, dtype); otherwise the deterministic
kc-tuned heuristic below — an absent cache (CPU, CI) is bit-identical
to the pre-tuner behavior.

Ties are kept by lowest global position (strict `m < T` extraction +
lowest-lane argmin), i.e. the same semantics as the "topk"/"seg" selects;
the engines' boundary-overflow detection + host repair applies unchanged.

The kernel requires affine data ids: row j of `d` has global id
``id_base + j``, rows at positions >= n_real are sentinels (masked to +inf,
reported as id -1). Both are trace-time constants, which every engine
staging path satisfies (chunks/shards are contiguous global row ranges).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dmlp_tpu.utils.compat import tpu_compiler_params

from dmlp_tpu.ops.pallas_distance import _tile

# Swept on v5e at 204800 x 10240 x 64, kc=40 (r3): small query tiles win
# (the while loop runs max-over-rows extra iterations, so fewer rows per
# tile means fewer wasted passes) and two half-block minima per pass beat
# one or four. (128, 12800, 2) measured 68 ms vs 148 ms for the previous
# (512, 8192, 4) default.
_TQ = 128    # query rows per tile
_TN = 12800  # data rows per block
_E = 2       # extraction candidates per loop iteration (half-block minima)

# Public padding contract for callers (engine.single, bench): pad data to
# whole BLOCK_ROWS blocks and queries to whole QUERY_TILE tiles so _tile
# never degenerates (see config.resolve_granule("extract")).
BLOCK_ROWS = _TN
QUERY_TILE = _TQ


def tuned_variant(kc: int) -> dict:
    """Per-width kernel tuning, measured on v5e at 204800 x 10240 x 64
    (SWEEP_WIDEK_r04.jsonl, fenced solve incl. the sort epilogue):

    - narrow lists (kc <= 64): the r3 default (tq=128, ne=2) — 101.7 ms
      at kc=64; ne=4 ties (101.3), tq/ne changes within noise.
    - wide lists (kc > 64): (tq=64, ne=4) wins consistently — 139 vs
      151 ms at kc=136, 188 vs 215 at kc=256, 306 vs 373 at kc=512
      (-18%). Wider lists make each insert pass O(tq * kc); smaller query
      tiles cut the max-over-rows wasted iterations and ne=4 inserts
      4 candidates per threshold scan. ne=8 / tq=32 / unroll=2 all
      measured worse (refinement rows in the same artifact).
    """
    if kc <= 64:
        return {"tile_q": _TQ, "ne": _E, "unroll": 1}
    return {"tile_q": 64, "ne": 4, "unroll": 1}


def _heuristic_variant(kc: int, b: int) -> dict:
    """The deterministic fallback: the kc-tuned variant, unless ITS
    ne-alignment can't tile this b (wide-k wants ne=4 → b % 512; a
    caller with pre-shaped shards, e.g. the multi-host feed, may only
    satisfy the ne=2 alignment) — then the default variant keeps kernel
    coverage at r3 tuning rather than silently dropping to the
    streaming select."""
    v = tuned_variant(kc)
    if b % (128 * v["ne"]) != 0 and b % (128 * _E) == 0:
        v = {"tile_q": _TQ, "ne": _E, "unroll": 1}
    return v


def _resolve_variant(kc: int, b: int, qb: int | None = None,
                     a: int | None = None,
                     precision: str = "f32") -> dict:
    """The variant actually used for (kc, b): the measured autotuner
    cache entry when one exists for this (device kind, bucket(b),
    bucket(a), kc, precision) (dmlp_tpu.tune.lookup_variant — never
    raises, and rejects entries whose ne-alignment cannot tile this b),
    else the deterministic heuristic. ``precision`` is a cache key
    axis, never a tiling constraint: a bf16 first pass spends one MXU
    pass per tile where f32 spends ~3, which moves the winning tile
    but not what CAN tile, so the heuristic fallback is shared. When
    the caller knows the full dispatch shape (qb, a), a cached variant
    must ALSO pass variant_supports (VMEM bound included) or
    resolution falls back — a cache entry may downgrade resolution to
    the heuristic but can never flip supports() False and disable the
    kernel. supports(), extract_topk, and the analytic cost model
    (obs.kernel_cost) resolve through this same function with the same
    shape arguments, so gate, kernel and counters can never
    disagree."""
    from dmlp_tpu.tune import lookup_variant
    cached = lookup_variant(kc, b, a=a, precision=precision)
    if cached is not None:
        if qb is None or a is None \
                or variant_supports(qb, b, a, kc, cached):
            return cached
    return _heuristic_variant(kc, b)


def resolve_variant(kc: int, b: int, qb: int | None = None,
                    a: int | None = None,
                    precision: str = "f32") -> dict:
    """Public form of the variant resolution (engines/bench/tools report
    it in spans and artifacts): the dict extract_topk will run with —
    always carries tile_q/ne/unroll, plus tile_n when the tuner cache
    pinned one. Pass the full (qb, a) dispatch shape where known so the
    reported variant matches the kernel's own resolution exactly."""
    return dict(_resolve_variant(kc, b, qb, a, precision))


def variant_supports(qb: int, b: int, a: int, kc: int, v: dict) -> bool:
    """supports() with an EXPLICIT variant — the gate the tuner sweep
    shares with extract_topk's own validation, so the sweep can never
    persist a variant the kernel would reject: whole lane-width
    sub-blocks (b % (128 * ne)), query tiles of 8, kc no wider than one
    block, and VMEM room for the distance scratch + double-buffered q/d
    blocks."""
    if qb % 8 != 0 or b % (128 * v["ne"]) != 0:
        return False
    tn = _tile(b, v.get("tile_n", _TN), 128 * v["ne"])
    tq = _tile(qb, v["tile_q"], 8)
    if kc > tn or kc > 512:
        return False
    vmem = (tq * tn + 2 * (tq + tn) * a + 4 * tq * kc) * 4
    return vmem <= 64 * 2**20


def supports(qb: int, b: int, a: int, kc: int) -> bool:
    """Shapes the kernel can tile WITH the variant resolved for this
    full dispatch shape (tuner cache entry or heuristic — same
    resolution extract_topk uses, VMEM-checked cache fallback
    included)."""
    return variant_supports(qb, b, a, kc, _resolve_variant(kc, b, qb, a))


def _dot_cross(q, d, precision: str):
    """The (tq, tn) cross-term block at the requested FIRST-PASS
    precision. "f32": HIGHEST-precision f32 dot (the default would
    truncate f32 to bf16 on the MXU — 1e-2 relative distance error
    measured on v5e, breaks neighbor selection; HIGHEST decomposes into
    ~3 bf16 passes instead). "bf16": ONE MXU pass on bf16-cast operands
    with f32 accumulation kept — the cast's distance perturbation is
    bounded by engine.finalize.lowp_eps, which every caller folds into
    its candidate window, prune threshold, and gate bound so the
    unchanged f64 rescore + boundary repair restores exact results."""
    if precision == "bf16":
        q = q.astype(jnp.bfloat16)  # check: lowp-eps=lowp_eps
        d = d.astype(jnp.bfloat16)  # check: lowp-eps=lowp_eps
    return jax.lax.dot_general(
        q, d, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


def _kernel(sc_ref, q_ref, d_ref, qn_ref, dn_ref, f_ref, cd_ref, ci_ref,
            od_ref, oi_ref, it_ref, dist_s, *, kc: int, fresh: bool, ne: int,
            unroll: int = 1, block_skip: bool = True,
            mxu_gate: bool = False, precision: str = "f32"):
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    tq, tn = dist_s.shape
    tq_kc = (tq, kc)
    # Runtime scalars from SMEM (static args here would recompile the
    # Mosaic kernel once per chunk — id_base differs every chunk).
    n_real = sc_ref[0, 0]
    id_base = sc_ref[0, 1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (tq, tn), 1)

    gate_on = None
    if not mxu_gate:
        cross = _dot_cross(q_ref[:], d_ref[:], precision)
        dist = qn_ref[:] + dn_ref[:] - 2.0 * cross
        dist = jnp.maximum(dist, 0.0)
        # Per-row floor (multi-pass extraction, engine.single
        # ._solve_extract_multipass): candidates strictly below the floor
        # were captured by an earlier pass — mask them so this pass
        # extracts the NEXT kc-wide slab. Single-pass callers pass -inf
        # (no-op).
        dist = jnp.where(dist < f_ref[:], jnp.inf, dist)
        pos = j * tn + lane
        dist = jnp.where(pos >= n_real, jnp.inf, dist)

        if fresh:
            # First block seeds the running list with its first kc columns
            # (cheaper than extracting kc entries one loop pass at a time).
            @pl.when(j == 0)
            def _():
                od_ref[:] = jax.lax.slice(dist, (0, 0), (tq, kc))
                kpos = jax.lax.broadcasted_iota(jnp.int32, tq_kc, 1)
                oi_ref[:] = jnp.where(kpos < n_real, id_base + kpos, -1)
            dist = jnp.where((j == 0) & (lane < kc), jnp.inf, dist)
        else:
            @pl.when(j == 0)
            def _():
                od_ref[:] = cd_ref[:]
                oi_ref[:] = ci_ref[:]

        dist_s[:] = dist
    else:
        # Fused streaming megakernel (ops.pallas_fused): the current
        # k-th-best thresholds gate the MXU TILE itself, not just the
        # extraction scan. A sound per-row lower bound on every distance
        # in the block needs only the norms already streamed in:
        # |q - d|^2 >= (|q| - |d|)^2, minimized over the block's real
        # |d| range [mn, mx] — zero when |q| falls inside it. The bound
        # is deflated by the engines' staging-eps cancellation margin
        # (engine.finalize.staging_eps, same constants) so f32 rounding
        # in the norm-expansion distance can never make a computed
        # distance fall below it: a gated-out block is exactly a block
        # whose extraction would have inserted nothing, and the kernel
        # skips the matmul, the scan, and the scratch store outright
        # (0 recorded iterations) — block skipping made free.
        if not fresh:
            @pl.when(j == 0)
            def _():
                od_ref[:] = cd_ref[:]
                oi_ref[:] = ci_ref[:]
        from dmlp_tpu.engine.finalize import (EPS_CANCEL_COEF,
                                              EPS_REL_F32, LOWP_COEF)
        na = q_ref.shape[1]
        lane1 = jax.lax.broadcasted_iota(jnp.int32, (1, tn), 1)
        real = (j * tn + lane1) < n_real
        dn = dn_ref[:]
        sdn = jnp.sqrt(jnp.maximum(dn, 0.0))
        mn = jnp.min(jnp.where(real, sdn, jnp.inf))
        mx = jnp.max(jnp.where(real, sdn, -jnp.inf))
        dn_hi = jnp.max(jnp.where(real, dn, 0.0))
        qn = qn_ref[:]
        sq = jnp.sqrt(jnp.maximum(qn, 0.0))
        gap = jnp.maximum(jnp.maximum(mn - sq, sq - mx), 0.0)
        lb = gap * gap                                     # (tq, 1)
        scale = jnp.maximum(qn, 0.0) + dn_hi
        # A low-precision pass perturbs the COMPUTED distances the gate
        # reasons about by up to lowp_eps more than f32 rounding alone,
        # so the deflation margin widens by LOWP_COEF * scale (the
        # device form of engine.finalize.lowp_eps — same composition
        # the host prune/hazard tests apply).
        eps = (EPS_REL_F32 * jnp.sqrt(lb * scale)
               + (EPS_CANCEL_COEF * (na + 2)
                  + LOWP_COEF[precision]) * scale)
        # All-sentinel blocks drive lb (and hence eps) to +inf; the
        # inf - inf NaN compares False below, which IS the correct skip.
        lb_safe = jnp.maximum(lb - eps, 0.0)
        t_cur = jnp.max(od_ref[:], axis=1, keepdims=True)  # (tq, 1)
        gate_on = jnp.max((lb_safe < t_cur).astype(jnp.int32)) > 0
        if fresh:
            # The first block must compute: it seeds the running lists
            # (and od_ref holds garbage before that, making t_cur
            # meaningless — forced on, its value never matters).
            gate_on = gate_on | (j == 0)

        @pl.when(gate_on)
        def _():
            cross = _dot_cross(q_ref[:], d_ref[:], precision)
            dist = qn_ref[:] + dn_ref[:] - 2.0 * cross
            dist = jnp.maximum(dist, 0.0)
            dist = jnp.where(dist < f_ref[:], jnp.inf, dist)
            pos = j * tn + lane
            dist = jnp.where(pos >= n_real, jnp.inf, dist)
            dist_s[:] = dist

        if fresh:
            @pl.when(j == 0)
            def _():
                d0 = dist_s[:]
                od_ref[:] = jax.lax.slice(d0, (0, 0), (tq, kc))
                kpos = jax.lax.broadcasted_iota(jnp.int32, tq_kc, 1)
                oi_ref[:] = jnp.where(kpos < n_real, id_base + kpos, -1)
                dist_s[:] = jnp.where(lane < kc, jnp.inf, d0)

    kiota = jax.lax.broadcasted_iota(jnp.int32, tq_kc, 1)
    w = tn // ne
    wlane = jax.lax.broadcasted_iota(jnp.int32, (tq, w), 1)

    def round_():
        # Each quarter independently: find its min, insert if it beats the
        # row's current k-th best, mask it out. All ops are 2D with
        # lane-aligned static slices — 3D reshapes / lane-offset slices
        # blow up the Mosaic compile.
        # (A pl.when skip of the argmin/insert/mask passes for no-improve
        # halves measured SLOWER — 79.5 vs 68.3 ms — the predication
        # overhead beats the saved passes; keep the straight-line form.)
        go = jnp.int32(0)
        for e in range(ne):
            qd = dist_s[:, e * w:(e + 1) * w]               # (tq, w)
            m = jnp.min(qd, axis=1, keepdims=True)          # (tq, 1)
            am = jnp.min(jnp.where(qd == m, wlane, w), axis=1,
                         keepdims=True)                     # (tq, 1)
            rd = od_ref[:]
            t = jnp.max(rd, axis=1, keepdims=True)          # (tq, 1)
            better = m < t                                  # (tq, 1)
            wi = jnp.min(jnp.where(rd == t, kiota, kc), axis=1,
                         keepdims=True)
            ins = better & (kiota == wi)
            od_ref[:] = jnp.where(ins, m, rd)
            gid = id_base + j * tn + e * w + am
            oi_ref[:] = jnp.where(ins, gid, oi_ref[:])
            dist_s[:, e * w:(e + 1) * w] = jnp.where(
                better & (wlane == am), jnp.inf, qd)
            go = go + jnp.max(better.astype(jnp.int32))
        return go

    def body(state):
        it, _ = state
        # `unroll` extraction rounds per loop-condition sync. Correctness
        # needs only the LAST round's found-any flag: if that round found
        # nothing, no remaining candidate beats any row's threshold.
        for _u in range(unroll - 1):
            round_()
        go = round_()
        return it + 1, go > 0

    if block_skip:
        # Threshold-gated block skipping: one VPU min over the block per
        # row, against the row's CURRENT k-th best. Strict `<` matches
        # the extraction's `m < T`, so a skipped block is exactly a
        # block whose first round would have inserted nothing — the
        # while-loop below then never starts (0 recorded iterations)
        # and the no-improve cost drops from a full ne-pass round to
        # this one reduction.
        t0 = jnp.max(od_ref[:], axis=1, keepdims=True)      # (tq, 1)
        # The MXU-gated kernel has no local `dist` value (the compute is
        # predicated); read the scratch it conditionally stored — stale
        # contents when the gate fired are masked out by the AND below.
        bmin = jnp.min(dist_s[:] if mxu_gate else dist, axis=1,
                       keepdims=True)                       # (tq, 1)
        go0 = jnp.max((bmin < t0).astype(jnp.int32)) > 0
    else:
        go0 = True
    if gate_on is not None:
        go0 = gate_on & go0
    iters, _ = jax.lax.while_loop(
        lambda s: s[1] & (s[0] <= tn), body, (jnp.int32(0), go0))
    # Diagnostic loop counts: lane j of this tile's block (row 0 is read
    # back; an iota-select avoids dynamic-lane scalar stores). With
    # block_skip, 0 means the prefilter skipped the block entirely.
    njs = it_ref.shape[1]
    ji = jax.lax.broadcasted_iota(jnp.int32, (tq, njs), 1)

    @pl.when(j == 0)
    def _():
        it_ref[:] = jnp.zeros((tq, njs), jnp.int32)
    it_ref[:] = jnp.where(ji == j, iters, it_ref[:])

    # Output blocks map to (i, 0): they stay VMEM-resident across the
    # data-block sweep and flush once after the last block.
    del nj


def extract_topk(q_attrs: jax.Array, d_attrs: jax.Array,
                 carry_d: jax.Array | None = None,
                 carry_i: jax.Array | None = None, *, n_real,
                 id_base=0, kc: int, interpret: bool = False,
                 tile_q: int | None = None, tile_n: int | None = None,
                 ne: int | None = None, unroll: int | None = None,
                 block_skip: bool = True, mxu_gate: bool = False,
                 floor: jax.Array | None = None, precision: str = "f32"):
    """(queries (Qb, A), data (B, A)) -> (dists (Qb, kc) f32 ascending-ish
    unsorted, ids (Qb, kc) i32, iters (Qb/tq, B/tn) i32 loop counts; 0 =
    the threshold prefilter skipped that block).
    Rows >= n_real are sentinels; data row j has global id id_base + j.
    Optional carry (prior running lists, e.g. from a previous chunk) is
    folded in; without it slots pad (+inf, -1). Optional ``floor``
    ((Qb, 1) f32): per-row distance floor — candidates with
    dist < floor are masked out (the multi-pass wide-k driver raises it
    to the previous pass's max − eps each pass).

    tile_q/tile_n/ne/unroll default to the resolved variant (the tuner
    cache entry when one exists, else the kc-tuned heuristic); pass them
    explicitly only to override (the tune sweep does). The resolution
    happens OUT HERE, before the jit boundary, so the CONCRETE variant
    is part of the jit cache key — a cache update mid-process (a sweep
    just ran) changes which compiled kernel the next call uses instead
    of silently reusing a trace baked with the old variant.
    ``block_skip`` toggles the threshold-gated block prefilter
    (output-identical either way; off only for A/B measurement,
    tools/roofline_extract.py). ``mxu_gate`` enables the fused
    megakernel's norm-bound MXU tile gating (output-identical;
    ops.pallas_fused.fused_topk is the public face, which also resolves
    variants from the fused tune-cache namespace). ``precision``
    ("f32" | "bf16") selects the FIRST-PASS dot dtype: "bf16" casts the
    streamed q/d tiles before the MXU (one pass instead of HIGHEST's
    ~3) with f32 accumulation kept — candidate lists then deviate from
    the f32 pass by at most engine.finalize.lowp_eps per distance, and
    callers MUST widen their candidate window / prune / hazard bounds
    by that margin (resolve_kcap + staging_eps composition do) for the
    exact pipeline to stay byte-identical. Static: part of the jit
    cache key, resolved by callers OUTSIDE every jit (R2 discipline).

    Gate on supports() first. Output lists are NOT sorted; callers sort by
    the composite key (ops.topk.select_topk) if order matters.
    """
    v = _resolve_variant(kc, d_attrs.shape[0], q_attrs.shape[0],
                         q_attrs.shape[1], precision)
    # Eager callers pass plain ints for the traced SMEM scalars; under
    # the sanitizer's transfer guard the jit argument conversion would
    # be an implicit host->device transfer — make it explicit here (a
    # traced value, e.g. from the mesh engines' shard_map bodies, passes
    # through untouched).
    import numpy as _onp
    if isinstance(n_real, (int, _onp.integer)):
        n_real = jax.device_put(_onp.int32(n_real))
    if isinstance(id_base, (int, _onp.integer)):
        id_base = jax.device_put(_onp.int32(id_base))
    if precision not in ("f32", "bf16"):
        raise ValueError(f"unsupported first-pass precision {precision!r} "
                         "(int8 is the gated follow-on — see ROADMAP)")
    return _extract_topk_jit(
        q_attrs, d_attrs, carry_d, carry_i, n_real=n_real,
        id_base=id_base, kc=kc, interpret=interpret,
        tile_q=v["tile_q"] if tile_q is None else tile_q,
        tile_n=v.get("tile_n", _TN) if tile_n is None else tile_n,
        ne=v["ne"] if ne is None else ne,
        unroll=v["unroll"] if unroll is None else unroll,
        block_skip=block_skip, mxu_gate=mxu_gate, floor=floor,
        precision=precision)


@functools.partial(
    jax.jit, static_argnames=("kc", "interpret", "tile_q", "tile_n", "ne",
                              "unroll", "block_skip", "mxu_gate",
                              "precision"))
def _extract_topk_jit(q_attrs, d_attrs, carry_d, carry_i, *, n_real,
                      id_base, kc: int, interpret: bool, tile_q: int,
                      tile_n: int, ne: int, unroll: int, block_skip: bool,
                      mxu_gate: bool, floor, precision: str = "f32"):
    qb, a = q_attrs.shape
    b = d_attrs.shape[0]
    tq = _tile(qb, tile_q, 8)
    tn = _tile(b, tile_n, 128 * ne)
    # Validate the ACTUAL tiling (supports() only covers the defaults):
    # the fresh-seed slice and quarter layout need kc <= tn, and the
    # distance scratch + double-buffered blocks must fit VMEM.
    vmem = (tq * tn + 2 * (tq + tn) * a + 4 * tq * kc) * 4
    if not (qb % 8 == 0 and b % (128 * ne) == 0 and kc <= tn
            and kc <= 512 and vmem <= 64 * 2**20):
        # ValueError, not assert: a caller that skipped supports() must
        # fail loudly under ``python -O`` too, not compute garbage.
        raise ValueError(
            f"untileable (qb={qb}, b={b}, kc={kc}, tq={tq}, tn={tn}, ne={ne})")

    q32 = q_attrs.astype(jnp.float32)
    d32 = d_attrs.astype(jnp.float32)
    qn = jnp.sum(q32 * q32, axis=-1, keepdims=True)
    dn = jnp.sum(d32 * d32, axis=-1)[None, :]

    fresh = carry_d is None
    if fresh:
        carry_d = jnp.full((qb, kc), jnp.inf, jnp.float32)
        carry_i = jnp.full((qb, kc), -1, jnp.int32)
    if floor is None:
        floor = jnp.full((qb, 1), -jnp.inf, jnp.float32)

    scalars = jnp.asarray([[n_real, id_base]], jnp.int32)     # (1, 2) SMEM
    grid = (qb // tq, b // tn)
    kern = functools.partial(_kernel, kc=kc, fresh=fresh, ne=ne,
                             unroll=unroll, block_skip=block_skip,
                             mxu_gate=mxu_gate, precision=precision)
    out_d, out_i, out_iters = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 2), lambda i, j: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tq, a), lambda i, j: (i, 0)),
            pl.BlockSpec((tn, a), lambda i, j: (j, 0)),
            pl.BlockSpec((tq, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, tn), lambda i, j: (0, j)),
            pl.BlockSpec((tq, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((tq, kc), lambda i, j: (i, 0)),
            pl.BlockSpec((tq, kc), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tq, kc), lambda i, j: (i, 0)),
            pl.BlockSpec((tq, kc), lambda i, j: (i, 0)),
            # One iters block per query tile (row 0 carries the counts)
            # keeps dim 0 safely "parallel" — a single shared block would
            # be clobbered across megacore cores.
            pl.BlockSpec((tq, b // tn), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((qb, kc), jnp.float32),
            jax.ShapeDtypeStruct((qb, kc), jnp.int32),
            jax.ShapeDtypeStruct((qb, b // tn), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((tq, tn), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=96 * 2**20),
        interpret=interpret,
    )(scalars, q32, d32, qn, dn, floor, carry_d, carry_i)
    return out_d, out_i, out_iters[::tq]
