"""Fused distance→top-k streaming megakernel: one HBM pass for the hot path.

This is the public face of the ``mxu_gate`` form of the extraction kernel
(ops.pallas_extract._kernel): one Pallas program computes each distance
tile on the MXU in VMEM and feeds it straight into the running top-k
carry state — the (nq, nd) distance matrix never exists in HBM — and, new
here, the current k-th-best thresholds gate the MXU TILE itself, not just
the extraction scan (the ROADMAP's "block skipping made free"). Per data
block the kernel derives a sound per-row distance lower bound from the
norms it already streams (|q - d|^2 >= (|q| - |d|)^2 over the block's
real |d| range), deflates it by the engines' staging-eps cancellation
margin (engine.finalize.staging_eps constants — the same bound the exact
pipeline already trusts for truncation hazards), and skips the matmul,
the scan, and the scratch store outright when no row's bound beats its
threshold. A gated-out block is provably a block whose extraction would
have inserted nothing, so outputs are BIT-IDENTICAL to the two-pass-era
pipeline (tests/test_pallas_fused.py fuzzes this, skip on/off).

Contrast with the pipeline it replaces where ``supports()`` holds: the
streaming "seg"/"topk" folds materialize every (Qb, B) distance tile to
HBM (ops.pallas_distance.fused_dist_segmin) and the selection re-reads
it — two passes over the dominant term of hot-path HBM traffic. The
analytic model pair ``obs.kernel_cost.fused_topk_cost`` /
``two_pass_equivalent_cost`` quantifies the eliminated write+read.

Variant resolution mirrors ops.pallas_extract but reads the FUSED
namespace of the measured tune cache (``dmlp_tpu.tune``, kernel
="fused_topk"): the fused tile space (tile_q x tile_n x ne x unroll) is
swept separately because the gate changes the operating point — gated
blocks cost one VPU bound pass, so larger data blocks amortize
differently than in the ungated kernel. An absent cache resolves to the
same deterministic heuristic as the ungated kernel (bit-identical CI).

Kill switch: ``DMLP_TPU_FUSED=0`` disables the fused path everywhere
(mirroring ``DMLP_TPU_RESILIENCE``); engines then run the tuned two-pass
extraction kernel — also the first rung the OOM degradation ladder
steps down to (resilience.degrade: fused -> tuned -> heuristic ->
streaming -> host).
"""

from __future__ import annotations

import os

import jax

from dmlp_tpu.ops.pallas_extract import (_TN, _heuristic_variant,
                                         extract_topk, variant_supports)
from dmlp_tpu.ops.pallas_extract import supports as extract_supports

FUSED_KERNEL = "fused_topk"


def fused_enabled() -> bool:
    """The fused-path kill switch ($DMLP_TPU_FUSED=0 disables) — read
    per call so tests and operators can flip it without re-imports."""
    return os.environ.get("DMLP_TPU_FUSED", "1") != "0"


def _resolve_variant(kc: int, b: int, qb: int | None = None,
                     a: int | None = None,
                     precision: str = "f32") -> dict:
    """Fused-namespace variant resolution: the measured tune-cache entry
    for (device kind, bucket(b), bucket(a), kc, precision) under kernel
    "fused_topk" when one exists and still passes the full supports
    gate, else the shared deterministic heuristic — exactly the
    extract kernel's resolution contract, keyed separately because the
    MXU gate shifts which tiles win (and per first-pass precision,
    because the MXU pass count per tile does too)."""
    from dmlp_tpu.tune import lookup_variant
    cached = lookup_variant(kc, b, a=a, kernel=FUSED_KERNEL,
                            precision=precision)
    if cached is not None:
        if qb is None or a is None \
                or variant_supports(qb, b, a, kc, cached):
            return cached
    return _heuristic_variant(kc, b)


def resolve_variant(kc: int, b: int, qb: int | None = None,
                    a: int | None = None,
                    precision: str = "f32") -> dict:
    """Public form (spans/artifacts report it): the variant fused_topk
    will run with at this dispatch shape."""
    return dict(_resolve_variant(kc, b, qb, a, precision))


def supports(qb: int, b: int, a: int, kc: int) -> bool:
    """Shapes the fused kernel can tile with ITS resolved variant (same
    tiling/VMEM constraints as the ungated kernel — the gate adds only
    per-block scalars)."""
    return variant_supports(qb, b, a, kc, _resolve_variant(kc, b, qb, a))


def variant_for(impl: str, kc: int, b: int, qb: int | None = None,
                a: int | None = None, precision: str = "f32") -> dict:
    """The variant an ``impl`` label ("fused" | "extract", from
    resolve_topk_kernel) will actually run with at this dispatch shape —
    the one helper engines use for span/artifact reporting, so the
    reported variant always comes from the SAME namespace (and
    precision key axis) the dispatch resolves through."""
    if impl == "fused":
        return resolve_variant(kc, b, qb, a, precision)
    from dmlp_tpu.ops.pallas_extract import resolve_variant as _rv
    return _rv(kc, b, qb, a, precision)


def fused_topk(q_attrs: jax.Array, d_attrs: jax.Array,
               carry_d: jax.Array | None = None,
               carry_i: jax.Array | None = None, *, n_real,
               id_base=0, kc: int, interpret: bool = False,
               block_skip: bool = True,
               floor: jax.Array | None = None, precision: str = "f32"):
    """Drop-in for ops.pallas_extract.extract_topk with the MXU tile
    gate on and variants resolved from the fused tune-cache namespace.
    Same signature, same (dists, ids, iters) outputs, bit-identical
    results; ``iters`` reports 0 for blocks either gate elided.
    ``precision`` ("f32" | "bf16") selects the first-pass dot dtype
    exactly as in extract_topk — the MXU-gate bound widens by the
    engine.finalize.lowp_eps margin in-kernel, so gating stays sound
    under the low-precision pass.

    The variant resolution happens HERE, outside the jit boundary, so
    the concrete fused/two-pass choice AND the concrete tiles are part
    of the jit cache key (the PR 3 in-jit-resolution bug class, lint
    R203). Gate on supports() first.
    """
    v = _resolve_variant(kc, d_attrs.shape[0], q_attrs.shape[0],
                         q_attrs.shape[1], precision)
    return extract_topk(
        q_attrs, d_attrs, carry_d, carry_i, n_real=n_real,
        id_base=id_base, kc=kc, interpret=interpret,
        tile_q=v["tile_q"], tile_n=v.get("tile_n", _TN), ne=v["ne"],
        unroll=v["unroll"], block_skip=block_skip, mxu_gate=True,
        floor=floor, precision=precision)


def resolve_topk_kernel(qb: int, b: int, a: int, kc: int,
                        rung: str = "fused"):
    """The engine-facing selector: (kernel callable, impl label) for one
    extract-path dispatch shape, or (None, None) when neither kernel
    tiles it (callers fall back to the streaming selects).

    Preference order: the fused megakernel when the kill switch allows
    it, the engine's degradation rung is still at or above "fused"
    (the "lowp" and "prune" rungs above it compose the low-precision
    first pass and scan pruning WITH the fused kernel), and the fused
    variant tiles the shape; else the tuned two-pass extraction kernel.
    MUST be called OUTSIDE any jitted body (lint R203) and the returned
    label must key every compiled-program cache that bakes the choice
    in — the selection is part of the jit cache key by construction.
    """
    if rung in ("lowp", "prune", "fused") and fused_enabled() \
            and supports(qb, b, a, kc):
        return fused_topk, "fused"
    if extract_supports(qb, b, a, kc):
        return extract_topk, "extract"
    return None, None
