from dmlp_tpu.ops.distance import pairwise_sq_l2, masked_pairwise_sq_l2  # noqa: F401
from dmlp_tpu.ops.topk import select_topk, merge_topk, streaming_topk, TopK  # noqa: F401
from dmlp_tpu.ops.vote import majority_vote, report_order  # noqa: F401
