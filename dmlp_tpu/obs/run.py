"""Versioned run-artifact records — the one schema every emitter shares.

Five rounds of benchmarking left ~15 ``tools/*.py`` scripts each inventing
its own ``BENCH_*.json`` shape; nothing downstream can consume them
uniformly. :class:`RunRecord` is the replacement going forward: a small
versioned envelope (schema, tool, kind, host context) around free-form
``config``/``metrics`` payloads plus the structured observability blocks
(``counters`` from obs.counters, ``comms`` from obs.comms, ``artifacts``
paths to trace files). Existing artifacts are grandfathered; new emitters
write RunRecords (the bench harness and the engine CLI already do).

Records serialize as strict JSON. ``write`` emits one record per file;
``append_jsonl`` appends one record per line for multi-run logs — both
atomic enough for the single-writer tooling here.

Schema 2 promotes the two fields the perf ledger (obs.ledger) keys
series on from free-form payload convention to the envelope: ``round``
(the measurement round, the ``_rNN`` suffix convention of the root
artifacts) and ``device`` (the device kind the run measured on — the
ledger refuses to compare rounds across devices, so emitters that know
their device must say so). Both are optional: schema-1 records load
unchanged and the ledger falls back to filename/round heuristics.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform
import re
import time
from typing import Any, Dict, Optional

#: bump on any backward-incompatible field change; consumers key on this
SCHEMA_VERSION = 2


def round_from_name(path: str) -> Optional[int]:
    """The measurement round encoded in an artifact filename (the
    ``_rNN`` convention: BENCH_r05.json -> 5), or None."""
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else None


def current_device() -> Optional[str]:
    """Best-effort device kind for the envelope ``device`` field:
    the first device's ``device_kind`` (falls back to platform name).
    Touches ``jax.devices()`` — callers that must not initialize a
    backend should pass ``device`` explicitly instead."""
    try:
        import jax
        dev = jax.devices()[0]
        return str(getattr(dev, "device_kind", None) or dev.platform)
    except Exception:
        return None


def _host_context() -> Dict[str, Any]:
    ctx: Dict[str, Any] = {"python": platform.python_version()}
    try:
        import jax
        ctx["jax"] = jax.__version__
        # Touching jax.devices() would initialize a backend as a side
        # effect (and can dial a remote TPU); record only what is free.
    except Exception:
        pass
    return ctx


@dataclasses.dataclass
class RunRecord:
    """One run's artifact: envelope + payload.

    ``kind`` names the workload family ("engine", "bench", "train", ...);
    ``tool`` names the emitter (e.g. "dmlp_tpu.cli", "dmlp_tpu.bench").
    ``config`` holds the inputs that produced the run, ``metrics`` its
    measurements; ``counters``/``comms``/``artifacts`` carry the obs
    subsystem's structured blocks when present."""

    kind: str
    tool: str
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)
    counters: Optional[Dict[str, Any]] = None
    comms: Optional[Dict[str, Any]] = None
    artifacts: Dict[str, str] = dataclasses.field(default_factory=dict)
    round: Optional[int] = None      # schema 2: measurement round (_rNN)
    device: Optional[str] = None     # schema 2: device kind measured on
    schema: int = SCHEMA_VERSION
    created_unix: float = dataclasses.field(default_factory=time.time)
    host: Dict[str, Any] = dataclasses.field(default_factory=_host_context)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v not in (None, {})}

    def to_json(self) -> str:
        try:
            return json.dumps(self.to_dict(), sort_keys=True)
        except TypeError as e:
            raise TypeError(
                f"RunRecord for tool={self.tool!r} contains a "
                f"non-JSON-serializable value: {e}") from None

    def write(self, path: str) -> str:
        """One record per file (atomic rename)."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json() + "\n")
        os.replace(tmp, path)
        return path

    def append_jsonl(self, path: str) -> str:
        """One record per line, appended — the multi-run log form."""
        line = self.to_json()
        with open(path, "a") as f:
            f.write(line + "\n")
        return path

    @staticmethod
    def load(path: str) -> "RunRecord":
        with open(path) as f:
            return RunRecord.from_dict(json.loads(f.readline()))

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "RunRecord":
        known = {f.name for f in dataclasses.fields(RunRecord)}
        schema = d.get("schema")
        if schema is not None and schema > SCHEMA_VERSION:
            raise ValueError(f"RunRecord schema {schema} is newer than "
                             f"this reader ({SCHEMA_VERSION})")
        return RunRecord(**{k: v for k, v in d.items() if k in known})
