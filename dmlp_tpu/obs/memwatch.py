"""Device-memory watermarks: the analytic peak-HBM model and its
reconciliation against measured watermarks.

Memory was the one resource with zero observability anywhere in the
package: FLOPs and collective bytes both have analytic models reconciled
against measurement (obs.kernel_cost vs measured iters, obs.comms vs
traces), while the resilience ladder reacted to OOM blindly. This module
is the missing sibling of :mod:`dmlp_tpu.obs.comms`, for bytes resident
in device memory:

- :func:`resident_bytes_model` — the analytic peak-HBM model per
  engine/config, computed from the SAME plan functions the dispatch
  paths use (``plan_chunks`` / ``resolve_kcap`` / ``fit_blocks``), so
  tests can validate the terms against hand-computed byte counts for a
  concrete shape. Terms cover the staged corpus (whole-dataset for the
  scan path, the :data:`~dmlp_tpu.engine.single._CHUNK_WINDOW` staging
  window for the chunked drivers, the resident dataset ×2 during the
  multipass concat), query blocks, double-buffered top-k carries, the
  extract/fused kernels' HBM-visible outputs, and the train step's
  params/grads/moments/batch/activations.
- **measured watermarks** — :func:`device_memory_stats` polls per-device
  ``memory_stats()`` (None on backends that report nothing — this
  container's CPU backend); :func:`live_array_bytes` sums live jax
  array bytes as the fallback basis. Neither ever *initializes* a
  backend: they no-op unless the process already imported jax.
- :func:`reconcile` — model vs measured with per-basis documented
  tolerance ratio bounds (:data:`RATIO_BOUNDS`), and the explicit
  ``mem_stats_unavailable`` marker where the backend cannot report
  memory — never a silent pass.

The model is a *resident-set* model: it counts the arrays the engine
deliberately keeps in device memory, not XLA's transient scratch or
allocator slack — hence ratio bounds rather than a percent band. The
``memory_stats`` basis is the real allocator (slack above the model);
the ``live_arrays`` basis counts every live buffer in the process
(warmup leftovers and observability scalars ride along), so its bounds
are looser and both are named in the reconcile record.

Import-light: jax strictly lazy; engine modules imported only inside
the model functions.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

#: documented model-vs-measured tolerance, per basis, as ratio bounds on
#: measured/model: the model must sit within [lo, hi]× of the watermark
RATIO_BOUNDS: Dict[str, tuple] = {
    # allocator stats: slack + XLA temporaries above the resident set,
    # fragmentation below a just-freed peak
    "memory_stats": (0.5, 3.0),
    # every live buffer in the process rides along (and the allocator
    # may cache freed chunk buffers the model already rotated out)
    "live_arrays": (0.3, 4.0),
}

#: byte widths shared with the engines (TopK triple = f32 + i32 + i32)
_TOPK_ITEMSIZE = 12
_EXTRACT_CARRY_ITEMSIZE = 8   # od f32 + oi i32


def _staging_itemsize(staging: str) -> int:
    return 2 if staging == "bfloat16" else 4


# -- measured bases -----------------------------------------------------------

def device_memory_stats() -> Optional[List[Optional[Dict[str, Any]]]]:
    """Per-device ``memory_stats()`` dicts (None entries where a device
    reports nothing), or None when jax was never imported — polling
    must not initialize a backend as a side effect."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax
        out = []
        for d in jax.devices():
            try:
                out.append(d.memory_stats())
            except Exception:  # a device without the API is a
                out.append(None)   # None entry, not a failure
        return out
    except Exception:  # observability never raises
        return None


def live_array_bytes() -> Optional[int]:
    """Total bytes of live jax arrays in this process — the fallback
    watermark basis on backends whose ``memory_stats()`` is None."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax
        return int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:  # observability never raises
        return None


def measured_watermark() -> Dict[str, Any]:
    """One-shot watermark: allocator peak when available, live-array
    bytes otherwise, explicit marker when neither basis reports. For a
    watermark tracked ACROSS a run, use the telemetry sampler's
    ``measured_peak()`` (it maxes over ticks)."""
    stats = device_memory_stats()
    if stats is not None:
        peaks = [st.get("peak_bytes_in_use", st.get("bytes_in_use", 0))
                 for st in stats if st]
        if peaks:
            return {"bytes": int(sum(peaks)), "basis": "memory_stats"}
    live = live_array_bytes()
    if live:
        return {"bytes": live, "basis": "live_arrays"}
    return {"unavailable": "backend reports no memory_stats and no "
                           "live jax arrays exist"}


# -- analytic models ----------------------------------------------------------

def single_engine_model(n: int, nq: int, na: int, kmax: int,
                        config=None, staging: Optional[str] = None
                        ) -> Dict[str, Any]:
    """Peak resident device bytes for one SingleChipEngine solve at
    (num_data n, num_queries nq, num_attrs na, max-k kmax), mirroring
    the dispatch planning in :mod:`dmlp_tpu.engine.single`:

    - the **scan path** ("sort") stages the whole padded dataset plus
      labels/ids and all query blocks;
    - the **chunked drivers** ("topk"/"seg"/"extract") hold at most the
      ``_CHUNK_WINDOW + 1`` in-flight staged chunks (the backpressure
      window plus the chunk being staged) — except the **multipass**
      wide-k plan, which keeps the dataset resident and briefly ×2
      during its concat;
    - top-k carries are double-buffered (the fold consumes the old
      carry while producing the new one), ``P`` slabs for multipass;
    - the extract/fused kernels' HBM-visible outputs (od/oi + the
      per-tile iters diagnostics) are the carry term — the distance
      tile itself lives only in VMEM (the whole point of the fused
      kernel), so no (Q, N) term appears on any path.

    Every term is reported; ``total_bytes`` is their sum. Hand-computed
    for a concrete shape in tests/test_telemetry.py.
    """
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import (_CHUNK_WINDOW, fit_blocks,
                                        plan_chunks, resolve_kcap,
                                        round_up)
    from dmlp_tpu.ops.pallas_extract import QUERY_TILE

    cfg = config or EngineConfig()
    staging = staging or cfg.resolve_dtype()
    item = _staging_itemsize(staging)
    n, nq = max(n, 1), max(nq, 1)
    select = cfg.resolve_select(round_up(n, 8))
    terms: Dict[str, int] = {}

    if select == "sort":
        # _solve_scan: whole dataset + labels/ids + all query blocks
        data_block = (min(cfg.data_block, round_up(n, 8))
                      if cfg.data_block is not None
                      else fit_blocks(n, cfg.resolve_data_block(select),
                                      granule=cfg.resolve_granule(select)))
        npad = round_up(n, data_block)
        kc = resolve_kcap(cfg, kmax, select, npad, staging=staging)
        qb = min(cfg.query_block, round_up(nq, 8))
        qpad = round_up(nq, qb)
        terms["staged_corpus"] = npad * na * item
        terms["labels_ids"] = npad * 8
        terms["query_blocks"] = qpad * na * item
        terms["topk_out"] = qpad * kc * _TOPK_ITEMSIZE
        return _finish(terms, select=select, kcap=kc, npad=npad,
                       qpad=qpad, staging=staging)

    if select == "extract":
        granule = cfg.resolve_granule("extract")
        npad, nchunks, chunk_rows = plan_chunks(n, granule, cfg.data_block)
        qpad = round_up(nq, QUERY_TILE)
        kc = resolve_kcap(cfg, kmax, "extract", nchunks * chunk_rows,
                          staging=staging)
        multipass = kc > 512
        window = min(nchunks, _CHUNK_WINDOW + 1)
        if multipass:
            # resident dataset + transient ×2 during the concat, and
            # P = ceil(kcap/512) carry slabs of the per-pass kc=512
            npasses = -(-kc // 512)
            terms["staged_corpus"] = 2 * npad * na * item
            terms["topk_carries"] = (npasses + 1) * qpad * 512 \
                * _EXTRACT_CARRY_ITEMSIZE
        else:
            terms["staged_corpus"] = window * chunk_rows * na * item
            # double-buffered od/oi during the fold chain
            terms["topk_carries"] = 2 * qpad * kc * _EXTRACT_CARRY_ITEMSIZE
        terms["query_blocks"] = qpad * na * item
        terms["labels_ids"] = n * 4          # labels staged once (finalize)
        # fused/extract scratch visible in HBM: the per-(tile) iters
        # diagnostics output, one i32 per grid cell per in-flight chunk
        terms["kernel_scratch"] = window * 4 * max(
            (qpad // 128) * max(chunk_rows // 1024, 1), 1)
        return _finish(terms, select=select, kcap=kc, npad=npad,
                       qpad=qpad, staging=staging,
                       multipass=multipass)

    # chunked streaming fold ("topk" / "seg")
    granule = cfg.resolve_granule(select)
    npad, nchunks, chunk_rows = plan_chunks(n, granule, cfg.data_block)
    qpad = round_up(nq, 8)
    kc = resolve_kcap(cfg, kmax, select, nchunks * chunk_rows,
                      staging=staging)
    window = min(nchunks, _CHUNK_WINDOW + 1)
    terms["staged_corpus"] = window * chunk_rows * na * item
    terms["labels_ids"] = window * chunk_rows * 8
    terms["query_blocks"] = qpad * na * item
    terms["topk_carries"] = 2 * qpad * kc * _TOPK_ITEMSIZE
    return _finish(terms, select=select, kcap=kc, npad=npad, qpad=qpad,
                   staging=staging)


def mesh_engine_model(n: int, nq: int, na: int, kmax: int,
                      mesh_shape, mode: str = "sharded",
                      config=None, staging: Optional[str] = None
                      ) -> Dict[str, Any]:
    """Peak resident bytes PER DEVICE for the mesh engines: each (data
    r × query c) cell holds its corpus shard + replicated query shard +
    its top-k lists, and the merge buffer differs by strategy — the
    all-gather merge materializes all r cells' (q_local, k) triples,
    the ring merge only the O(k) accumulator (that asymmetry IS the
    ring engine's reason to exist, now a modeled number)."""
    from dmlp_tpu.config import EngineConfig
    from dmlp_tpu.engine.single import resolve_kcap, round_up

    cfg = config or EngineConfig(mode=mode)
    staging = staging or cfg.resolve_dtype()
    item = _staging_itemsize(staging)
    r, c = mesh_shape
    n, nq = max(n, 1), max(nq, 1)
    shard_rows = round_up(-(-n // r), 8)
    q_local = round_up(-(-nq // c), 8)
    kc = resolve_kcap(cfg, kmax, cfg.resolve_select(shard_rows),
                      shard_rows, staging=staging)
    terms = {
        "corpus_shard": shard_rows * na * item,
        "labels_ids_shard": shard_rows * 8,
        "query_shard": q_local * na * item,
        "local_topk": q_local * kc * _TOPK_ITEMSIZE,
        # mode="ring" keeps the O(k) accumulator; "sharded" (allgather)
        # materializes all r lists, and "auto" (GSPMD) prices that
        # worst case — the compiler may pick it.
        "merge_buffer": (2 if mode == "ring" else r)
        * q_local * kc * _TOPK_ITEMSIZE,
    }
    return _finish(terms, mode=mode, mesh=[r, c], kcap=kc,
                   shard_rows=shard_rows, q_local=q_local,
                   staging=staging, per_device=True, n_devices=r * c)


def train_step_model(dims, batch: int, optimizer: str = "sgd",
                     mesh_shape=None, compute_dtype: Optional[str] = None
                     ) -> Dict[str, Any]:
    """Peak resident bytes per device for one dp×tp train step: params
    + grads + optimizer moments (adam: 2× params) + the local batch +
    the forward activations kept for backward (one (batch_local,
    width) f32 per layer boundary). tp shards the hidden dims across
    ``tp``; dp shards the batch across ``dp``."""
    dims = list(dims)
    dp, tp = (mesh_shape or (1, 1))[:2]
    param_count = sum(dims[i] * dims[i + 1] + dims[i + 1]
                      for i in range(len(dims) - 1))
    pbytes = param_count * 4 // max(tp, 1)
    moments = {"sgd": 0, "adam": 2}.get(optimizer, 0)
    b_local = max(batch // max(dp, 1), 1)
    act_item = 2 if compute_dtype == "bfloat16" else 4
    acts = b_local * sum(dims[1:]) * act_item // max(tp, 1)
    terms = {
        "params": pbytes,
        "grads": pbytes,
        "opt_moments": moments * pbytes,
        "batch": b_local * (dims[0] + 1) * 4,
        "activations": acts,
    }
    return _finish(terms, kind="train", dims=dims, batch=batch,
                   optimizer=optimizer, per_device=True,
                   n_devices=max(dp, 1) * max(tp, 1))


def serve_engine_model(capacity_rows: int, na: int,
                       staging: str = "float32", qpad: int = 0,
                       kcap: int = 0, extract_chunks: int = 0,
                       chunk_rows: int = 0,
                       summary_blocks: int = 0,
                       multipass_rows: int = 0) -> Dict[str, Any]:
    """Peak resident device bytes for the serving layer's
    :class:`~dmlp_tpu.serve.engine.ResidentEngine`: the capacity-padded
    resident corpus (+ labels/ids mask arrays), the extract path's
    resident chunk copies when staged, and — when a micro-batch bucket
    (qpad, kcap) is given — that batch's transient terms (padded query
    block + double-buffered candidate lists). The admission controller
    reads the corpus terms as the floor and prices each bucket's
    marginal bytes on top."""
    item = _staging_itemsize(staging)
    terms: Dict[str, int] = {
        "resident_corpus": capacity_rows * na * item,
        "labels_ids": capacity_rows * 8,
    }
    if extract_chunks:
        terms["extract_chunks"] = extract_chunks * chunk_rows * na * item
    if summary_blocks:
        # Device-resident block summaries of the pruned two-stage
        # solve (ops.summaries.stage_summaries): two (B, A) f32 boxes,
        # two (B,) f32 norm bands, one (B,) i32 count vector.
        terms["resident_summaries"] = summary_blocks * (8 * na + 12)
    if multipass_rows:
        # The wide-k multipass path keeps a SECOND full copy of the
        # resident chunks concatenated on device (passes 2+ re-sweep
        # it whole); un-modeled it would let admission over-admit by a
        # corpus once the first wide-k bucket warms.
        terms["multipass_resident"] = multipass_rows * na * item
    if qpad:
        terms["query_blocks"] = qpad * na * item
        terms["topk_carries"] = 2 * qpad * kcap * _TOPK_ITEMSIZE
    return _finish(terms, kind="serve", capacity_rows=capacity_rows,
                   staging=staging)


def fleet_engine_model(mesh_shape, shard_rows: int, na: int,
                       staging: str = "float32", chunks: int = 0,
                       chunk_rows: int = 0, monolithic: bool = False,
                       capacity_rows: int = 0, summary_blocks: int = 0,
                       qloc: int = 0, kcap: int = 0,
                       merge: str = "allgather") -> Dict[str, Any]:
    """Peak resident bytes PER DEVICE for the mesh-resident serving
    engine (:class:`~dmlp_tpu.fleet.mesh_engine.MeshResidentEngine`):
    each device holds its shard's resident chunk buffers (or the
    monolithic shard slice), the replicated label vector, its share of
    the resident summaries, and — when a micro-batch bucket (qloc,
    kcap) is given — that batch's transient terms: the per-column query
    shard, the local candidate lists, and the merge buffer (all R
    shards' lists for the all-gather merge, the O(k) accumulator for
    the ring). The admission controller reads the corpus terms as the
    per-device floor and prices each bucket's marginal bytes on top."""
    item = _staging_itemsize(staging)
    r, c = mesh_shape
    terms: Dict[str, int] = {
        # Replicated labels ride every device (tiny — int32 * capacity).
        "labels_replicated": max(capacity_rows, r * shard_rows) * 4,
    }
    if chunks:
        terms["resident_chunks"] = chunks * chunk_rows * na * item
    if monolithic:
        terms["monolithic_shard"] = shard_rows * na * item
        terms["labels_ids_shard"] = shard_rows * 8
    if summary_blocks:
        terms["resident_summaries"] = summary_blocks * (8 * na + 12)
    if qloc:
        terms["query_shard"] = qloc * na * item
        terms["local_topk"] = qloc * kcap * _TOPK_ITEMSIZE
        # Ring keeps the O(k) accumulator; allgather materializes all R
        # lists. "gspmd" (merge="auto") prices the allgather worst case:
        # the compiler may choose it, and the admission controller must
        # not under-budget on a schedule it cannot see.
        terms["merge_buffer"] = (2 if merge == "ring" else r) \
            * qloc * kcap * _TOPK_ITEMSIZE
    return _finish(terms, kind="fleet", mesh=[r, c],
                   shard_rows=shard_rows, staging=staging,
                   per_device=True, n_devices=r * c)


def _finish(terms: Dict[str, int], **meta) -> Dict[str, Any]:
    out: Dict[str, Any] = {"model_schema": 1,
                           "terms": {k: int(v) for k, v in terms.items()},
                           "total_bytes": int(sum(terms.values()))}
    out.update(meta)
    return out


def resident_bytes_model(kind: str, **params) -> Dict[str, Any]:
    """Dispatch on workload kind: "single" | "sharded" | "ring" |
    "train" — the one public entry the CLI/engines/smoke call."""
    if kind == "single":
        return single_engine_model(**params)
    if kind in ("sharded", "ring"):
        return mesh_engine_model(mode=kind, **params)
    if kind == "train":
        return train_step_model(**params)
    if kind == "serve":
        return serve_engine_model(**params)
    if kind == "fleet":
        return fleet_engine_model(**params)
    raise ValueError(f"unknown workload kind {kind!r}")


def model_for_engine(engine, inp) -> Dict[str, Any]:
    """The analytic model for a live engine + parsed input — reads the
    engine's real config/staging so the model sees exactly the plan the
    solve will resolve."""
    p = inp.params
    kmax = int(inp.ks.max()) if p.num_queries else 1
    if hasattr(engine, "mem_model"):
        # The resident serving engines (serve.ResidentEngine,
        # fleet.MeshResidentEngine) own their model parameterization —
        # bucket_plan is the one kcap derivation, so the model cannot
        # drift from what the solve allocates.
        return engine.mem_model(p.num_queries, kmax)
    if type(engine).__name__ == "SingleChipEngine":
        return single_engine_model(p.num_data, p.num_queries, p.num_attrs,
                                   kmax, config=engine.config,
                                   staging=engine._staging)
    mode = {"RingEngine": "ring",
            "AutoShardedEngine": "auto"}.get(
        type(engine).__name__, "sharded")
    return mesh_engine_model(p.num_data, p.num_queries, p.num_attrs,
                             kmax, tuple(engine.mesh.devices.shape),
                             mode=mode, config=engine.config,
                             staging=engine._staging)


def note_engine_model(engine, inp) -> Optional[Dict[str, Any]]:
    """Engine hook: compute the model and publish it (gauge +
    ``engine.last_mem_model``) when a telemetry session is active;
    no-op otherwise so the hot path pays one module-global read."""
    from dmlp_tpu.obs import telemetry
    if not telemetry.enabled():
        engine.last_mem_model = None
        return None
    try:
        model = model_for_engine(engine, inp)
        engine.last_mem_model = model
        telemetry.registry().gauge("mem.model.resident_bytes").set(
            model["total_bytes"])
        return model
    except Exception:  # observability never fails a solve
        engine.last_mem_model = None
        return None


# -- reconciliation -----------------------------------------------------------

def reconcile(model: Dict[str, Any],
              measured: Dict[str, Any]) -> Dict[str, Any]:
    """Model vs measured watermark. ``measured`` is a
    :func:`measured_watermark` / sampler ``measured_peak()`` dict;
    an unavailable basis yields the explicit ``mem_stats_unavailable``
    marker (markers never gate — PR 5 convention). Otherwise the
    verdict is ``within_tolerance`` against the basis's documented
    :data:`RATIO_BOUNDS`."""
    # Measured bases are PROCESS-WIDE (sums over devices); a per-device
    # model must scale by its device count before the two compare —
    # otherwise an 8-device mesh run reports a healthy solve as ~8x
    # over model.
    scale = int(model.get("n_devices", 1)) if model.get("per_device") \
        else 1
    out: Dict[str, Any] = {
        "model_bytes": int(model["total_bytes"]) * scale}
    if scale != 1:
        out["model_bytes_per_device"] = int(model["total_bytes"])
        out["n_devices"] = scale
    if "unavailable" in measured or not measured.get("bytes"):
        out["mem_stats_unavailable"] = measured.get(
            "unavailable", "measured watermark is zero")
        return out
    basis = measured.get("basis", "memory_stats")
    lo, hi = RATIO_BOUNDS.get(basis, RATIO_BOUNDS["memory_stats"])
    mbytes = int(measured["bytes"])
    ratio = mbytes / max(out["model_bytes"], 1)
    out.update(measured_bytes=mbytes, basis=basis,
               ratio=round(ratio, 3), ratio_bounds=[lo, hi],
               delta_pct=round((mbytes - out["model_bytes"])
                               / out["model_bytes"] * 100.0, 2)
               if out["model_bytes"] else None,
               within_tolerance=bool(lo <= ratio <= hi))
    return out


__all__ = [
    "RATIO_BOUNDS", "device_memory_stats", "live_array_bytes",
    "measured_watermark", "single_engine_model", "mesh_engine_model",
    "train_step_model", "serve_engine_model", "resident_bytes_model",
    "model_for_engine",
    "note_engine_model", "reconcile",
]
