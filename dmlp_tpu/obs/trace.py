"""Lightweight span tracer exporting Chrome-trace / Perfetto JSON.

One process-wide :class:`Tracer` (installed with :func:`install`) collects
complete-duration events (``ph: "X"``) from ``with span("name"):`` blocks
scattered through the engines, the CLI, and the train loop. When no tracer
is installed every hook degenerates to a module-global read returning a
shared no-op span — the hot paths pay nothing measurable (the <2%
instrumentation-overhead budget is enforced by the obs-smoke bench).

Device work is asynchronous under JAX, so a span that brackets only the
*enqueue* of a dispatch would lie about where time goes. Spans therefore
support explicit device fencing: ``sp.fence(arrays)`` makes the span's
closing edge call ``jax.block_until_ready`` on those arrays, so the
recorded duration covers the device work the block launched. Callers that
already synchronize (``jax.device_get``, host readbacks) need no fence.

Export is the Chrome trace-event JSON format — loadable directly in
https://ui.perfetto.dev or chrome://tracing: ``ts``/``dur`` are
microseconds from the tracer's epoch, nested ``X`` events on one thread
render as a flame stack. On a real TPU the tracer can additionally mirror
every span into ``jax.profiler`` annotations (``annotate=True``) so the
same span names appear inside an XLA profiler capture
(``jax.profiler.start_trace`` / ``--profile``).

This module must stay import-light (no jax import at module level): the
CLI imports it unconditionally.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_clock = time.perf_counter

# -- telemetry bridge ---------------------------------------------------------
# When a telemetry session (obs.telemetry) is active it registers
# observers here; every completed span / instant is forwarded (span
# latency histograms + flight-recorder events) WHETHER OR NOT a Tracer
# is installed — `span()` hands out a minimal timing span when only the
# observer wants it. Both slots None (the default) keeps the
# uninstrumented fast path at one module-global read.
_span_observer = None
_instant_observer = None


def set_telemetry_observer(span_cb, instant_cb) -> None:
    """Install/clear the telemetry forwarding callbacks.
    ``span_cb(name, dur_ms, args)``; ``instant_cb(name, args)``."""
    global _span_observer, _instant_observer
    _span_observer = span_cb
    _instant_observer = instant_cb


class _TelemetrySpan:
    """Minimal timing span used when telemetry observes but no Tracer
    is installed: measures wall duration (honoring device fences, like
    the real Span) and forwards one observation — no event storage."""

    __slots__ = ("name", "args", "_t0", "_fences")

    def __init__(self, name: str, args: Dict[str, Any]):
        self.name = name
        self.args = dict(args) if args else {}
        self._t0 = 0.0
        self._fences: list = []

    def set(self, **kwargs) -> None:
        self.args.update(kwargs)

    def fence(self, value) -> None:
        self._fences.append(value)

    def __enter__(self) -> "_TelemetrySpan":
        self._t0 = _clock()
        return self

    def __exit__(self, *exc) -> bool:
        if self._fences:
            try:
                import jax
                jax.block_until_ready(self._fences)
            except Exception:
                pass
            self._fences = []
        cb = _span_observer
        if cb is not None:
            cb(self.name, (_clock() - self._t0) * 1e3, self.args)
        return False


class _NullSpan:
    """Shared no-op span: the uninstrumented fast path. Stateless, so one
    singleton serves every (possibly nested, possibly concurrent) site."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kwargs) -> None:
        pass

    def fence(self, value) -> None:
        pass


NULL_SPAN = _NullSpan()


class Span:
    """One traced region. Use as a context manager; ``set()`` attaches
    args (rendered in the Perfetto detail pane), ``fence()`` registers
    device values to ``block_until_ready`` before the closing timestamp."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_fences", "_annot")

    def __init__(self, tracer: "Tracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.args = dict(args) if args else {}
        self._t0 = 0.0
        self._fences: list = []
        self._annot = None

    def set(self, **kwargs) -> None:
        self.args.update(kwargs)

    def fence(self, value) -> None:
        self._fences.append(value)

    def __enter__(self) -> "Span":
        if self._tracer._annotate:
            try:
                from jax.profiler import TraceAnnotation
                self._annot = TraceAnnotation(self.name)
                self._annot.__enter__()
            except Exception:
                self._annot = None
        self._t0 = _clock()
        return self

    def __exit__(self, *exc) -> bool:
        if self._fences:
            try:
                import jax
                jax.block_until_ready(self._fences)
            except Exception:
                pass  # fencing is best-effort; the span still records
            self._fences = []
        t1 = _clock()
        if self._annot is not None:
            try:
                self._annot.__exit__(*exc)
            except Exception:
                pass
        self._tracer._complete(self.name, self._t0, t1, self.args)
        return False


class Tracer:
    """Thread-safe collector of Chrome-trace events.

    ``annotate=True`` mirrors spans into ``jax.profiler.TraceAnnotation``
    so they show up inside an XLA profiler capture on real TPUs;
    ``profile_dir`` additionally brackets the tracer's lifetime with
    ``jax.profiler.start_trace``/``stop_trace`` (the heavyweight on-device
    capture — span JSON stays available either way).
    """

    #: the tracer's clock domain: per-process ``time.perf_counter`` is
    #: monotonic but has a process-private epoch — timestamps from two
    #: "monotonic" traces are NOT comparable until a merge aligns them
    #: on a shared sync event (tools/merge_traces.py then stamps the
    #: merged doc "synced"). Exported in trace metadata so downstream
    #: skew analysis can refuse mixed clock domains instead of
    #: producing nonsense numbers.
    clock_source = "monotonic"

    def __init__(self, annotate: bool = False,
                 profile_dir: Optional[str] = None):
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._epoch = _clock()
        self._pid = os.getpid()
        self._tids: Dict[int, int] = {}
        self._annotate = annotate
        self._profile_dir = profile_dir
        self._profiling = False
        if profile_dir:
            try:
                import jax
                jax.profiler.start_trace(profile_dir)
                self._profiling = True
            except Exception:
                self._profiling = False

    # -- recording -----------------------------------------------------------
    def span(self, name: str, **args) -> Span:
        return Span(self, name, args)

    def instant(self, name: str, ts: float = None, **args) -> None:
        """A zero-duration marker (``ph: "i"``). ``ts`` (epoch-relative
        us) lets a caller that already stamped a clock read reuse it —
        the dist tracer's clock-sync marker must carry EXACTLY the
        timestamp the merge aligns on, not a second read µs later."""
        if ts is None:
            ts = (_clock() - self._epoch) * 1e6
        self._append({"name": name, "ph": "i", "ts": ts, "s": "t",
                      "pid": self._pid, "tid": self._tid(),
                      **({"args": args} if args else {})})

    def counter(self, name: str, **series) -> None:
        """A counter sample (``ph: "C"``) — Perfetto renders a track."""
        ts = (_clock() - self._epoch) * 1e6
        self._append({"name": name, "ph": "C", "ts": ts, "pid": self._pid,
                      "args": {k: float(v) for k, v in series.items()}})

    def sync_instant(self, name: str, **args) -> None:
        """A clock-sync marker pairing one perf_counter read with one
        wall-clock read taken back-to-back. Unbarriered fleet processes
        have no shared event to align on (unlike the dist collective
        barrier), but they do share the host's wall clock — the merge
        recovers per-process offsets from the (ts, unix_ms) pair, so
        the two reads must bracket nothing in between."""
        t = _clock()
        unix_ms = time.time() * 1e3
        self.instant(name, ts=(t - self._epoch) * 1e6,
                     unix_ms=unix_ms, **args)

    def _complete(self, name: str, t0: float, t1: float,
                  args: Dict[str, Any]) -> None:
        ev = {"name": name, "ph": "X",
              "ts": (t0 - self._epoch) * 1e6,
              "dur": max((t1 - t0) * 1e6, 0.0),
              "pid": self._pid, "tid": self._tid()}
        if args:
            ev["args"] = args
        self._append(ev)
        cb = _span_observer
        if cb is not None:
            cb(name, max((t1 - t0) * 1e3, 0.0), args)

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            return self._tids.setdefault(ident, len(self._tids))

    def _append(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)

    def events(self) -> List[dict]:
        """Thread-safe snapshot of the recorded events — the obs.hlo
        trace-reconcile leg reads collective span byte args from it."""
        with self._lock:
            return list(self._events)

    # -- export --------------------------------------------------------------
    def to_dict(self, process_name: str = "dmlp_tpu") -> dict:
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "args": {"name": process_name}}]
        with self._lock:
            events = meta + list(self._events)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "clock": {"source": self.clock_source}}

    def write(self, path: str, process_name: str = "dmlp_tpu") -> None:
        if self._profiling:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._profiling = False
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(process_name), f)
        os.replace(tmp, path)


# -- process-wide hook -------------------------------------------------------
_active: Optional[Tracer] = None


def install(tracer: Tracer) -> Tracer:
    """Make ``tracer`` the process-wide collector hooks report to."""
    global _active
    _active = tracer
    return tracer


def uninstall() -> None:
    global _active
    _active = None


def active() -> Optional[Tracer]:
    return _active


def span(name: str, **args):
    """Instrumentation hook: a Span on the installed tracer, a minimal
    timing span when only a telemetry session observes, or the shared
    no-op span when both are off (the common case; near-zero cost)."""
    t = _active
    if t is not None:
        return t.span(name, **args)
    if _span_observer is not None:
        return _TelemetrySpan(name, args)
    return NULL_SPAN


def instant(name: str, **args) -> None:
    t = _active
    if t is not None:
        t.instant(name, **args)
    cb = _instant_observer
    if cb is not None:
        cb(name, args)


def counter(name: str, **series) -> None:
    t = _active
    if t is not None:
        t.counter(name, **series)


def sinks_active() -> bool:
    """True when completed spans go anywhere (Tracer or telemetry
    observer). Request-phase instrumentation that must be zero-cost
    when untraced gates its clock reads on this."""
    return _active is not None or _span_observer is not None


def complete_at(name: str, t0: float, t1: float, **args) -> None:
    """Record a span from caller-measured ``perf_counter`` endpoints.

    The ``with span():`` form can only bracket one thread's stack
    frame; request phases (queue wait, scheduled-fire latency) start
    on one thread and end on another, so the producer stamps ``t0``,
    the consumer stamps ``t1``, and this records the interval as a
    regular complete event — same tracer + observer fan-out as Span
    exit, no-op when no sink is installed."""
    t = _active
    if t is not None:
        t._complete(name, t0, t1, args)
        return
    cb = _span_observer
    if cb is not None:
        cb(name, max((t1 - t0) * 1e3, 0.0), args)
