"""Static per-dispatch counters from XLA cost analysis + roofline summary.

XLA knows, at compile time, how many model FLOPs and HBM bytes each
compiled program touches: ``jitted.lower(...).compile().cost_analysis()``.
This module turns that into run-level counters without perturbing the hot
path: engines *record* their dispatches into an installed
:class:`CostProbe` (shapes only — arguments are reduced to
``jax.ShapeDtypeStruct`` specs immediately, so no device buffer is kept
alive), and the probe *collects* after the timed region by re-lowering
each unique (function, shapes, statics) signature once and multiplying by
its dispatch count.

Cost analysis is best-effort across backends and program kinds: every
per-entry failure is swallowed and counted as ``skipped``; a collection
where nothing was analyzable returns ``{"counters_unavailable": True}``
— the explicit marker the CLI metrics contract requires instead of
silence. Pallas kernels expose no XLA cost model at all, so the flagship
extract/distance kernels resolve through the analytic per-kernel models
in :mod:`dmlp_tpu.obs.kernel_cost` instead (consulted first — XLA's
numbers for an interpret-mode Pallas program would measure the
emulation); analytically-resolved dispatch counts are reported
separately as ``dispatches_analytic_model``.

The roofline summary reuses the training side's per-chip peak table
(train.metrics.PEAK_FLOPS_BY_KIND) so KNN solves and train steps report
achieved-vs-peak on the same scale.

Import-light: jax is imported lazily, only when a probe is actually used.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

__all__ = ["CostProbe", "normalize_cost", "lowered_cost", "roofline",
           "install", "uninstall", "active", "record_dispatch",
           "record_measured_iters"]


# cost_analysis() shapes normalize_cost could not use, deduplicated and
# bounded — attached to the counters_unavailable marker so the next JAX
# API drift (a renamed key, a new container type) is diagnosable from a
# ledger entry instead of a repro session.
_UNRECOGNIZED_MAX = 4
_unrecognized_shapes: list = []


def _note_unrecognized(raw) -> None:
    desc: Dict[str, Any] = {"type": type(raw).__name__}
    if isinstance(raw, dict):
        desc["keys"] = sorted(str(k) for k in raw)[:16]
    if desc not in _unrecognized_shapes and \
            len(_unrecognized_shapes) < _UNRECOGNIZED_MAX:
        _unrecognized_shapes.append(desc)


def normalize_cost(raw) -> Optional[Dict[str, float]]:
    """Normalize ``cost_analysis()`` output across JAX versions: a dict,
    a one-element list of dicts, or None. Returns {flops, bytes_accessed}
    (floats; absent keys -> 0.0), or None when there is nothing usable —
    noting the raw shape it could not use (see ``_note_unrecognized``)."""
    if raw is None:
        return None
    if isinstance(raw, (list, tuple)):
        if not raw:
            _note_unrecognized(raw)
            return None
        raw = raw[0]
    if not isinstance(raw, dict):
        _note_unrecognized(raw)
        return None
    flops = float(raw.get("flops", 0.0) or 0.0)
    byts = float(raw.get("bytes accessed", 0.0) or 0.0)
    if flops == 0.0 and byts == 0.0:
        if "flops" not in raw and "bytes accessed" not in raw:
            # a dict that carries NEITHER expected key is shape drift,
            # not a genuinely zero-cost program — record its keys
            _note_unrecognized(raw)
        return None
    return {"flops": flops, "bytes_accessed": byts}


def lowered_cost(fn, *args, **kwargs) -> Optional[Dict[str, float]]:
    """Cost analysis of one jitted signature; None when unavailable
    (non-jitted callable, backend without a cost model, lowering error)."""
    try:
        return normalize_cost(fn.lower(*args, **kwargs).compile()
                              .cost_analysis())
    except Exception:
        return None


class CostProbe:
    """Accumulates dispatch records (shape specs, not buffers) keyed by
    signature; ``collect()`` resolves them into summed counters."""

    def __init__(self) -> None:
        # key -> [fn, spec_args, static_kwargs, count, site]
        self._entries: Dict[Tuple, list] = {}
        # (site, (qb, b, a, kc)) -> iters_total — measured extract-loop
        # iteration counts the engines read back post-fence, keyed by
        # dispatch shape like the dispatch records themselves (two
        # solves at different shapes under one site must cost their
        # iterations at their own tiles, not the first shape's)
        self._measured_iters: Dict[Tuple, int] = {}

    def reset(self) -> None:
        """Drop recorded dispatches — callers bracket untimed work (e.g.
        a warmup solve) so counters match the timed region only."""
        self._entries.clear()
        self._measured_iters.clear()

    def record(self, fn, args: tuple, statics: Optional[dict] = None,
               count: int = 1, site: str = "") -> None:
        """Note ``count`` dispatches of ``fn(*args, **statics)``. ``args``
        are reduced to ShapeDtypeStructs here — nothing stays alive."""
        try:
            import jax
            specs = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args)
        except Exception:
            return  # non-array leaves etc. — observability must not raise
        statics = dict(statics or {})
        key = (id(fn), site,
               str(jax.tree_util.tree_structure(specs)),
               str(jax.tree_util.tree_leaves(specs)),
               tuple(sorted((k, str(v)) for k, v in statics.items())))
        entry = self._entries.get(key)
        if entry is not None:
            entry[3] += count
        else:
            self._entries[key] = [fn, specs, statics, count, site]

    def dispatches(self) -> list:
        """Read-only view of the recorded signatures —
        ``[(fn, spec_args, static_kwargs, count, site), ...]`` — for
        downstream introspection (obs.hlo lowers each unique signature
        once to read its compiled collective schedule and memory)."""
        return [tuple(e) for e in self._entries.values()]

    def record_measured_iters(self, site: str, iters_total: int,
                              shape: Tuple[int, int, int, int],
                              kernel: str = "extract") -> None:
        """Attach MEASURED extraction-loop iteration counts to ``site``
        (summed over the kernel's iters output across that site's
        dispatches at this shape). ``shape`` is the per-dispatch
        (qb, b, a, kc); ``kernel`` ("extract" | "fused") names which
        top-k kernel dispatched, so the collect pass costs each (site,
        shape, kernel)'s count at that kernel's own resolved tiles
        (obs.kernel_cost.extract_loop_cost) and the site's total is no
        longer just the deterministic lower bound."""
        key = (site, tuple(shape), kernel)
        self._measured_iters[key] = \
            self._measured_iters.get(key, 0) + int(iters_total)

    def collect(self) -> Dict[str, Any]:
        """Resolve every recorded signature through cost analysis.

        Returns summed ``flops`` / ``bytes_accessed`` with per-site
        breakdown, or ``{"counters_unavailable": True, ...}`` when no
        signature was analyzable (e.g. a backend with no cost model).
        Functions with a registered analytic model (the Pallas kernels,
        obs.kernel_cost) resolve through it instead of XLA."""
        from dmlp_tpu.obs import kernel_cost

        flops = byts = 0.0
        analyzed = skipped = dispatches = analytic = 0
        per_site: Dict[str, Dict[str, float]] = {}
        for fn, specs, statics, count, site in self._entries.values():
            dispatches += count
            cost = kernel_cost.analytic_cost(fn, specs, statics)
            if cost is not None:
                analytic += count
            else:
                cost = lowered_cost(fn, *specs, **statics)
            if cost is None:
                skipped += count
                continue
            analyzed += count
            flops += cost["flops"] * count
            byts += cost["bytes_accessed"] * count
            if site:
                agg = per_site.setdefault(
                    site, {"flops": 0.0, "bytes_accessed": 0.0,
                           "dispatches": 0})
                agg["flops"] += cost["flops"] * count
                agg["bytes_accessed"] += cost["bytes_accessed"] * count
                agg["dispatches"] += count
        if analyzed == 0:
            out = {"counters_unavailable": True,
                   "dispatches_recorded": dispatches}
            if _unrecognized_shapes:
                out["unrecognized_cost_shapes"] = \
                    [dict(d) for d in _unrecognized_shapes]
                try:
                    import jax
                    out["jax_version"] = jax.__version__
                except Exception:
                    pass
            return out
        # Measured extraction terms: fold each (site, shape, kernel)'s
        # read-back iters count into the totals (count-independent — the
        # engines already summed across that site's dispatches at the
        # shape); ``kernel`` picks the tune-cache namespace the tiles
        # cost at (the fused megakernel may resolve different ones).
        iters_all = 0
        for (site, shape, kern), iters_total in \
                self._measured_iters.items():
            try:
                loop_flops = kernel_cost.extract_loop_cost(
                    *shape, iters_total=iters_total, kernel=kern)
            except Exception:
                continue
            flops += loop_flops
            iters_all += iters_total
            if site in per_site:
                per_site[site]["flops"] += loop_flops
                per_site[site]["extraction_term"] = "measured"
                per_site[site]["extract_iters_total"] = \
                    per_site[site].get("extract_iters_total", 0) \
                    + iters_total
        out: Dict[str, Any] = {
            "flops": flops, "bytes_accessed": byts,
            "dispatches_recorded": dispatches,
            "dispatches_analyzed": analyzed,
        }
        if iters_all:
            out["extract_iters_total"] = iters_all
            out["extraction_term"] = "measured"
        if analytic:
            # Name the modeled share: these dispatches carry analytic
            # (obs.kernel_cost) numbers, not XLA cost analysis.
            out["dispatches_analytic_model"] = analytic
        if skipped:
            # No silent caps: name what the totals do NOT cover.
            out["dispatches_skipped_no_cost_model"] = skipped
        if per_site:
            out["per_site"] = per_site
        return out


def roofline(flops: float, bytes_accessed: float, elapsed_s: float,
             n_chips: int = 1) -> Dict[str, float]:
    """Achieved-vs-peak summary for a solve that took ``elapsed_s``.

    Peak comes from the training side's per-chip table
    (train.metrics.peak_flops_per_chip), so 'utilization_vs_peak' is
    directly comparable to the train loop's MFU. Conservative fallback
    peak on unknown hardware, same as there."""
    out = {"flops": flops, "bytes_accessed": bytes_accessed,
           "elapsed_s": elapsed_s}
    if elapsed_s > 0:
        out["achieved_flops_per_s"] = flops / elapsed_s
        out["achieved_bytes_per_s"] = bytes_accessed / elapsed_s
    if bytes_accessed > 0:
        out["arithmetic_intensity"] = flops / bytes_accessed
    try:
        from dmlp_tpu.train.metrics import peak_flops_per_chip
        peak = peak_flops_per_chip()
        out["peak_flops_per_chip"] = peak
        if elapsed_s > 0 and peak > 0:
            out["utilization_vs_peak"] = flops / (elapsed_s * n_chips * peak)
    except Exception:
        pass  # no backend / no devices: the static counters still stand
    return out


# -- process-wide hook (mirrors obs.trace) -----------------------------------
_active: Optional[CostProbe] = None


def install(probe: Optional[CostProbe] = None) -> CostProbe:
    global _active
    _active = probe if probe is not None else CostProbe()
    return _active


def uninstall() -> None:
    global _active
    _active = None


def active() -> Optional[CostProbe]:
    return _active


def record_dispatch(fn, args: tuple, statics: Optional[dict] = None,
                    count: int = 1, site: str = "") -> None:
    """Hot-path hook: records into the installed probe, no-op otherwise."""
    p = _active
    if p is not None:
        p.record(fn, args, statics=statics, count=count, site=site)


def record_measured_iters(site: str, iters_total: int,
                          shape: Tuple[int, int, int, int],
                          kernel: str = "extract") -> None:
    """Post-fence hook: measured extract-loop iters for ``site``
    (see CostProbe.record_measured_iters); no-op without a probe."""
    p = _active
    if p is not None:
        p.record_measured_iters(site, iters_total, shape, kernel=kernel)
