"""Analytic FLOPs / HBM-bytes models for the Pallas kernels.

XLA's cost analysis returns nothing for ``pallas_call`` programs, so the
flagship extract path reported ``counters_unavailable`` on TPU (ROADMAP
open item). But the kernels' work is a closed-form function of their
dispatch shapes — the grid, tile sizes, and block sweep are all decided
before launch — so this module models each kernel analytically and
:mod:`dmlp_tpu.obs.counters` consults the registry as the resolution
path for these functions (before attempting XLA cost analysis, whose
numbers for an interpret-mode Pallas program would measure the
emulation, not the kernel).

Model scope, per kernel:

- **flops** count the deterministic arithmetic: the MXU cross-term
  matmul (2*Q*B*A — the same convention XLA uses for dot), the norm
  reductions, and the elementwise norm-expansion epilogue. The extract
  kernel's while-loop passes are data-dependent, so by default the model
  is the deterministic lower bound — but the kernel reports its per-tile
  iteration counts, and callers that read them back can pass
  ``iters_total`` to :func:`extract_topk_cost` (or feed
  ``CostProbe.record_measured_iters``) to add the MEASURED extraction
  term (:func:`extract_loop_cost`); the returned dict then carries
  ``extraction_term: "measured"`` instead of ``"modeled_lower_bound"``.
  Both the single-chip engine extract paths AND the mesh engines do
  this whenever a probe is installed: the sharded programs return each
  cell's summed iters through their shard_map fold outputs
  (engine.sharded), so the sharded extraction term is measured too.
- **bytes_accessed** count HBM traffic implied by the BlockSpec sweep:
  each query tile re-reads the data panel and each data block re-reads
  the query panel (Pallas streams blocks from HBM each grid step; only
  the revisited output blocks stay VMEM-resident), plus the outputs.
  Operands are streamed as f32 (both kernels cast on entry).

The distance model's matmul term is validated against XLA's own cost
analysis of the equivalent non-Pallas ``ops.distance`` dispatch
(tests/test_obs_dist.py, 5% tolerance).

Import-light: the ops modules (and hence jax) load only when a cost is
actually resolved.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["extract_topk_cost", "extract_loop_cost", "fused_topk_cost",
           "two_pass_equivalent_cost", "fused_dist_segmin_cost",
           "summaries_score_cost", "analytic_cost", "MXU_PASSES"]

#: MXU hardware passes per dot tile by first-pass precision: the MXU
#: multiplies in bf16, so an f32 dot at HIGHEST preferred precision
#: decomposes into ~3 bf16 product passes (the bf16x3 scheme), while a
#: "bf16" first pass (ops.pallas_* ``precision="bf16"``, f32
#: accumulation) issues ONE. The ``flops`` fields below deliberately do
#: NOT scale by this — they keep XLA's dot convention (2*Q*B*A
#: regardless of precision) so flops stay comparable across arms and
#: history; the pass count is reported alongside as ``mxu_passes`` /
#: ``mxu_precision`` for roofline math that wants hardware-issue terms.
MXU_PASSES = {"f32": 3, "bf16": 1}


def _variant_resolver(kernel: str):
    """The ``_resolve_variant`` of the tune-cache namespace ``kernel``
    ("extract" | "fused") costs its tiles through — ONE mapping so a
    new kernel namespace (or a rename) cannot update one model and
    silently leave another costing at the wrong namespace's tiles."""
    if kernel == "fused":
        from dmlp_tpu.ops.pallas_fused import _resolve_variant
    else:
        from dmlp_tpu.ops.pallas_extract import _resolve_variant
    return _resolve_variant


def extract_loop_cost(qb: int, b: int, a: int, kc: int,
                      iters_total: int, kernel: str = "extract",
                      precision: str = "f32") -> float:
    """MEASURED extraction-loop FLOPs for ``iters_total`` recorded loop
    iterations (summed over the kernel's (Qb/tq, B/tn) ``iters`` output,
    possibly across many dispatches at the same shape).

    One recorded iteration runs ``unroll`` extraction rounds over one
    (tq, tn) tile; each round does, per ne-quarter of width w = tn/ne:
    the quarter min (tq*w), the argmin iota-select (2*tq*w), the mask-out
    (2*tq*w), and the threshold/insert ops on the (tq, kc) lists
    (~4*tq*kc) — so ~5*tq*tn + 4*ne*tq*kc FLOPs per round. ``a`` (the
    attribute width) does not enter the loop arithmetic but DOES enter
    variant resolution (the tuner cache keys on it and the VMEM gate
    scales with it), so it must match the dispatch. ``kernel``
    ("extract" | "fused") selects WHICH tune-cache namespace the tiles
    resolve through — the fused megakernel may run different tiles, so
    its measured iterations must be costed at its own resolution (and
    ``precision`` keys the same resolution — per-precision winners may
    pin different tiles)."""
    from dmlp_tpu.ops.pallas_distance import _tile
    from dmlp_tpu.ops.pallas_extract import _TN

    v = _variant_resolver(kernel)(kc, b, qb, a, precision)
    tq = _tile(qb, v["tile_q"], 8)
    tn = _tile(b, v.get("tile_n", _TN), 128 * v["ne"])
    round_flops = 5.0 * tq * tn + 4.0 * v["ne"] * tq * kc
    return float(iters_total) * v.get("unroll", 1) * round_flops


def _streaming_cost(qb: int, b: int, a: int, kc: int,
                    kernel: str = "extract",
                    precision: str = "f32") -> Dict[str, float]:
    """The SHARED deterministic model of one streaming top-k dispatch
    (the (qb, b) distance tile lives only in VMEM): flops + HBM bytes
    at the tiles the ``kernel`` namespace ("extract" | "fused")
    resolves for this shape. One body for both kernels — the fused
    megakernel adds only its gate term on top — so a future fix to any
    shared term cannot drift between the two models. ``precision``
    keys the variant resolution (per-precision winners) but does NOT
    change the modeled flops/bytes — operands stream at their staged
    width either way and the in-VMEM cast is free of HBM traffic."""
    from dmlp_tpu.ops.pallas_distance import _tile
    from dmlp_tpu.ops.pallas_extract import _TN

    v = _variant_resolver(kernel)(kc, b, qb, a, precision)
    tq = _tile(qb, v["tile_q"], 8)
    tn = _tile(b, v.get("tile_n", _TN), 128 * v["ne"])
    flops = (2.0 * qb * b * a      # MXU cross-term block
             + 2.0 * (qb + b) * a  # |q|^2 / |d|^2 norm reductions
             + 4.0 * qb * b        # expansion + clamp + floor/sentinel masks
             + 1.0 * qb * b)       # block-skip prefilter min, one VPU pass
    byts = 4.0 * ((qb // tq) * b * a    # data panel, once per query tile
                  + (b // tn) * qb * a  # query panel, once per data block
                  + (qb // tq) * b      # dn row, once per query tile
                  + (b // tn) * qb      # qn column, once per data block
                  + 2 * qb * kc         # running (dists, ids) lists out
                  + qb // tq * (b // tn))  # iteration diagnostics
    return {"flops": flops, "bytes_accessed": byts,
            "tq": tq, "tn": tn}


def extract_topk_cost(qb: int, b: int, a: int, kc: int,
                      iters_total: Optional[int] = None,
                      precision: str = "f32") -> Dict[str, float]:
    """Cost of one ``ops.pallas_extract.extract_topk`` dispatch at
    (queries (qb, a), data (b, a), list width kc). Without
    ``iters_total`` the data-dependent while-loop is excluded
    (deterministic lower bound); with it, the measured extraction term
    (:func:`extract_loop_cost`) is added and the dict says so.
    ``precision`` ("f32" | "bf16") keys the tile resolution and is
    reported back with its MXU pass count (:data:`MXU_PASSES`) —
    ``flops`` itself keeps the precision-independent dot convention."""
    base = _streaming_cost(qb, b, a, kc, precision=precision)
    out = {"flops": base["flops"], "bytes_accessed": base["bytes_accessed"],
           "extraction_term": "modeled_lower_bound",
           "mxu_precision": precision,
           "mxu_passes": MXU_PASSES.get(precision, 3)}
    if iters_total is not None:
        out["flops"] += extract_loop_cost(qb, b, a, kc, iters_total,
                                          precision=precision)
        out["extraction_term"] = "measured"
        out["extract_iters_total"] = int(iters_total)
    return out


def fused_topk_cost(qb: int, b: int, a: int, kc: int,
                    iters_total: Optional[int] = None,
                    precision: str = "f32") -> Dict[str, float]:
    """Cost of one ``ops.pallas_fused.fused_topk`` dispatch — the fused
    distance→top-k streaming megakernel. Same one-pass HBM structure as
    :func:`extract_topk_cost` (the (qb, b) distance tile lives only in
    VMEM), with tiles resolved from the FUSED tune-cache namespace and
    the per-block norm-bound MXU gate added to the deterministic FLOPs
    (one VPU pass over the block's dn row + a per-row bound: the price
    of being able to skip the matmul outright).

    The dict also quantifies what the fusion ELIMINATES: the two-pass
    pipeline's HBM write+read of the full (qb, b) distance matrix
    (:func:`two_pass_equivalent_cost`), as
    ``hbm_bytes_two_pass_equiv`` / ``hbm_bytes_saved_vs_two_pass`` /
    ``hbm_traffic_reduction_x`` — the ROADMAP's "one HBM pass for the
    whole hot path" claim as a checked number, not prose. Both sides of
    that delta resolve through the SAME (fused) tile namespace, so the
    saved bytes are EXACTLY the 2·4·qb·b distance round-trip — a cached
    fused variant with different tiles than the extract namespace
    cannot leak tile-resolution differences into the metric.
    ``precision`` keys the tile resolution (both sides of the delta)
    and reports its MXU pass count; ``flops`` stays convention-stable.
    """
    base = _streaming_cost(qb, b, a, kc, kernel="fused",
                           precision=precision)
    tq, tn = base["tq"], base["tn"]
    flops = (base["flops"]
             # The MXU gate itself, per (tq, tn) grid cell: ~3 block
             # reductions over the dn row + ~8 scalar ops per query row
             # for the (|q|-|d|)^2 bound and its eps deflation. (The
             # cross-term block above is an upper bound: gated-out
             # blocks skip the matmul entirely.)
             + (qb // tq) * (b // tn) * (3.0 * tn + 8.0 * tq))
    byts = base["bytes_accessed"]
    tp = two_pass_equivalent_cost(qb, b, a, kc, precision=precision)
    out: Dict[str, float] = {
        "flops": flops, "bytes_accessed": byts,
        "extraction_term": "modeled_lower_bound",
        "mxu_precision": precision,
        "mxu_passes": MXU_PASSES.get(precision, 3),
        "hbm_bytes_two_pass_equiv": tp["bytes_accessed"],
        "hbm_bytes_saved_vs_two_pass": tp["bytes_accessed"] - byts,
        "hbm_traffic_reduction_x": round(tp["bytes_accessed"] / byts, 2),
    }
    if iters_total is not None:
        out["flops"] += extract_loop_cost(qb, b, a, kc, iters_total,
                                          kernel="fused",
                                          precision=precision)
        out["extraction_term"] = "measured"
        out["extract_iters_total"] = int(iters_total)
    return out


def two_pass_equivalent_cost(qb: int, b: int, a: int, kc: int,
                             kernel: str = "fused",
                             precision: str = "f32") -> Dict[str, float]:
    """What the SAME dispatch costs when the (qb, b) distance matrix
    round-trips HBM between a distance kernel and a selection pass —
    the pre-fused hot path's two passes over its dominant term:
    everything the streaming kernel reads anyway, PLUS one full write
    and one full re-read of the f32 distance tile. ``kernel`` picks the
    tile namespace of the streaming base; it defaults to "fused" so the
    fused model's ``hbm_bytes_saved_vs_two_pass`` is exactly the
    round-trip delta by construction (same tiles on both sides), and
    ``precision`` keys that shared resolution too."""
    base = _streaming_cost(qb, b, a, kc, kernel=kernel,
                           precision=precision)
    return {"flops": base["flops"],
            "bytes_accessed": base["bytes_accessed"]
            + 4.0 * 2.0 * qb * b}


def fused_dist_segmin_cost(qb: int, b: int, a: int) -> Dict[str, float]:
    """Deterministic cost of one ``ops.pallas_distance.fused_dist_segmin``
    dispatch: the distance tile is written to HBM (unlike extract) plus
    one 128-wide segment-min pass while the block is in VMEM."""
    from dmlp_tpu.ops.pallas_distance import _TN, _TQ, SEG, _tile

    tq = _tile(qb, _TQ, SEG)
    tn = _tile(b, _TN, 8 * SEG)
    flops = (2.0 * qb * b * a
             + 2.0 * (qb + b) * a
             + 4.0 * qb * b        # expansion + clamp + sentinel mask
             + 1.0 * qb * b)       # segment-min reduction
    byts = 4.0 * ((qb // tq) * b * a
                  + (b // tn) * qb * a
                  + (qb // tq) * 2 * b   # dn + ids rows, per query tile
                  + (b // tn) * qb       # qn column, per data block
                  + qb * b               # the (Qb, B) distance tile out
                  + qb * (b // SEG))     # the transposed segmin out
    return {"flops": flops, "bytes_accessed": byts}


def summaries_score_cost(qb: int, nblocks: int, a: int
                         ) -> Dict[str, float]:
    """Deterministic cost of one ``ops.summaries.score_blocks``
    dispatch (the pruned two-stage solve's per-batch scoring pass over
    the resident block summaries): per (query, block) the norm-band
    bound (~6 ops), the box gap + farthest-corner reductions (~6*a),
    and the threshold accumulation's sort/cumsum (~log2(B) per entry).
    Bytes are the summaries + queries in, the (B,) mask out — the
    whole point is that this is O(blocks * a), not O(corpus)."""
    import math
    logb = max(math.ceil(math.log2(max(nblocks, 2))), 1)
    flops = (2.0 * qb * a                       # query norms
             + qb * nblocks * (6.0 * a + 6.0)   # box + band bounds
             + qb * nblocks * (logb + 4.0))     # sort/cumsum/threshold
    byts = 4.0 * (qb * a                        # query panel
                  + nblocks * (2.0 * a + 3.0)   # boxes + bands + counts
                  + 3.0 * qb * nblocks          # lb/ub/order temps
                  + nblocks)                    # survivor mask out
    return {"flops": flops, "bytes_accessed": byts}


def _extract_entry(specs, statics) -> Optional[Dict[str, float]]:
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(specs)
        (qb, a), (b, _) = leaves[0].shape, leaves[1].shape
        kc = int(statics["kc"])
    except Exception:
        return None
    return extract_topk_cost(qb, b, a, kc,
                             precision=str(statics.get("precision",
                                                       "f32")))


def _fused_entry(specs, statics) -> Optional[Dict[str, float]]:
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(specs)
        (qb, a), (b, _) = leaves[0].shape, leaves[1].shape
        kc = int(statics["kc"])
    except Exception:
        return None
    return fused_topk_cost(qb, b, a, kc,
                           precision=str(statics.get("precision",
                                                     "f32")))


def _segmin_entry(specs, statics) -> Optional[Dict[str, float]]:
    del statics
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(specs)
        (qb, a), (b, _) = leaves[0].shape, leaves[1].shape
    except Exception:
        return None
    return fused_dist_segmin_cost(qb, b, a)


def _score_entry(specs, statics) -> Optional[Dict[str, float]]:
    del statics
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(specs)
        (qb, a) = leaves[0].shape          # q (qpad, a)
        (nblocks,) = leaves[3].shape       # counts (B,)
    except Exception:
        return None
    return summaries_score_cost(qb, nblocks, a)


def analytic_cost(fn, specs, statics: Optional[dict] = None
                  ) -> Optional[Dict[str, float]]:
    """The registered analytic cost of one dispatch of ``fn`` at the
    recorded shape specs, or None when ``fn`` has no model (the caller
    then falls through to XLA cost analysis). Never raises."""
    try:
        from dmlp_tpu.ops import pallas_distance, pallas_extract, \
            pallas_fused, summaries
        models = {
            id(pallas_extract.extract_topk): _extract_entry,
            id(pallas_fused.fused_topk): _fused_entry,
            id(pallas_distance.fused_dist_segmin): _segmin_entry,
            id(summaries.score_blocks): _score_entry,
        }
        entry = models.get(id(fn))
        if entry is None:
            return None
        return entry(specs, dict(statics or {}))
    except Exception:
        return None
