"""Streaming SLO engine: declarative objectives, multi-window burn
rates, and the predictive-autoscaling signal.

PR 16's tail attribution showed that "a queue-phase p99 creeping
round-over-round is a tripwire BEFORE the end-to-end SLO slips" — but
only offline, over merged trace files. This module is the LIVE half:
objectives declared as strings, evaluated continuously against the
process registry's windowed histograms (obs.telemetry's rotating
sub-window rings), with the alert lifecycle and trend slopes exported
on every channel the fleet already watches.

- **Objectives** (:func:`parse_objective`)::

      fleet.request_latency_ms p99 < 50 over 60s
      serve.ok/serve.requests availability > 0.99 over 1m

  A latency objective ``pQQ < X over W`` budgets a ``1 - QQ`` bad
  fraction (samples slower than X ms) over window W; an availability
  objective ``good/total > Y`` budgets ``1 - Y`` failed requests.

- **Dual-window burn rates.** Burn = observed bad fraction over a
  window, divided by the budget: burn 1.0 consumes the error budget
  exactly at the sustainable rate. Each objective is evaluated on a
  FAST window (onset detection, default ``window_s / 6``) and its
  SLOW declared window (sustained-violation confirmation) — the
  Google-SRE multi-window rule scaled to in-process horizons.

- **Alert lifecycle with hysteresis** (flap suppression):
  ``ok → pending`` when the fast burn exceeds budget; ``pending →
  firing`` only after BOTH windows burn hot for ``for_ticks``
  consecutive evaluations; ``firing → ok`` (and ``pending → ok``)
  only after ``clear_ticks`` consecutive healthy evaluations. A load
  spike that alternates good/bad ticks parks in ``pending`` instead
  of flapping fire/clear. Every transition is emitted as an
  ``slo.alert`` trace instant (validated by ``tools/check_trace.py
  --fleet``), a flight-recorder event, and an ``slo.transitions``
  counter; entering ``firing`` additionally dumps the flight ring
  (``FLIGHT_slo_breach_*.json`` — the last 512 events around the
  violation are always captured).

- **Trend estimators.** Per tracked latency series the evaluator
  records the fast-window median each tick and fits a robust
  Theil–Sen slope (median of pairwise slopes — one straggler tick
  cannot bend it). Exposed as ``slo.trend.slope_ms_per_s`` +
  ``slo.trend.projected_crossing_s`` gauges; the projected time to
  threshold crossing is the LEADING signal
  ``fleet.autoscale.predictive_target_replicas`` consumes — scale on
  latency slope, not queue depth.

- **OpenMetrics.** The ``slo_*`` family rides the existing registry
  exposition: ``slo_ok`` / ``slo_pending`` / ``slo_firing`` (one-hot
  per objective, keyed by objective id), ``slo_burn_rate_fast`` /
  ``slo_burn_rate_slow``, and the trend gauges — a scraper needs no
  new endpoint to see objective state.

Import-light (stdlib only), lock-discipline clean: state mutates under
the evaluator's lock, emission (gauges, trace instants, flight dumps)
happens strictly after release — no registry or sink call ever runs
under it.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from dmlp_tpu.obs import telemetry
from dmlp_tpu.obs import trace as obs_trace

# -- objective grammar --------------------------------------------------------

#: alert lifecycle states (ordered by severity)
OK, PENDING, FIRING = "ok", "pending", "firing"
_STATE_LEVEL = {OK: 0, PENDING: 1, FIRING: 2}

_LATENCY_RE = re.compile(
    r"^(?P<metric>[a-z][a-z0-9_.]*)\s+p(?P<q>\d{1,2}(\.\d+)?)\s*<\s*"
    r"(?P<x>[0-9.]+)\s+over\s+(?P<w>[0-9.]+(ms|s|m|h)?)$")
_AVAIL_RE = re.compile(
    r"^(?P<good>[a-z][a-z0-9_.]*)/(?P<total>[a-z][a-z0-9_.]*)\s+"
    r"availability\s*>\s*(?P<y>0?\.[0-9]+|1(\.0+)?)\s+"
    r"over\s+(?P<w>[0-9.]+(ms|s|m|h)?)$")
_WINDOW_UNITS = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_window(text: str) -> float:
    """``"10s"`` / ``"1m"`` / ``"0.5h"`` / bare seconds -> seconds."""
    m = re.match(r"^([0-9.]+)(ms|s|m|h)?$", text.strip())
    if not m:
        raise ValueError(f"unparseable window {text!r}")
    return float(m.group(1)) * _WINDOW_UNITS.get(m.group(2) or "s", 1.0)


class Objective:
    """One declared objective. ``kind`` is ``"latency"`` (histogram
    quantile under a threshold) or ``"availability"`` (good/total
    counter ratio above a target). ``budget`` is the allowed bad
    fraction the burn rate is normalized by."""

    def __init__(self, name: str, kind: str, *, metric: str = "",
                 quantile: float = 0.99, threshold: float = 0.0,
                 good: str = "", total: str = "", target: float = 0.0,
                 window_s: float = 60.0,
                 sample_fn: Optional[Callable[[], Tuple[float, float]]]
                 = None):
        if kind not in ("latency", "availability"):
            raise ValueError(f"objective kind {kind!r}")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.quantile = float(quantile)
        self.threshold = float(threshold)
        self.good = good
        self.total = total
        self.target = float(target)
        self.window_s = float(window_s)
        #: cumulative (good, total) override — the router feeds
        #: fleet-wide availability from the MERGED scrape through this
        self.sample_fn = sample_fn
        if kind == "latency" and not (0.0 < self.quantile < 1.0):
            raise ValueError(f"latency quantile {quantile}")
        if kind == "availability" and not (0.0 < self.target < 1.0):
            raise ValueError(f"availability target {target}")

    @property
    def budget(self) -> float:
        """Allowed bad fraction: ``1 - q`` / ``1 - target``."""
        return (1.0 - self.quantile if self.kind == "latency"
                else 1.0 - self.target)

    def window_label(self) -> str:
        w = self.window_s
        return f"{w / 60:g}m" if w >= 60 else f"{w:g}s"

    def describe(self) -> str:
        if self.kind == "latency":
            return (f"{self.metric} p{self.quantile * 100:g} < "
                    f"{self.threshold:g} over {self.window_label()}")
        return (f"{self.good}/{self.total} availability > "
                f"{self.target:g} over {self.window_label()}")


def parse_objective(spec: str, name: Optional[str] = None) -> Objective:
    """Parse one declarative objective string (module docstring
    grammar). ``name`` defaults to a derived id such as
    ``fleet.request_latency_ms:p99``."""
    s = spec.strip()
    m = _LATENCY_RE.match(s)
    if m:
        q = float(m.group("q")) / 100.0
        return Objective(
            name or f"{m.group('metric')}:p{m.group('q')}", "latency",
            metric=m.group("metric"), quantile=q,
            threshold=float(m.group("x")),
            window_s=parse_window(m.group("w")))
    m = _AVAIL_RE.match(s)
    if m:
        return Objective(
            name or f"{m.group('total')}:availability", "availability",
            good=m.group("good"), total=m.group("total"),
            target=float(m.group("y")),
            window_s=parse_window(m.group("w")))
    raise ValueError(
        f"unparseable objective {spec!r} (expected "
        "'<metric> pQQ < X over W' or "
        "'<good>/<total> availability > Y over W')")


# -- robust trend -------------------------------------------------------------

def theil_sen(points: Sequence[Tuple[float, float]]) -> float:
    """Median of all pairwise slopes — the robust trend estimator (up
    to ~29% outlier points cannot bend it, unlike least squares).
    NaN below two distinct x values."""
    slopes: List[float] = []
    n = len(points)
    for i in range(n):
        xi, yi = points[i]
        for j in range(i + 1, n):
            xj, yj = points[j]
            if xj != xi:
                slopes.append((yj - yi) / (xj - xi))
    if not slopes:
        return math.nan
    slopes.sort()
    mid = len(slopes) // 2
    if len(slopes) % 2:
        return slopes[mid]
    return 0.5 * (slopes[mid - 1] + slopes[mid])


# -- evaluator ----------------------------------------------------------------

class _ObjectiveState:
    """Mutable per-objective evaluation state (guarded by the
    evaluator's lock)."""

    def __init__(self, obj: Objective):
        self.obj = obj
        self.state = OK
        self.bad_streak = 0
        self.good_streak = 0
        #: (t, cumulative good, cumulative total) ring (availability)
        self.counter_ring: deque = deque()
        #: (t, fast-window median) ring for the trend fit
        self.trend_ring: deque = deque()
        self.signals: Dict[str, Any] = {"state": OK}
        self.cycles = 0            # completed ok->...->ok alert cycles


class SLOEvaluator:
    """Continuous evaluation of declared objectives against a live
    registry. ``tick()`` is one evaluation pass (tests and in-process
    hosts drive it directly); ``start()`` runs it on a deadline-
    anchored background thread.

    ``trend_metrics`` names EXTRA histograms (e.g. the queue-phase
    latency) whose fast-window median slope is tracked and exported
    even without an objective on them — the queue-phase tripwire."""

    def __init__(self, objectives: Sequence[Any],
                 registry: Optional[telemetry.Registry] = None, *,
                 fast_s: Optional[float] = None,
                 for_ticks: int = 2, clear_ticks: int = 3,
                 min_samples: int = 1, trend_points: int = 12,
                 trend_metrics: Sequence[str] = (),
                 sub_s: Optional[float] = None,
                 time_fn=None, flight_dump: bool = True):
        self.registry = registry or telemetry.REGISTRY
        self.objectives: List[Objective] = [
            o if isinstance(o, Objective) else parse_objective(o)
            for o in objectives]
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")
        self.for_ticks = max(int(for_ticks), 1)
        self.clear_ticks = max(int(clear_ticks), 1)
        self.min_samples = max(int(min_samples), 1)
        self.trend_points = max(int(trend_points), 3)
        self.trend_metrics = list(trend_metrics)
        self.flight_dump = flight_dump
        self._time = time_fn or time.monotonic
        self._fast_s = fast_s
        self._sub_s = sub_s
        self._lock = threading.Lock()
        self._states = {o.name: _ObjectiveState(o)
                        for o in self.objectives}
        self._trend_rings: Dict[str, deque] = {
            m: deque() for m in self.trend_metrics}
        self.transitions: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._bind_windows()

    # -- window plumbing -------------------------------------------------------

    def fast_window(self, obj: Objective) -> float:
        if self._fast_s is not None:
            return min(float(self._fast_s), obj.window_s)
        return max(obj.window_s / 6.0, 2.0 * self._sub_for(obj))

    def _sub_for(self, obj: Objective) -> float:
        if self._sub_s is not None:
            return float(self._sub_s)
        # Enough resolution for the fast window: >= 4 sub-windows in
        # window_s / 6, capped at the module default.
        return min(telemetry.WINDOW_SUB_S, obj.window_s / 24.0)

    def _bind_windows(self) -> None:
        """Enable the sliding-window ring on every histogram an
        objective or trend series reads (get-or-create: declaring an
        objective before the serving path registers the histogram is
        fine — R6 get-or-create returns the same object later)."""
        horizons = [o.window_s for o in self.objectives] or [60.0]
        max_w = max(horizons)
        for obj in self.objectives:
            if obj.kind != "latency":
                continue
            h = self.registry.histogram(obj.metric, unit="ms")  # check: allow-metric-name — objective-declared series
            h.enable_windows(max_window_s=max(max_w, obj.window_s),
                             sub_s=self._sub_for(obj),
                             time_fn=self._time)
        sub = (float(self._sub_s) if self._sub_s is not None
               else min(telemetry.WINDOW_SUB_S, max_w / 24.0))
        for name in self.trend_metrics:
            h = self.registry.histogram(name, unit="ms")  # check: allow-metric-name — trend-declared series
            h.enable_windows(max_window_s=max_w, sub_s=sub,
                             time_fn=self._time)

    # -- one evaluation pass ---------------------------------------------------

    def _measure(self, st: _ObjectiveState, now: float
                 ) -> Dict[str, Any]:
        """Raw window measurements for one objective — registry reads
        only, NO evaluator state mutation (runs outside the lock)."""
        obj = st.obj
        out: Dict[str, Any] = {"objective": obj.name,
                               "window": obj.window_label(),
                               "budget": obj.budget}
        if obj.kind == "latency":
            out["threshold"] = obj.threshold
            h = self.registry.get(obj.metric)
            fast = self.fast_window(obj)
            bf, nf = h.window_above(fast, obj.threshold)
            bs, ns = h.window_above(obj.window_s, obj.threshold)
            out["fast_n"], out["slow_n"] = nf, ns
            out["burn_fast"] = (bf / nf / obj.budget) if nf else 0.0
            out["burn_slow"] = (bs / ns / obj.budget) if ns else 0.0
            out["p_fast"] = h.window_quantile(fast, obj.quantile)
            out["p_window"] = h.window_quantile(obj.window_s,
                                                obj.quantile)
            out["median_fast"] = h.window_quantile(fast, 0.5)
        else:
            if obj.sample_fn is not None:
                good, total = obj.sample_fn()
            else:
                g = self.registry.get(obj.good)
                t = self.registry.get(obj.total)
                good = g.total() if g is not None else 0.0
                total = t.total() if t is not None else 0.0
            out["cum_good"], out["cum_total"] = float(good), float(total)
        return out

    def _avail_burns(self, st: _ObjectiveState, now: float,
                     meas: Dict[str, Any]) -> None:
        """Availability burn rates from the cumulative-counter ring
        (mutates the ring — caller holds the lock)."""
        obj = st.obj
        ring = st.counter_ring
        ring.append((now, meas["cum_good"], meas["cum_total"]))
        while len(ring) > 2 and ring[1][0] <= now - obj.window_s:
            ring.popleft()

        def burn(window: float) -> Tuple[float, float]:
            base = ring[0]
            for entry in ring:
                if entry[0] >= now - window:
                    break
                base = entry
            dgood = meas["cum_good"] - base[1]
            dtotal = meas["cum_total"] - base[2]
            if dtotal <= 0:
                return 0.0, 0.0
            bad_frac = max(dtotal - dgood, 0.0) / dtotal
            return bad_frac / obj.budget, dtotal

        meas["burn_fast"], meas["fast_n"] = burn(self.fast_window(obj))
        meas["burn_slow"], meas["slow_n"] = burn(obj.window_s)

    @staticmethod
    def next_state(state: str, hot_fast: bool, hot_slow: bool,
                   bad_streak: int, good_streak: int,
                   for_ticks: int, clear_ticks: int) -> str:
        """The PURE lifecycle rule (unit-testable): dual-window entry,
        streak-based hysteresis, no firing->pending shortcut."""
        if state == OK:
            return PENDING if hot_fast else OK
        if state == PENDING:
            if hot_fast and hot_slow and bad_streak >= for_ticks:
                return FIRING
            if not hot_fast and good_streak >= clear_ticks:
                return OK
            return PENDING
        # FIRING clears only after a full healthy streak on BOTH
        # windows — a single good tick inside a flapping overload
        # must not clear (and re-fire) the alert.
        if not hot_fast and not hot_slow \
                and good_streak >= clear_ticks:
            return OK
        return FIRING

    def tick(self) -> List[Dict[str, Any]]:
        """One evaluation pass over every objective. Returns the
        transitions it emitted (empty list most ticks)."""
        now = self._time()
        measures = [self._measure(st, now)
                    for st in self._states.values()]
        trend_raw: Dict[str, float] = {}
        for name in self.trend_metrics:
            h = self.registry.get(name)
            if isinstance(h, telemetry.Histogram) and h.windowed:
                sub = h._sub_s
                trend_raw[name] = h.window_quantile(
                    max(4 * sub, 10.0), 0.5)
        emitted: List[Dict[str, Any]] = []
        gauge_sets: List[Tuple[str, float, str]] = []
        with self._lock:
            for meas in measures:
                st = self._states[meas["objective"]]
                obj = st.obj
                if obj.kind == "availability":
                    self._avail_burns(st, now, meas)
                hot_fast = meas["burn_fast"] > 1.0 \
                    and meas["fast_n"] >= self.min_samples
                hot_slow = meas["burn_slow"] > 1.0 \
                    and meas["slow_n"] >= self.min_samples
                if hot_fast:
                    st.bad_streak += 1
                    st.good_streak = 0
                else:
                    st.good_streak += 1
                    st.bad_streak = 0
                new = self.next_state(
                    st.state, hot_fast, hot_slow, st.bad_streak,
                    st.good_streak, self.for_ticks, self.clear_ticks)
                med = meas.get("median_fast")
                if med is not None and not math.isnan(med):
                    st.trend_ring.append((now, med))
                    while len(st.trend_ring) > self.trend_points:
                        st.trend_ring.popleft()
                slope = theil_sen(list(st.trend_ring))
                meas["slope_ms_per_s"] = slope
                p_now = meas.get("p_fast")
                if obj.kind == "latency" and p_now is not None \
                        and not math.isnan(p_now) \
                        and not math.isnan(slope) and slope > 0 \
                        and p_now < obj.threshold:
                    meas["projected_s"] = \
                        (obj.threshold - p_now) / slope
                else:
                    meas["projected_s"] = math.inf
                meas["state"], meas["prev"] = new, st.state
                if new != st.state:
                    if new == OK and st.state != OK:
                        st.cycles += 1
                    tr = {"objective": obj.name, "prev": st.state,
                          "state": new, "window": obj.window_label(),
                          "burn_fast": round(meas["burn_fast"], 4),
                          "burn_slow": round(meas["burn_slow"], 4),
                          "t": now}
                    self.transitions.append(tr)
                    emitted.append(tr)
                    st.state = new
                    st.bad_streak = 0
                    st.good_streak = 0
                st.signals = dict(meas)
                lvl = _STATE_LEVEL[new]
                gauge_sets += [
                    ("slo.state", float(lvl), obj.name),
                    ("slo.ok", 1.0 if lvl == 0 else 0.0, obj.name),
                    ("slo.pending", 1.0 if lvl == 1 else 0.0,
                     obj.name),
                    ("slo.firing", 1.0 if lvl == 2 else 0.0,
                     obj.name),
                    ("slo.burn_rate.fast",
                     round(meas["burn_fast"], 4), obj.name),
                    ("slo.burn_rate.slow",
                     round(meas["burn_slow"], 4), obj.name)]
                if not math.isnan(slope):
                    gauge_sets.append(("slo.trend.slope_ms_per_s",
                                       round(slope, 4), obj.name))
                    if math.isfinite(meas["projected_s"]):
                        gauge_sets.append(
                            ("slo.trend.projected_crossing_s",
                             round(meas["projected_s"], 3), obj.name))
            for name, med in trend_raw.items():
                ring = self._trend_rings[name]
                if not math.isnan(med):
                    ring.append((now, med))
                    while len(ring) > self.trend_points:
                        ring.popleft()
                slope = theil_sen(list(ring))
                if not math.isnan(slope):
                    gauge_sets.append(("slo.trend.slope_ms_per_s",
                                       round(slope, 4), name))
        # Emission strictly AFTER the evaluator lock is released: the
        # registry's metric locks and the trace/flight sinks stay leaf
        # locks (R7 lock-ordering discipline).
        for name, value, label in gauge_sets:
            # Names are the literal slo.* family above, routed through
            # one emission loop; the objective id rides as the label.
            self.registry.gauge(name).set(value, label=label)  # check: allow-metric-name
        for tr in emitted:
            self.registry.counter("slo.transitions").inc(
                label=tr["state"])
            obs_trace.instant("slo.alert", objective=tr["objective"],
                              prev=tr["prev"], state=tr["state"],
                              window=tr["window"],
                              burn_fast=tr["burn_fast"],
                              burn_slow=tr["burn_slow"])
            telemetry.flight_event("slo.alert",
                                   objective=tr["objective"],
                                   prev=tr["prev"], state=tr["state"],
                                   window=tr["window"])
            if tr["state"] == FIRING and self.flight_dump:
                safe = re.sub(r"[^A-Za-z0-9_]+", "_", tr["objective"])
                telemetry.dump_on_crash(f"slo_breach_{safe}")
        return emitted

    # -- signal taps -----------------------------------------------------------

    def signals(self, objective: str) -> Dict[str, Any]:
        """The latest evaluation of one objective — burn rates, window
        quantiles, slope, projected crossing, state. The predictive
        autoscale policy's input."""
        with self._lock:
            st = self._states[objective]
            return dict(st.signals)

    def trend_slope(self, metric: str) -> float:
        """Latest Theil–Sen slope (ms/s) of a trend-tracked metric."""
        with self._lock:
            ring = self._trend_rings.get(metric)
            pts = list(ring) if ring else []
        return theil_sen(pts)

    def state(self, objective: str) -> str:
        with self._lock:
            return self._states[objective].state

    def alert_cycles(self, objective: str) -> int:
        """Completed ok -> (pending|firing)+ -> ok cycles."""
        with self._lock:
            return self._states[objective].cycles

    def snapshot(self) -> Dict[str, Any]:
        """Stats-endpoint view: per-objective spec, state, burn rates,
        transition count."""
        with self._lock:
            out: Dict[str, Any] = {"objectives": {}}
            for name, st in self._states.items():
                sig = st.signals
                out["objectives"][name] = {
                    "spec": st.obj.describe(),
                    "state": st.state,
                    "burn_fast": round(sig.get("burn_fast", 0.0), 4),
                    "burn_slow": round(sig.get("burn_slow", 0.0), 4),
                    "cycles": st.cycles}
            out["transitions"] = len(self.transitions)
            return out

    # -- background loop -------------------------------------------------------

    def start(self, interval_s: float = 0.5) -> None:
        """Evaluate every ``interval_s`` on a daemon thread (deadline-
        anchored — the Sampler's drift fix applies here too)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = threading.Event()
        stop = self._stop

        def loop() -> None:
            deadline = time.monotonic()
            while not stop.is_set():
                try:
                    self.tick()
                except Exception:  # check: no-retry — evaluation must
                    pass           # never kill the host; next tick
                    #                re-reads everything from scratch
                deadline, delay = telemetry.Sampler._next_deadline(
                    deadline, time.monotonic(), float(interval_s))
                stop.wait(delay)

        self._thread = threading.Thread(target=loop, name="slo-eval",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=5.0)


__all__ = [
    "OK", "PENDING", "FIRING", "Objective", "parse_objective",
    "parse_window", "theil_sen", "SLOEvaluator",
]
