"""Unified observability: span traces, XLA cost counters, collective
traffic accounting, and versioned run artifacts.

The reference's entire observability surface is one ``Time taken: <ms> ms``
stderr line (common.cpp:130). That contract line stays byte-identical
(utils.timing); this package is everything on top of it, unified so the
engines, the train loop, and the bench harness stop inventing private
timing/metrics schemas:

- :mod:`dmlp_tpu.obs.trace` — lightweight span tracer exporting
  Chrome-trace / Perfetto-loadable JSON, with an optional bridge to
  ``jax.profiler`` annotations on real TPUs.
- :mod:`dmlp_tpu.obs.dist_trace` — the multi-process half: per-rank
  tracers (rank = Perfetto pid) writing ``trace-rank<NN>.json`` with
  barrier-stamped clock-sync markers; ``tools/merge_traces.py`` merges
  the rank files into one aligned multi-process trace.
- :mod:`dmlp_tpu.obs.counters` — static per-dispatch FLOPs / HBM-bytes
  counters from XLA's ``compiled.cost_analysis()``, with an
  achieved-vs-peak roofline summary.
- :mod:`dmlp_tpu.obs.kernel_cost` — analytic FLOPs/bytes models for the
  Pallas kernels (which expose no XLA cost model); the counters probe
  resolves registered kernels through these instead of reporting
  ``counters_unavailable``.
- :mod:`dmlp_tpu.obs.comms` — analytic collective-traffic accounting
  (bytes per mesh axis for the all-gather merge, the ring ``ppermute``
  merge, grad ``psum``, the MoE all-to-all, and the pipeline's
  activation ``ppermute``).
- :mod:`dmlp_tpu.obs.run` — the versioned :class:`RunRecord` artifact
  writer all emitters share (replacing the divergent ``BENCH_*.json``
  shapes going forward; the legacy ``tools/*`` emitters are migrated).
- :mod:`dmlp_tpu.obs.telemetry` — the LIVE half: a process-wide
  thread-safe metrics registry (counters / gauges / log-bucket
  streaming histograms with bounded-error p50/p95/p99), a background
  device-memory sampler, OpenMetrics file/HTTP export
  (``--telemetry``), and the crash flight recorder (bounded
  span/event ring dumped as ``FLIGHT_*.json`` on crash, fatal fault,
  or SIGTERM). The resilience counters write through this registry —
  one source of truth for live scrapes and end-of-run blocks.
- :mod:`dmlp_tpu.obs.memwatch` — device-memory watermarks: the
  analytic peak-HBM resident-set model per engine/config (the comms
  model's missing memory sibling), measured bases
  (``memory_stats()`` / live-array bytes, with the explicit
  ``mem_stats_unavailable`` marker), and their reconciliation under
  documented per-basis tolerance bounds.
- :mod:`dmlp_tpu.obs.ledger` — the perf ledger: ingests every run
  artifact (schema RunRecords AND the grandfathered legacy shapes)
  into per-series round-keyed trajectories with noise-aware A/B deltas
  (MAD bands over per-trial samples; explicit ``insufficient_trials``
  / ``device_mismatch`` markers). Rendered by ``python -m
  dmlp_tpu.report``; gated by ``tools/perf_gate.py`` (``make
  perf-gate``).

Every module here is import-light: none of them import jax at module
level, so the CLI's fast startup path is unaffected when observability is
off, and the no-op span/probe hooks in the engine hot paths cost one
module-global read each.
"""

from dmlp_tpu.obs.run import SCHEMA_VERSION, RunRecord  # noqa: F401
