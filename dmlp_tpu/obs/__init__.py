"""Unified observability: span traces, XLA cost counters, collective
traffic accounting, and versioned run artifacts.

The reference's entire observability surface is one ``Time taken: <ms> ms``
stderr line (common.cpp:130). That contract line stays byte-identical
(utils.timing); this package is everything on top of it, unified so the
engines, the train loop, and the bench harness stop inventing private
timing/metrics schemas:

- :mod:`dmlp_tpu.obs.trace` — lightweight span tracer exporting
  Chrome-trace / Perfetto-loadable JSON, with an optional bridge to
  ``jax.profiler`` annotations on real TPUs.
- :mod:`dmlp_tpu.obs.counters` — static per-dispatch FLOPs / HBM-bytes
  counters from XLA's ``compiled.cost_analysis()``, with an
  achieved-vs-peak roofline summary.
- :mod:`dmlp_tpu.obs.comms` — analytic collective-traffic accounting
  (bytes per mesh axis for the all-gather merge, the ring ``ppermute``
  merge, grad ``psum``, and the MoE all-to-all).
- :mod:`dmlp_tpu.obs.run` — the versioned :class:`RunRecord` artifact
  writer all emitters share (replacing the divergent ``BENCH_*.json``
  shapes going forward).

Every module here is import-light: none of them import jax at module
level, so the CLI's fast startup path is unaffected when observability is
off, and the no-op span/probe hooks in the engine hot paths cost one
module-global read each.
"""

from dmlp_tpu.obs.run import SCHEMA_VERSION, RunRecord  # noqa: F401
