"""Compiled-program introspection: the HLO-derived collective/memory ledger.

Every analytic model in this package (obs.comms traffic, obs.memwatch
peak-HBM, obs.kernel_cost FLOPs/bytes) is checked against traces and
watermarks — but never against what XLA actually compiled. This module
closes that loop: given any ``jax.stages.Compiled`` (or a jitted fn plus
abstract args to lower), it extracts

- the **collective schedule** — ``compiled.as_text()`` parsed for
  ``all-reduce`` / ``all-gather`` / ``reduce-scatter`` /
  ``collective-permute`` / ``all-to-all`` ops with operand shapes,
  element types and ``replica_groups``, with while-loop trip counts
  (``known_trip_count`` backend config) folded in so a scanned ring
  ppermute counts its R-1 hops, not 1;
- **memory** — ``compiled.memory_analysis()`` (temp / argument / output /
  alias bytes), with the explicit ``hlo_memory_unavailable`` marker where
  the backend returns nothing;
- **cost** — the existing ``cost_analysis()`` path (obs.counters
  .normalize_cost), unified behind the same record.

One schema-versioned :class:`HloReport` per compiled executable, cached
by executable fingerprint (sha-256 of the HLO text — two lowers of the
same program parse once).

**Byte convention.** ``bytes_moved`` uses the same per-device wire-byte
accounting obs.comms documents (the ring-algorithm bound), so the two
sides reconcile without per-kind fudge factors: all-gather moves
(g-1) x shard bytes per device, all-reduce 2(g-1)/g x buffer,
reduce-scatter and all-to-all (g-1)/g x buffer, collective-permute the
full operand per source->target pair. Totals cover all devices, groups
and loop iterations.

**Three-way reconcile** (:func:`three_way`): HLO-derived collective
bytes vs the ``# check: comms-model=`` analytic models
(:data:`MODEL_COLLECTIVE_KINDS` is the annotation->kind table check
family R10 validates against), vs traced ``dist.*`` span traffic where
traces exist, and ``memory_analysis`` vs the memwatch model + live
watermark. Tolerances are documented ratio bounds
(:data:`COMMS_RATIO_BOUNDS`, :data:`MEMORY_RATIO_BOUNDS`); an
unavailable basis yields an explicit ``*_unavailable`` marker, never
silence (markers never gate — PR 5 convention).

Import-light: jax is touched only when a compiled object is actually
introspected; parsing is pure text.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import Any, Dict, List, Optional, Tuple

#: bump on any backward-incompatible HloReport field change
SCHEMA_VERSION = 1

#: the HLO collective opcodes the parser recognizes (async ``-start``
#: forms normalize onto these; ``-done`` halves are bookkeeping, skipped)
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

#: obs.comms model *function* -> the HLO collective kind its formula
#: prices. This is the reconcile table: every ``# check: comms-model=``
#: annotation must name a key here (check family R10), so a renamed
#: model cannot leave a dangling annotation that reconciles nothing.
MODEL_COLLECTIVE_KINDS: Dict[str, str] = {
    "allgather_topk_traffic": "all-gather",
    "host_allgather_candidates_traffic": "all-gather",
    "ring_topk_traffic": "collective-permute",
    "pipeline_ppermute_traffic": "collective-permute",
    "psum_traffic": "all-reduce",
    "tp_psum_activation_traffic": "all-reduce",
    "ep_psum_combine_traffic": "all-reduce",
    "moe_a2a_traffic": "all-to-all",
}

#: CollectiveTraffic.collective record name -> HLO collective kind (the
#: runtime face of the same table: engine.last_comms entries map through
#: this when reconciling a live record instead of a source annotation)
TRAFFIC_COLLECTIVE_KINDS: Dict[str, str] = {
    "all_gather_merge_topk": "all-gather",
    "host_allgather_candidates": "all-gather",
    "ring_allreduce_topk": "collective-permute",
    "ppermute_pipeline": "collective-permute",
    "psum_grads": "all-reduce",
    "psum_tp_activations": "all-reduce",
    "psum_ep_combine": "all-reduce",
    "moe_all_to_all": "all-to-all",
    # gspmd_* records are HLO-derived (traffic_from_report) — identity
    "gspmd_all-reduce": "all-reduce",
    "gspmd_all-gather": "all-gather",
    "gspmd_reduce-scatter": "reduce-scatter",
    "gspmd_collective-permute": "collective-permute",
    "gspmd_all-to-all": "all-to-all",
}

#: traced span name -> collective kind, for the trace leg of the
#: reconcile (spans must carry an ``nbytes`` arg to participate;
#: dist.allgather_candidates is the multi-host candidate gather whose
#: analytic twin tools/merge_traces.py already checks per rank)
SPAN_COLLECTIVE_KINDS: Dict[str, str] = {
    "dist.allgather_candidates": "all-gather",
}

#: documented model-vs-HLO tolerance, as ratio bounds on
#: hlo_bytes/model_bytes: padding rounds differently on the two sides
#: (the model prices q_local x k exactly; the compiled program moves the
#: padded buffers), and XLA may fuse or resplit a collective — within
#: [0.5, 2.0]x the schedule corroborates the model, outside it one of
#: the two is wrong.
COMMS_RATIO_BOUNDS: Tuple[float, float] = (0.5, 2.0)

#: memory_analysis-vs-model ratio bounds (hlo/model). The two sides
#: price different things on purpose: the memwatch model prices the
#: solve's RESIDENT arrays, while XLA's static buffer assignment prices
#: one executable's full temp set without the liveness sharing a real
#: run gets (observed ~9x above the model on the monolithic CPU solve)
#: and, on chunked paths, sits far BELOW the model (one chunk's buffers
#: vs the staged corpus). This leg is an order-of-magnitude
#: corroboration, not an equality check — hence bounds much wider than
#: :data:`COMMS_RATIO_BOUNDS`.
MEMORY_RATIO_BOUNDS: Tuple[float, float] = (0.02, 16.0)

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "tf32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
}

# f32[8,1,16] — dtype token then dims (scalars: f32[] -> 1 element)
_SHAPE_RE = re.compile(r"([a-z]+[0-9a-z]*)\[([0-9,]*)\]")
_OPCODE_RE = re.compile(
    r"\s(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(-start|-done)?\(")
_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}"
                                r"(?:,\{[^}]*\})*)?\}")
_REPLICA_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[\d+\]")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^}]*\}"
                       r"(?:,\{[^}]*\})*)\}")
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")
_TRIP_COUNT_RE = re.compile(r"known_trip_count[\"':\s{]+n[\"':\s]+(\d+)")
_WHILE_RE = re.compile(r"\swhile\(")
_BODY_RE = re.compile(r"body=%?([\w.\-]+)")
# computation definition: `%name (args...) -> type {` (args may nest
# parens and carry /*index=N*/ comments — only the leading name matters)
_COMPUTATION_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(")


def _shape_bytes(segment: str) -> Tuple[int, List[str]]:
    """Total bytes + dtypes of every ``dtype[dims]`` shape in ``segment``
    (layout suffixes like ``{2,1,0}`` follow the bracket and don't
    match). Unknown dtypes count 0 bytes rather than guessing."""
    total = 0
    dtypes: List[str] = []
    for dt, dims in _SHAPE_RE.findall(segment):
        item = _DTYPE_BYTES.get(dt)
        if item is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * item
        dtypes.append(dt)
    return total, dtypes


def _parse_groups(line: str,
                  num_partitions: Optional[int]) -> Tuple[int, int]:
    """(group_size, n_groups) from ``replica_groups`` — explicit list or
    iota form; an absent/empty attribute means one group of every
    partition (XLA's default)."""
    m = _REPLICA_IOTA_RE.search(line)
    if m:
        return int(m.group(2)), int(m.group(1))
    m = _REPLICA_GROUPS_RE.search(line)
    if m and m.group(1):
        groups = re.findall(r"\{([^}]*)\}", m.group(1))
        sizes = [len([x for x in g.split(",") if x.strip()])
                 for g in groups]
        return (max(sizes) if sizes else 1), len(groups)
    return (num_partitions or 1), 1


def _parse_pairs(line: str) -> Tuple[int, int, int]:
    """(n_pairs, ring_length, n_rings) from ``source_target_pairs``:
    follow the permutation's cycles — the ring length is the mesh-axis
    size the permute walks, the number of cycles its group count."""
    m = _PAIRS_RE.search(line)
    if not m:
        return 0, 1, 1
    pairs = [tuple(int(x) for x in g.split(","))
             for g in re.findall(r"\{([^{}]*)\}", m.group(1))
             if "," in g]
    if not pairs:
        return 0, 1, 1
    nxt = dict(pairs)
    seen: set = set()
    cycles: List[int] = []
    for start in nxt:
        if start in seen:
            continue
        length, cur = 0, start
        while cur not in seen:
            seen.add(cur)
            length += 1
            cur = nxt.get(cur, start)
            if cur == start:
                break
        cycles.append(length)
    ring = max(cycles) if cycles else 1
    return len(pairs), ring, max(len(cycles), 1)


def _bytes_moved(kind: str, operand_bytes: int, group_size: int,
                 n_groups: int, n_pairs: int, count: int) -> int:
    """Total wire bytes under the obs.comms ring-bound convention
    (module docstring), across all devices, groups and iterations."""
    g = max(group_size, 1)
    if kind == "collective-permute":
        return operand_bytes * max(n_pairs, 1) * count
    if kind == "all-gather":
        per_dev = (g - 1) * operand_bytes
    elif kind == "all-reduce":
        per_dev = round(2 * (g - 1) * operand_bytes / g)
    else:  # reduce-scatter, all-to-all: (g-1)/g of the buffer leaves
        per_dev = round((g - 1) * operand_bytes / g)
    return per_dev * g * n_groups * count


def parse_collectives(hlo_text: str) -> List[Dict[str, Any]]:
    """Every collective op in the (scheduled, SPMD per-device) HLO text,
    with derived byte counts.

    Tracks which computation each op sits in and multiplies ops inside
    ``while`` bodies by the loop's ``known_trip_count`` (transitively for
    nested loops). A loop without a statically-known trip count marks its
    collectives ``trip_count_unknown`` and counts them once — an honest
    lower bound, never a guess."""
    num_partitions = None
    m = _NUM_PARTITIONS_RE.search(hlo_text)
    if m:
        num_partitions = int(m.group(1))

    ops: List[Dict[str, Any]] = []
    # body computation -> (trip_count or None), caller computation
    loops: Dict[str, Tuple[Optional[int], str]] = {}
    comp = ""
    for raw in hlo_text.splitlines():
        stripped = raw.strip()
        # instruction lines assign with ` = `; /*index=N*/ and
        # source_line=N carry bare '=' and must not disqualify a def
        if stripped.endswith("{") and " = " not in stripped \
                and "->" in stripped:
            cm = _COMPUTATION_RE.match(stripped)
            if cm:
                comp = cm.group(1)
                continue
        if _WHILE_RE.search(raw):
            bm = _BODY_RE.search(raw)
            if bm:
                tm = _TRIP_COUNT_RE.search(raw)
                loops[bm.group(1)] = (
                    int(tm.group(1)) if tm else None, comp)
            continue
        om = _OPCODE_RE.search(raw)
        if not om or om.group(2) == "-done":
            continue
        kind = om.group(1)
        # result shapes sit between '=' and the opcode; operands inside
        # the opcode's parens (balanced scan — attrs follow the close)
        eq = raw.find("=")
        result_seg = raw[eq + 1: om.start()] if eq >= 0 else ""
        start = raw.find("(", om.end() - 1)
        depth, end = 0, len(raw)
        for i in range(start, len(raw)):
            if raw[i] == "(":
                depth += 1
            elif raw[i] == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        operand_seg = raw[start:end + 1]
        result_bytes, result_dtypes = _shape_bytes(result_seg)
        operand_bytes, operand_dtypes = _shape_bytes(operand_seg)
        n_pairs = ring = n_rings = 0
        if kind == "collective-permute":
            n_pairs, ring, n_rings = _parse_pairs(raw)
            group_size, n_groups = ring, n_rings
        else:
            group_size, n_groups = _parse_groups(raw, num_partitions)
            if kind == "all-gather" and result_bytes \
                    and group_size > 1 and not operand_bytes:
                # degenerate text without operand shapes: derive the
                # shard payload from the gathered result
                operand_bytes = result_bytes // group_size
        ops.append({
            "kind": kind, "computation": comp,
            "dtypes": operand_dtypes or result_dtypes,
            "operand_bytes": operand_bytes,
            "result_bytes": result_bytes,
            "group_size": group_size, "n_groups": n_groups,
            **({"n_pairs": n_pairs} if n_pairs else {}),
        })

    # transitive loop multiplier per computation (nested whiles multiply)
    def _trip(c: str, depth: int = 0) -> Tuple[int, bool]:
        if c not in loops or depth > 16:
            return 1, False
        n, caller = loops[c]
        outer, unknown = _trip(caller, depth + 1)
        if n is None:
            return outer, True
        return n * outer, unknown

    for op in ops:
        count, unknown = _trip(op.pop("computation"))
        op["count"] = count
        if unknown:
            op["trip_count_unknown"] = True
        op["bytes_moved"] = _bytes_moved(
            op["kind"], op["operand_bytes"], op["group_size"],
            op["n_groups"], op.get("n_pairs", 0), count)
    return ops


def collective_totals(
        collectives: List[Dict[str, Any]],
        dispatch_count: int = 1) -> Dict[str, Dict[str, int]]:
    """Per-kind {ops, count, bytes_moved} aggregate; ``dispatch_count``
    scales a program executed N times (the probe's multiplicity)."""
    out: Dict[str, Dict[str, int]] = {}
    for op in collectives:
        agg = out.setdefault(op["kind"],
                             {"ops": 0, "count": 0, "bytes_moved": 0})
        agg["ops"] += 1
        agg["count"] += op["count"] * dispatch_count
        agg["bytes_moved"] += op["bytes_moved"] * dispatch_count
    return out


def guess_axis(group_size: int,
               mesh_axes: Optional[Dict[str, int]]) -> str:
    """Best-effort mesh-axis attribution: a group size that matches
    exactly one declared axis size names that axis; anything else is an
    honest ``unknown`` (never a guess between ambiguous axes)."""
    if not mesh_axes:
        return "unknown"
    hits = [a for a, s in mesh_axes.items() if s == group_size]
    return hits[0] if len(hits) == 1 else "unknown"


# -- the per-executable record ------------------------------------------------

def fingerprint_text(hlo_text: str) -> str:
    """Executable fingerprint: sha-256 of the compiled HLO text (16 hex
    chars — the cache key and the schedule-identity token the serve
    smoke compares between ready and drain)."""
    return hashlib.sha256(hlo_text.encode()).hexdigest()[:16]


@dataclasses.dataclass
class HloReport:
    """One compiled executable's introspection record."""

    label: str
    fingerprint: str
    collectives: List[Dict[str, Any]]
    totals: Dict[str, Dict[str, int]]
    memory: Dict[str, Any]
    cost: Dict[str, Any]
    platform: Optional[str] = None
    schema: int = SCHEMA_VERSION

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


def memory_report(compiled) -> Dict[str, Any]:
    """``memory_analysis()`` as a plain dict, or the explicit
    ``hlo_memory_unavailable`` marker when the backend reports
    nothing."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:
        return {"hlo_memory_unavailable": f"memory_analysis raised "
                                          f"{type(e).__name__}: {e}"}
    if ma is None:
        return {"hlo_memory_unavailable":
                "backend returned no memory analysis"}
    out: Dict[str, Any] = {}
    for field in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "alias_size_in_bytes",
                  "generated_code_size_in_bytes"):
        v = getattr(ma, field, None)
        if v is not None:
            out[field.replace("_size_in_bytes", "_bytes")] = int(v)
    if not out:
        return {"hlo_memory_unavailable":
                f"unrecognized memory_analysis shape: "
                f"{type(ma).__name__}"}
    return out


def cost_report(compiled) -> Dict[str, Any]:
    """``cost_analysis()`` normalized (obs.counters.normalize_cost), or
    the explicit marker."""
    from dmlp_tpu.obs.counters import normalize_cost
    try:
        cost = normalize_cost(compiled.cost_analysis())
    except Exception as e:
        return {"cost_unavailable": f"cost_analysis raised "
                                    f"{type(e).__name__}: {e}"}
    if cost is None:
        return {"cost_unavailable": "no usable flops/bytes in "
                                    "cost_analysis output"}
    return cost


# fingerprint -> HloReport; two lowers of the same program parse once.
_REPORT_CACHE: Dict[str, HloReport] = {}
cache_stats = {"hits": 0, "misses": 0}


def clear_cache() -> None:
    _REPORT_CACHE.clear()
    cache_stats["hits"] = cache_stats["misses"] = 0


def report_for(compiled, label: str = "") -> HloReport:
    """The :class:`HloReport` for a ``jax.stages.Compiled``, cached by
    executable fingerprint (the label of the first introspection
    sticks)."""
    text = compiled.as_text()
    fp = fingerprint_text(text)
    cached = _REPORT_CACHE.get(fp)
    if cached is not None:
        cache_stats["hits"] += 1
        return cached
    cache_stats["misses"] += 1
    collectives = parse_collectives(text)
    platform = None
    try:
        platform = compiled.runtime_executable().platform  # pragma: no cover
    except Exception:
        try:
            import jax
            platform = jax.default_backend()
        except Exception:
            pass
    rep = HloReport(label=label, fingerprint=fp,
                    collectives=collectives,
                    totals=collective_totals(collectives),
                    memory=memory_report(compiled),
                    cost=cost_report(compiled),
                    platform=platform)
    _REPORT_CACHE[fp] = rep
    return rep


def report_for_fn(fn, specs, statics: Optional[dict] = None,
                  label: str = "") -> Optional[HloReport]:
    """Lower + compile a jitted fn on abstract args and introspect the
    result; None when the signature cannot lower (non-jitted callable,
    tracing error) — introspection never raises into a solve."""
    try:
        compiled = fn.lower(*specs, **(statics or {})).compile()
    except Exception:
        return None
    return report_for(compiled, label=label)


def probe_reports(probe) -> Tuple[List[Tuple[HloReport, int, str]], int]:
    """Introspect every dispatch signature an obs.counters.CostProbe
    recorded: [(report, dispatch_count, site)], plus how many signatures
    could not lower (Pallas kernels expose no HLO executable this way —
    counted, never silent)."""
    out: List[Tuple[HloReport, int, str]] = []
    skipped = 0
    for fn, specs, statics, count, site in probe.dispatches():
        rep = report_for_fn(fn, specs, statics=statics, label=site)
        if rep is None:
            skipped += 1
            continue
        out.append((rep, count, site))
    return out, skipped


def traffic_from_report(report: HloReport,
                        mesh_axes: Optional[Dict[str, int]] = None,
                        count: int = 1) -> List[Any]:
    """The compiled schedule as obs.comms.CollectiveTraffic records —
    how a compiler-chosen (GSPMD) schedule becomes a REAL comms record
    instead of the honest-but-empty one: collective names are
    ``gspmd_<kind>``, axes are best-effort mesh attribution
    (:func:`guess_axis`), and bytes reproduce ``bytes_moved`` under the
    shared convention."""
    from dmlp_tpu.obs.comms import CollectiveTraffic
    out: List[Any] = []
    for kind, agg in sorted(report.totals.items()):
        sized = [op for op in report.collectives if op["kind"] == kind]
        g = max((op["group_size"] for op in sized), default=1)
        n_groups = max((op["n_groups"] for op in sized), default=1)
        per_dev = round(agg["bytes_moved"] / max(g * n_groups, 1))
        out.append(CollectiveTraffic(
            f"gspmd_{kind}", guess_axis(g, mesh_axes), g,
            per_dev, per_dev, n_groups=n_groups, count=count,
            note=f"HLO-derived: {agg['ops']} op(s), "
                 f"{agg['count']} execution(s), fingerprint "
                 f"{report.fingerprint}"))
    return out


# -- the three-way reconcile --------------------------------------------------

def _traffic_kind_bytes(traffics) -> Tuple[Dict[str, int],
                                           Dict[str, List[str]]]:
    per_kind: Dict[str, int] = {}
    names: Dict[str, List[str]] = {}
    for t in traffics or []:
        d = t.to_dict() if hasattr(t, "to_dict") else dict(t)
        kind = TRAFFIC_COLLECTIVE_KINDS.get(d.get("collective", ""))
        if kind is None:
            kind = "unknown"
        per_kind[kind] = per_kind.get(kind, 0) + int(d["bytes_total"])
        names.setdefault(kind, []).append(d.get("collective", "?"))
    return per_kind, names


def reconcile_comms(reports: List[Tuple[HloReport, int, str]],
                    traffics) -> Dict[str, Any]:
    """HLO-derived collective bytes vs the analytic obs.comms records.

    Per collective kind: both sides' totals, their ratio and the
    :data:`COMMS_RATIO_BOUNDS` verdict. A kind only one side claims gets
    the honest one-sided marker instead of a fake ratio — ``hlo_only``
    is exactly what a partitioner-chosen (GSPMD) schedule looks like,
    ``model_only`` means the model prices a collective the compiled
    program never dispatches."""
    hlo_bytes: Dict[str, int] = {}
    for rep, count, _site in reports:
        for kind, agg in rep.totals.items():
            hlo_bytes[kind] = hlo_bytes.get(kind, 0) \
                + agg["bytes_moved"] * count
    model_bytes, model_names = _traffic_kind_bytes(traffics)
    kinds: Dict[str, Any] = {}
    for kind in sorted(set(hlo_bytes) | set(model_bytes)):
        h, mdl = hlo_bytes.get(kind, 0), model_bytes.get(kind, 0)
        ent: Dict[str, Any] = {"hlo_bytes": h, "model_bytes": mdl}
        if model_names.get(kind):
            ent["models"] = sorted(set(model_names[kind]))
        if h and mdl:
            ratio = h / mdl
            lo, hi = COMMS_RATIO_BOUNDS
            ent.update(ratio=round(ratio, 3),
                       ratio_bounds=[lo, hi],
                       within_tolerance=bool(lo <= ratio <= hi))
        elif h:
            ent["hlo_only"] = True
        else:
            ent["model_only"] = True
        kinds[kind] = ent
    out: Dict[str, Any] = {"kinds": kinds}
    if not kinds:
        out["no_collectives"] = True
    return out


def reconcile_trace(reports: List[Tuple[HloReport, int, str]],
                    events: Optional[List[dict]]) -> Dict[str, Any]:
    """HLO bytes vs traced collective span traffic, where traces exist.

    Only spans named in :data:`SPAN_COLLECTIVE_KINDS` AND carrying an
    ``nbytes`` arg participate (the dist/fleet hand-offs); a run with no
    such spans — every single-process solve — reports the explicit
    ``trace_unavailable`` marker. Host-level collectives
    (process_allgather) never appear in a compiled program, so a traced
    kind with no HLO twin is expected cross-domain, marked
    ``hlo_side_absent`` rather than failed."""
    span_bytes: Dict[str, int] = {}
    for ev in events or []:
        kind = SPAN_COLLECTIVE_KINDS.get(ev.get("name", ""))
        nbytes = (ev.get("args") or {}).get("nbytes")
        if kind is None or not isinstance(nbytes, (int, float)):
            continue
        span_bytes[kind] = span_bytes.get(kind, 0) + int(nbytes)
    if not span_bytes:
        return {"trace_unavailable":
                "no traced collective spans carry byte counts "
                "(single-process solves dispatch collectives inside "
                "the compiled program only)"}
    hlo_bytes: Dict[str, int] = {}
    for rep, count, _site in reports:
        for kind, agg in rep.totals.items():
            hlo_bytes[kind] = hlo_bytes.get(kind, 0) \
                + agg["bytes_moved"] * count
    kinds: Dict[str, Any] = {}
    for kind, sb in sorted(span_bytes.items()):
        ent: Dict[str, Any] = {"trace_bytes": sb,
                               "hlo_bytes": hlo_bytes.get(kind, 0)}
        if not ent["hlo_bytes"]:
            ent["hlo_side_absent"] = True
        else:
            ratio = ent["hlo_bytes"] / sb
            lo, hi = COMMS_RATIO_BOUNDS
            ent.update(ratio=round(ratio, 3), ratio_bounds=[lo, hi],
                       within_tolerance=bool(lo <= ratio <= hi))
        kinds[kind] = ent
    return {"kinds": kinds}


def reconcile_memory(reports: List[Tuple[HloReport, int, str]],
                     mem_block: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """``memory_analysis`` vs the memwatch model + live watermark.

    The HLO side is the LARGEST single executable's static footprint
    (argument + output + temp bytes — per device); the model side is the
    memwatch ``mem`` block the CLI already computes (model bytes +
    measured watermark + its own verdict). Bounds are
    :data:`MEMORY_RATIO_BOUNDS`; either side missing yields its
    marker."""
    sized = []
    for rep, _count, site in reports:
        m = rep.memory
        if "hlo_memory_unavailable" in m:
            continue
        sized.append((m.get("argument_bytes", 0)
                      + m.get("output_bytes", 0)
                      + m.get("temp_bytes", 0), site))
    if not sized:
        why = "no executable reported memory analysis"
        for rep, _count, _site in reports:
            mark = rep.memory.get("hlo_memory_unavailable")
            if mark:
                why = mark
                break
        return {"hlo_memory_unavailable": why}
    peak, peak_site = max(sized)
    out: Dict[str, Any] = {"hlo_peak_bytes": int(peak),
                           "hlo_peak_site": peak_site,
                           "executables_with_memory": len(sized)}
    if not mem_block or "model_bytes" not in mem_block:
        out["mem_model_unavailable"] = \
            "no memwatch mem block to reconcile against"
        return out
    model = int(mem_block.get("model_bytes_per_device",
                              mem_block["model_bytes"]))
    lo, hi = MEMORY_RATIO_BOUNDS
    ratio = peak / max(model, 1)
    out.update(model_bytes_per_device=model, ratio=round(ratio, 3),
               ratio_bounds=[lo, hi],
               within_tolerance=bool(lo <= ratio <= hi))
    if mem_block.get("measured_bytes"):
        out["measured_bytes"] = mem_block["measured_bytes"]
        out["measured_basis"] = mem_block.get("basis")
    elif mem_block.get("mem_stats_unavailable"):
        out["mem_stats_unavailable"] = mem_block["mem_stats_unavailable"]
    return out


def three_way(reports: List[Tuple[HloReport, int, str]],
              traffics=None, events: Optional[List[dict]] = None,
              mem_block: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
    """The full reconcile: HLO vs analytic comms models, vs traced span
    traffic, vs the memwatch model + watermark. Each leg carries its own
    verdicts/markers; none ever raises."""
    return {"comms_model": reconcile_comms(reports, traffics),
            "trace": reconcile_trace(reports, events),
            "memory": reconcile_memory(reports, mem_block)}


# -- the run-level document (what --hlo-report writes) ------------------------

def build_report_doc(reports: List[Tuple[HloReport, int, str]],
                     skipped: int = 0, traffics=None,
                     events: Optional[List[dict]] = None,
                     mem_block: Optional[Dict[str, Any]] = None,
                     mesh_axes: Optional[Dict[str, int]] = None
                     ) -> Dict[str, Any]:
    """One run's introspection document: every executable's report (with
    dispatch multiplicity), merged per-kind/per-axis totals, and the
    three-way reconcile. ``skipped`` names the signatures that could not
    lower — no silent caps."""
    totals: Dict[str, Dict[str, int]] = {}
    by_axis: Dict[str, int] = {}
    for rep, count, _site in reports:
        for kind, agg in rep.totals.items():
            t = totals.setdefault(kind, {"ops": 0, "count": 0,
                                         "bytes_moved": 0})
            t["ops"] += agg["ops"]
            t["count"] += agg["count"] * count
            t["bytes_moved"] += agg["bytes_moved"] * count
        for op in rep.collectives:
            ax = guess_axis(op["group_size"], mesh_axes)
            by_axis[ax] = by_axis.get(ax, 0) \
                + op["bytes_moved"] * count
    doc: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "executables": [dict(rep.to_dict(), dispatch_count=count,
                             site=site)
                        for rep, count, site in reports],
        "collective_totals": totals,
        "collective_bytes_total": sum(t["bytes_moved"]
                                      for t in totals.values()),
        "bytes_by_axis": by_axis,
        "reconcile": three_way(reports, traffics=traffics, events=events,
                               mem_block=mem_block),
    }
    if skipped:
        doc["signatures_skipped_no_hlo"] = skipped
    if not reports:
        doc["hlo_unavailable"] = "no dispatch signature could be " \
                                 "lowered to a compiled executable"
    return doc


def flat_metrics(doc: Dict[str, Any]) -> Dict[str, Any]:
    """The ledger-facing scalars of a report doc — what the ``hlo/``
    series family gates round-over-round (collective bytes per kind, op
    counts, static peak memory)."""
    out: Dict[str, Any] = {
        "collective_bytes_total": doc.get("collective_bytes_total", 0),
        "executables_introspected": len(doc.get("executables", ())),
    }
    for kind, agg in (doc.get("collective_totals") or {}).items():
        key = kind.replace("-", "_")
        out[f"{key}_bytes"] = agg["bytes_moved"]
        out[f"{key}_count"] = agg["count"]
    mem = (doc.get("reconcile") or {}).get("memory") or {}
    if "hlo_peak_bytes" in mem:
        out["hlo_peak_bytes"] = mem["hlo_peak_bytes"]
    if "ratio" in mem:
        out["mem_ratio_vs_model"] = mem["ratio"]
    return out


__all__ = [
    "SCHEMA_VERSION", "COLLECTIVE_KINDS", "MODEL_COLLECTIVE_KINDS",
    "TRAFFIC_COLLECTIVE_KINDS", "SPAN_COLLECTIVE_KINDS",
    "COMMS_RATIO_BOUNDS", "MEMORY_RATIO_BOUNDS",
    "parse_collectives", "collective_totals", "guess_axis",
    "fingerprint_text", "HloReport", "memory_report", "cost_report",
    "clear_cache", "cache_stats", "report_for", "report_for_fn",
    "probe_reports", "traffic_from_report",
    "reconcile_comms", "reconcile_trace", "reconcile_memory",
    "three_way", "build_report_doc", "flat_metrics",
]
