"""Perf ledger: every run artifact, one queryable series store.

Five measurement rounds left ~40 root-level ``BENCH_*/SWEEP_*/
TRAINBENCH_*/...`` artifacts in a dozen private shapes, plus the
schema RunRecords the obs-era emitters write. Nothing could answer
"did config 2 get faster between r04 and r05, beyond link noise?"
without a human opening JSON files. This module is the consolidation
layer:

- :func:`build_ledger` scans a directory for perf artifacts, parses
  each through a family parser (schema RunRecords first, then the
  grandfathered legacy shapes, then a generic numeric walker), and
  returns one versioned ledger document. Files that match the artifact
  patterns but defeat every parser become explicit ``unparseable``
  entries — a ledger that silently drops an artifact would hide exactly
  the regressions it exists to catch.
- Each parsed artifact contributes :class:`SeriesPoint` rows keyed by
  (series name, round, device, dtype): the series name encodes
  workload + config ("harness/config2/engine_ms"), the round comes
  from the envelope (schema-2 RunRecords) or the ``_rNN`` filename
  convention, and per-trial samples ride along when the artifact
  recorded them (``engine_ms_reps``, ``times_ms`` — the raw material
  for noise-aware comparison).
- :func:`compare_points` computes the noise-aware A/B delta between
  two rounds of one series: median-vs-median with a MAD-derived noise
  band when both sides carry >= :data:`MIN_TRIALS` trials, and HONEST
  markers otherwise — ``insufficient_trials`` when either side is a
  single-shot number (the delta is still reported, flagged as
  unqualified), ``device_mismatch`` when the rounds ran on different
  hardware (a v5e-vs-CPU "regression" is not a regression).

``python -m dmlp_tpu.report`` renders the ledger as markdown/JSON;
``tools/perf_gate.py`` turns the comparisons into a CI gate.

Import-light and side-effect-free: pure JSON reading, no jax.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from dmlp_tpu.obs.run import RunRecord, round_from_name

#: bump on any backward-incompatible ledger-document change
LEDGER_SCHEMA = 1

#: fewest per-trial samples a side needs before a delta is qualified
#: against a noise band instead of marked ``insufficient_trials``
MIN_TRIALS = 3

#: noise band = max(Z * 1.4826 * MAD / sqrt(n), REL_FLOOR * median) —
#: the MAD term models trial scatter, the floor absorbs ms-quantized
#: timers whose 3-trial MAD can collapse to ~0 and declare a 2 ms
#: wobble "significant"
NOISE_Z = 2.0
NOISE_REL_FLOOR = 0.02

#: root-level filename patterns the ledger claims (glob syntax);
#: everything matching one of these MUST end up as an entry — parsed
#: or explicitly unparseable, never silently absent. The ``*_r[0-9]*``
#: catch-alls claim ANY file following the round-suffix convention —
#: the README tells emitters "drop an _rNN-named RunRecord at the
#: root and the ledger picks it up", so an unknown prefix must not be
#: silently invisible.
ARTIFACT_PATTERNS = (
    "BENCH_*.json", "BENCH_*.jsonl", "SWEEP_*.jsonl", "SWEEP_*.json",
    "TRAINBENCH_*.json", "TRAINBENCH_*.jsonl", "TRAIN_CURVE_*.jsonl",
    "ROOFLINE_*.json", "PIPEBENCH_*.json", "HARNESS_*.json",
    "CAPACITY_*.json", "MULTICHIP_*.json", "SCALE_*.json",
    "PROFILE_*.json", "MESH_OVERHEAD_*.json", "OFFLOAD_DECOMP_*.json",
    "WIDEK_MP_*.json", "FUZZ_*.json", "TIE_SEMANTICS_*.json",
    "REPAIR_SWEEP_*.json", "BASELINE.json", "TUNE_*.json",
    "*_r[0-9]*.json", "*_r[0-9]*.jsonl",
)

#: series units whose LOWER values are better (everything timing);
#: key-name suffix heuristics — see _better_direction
_LOWER_BETTER_HINTS = ("_ms", "_s", "_us", "_sec", "ms", "elapsed",
                      "time", "wall", "overhead_pct", "peak_hbm",
                      "breach", "burn")
# NOTE: no bare "pairs" hint — it would substring-match "repairs"
# (a repair COUNT, where more is worse) and invert the gate's verdict;
# qd_pairs_per_sec is already covered by "per_sec".
_HIGHER_BETTER_HINTS = ("per_sec", "per_chip", "mfu",
                       "tflops", "pct_of_roof", "samples", "speedup",
                       "efficiency", "qps")


def _better_direction(metric: str) -> str:
    """"lower" | "higher" | "info" for a metric name — gates only act
    on series with a known direction."""
    low = metric.lower()
    for h in _HIGHER_BETTER_HINTS:
        if h in low:
            return "higher"
    for h in _LOWER_BETTER_HINTS:
        if low.endswith(h) or h in low.split("/")[-1]:
            return "lower"
    return "info"


@dataclasses.dataclass
class SeriesPoint:
    """One measured value of one tracked series in one round."""

    series: str                      # round-independent series key
    value: float
    round: Optional[int] = None
    trials: Optional[List[float]] = None   # raw per-trial samples
    device: str = "unspecified"
    dtype: str = "unspecified"
    source: str = ""                 # artifact file the point came from
    better: str = "lower"            # lower | higher | info
    unit: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items()
                if v not in (None, "", "unspecified") or k == "value"}


def _point(series: str, value, round_: Optional[int], source: str,
           trials=None, device=None, dtype=None,
           unit: str = "") -> Optional[SeriesPoint]:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    if not math.isfinite(v):
        return None
    tr = None
    if trials:
        tr = [float(t) for t in trials
              if isinstance(t, (int, float)) and math.isfinite(t)]
        tr = tr or None
    return SeriesPoint(series=series, value=v, round=round_, trials=tr,
                       device=str(device or "unspecified"),
                       dtype=str(dtype or "unspecified"), source=source,
                       better=_better_direction(series), unit=unit)


# -- family parsers ----------------------------------------------------------
# Each takes (path, round, docs) where docs is the list of parsed JSON
# values (one per line for .jsonl, one element for .json) and returns a
# list of SeriesPoints; raising or returning None marks the file
# unparseable. Registered in _FAMILIES below, first match wins.

def _is_runrecord(doc) -> bool:
    return (isinstance(doc, dict) and isinstance(doc.get("schema"), int)
            and "kind" in doc and "tool" in doc)


_TRIAL_KEYS = ("engine_ms_reps", "times_ms", "rep_ms", "samples_ms")

# Generic walker caps — announced per entry, never silent.
_GENERIC_MAX_POINTS = 48
_GENERIC_MAX_DEPTH = 5
_SKIP_KEYS = {"schema", "created_unix", "seed", "rc", "n", "np",
              "num_data", "num_queries", "num_attrs", "k", "kc", "kmax",
              "n_chips", "n_devices", "batch", "steps", "config",
              "config_id", "round", "port", "pid",
              # config/shape subtrees: inputs, not measurements
              "shape", "dims", "mesh", "tiles", "variant", "kcap",
              "nq", "na", "dblock", "host"}


def _walk_numeric(doc, prefix: str, out: List[Tuple[str, float]],
                  depth: int = 0) -> int:
    """Collect (path, value) numeric leaves; returns count of leaves
    DROPPED by the caps (the entry records it)."""
    dropped = 0
    if depth > _GENERIC_MAX_DEPTH:
        return 1
    if isinstance(doc, dict):
        for key, v in doc.items():
            if key in _SKIP_KEYS or key.startswith("note"):
                continue
            sub = f"{prefix}/{key}" if prefix else str(key)
            dropped += _walk_numeric(v, sub, out, depth + 1)
    elif isinstance(doc, list):
        # Lists of scalars are trial samples, not separate series;
        # lists of dicts index by position (ladder levels, sweep rows).
        if doc and all(isinstance(x, (int, float)) for x in doc):
            return 0
        for i, v in enumerate(doc[:16]):
            dropped += _walk_numeric(v, f"{prefix}[{i}]", out, depth + 1)
        dropped += max(len(doc) - 16, 0)
    elif isinstance(doc, bool):
        return 0
    elif isinstance(doc, (int, float)):
        if len(out) < _GENERIC_MAX_POINTS:
            out.append((prefix, float(doc)))
        else:
            dropped += 1
    return dropped


def _doc_device(doc) -> Optional[str]:
    if not isinstance(doc, dict):
        return None
    for key in ("device", "device_kind", "platform"):
        v = doc.get(key)
        if isinstance(v, str) and v:
            return v
    shape = doc.get("shape")
    if isinstance(shape, dict):
        v = shape.get("device_kind")
        if isinstance(v, str) and v:
            return v
    return None


def _doc_dtype(doc) -> Optional[str]:
    if not isinstance(doc, dict):
        return None
    v = doc.get("dtype")
    if isinstance(v, str):
        return v
    shape = doc.get("shape")
    if isinstance(shape, dict) and isinstance(shape.get("dtype"), str):
        return shape["dtype"]
    return None


def _trials_for_metric(key: str, metrics: Dict[str, Any]):
    """The per-trial sample list belonging to scalar metric ``key``,
    by the emitters' naming conventions: ``X -> X_reps`` (engine_ms ->
    engine_ms_reps), ``X_median_ms -> X_times_ms`` (the migrated A/B
    tools' per-arm lists: a2a_median_ms -> a2a_times_ms), and the bare
    ``times_ms`` for a bare ``median_ms``/``engine_ms``. Without this,
    an emitter could record 7 honest trials and the gate would still
    mark its series insufficient_trials forever."""
    candidates = [f"{key}_reps"]
    if key.endswith("_median_ms"):
        candidates.append(key[: -len("_median_ms")] + "_times_ms")
    if key in ("median_ms", "engine_ms"):
        candidates.append("times_ms")
    for ck in candidates:
        v = metrics.get(ck)
        if isinstance(v, list) and v:
            return v
    return None


def _runrecord_series_name(rec: RunRecord, key: str) -> str:
    """Series key for one RunRecord metric — LEGACY-COMPATIBLE for the
    emitters that replaced a grandfathered artifact family, so the
    round-over-round trajectory survives the migration (a series that
    changes name at the migration round has no previous round, and the
    gate would pass vacuously right when coverage matters):

    - dmlp_tpu.bench per-config records continue the ``HARNESS_rNN``
      series (``harness/configN/<metric>``);
    - telemetry snapshot/smoke records (kind "telemetry": the live
      registry serialized as a RunRecord, and tools/telemetry_smoke.py's
      overhead + peak-HBM reconcile) key ``telemetry/<metric>`` — ONE
      family for both emitters so the peak-HBM watermark,
      model-vs-measured delta, and telemetry-overhead series stay
      round-comparable regardless of which tool wrote the round;
    - serving records (kind "serve": the daemon's periodic/final
      snapshot_record and the bench harness's trace-replay A/B) key
      ``serve/<metric>`` — one family for both emitters so sustained
      QPS, latency quantiles, and cold-start compile time stay
      round-comparable (gated by tools/perf_gate.py);
    - tools.trainbench_moe continues ``trainbench/moe/<arm>/<metric>``
      (``a2a_median_ms`` -> ``trainbench/moe/a2a/median_ms``);
    - tools.bench_offload_ladder continues
      ``trainbench/ladder/<level>/<metric>``.

    Everything else keys ``{kind}:{tool}[/configN]/{metric}``."""
    cid = rec.config.get("config_id") if isinstance(rec.config, dict) \
        else None
    if rec.kind == "prune":
        # Pruned-vs-dense A/B records (bench --prune-ab, the prune
        # smoke/capacity tools): one ``prune/`` family regardless of
        # emitter so scanned-bytes and per-arm engine times stay
        # round-comparable (gated by tools/perf_gate.py).
        cfg_tag = f"/config{cid}" if cid is not None else ""
        return f"prune{cfg_tag}/{key}"
    if rec.kind == "precision":
        # bf16-vs-f32 first-pass A/B records (bench --precision-ab,
        # tools/precision_smoke.py): one ``precision/`` family
        # regardless of emitter so the per-arm engine times and the
        # window-inflation meters stay round-comparable (gated by
        # tools/perf_gate.py).
        cfg_tag = f"/config{cid}" if cid is not None else ""
        return f"precision{cfg_tag}/{key}"
    if rec.kind == "auto":
        # Compiler-sharded vs hand-rolled A/B records (bench --auto-ab,
        # make auto-smoke): one ``auto/`` family regardless of emitter
        # so the per-arm engine times and warmup-compile splits stay
        # round-comparable (gated by tools/perf_gate.py).
        cfg_tag = f"/config{cid}" if cid is not None else ""
        return f"auto{cfg_tag}/{key}"
    if rec.kind == "hlo":
        # Compiled-program introspection records (CLI --hlo-report,
        # obs.hlo): one ``hlo/<mode>/<metric>`` series per engine mode
        # so the partitioner-chosen collective-bytes trajectory gates
        # per engine in tools/perf_gate.py — a GSPMD upgrade that
        # silently doubles all-gather traffic on the auto engine can't
        # hide behind the hand-rolled engines' unchanged schedules.
        mode = rec.config.get("mode") if isinstance(rec.config, dict) \
            else None
        tag = (f"/{mode}" if mode
               else (f"/config{cid}" if cid is not None else ""))
        return f"hlo{tag}/{key}"
    if rec.tool == "dmlp_tpu.bench" and cid is not None:
        return f"harness/config{cid}/{key}"
    if rec.kind == "telemetry":
        cfg_tag = f"/config{cid}" if cid is not None else ""
        return f"telemetry{cfg_tag}/{key}"
    if rec.kind == "serve":
        return f"serve/{key}"
    if rec.kind == "tailattrib":
        # Tail-latency attribution (tools/tail_attrib.py over a merged
        # fleet trace): ``fleet/<level>/phase/<metric>`` so the
        # per-phase p99 contribution at each offered-load level gates
        # alongside the end-to-end fleet/<level>/ SLO series it
        # decomposes.
        lvl = rec.config.get("level") if isinstance(rec.config, dict) \
            else None
        tag = f"/{lvl}" if lvl else ""
        return f"fleet{tag}/phase/{key}"
    if rec.kind == "slo":
        # SLO ramp A/B records (fleet.loadgen.ramp_record): one
        # ``slo/<arm>/<metric>`` series per autoscale arm (config
        # "arm" = "predictive" / "reactive"), so the breach count and
        # peak-p99 of each arm gate independently — the predictive
        # arm's zero-breach contract can't hide behind the reactive
        # arm's expected firing.
        arm = rec.config.get("arm") if isinstance(rec.config, dict) \
            else None
        tag = (f"/{arm}" if arm
               else (f"/config{cid}" if cid is not None else ""))
        return f"slo{tag}/{key}"
    if rec.kind == "fleet":
        # Open-loop SLO records (fleet.loadgen) + the router snapshot:
        # one ``fleet/<level>/<metric>`` series per offered-load level
        # (config "level" = "x1", "x2", ... or "router"), so the
        # p99-under-offered-load curve gates level-by-level in
        # tools/perf_gate.py — a regression at x4 can't hide behind an
        # improvement at x1.
        lvl = rec.config.get("level") if isinstance(rec.config, dict) \
            else None
        tag = (f"/{lvl}" if lvl
               else (f"/config{cid}" if cid is not None else ""))
        return f"fleet{tag}/{key}"
    if rec.tool == "tools.trainbench_moe":
        m = re.match(r"(dense|a2a)_(.+)$", key)
        if m:
            return f"trainbench/moe/{m.group(1)}/{m.group(2)}"
        return f"trainbench/moe/{key}"
    if rec.tool == "tools.bench_offload_ladder":
        m = re.match(r"(none|params|all)_(.+)$", key)
        if m:
            return f"trainbench/ladder/{m.group(1)}/{m.group(2)}"
        return f"trainbench/ladder/{key}"
    cfg_tag = f"/config{cid}" if cid is not None else ""
    return f"{rec.kind}:{rec.tool}{cfg_tag}/{key}"


def _parse_runrecord_docs(path: str, round_: Optional[int],
                          docs: List[Any]) -> List[SeriesPoint]:
    """Schema RunRecords (single or JSONL): the by-construction path."""
    points: List[SeriesPoint] = []
    for doc in docs:
        rec = RunRecord.from_dict(doc)     # raises on a newer schema
        r = rec.round if rec.round is not None else round_
        device = rec.device or _doc_device(rec.config) \
            or _doc_device(rec.metrics)
        dtype = _doc_dtype(rec.config) or _doc_dtype(rec.metrics)
        metrics = rec.metrics if isinstance(rec.metrics, dict) else {}
        for key, v in metrics.items():
            if key in _TRIAL_KEYS or key in _SKIP_KEYS \
                    or isinstance(v, bool):
                # identifier/envelope echoes (config id, counts) are
                # inputs, not measurements — same rule as the generic
                # walker's _SKIP_KEYS
                continue
            if isinstance(v, (int, float)):
                pt = _point(_runrecord_series_name(rec, key), v, r, path,
                            trials=_trials_for_metric(key, metrics),
                            device=device, dtype=dtype)
                if pt is not None:
                    points.append(pt)
        # A record with no scalar metrics (e.g. an *_unavailable marker
        # record) still yields a parsed entry with zero series — the
        # caller records it as covered, not dropped.
    return points


def _parse_bench(path: str, round_: Optional[int],
                 docs: List[Any]) -> List[SeriesPoint]:
    """Legacy ``BENCH_rNN.json``: bench.py's {parsed: {metric, value,
    shape}} envelope."""
    (doc,) = docs
    parsed = doc["parsed"]
    shape = parsed.get("shape", {})
    tag = (f"n{shape.get('num_data', '?')}_q{shape.get('num_queries', '?')}"
           f"_a{shape.get('num_attrs', '?')}_k{shape.get('k', '?')}"
           f"_{shape.get('mode', '?')}")
    pts = []
    pt = _point(f"bench/{parsed['metric']}/{tag}", parsed["value"], round_,
                path, device=_doc_device(parsed),
                dtype=(parsed.get("path") or {}).get("dtype"),
                unit=parsed.get("unit", "ms"))
    if pt is None:
        raise ValueError("bench parsed.value not numeric")
    pts.append(pt)
    for extra in ("device_solve_ms", "qd_pairs_per_sec",
                  "vs_reference_binary"):
        p = _point(f"bench/{extra}/{tag}", parsed.get(extra), round_, path)
        if p is not None:
            pts.append(p)
    return pts


def _parse_harness(path: str, round_: Optional[int],
                   docs: List[Any]) -> List[SeriesPoint]:
    """``HARNESS_rNN.json``: the per-config benchmark suite — the
    primary gated series (engine_ms with per-rep trials from r04 on)."""
    (doc,) = docs
    pts = []
    for cfg in doc["configs"]:
        cid = cfg["config"]
        pt = _point(f"harness/config{cid}/engine_ms", cfg.get("engine_ms"),
                    round_, path, trials=cfg.get("engine_ms_reps"),
                    device=_doc_device(cfg), unit="ms")
        if pt is not None:
            pts.append(pt)
        p2 = _point(f"harness/config{cid}/vs_reference_binary",
                    cfg.get("vs_reference_binary"), round_, path)
        if p2 is not None:
            pts.append(p2)
    if not pts:
        raise ValueError("harness file with no usable configs")
    return pts


def _parse_sweep_jsonl(path: str, round_: Optional[int],
                       docs: List[Any]) -> List[SeriesPoint]:
    """``SWEEP_rNN_{cpu,tpu}.jsonl`` (chip-scaling train sweeps) and
    ``SWEEP_WIDEK_*.jsonl`` (kernel-variant sweeps)."""
    base = os.path.basename(path)
    plat = "tpu" if "_tpu" in base else ("cpu" if "_cpu" in base else "")
    widek = "WIDEK" in base.upper()
    pts: List[SeriesPoint] = []
    best_by_kc: Dict[int, float] = {}
    for doc in docs:
        if not isinstance(doc, dict) or "summary" in doc:
            continue
        if widek and "kc" in doc and "ms" in doc:
            kc = int(doc["kc"])
            best_by_kc[kc] = min(best_by_kc.get(kc, float("inf")),
                                 float(doc["ms"]))
            continue
        if "n_chips" in doc and "step_time_ms" in doc:
            tag = f"chips{doc['n_chips']}"
            dev = plat or _doc_device(doc)
            p = _point(f"sweep/step_time_ms/{tag}", doc["step_time_ms"],
                       round_, path, device=dev, dtype=_doc_dtype(doc),
                       unit="ms")
            if p is not None:
                pts.append(p)
            p2 = _point(f"sweep/samples_per_sec_per_chip/{tag}",
                        doc.get("samples_per_sec_per_chip"), round_, path,
                        device=dev, dtype=_doc_dtype(doc))
            if p2 is not None:
                pts.append(p2)
    for kc, ms in sorted(best_by_kc.items()):
        p = _point(f"sweep_widek/best_ms/kc{kc}", ms, round_, path,
                   unit="ms")
        if p is not None:
            pts.append(p)
    if not pts:
        raise ValueError("sweep jsonl with no recognizable rows")
    return pts


def _parse_roofline(path: str, round_: Optional[int],
                    docs: List[Any]) -> List[SeriesPoint]:
    """Legacy ``ROOFLINE_rNN.json`` (r06+ are RunRecords and resolve
    through the RunRecord parser first)."""
    (doc,) = docs
    cor = doc["corrected"]
    dev = _doc_device(doc)
    pts = []
    for key in ("kernel_ms", "extraction_term_ms", "mxu_floor_ms",
                "pct_of_roof"):
        p = _point(f"roofline/{key}", cor.get(key), round_, path,
                   device=dev)
        if p is not None:
            pts.append(p)
    if not pts:
        raise ValueError("roofline file with no corrected block values")
    return pts


def _parse_trainbench(path: str, round_: Optional[int],
                      docs: List[Any]) -> List[SeriesPoint]:
    """``TRAINBENCH_*`` legacy shapes: metric/value (r02/r03/b64k),
    offload ladder (levels list), MoE dispatch A/B."""
    (doc,) = docs
    base = os.path.basename(path)
    tag = re.sub(r"^TRAINBENCH_r\d+_?|\.json$", "", base) or "mlp"
    dev = _doc_device(doc)
    dt = _doc_dtype(doc)
    pts: List[SeriesPoint] = []
    if "levels" in doc:                       # offload ladder
        for lvl in doc["levels"]:
            name = lvl.get("offload", "?")
            for key in ("step_time_ms", "mfu"):
                p = _point(f"trainbench/{tag}/{name}/{key}", lvl.get(key),
                           round_, path, device=dev, dtype=dt)
                if p is not None:
                    pts.append(p)
    elif "dispatch" in doc:                   # MoE dense-vs-a2a
        for name, cell in doc["dispatch"].items():
            p = _point(f"trainbench/moe/{name}/median_ms",
                       cell.get("median_ms"), round_, path, device=dev,
                       dtype=dt, unit="ms")
            if p is not None:
                pts.append(p)
    elif "metric" in doc and "value" in doc:  # metric/value envelope
        pts_extra = [("value", doc["metric"]), ("mfu", "mfu"),
                     ("step_time_ms", "step_time_ms")]
        for key, name in pts_extra:
            p = _point(f"trainbench/{tag}/{name}", doc.get(key), round_,
                       path, device=dev, dtype=dt,
                       unit=doc.get("unit", "") if key == "value" else "")
            if p is not None:
                pts.append(p)
    if not pts:
        raise ValueError("unrecognized TRAINBENCH shape")
    return pts


def _parse_pipebench(path: str, round_: Optional[int],
                     docs: List[Any]) -> List[SeriesPoint]:
    (doc,) = docs
    dev = _doc_device(doc)
    pts = []
    for sweep_name, rows in doc["sweeps"].items():
        for row in rows:
            tag = (f"{sweep_name}/m{row.get('n_micro', '?')}"
                   f"s{row.get('stages', '?')}v{row.get('virtual', '?')}")
            for sched in ("gpipe", "interleaved"):
                cell = row.get(sched)
                if isinstance(cell, dict):
                    p = _point(f"pipebench/{tag}/{sched}/median_ms",
                               cell.get("median_ms"), round_, path,
                               device=dev, unit="ms")
                    if p is not None:
                        pts.append(p)
    if not pts:
        raise ValueError("pipebench file with no sweep rows")
    return pts


def _parse_bf16_legacy(path: str, round_: Optional[int],
                       docs: List[Any]) -> List[SeriesPoint]:
    """Grandfathered ``BENCH_BF16_r04``-era shape, emitted under the
    MIGRATED emitter's series names (``bench:tools.bench_bf16_staging/
    {arm}_median_ms``) so the r04 trajectory continues through the
    RunRecord rounds instead of restarting at the migration."""
    (doc,) = docs
    dev = _doc_device(doc)
    pts = []
    for run in doc["runs"]:
        arm = run.get("staging", "?")
        for key in ("median_ms", "min_ms"):
            p = _point(f"bench:tools.bench_bf16_staging/{arm}_{key}",
                       run.get(key), round_, path,
                       trials=run.get("times_ms") if key == "median_ms"
                       else None, device=dev, unit="ms")
            if p is not None:
                pts.append(p)
    if not pts:
        raise ValueError("BENCH_BF16 file with no runs")
    return pts


def _parse_capacity_legacy(path: str, round_: Optional[int],
                           docs: List[Any]) -> List[SeriesPoint]:
    """Grandfathered ``CAPACITY_BEYOND_HBM_r04``-era shape, emitted
    under the migrated emitter's series names (same continuity
    rationale as the bf16 parser)."""
    (doc,) = docs
    dev = _doc_device(doc)
    pts = []
    for key in ("solve_wall_s", "gen_s", "qd_pairs_per_sec_wall",
                "dataset_vs_hbm", "repairs", "validate_mismatches"):
        p = _point(f"capacity:tools.capacity_beyond_hbm/{key}",
                   doc.get(key), round_, path, device=dev)
        if p is not None:
            pts.append(p)
    if not pts:
        raise ValueError("capacity file with no known metrics")
    return pts


def _parse_generic(path: str, round_: Optional[int],
                   docs: List[Any]) -> List[SeriesPoint]:
    """Last-resort family: walk numeric leaves into series named by
    their JSON path. Keeps single-shape one-off artifacts (PROFILE,
    MESH_OVERHEAD, FUZZ, ...) queryable without a bespoke parser; the
    walker's caps are recorded on the entry by build_ledger."""
    base = os.path.basename(path)
    family = re.sub(r"_r\d+.*$|\.jsonl?$", "", base).lower() or "artifact"
    pts: List[SeriesPoint] = []
    dropped = 0
    for li, doc in enumerate(docs[:32]):
        leaves: List[Tuple[str, float]] = []
        dropped += _walk_numeric(doc, "", leaves)
        prefix = f"{family}" if len(docs) == 1 else f"{family}/line{li}"
        dev = _doc_device(doc)
        dt = _doc_dtype(doc)
        for key, v in leaves:
            p = _point(f"{prefix}/{key}", v, round_, path, device=dev,
                       dtype=dt)
            if p is not None:
                pts.append(p)
    dropped += max(len(docs) - 32, 0)
    if not pts:
        # Valid JSON with no numeric perf content (pass/fail status
        # records like MULTICHIP_*, prose anchors like BASELINE.json):
        # a legitimately series-free artifact — parsed, zero series.
        # Truly unreadable files never reach here (ingest_file catches
        # the JSON decode error first).
        return []
    # Smuggle the drop count to build_ledger via an attribute-free
    # channel: a sentinel info point (explicit, filterable).
    if dropped:
        pts.append(SeriesPoint(series=f"{family}/_generic_leaves_dropped",
                               value=float(dropped), round=round_,
                               source=path, better="info"))
    return pts


#: ordered (predicate, family name, parser); first predicate match wins
_FAMILIES: List[Tuple[Callable[[str, List[Any]], bool], str,
                      Callable[[str, Optional[int], List[Any]],
                               List[SeriesPoint]]]] = [
    (lambda p, docs: all(_is_runrecord(d) for d in docs),
     "runrecord", _parse_runrecord_docs),
    (lambda p, docs: (os.path.basename(p).startswith("BENCH_r")
                      and len(docs) == 1 and isinstance(docs[0], dict)
                      and "parsed" in docs[0]),
     "bench", _parse_bench),
    (lambda p, docs: (os.path.basename(p).startswith("HARNESS")
                      and len(docs) == 1 and isinstance(docs[0], dict)
                      and "configs" in docs[0]),
     "harness", _parse_harness),
    (lambda p, docs: os.path.basename(p).startswith("SWEEP"),
     "sweep", _parse_sweep_jsonl),
    (lambda p, docs: (os.path.basename(p).startswith("ROOFLINE")
                      and len(docs) == 1 and isinstance(docs[0], dict)
                      and "corrected" in docs[0]),
     "roofline", _parse_roofline),
    (lambda p, docs: (os.path.basename(p).startswith("TRAINBENCH")
                      and len(docs) == 1),
     "trainbench", _parse_trainbench),
    (lambda p, docs: (os.path.basename(p).startswith("PIPEBENCH")
                      and len(docs) == 1 and isinstance(docs[0], dict)
                      and "sweeps" in docs[0]),
     "pipebench", _parse_pipebench),
    (lambda p, docs: (os.path.basename(p).startswith("BENCH_BF16")
                      and len(docs) == 1 and isinstance(docs[0], dict)
                      and "runs" in docs[0]),
     "bench_bf16", _parse_bf16_legacy),
    (lambda p, docs: (os.path.basename(p).startswith("CAPACITY_BEYOND")
                      and len(docs) == 1 and isinstance(docs[0], dict)
                      and "solve_wall_s" in docs[0]),
     "capacity", _parse_capacity_legacy),
    (lambda p, docs: True, "generic", _parse_generic),
]


def _load_docs(path: str) -> List[Any]:
    with open(path) as f:
        text = f.read()
    if path.endswith(".jsonl"):
        return [json.loads(ln) for ln in text.splitlines() if ln.strip()]
    return [json.loads(text)]


def ingest_file(path: str) -> Dict[str, Any]:
    """Parse one artifact into an entry dict:
    ``{source, family, round, status, points | error}``."""
    round_ = round_from_name(path)
    entry: Dict[str, Any] = {"source": os.path.basename(path),
                             "round": round_}
    try:
        docs = _load_docs(path)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        entry.update(family="unknown", status="unparseable",
                     error=f"unreadable: {e}")
        return entry
    for pred, family, parser in _FAMILIES:
        try:
            if not pred(path, docs):
                continue
        except Exception:
            continue
        try:
            points = parser(path, round_, docs)
        except Exception as e:
            if family == "generic":
                entry.update(family=family, status="unparseable",
                             error=f"{type(e).__name__}: {e}")
                return entry
            continue  # next family (generic is the terminal fallback)
        dropped = [p for p in points
                   if p.series.endswith("/_generic_leaves_dropped")]
        points = [p for p in points
                  if not p.series.endswith("/_generic_leaves_dropped")]
        entry.update(family=family, status="parsed",
                     points=[p.to_dict() for p in points])
        if dropped:
            entry["generic_leaves_dropped"] = int(dropped[0].value)
        return entry
    entry.update(family="unknown", status="unparseable",
                 error="no family parser accepted the document")
    return entry


def discover_artifacts(root: str) -> List[str]:
    seen = {}
    for pattern in ARTIFACT_PATTERNS:
        for p in glob.glob(os.path.join(root, pattern)):
            if os.path.isfile(p):
                seen[os.path.abspath(p)] = p
    return sorted(seen.values())


def build_ledger(root: str = ".",
                 paths: Optional[List[str]] = None) -> Dict[str, Any]:
    """Ingest every perf artifact under ``root`` (or the explicit
    ``paths``) into one ledger document. Every discovered file becomes
    exactly one entry; coverage is reported explicitly."""
    files = paths if paths is not None else discover_artifacts(root)
    entries = [ingest_file(p) for p in files]
    series: Dict[str, List[Dict[str, Any]]] = {}
    for entry in entries:
        for pd in entry.get("points", []):
            series.setdefault(pd["series"], []).append(pd)
    for pts in series.values():
        pts.sort(key=lambda p: (p.get("round") is None,
                                p.get("round") or 0, p.get("source", "")))
    parsed = sum(1 for e in entries if e["status"] == "parsed")
    return {
        "ledger_schema": LEDGER_SCHEMA,
        "root": os.path.abspath(root),
        "entries": entries,
        "series": series,
        "coverage": {
            "files": len(entries),
            "parsed": parsed,
            "unparseable": len(entries) - parsed,
            "fraction": (parsed / len(entries)) if entries else 1.0,
            "unparseable_sources": [e["source"] for e in entries
                                    if e["status"] != "parsed"],
        },
    }


# -- noise-aware comparison --------------------------------------------------

def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def noise_band(trials: List[float]) -> float:
    """Half-width of the noise band around the trials' median:
    ``max(Z * 1.4826 * MAD / sqrt(n), REL_FLOOR * |median|)``."""
    med = _median(trials)
    mad = _median([abs(t - med) for t in trials])
    sigma = 1.4826 * mad
    return max(NOISE_Z * sigma / math.sqrt(len(trials)),
               NOISE_REL_FLOOR * abs(med))


def compare_points(prev: Dict[str, Any],
                   cur: Dict[str, Any]) -> Dict[str, Any]:
    """Noise-aware delta of ``cur`` vs ``prev`` (two rounds of one
    series, as ledger point dicts). Never silently compares
    incomparables: the result either qualifies the delta against a
    noise band or carries an explicit marker."""
    out: Dict[str, Any] = {
        "series": cur.get("series"),
        "prev_round": prev.get("round"), "cur_round": cur.get("round"),
        "prev": prev.get("value"), "cur": cur.get("value"),
    }
    dev_a = prev.get("device", "unspecified")
    dev_b = cur.get("device", "unspecified")
    if dev_a != dev_b:
        out["marker"] = "device_mismatch"
        out["devices"] = [dev_a, dev_b]
        return out
    pv, cv = float(prev["value"]), float(cur["value"])
    if pv != 0:
        out["delta_pct"] = round((cv - pv) / abs(pv) * 100.0, 2)
    ta, tb = prev.get("trials"), cur.get("trials")
    if not ta or not tb or len(ta) < MIN_TRIALS or len(tb) < MIN_TRIALS:
        out["marker"] = "insufficient_trials"
        out["trials"] = [len(ta or []), len(tb or [])]
        return out
    med_a, med_b = _median(ta), _median(tb)
    band = noise_band(ta) + noise_band(tb)
    out["median_prev"], out["median_cur"] = med_a, med_b
    out["noise_band"] = round(band, 3)
    out["significant"] = abs(med_b - med_a) > band
    better = cur.get("better", "lower")
    if out["significant"] and better in ("lower", "higher"):
        worse = med_b > med_a if better == "lower" else med_b < med_a
        out["regressed"] = worse
        out["improved"] = not worse
    else:
        out["regressed"] = False
        out["improved"] = False
    return out


def _latest_same_device_pair(by_round: Dict[int, Dict[str, Any]],
                             rounds: List[int]):
    """The newest (prev, cur) round pair measured on the SAME device,
    or None. Scans newest-first so the freshest comparable evidence
    wins."""
    for i in range(len(rounds) - 1, 0, -1):
        cur_dev = by_round[rounds[i]].get("device", "unspecified")
        for j in range(i - 1, -1, -1):
            if by_round[rounds[j]].get("device",
                                       "unspecified") == cur_dev:
                return rounds[j], rounds[i]
    return None


def series_deltas(ledger: Dict[str, Any],
                  min_rounds: int = 2) -> List[Dict[str, Any]]:
    """Round-over-round comparisons for every series with at least
    ``min_rounds`` distinct rounds. Points within a round are reduced
    to the LAST one (files sort deterministically).

    Emits the adjacent newest pair (which may carry a
    ``device_mismatch`` marker), AND — when that pair is not the
    newest same-device pair — the newest comparison between rounds on
    one device. Without the second comparison, landing one
    foreign-device round at the root (a CPU-container artifact after a
    TPU series) would silently un-gate the still-comparable earlier
    pair, disabling regression detection exactly by adding data."""
    out = []
    for name, pts in sorted(ledger.get("series", {}).items()):
        by_round: Dict[int, Dict[str, Any]] = {}
        for p in pts:
            r = p.get("round")
            if r is not None:
                by_round[int(r)] = p
        if len(by_round) < min_rounds:
            continue
        rounds = sorted(by_round)
        pairs = [(rounds[-2], rounds[-1])]
        same_dev = _latest_same_device_pair(by_round, rounds)
        if same_dev is not None and same_dev not in pairs:
            pairs.append(same_dev)
        for prev_r, cur_r in pairs:
            cmp = compare_points(by_round[prev_r], by_round[cur_r])
            cmp["series"] = name
            cmp["rounds"] = rounds
            out.append(cmp)
    return out
