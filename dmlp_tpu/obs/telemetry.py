"""Live in-process telemetry: metrics registry, sampler, OpenMetrics
export, and the crash flight recorder.

Every observability layer so far (spans, counters, comms models, the
perf ledger) is post-hoc — artifacts written after a batch run exits.
This module is the LIVE half, the substrate the future serving daemon's
p50/p95/p99 / QPS / memory-headroom contract lands on:

- :class:`Registry` — a thread-safe in-process metrics store of
  counters, gauges, and streaming histograms. Histograms use fixed
  log-spaced buckets (:data:`HIST_BUCKETS_PER_DECADE` per decade), so
  quantile estimates carry a *bounded, documented* relative error
  (:data:`HIST_QUANTILE_REL_ERROR`) with O(1) memory per metric —
  exact-enough p50/p95/p99 without retaining samples. One process-wide
  registry (:data:`REGISTRY`) always exists: recording is cheap and
  unconditional (the resilience counters write through it); *export*
  (sampler, snapshot file, HTTP endpoint, flight recorder) is what
  ``--telemetry`` opts into via :class:`TelemetrySession`.
- :class:`Sampler` — a low-overhead background thread polling
  per-device ``memory_stats()`` into ``mem.device.*`` gauges (with the
  honest ``mem.stats_unavailable`` gauge on backends that report
  nothing — this container's CPU backend returns None), live-array
  bytes as the fallback watermark basis, heartbeat age
  (``$DMLP_TPU_HEARTBEAT``), and uptime. Start/stop are idempotent.
  The sampler never *initializes* a jax backend: it only polls devices
  when the process already imported jax.
- **OpenMetrics export** — :meth:`Registry.to_openmetrics` renders the
  text exposition format (dots map to underscores, counters get
  ``_total``, histograms emit cumulative ``_bucket{le=...}`` series,
  terminated by ``# EOF``); :func:`validate_openmetrics` is the
  structural validator CI uses (no external dependency).
  :class:`TelemetrySession` rewrites a snapshot file periodically
  (``--telemetry FILE``) and can serve the same text on an opt-in
  localhost HTTP endpoint (``--telemetry-port``) for the serving
  daemon's scrape loop.
- :class:`FlightRecorder` — a bounded ring buffer of recent spans,
  instants, explicit events, and counter deltas, dumped to a
  ``FLIGHT_<reason>.json`` artifact on crash, fatal-classified fault
  (resilience.retry), or SIGTERM — the post-mortem evidence the chaos
  harness's injected failures previously vanished without.

Span-derived phase latencies come from one seam: when a session is
active, :mod:`dmlp_tpu.obs.trace` forwards every completed span and
instant here (``span.<name>_ms`` histograms + flight events), whether
or not a Tracer is installed — the contract channels stay
byte-identical either way (everything here is stderr/filesystem-only).

Import-light by design (stdlib only, jax strictly lazy): the resilience
hot paths write through the registry unconditionally.
"""

from __future__ import annotations

import json
import math
import os
import re
import signal
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# -- histogram bucketing ------------------------------------------------------

#: log-spaced buckets per decade; 20 → adjacent bounds grow by 10^0.05
HIST_BUCKETS_PER_DECADE = 20
#: smallest / largest finite bucket upper bounds (values outside clamp
#: into the first / overflow bucket; min/max are tracked exactly)
HIST_LO = 1e-3
HIST_DECADES = 10
#: documented quantile relative error: a quantile estimate is the
#: geometric midpoint of its bucket, so the worst-case relative error is
#: sqrt(growth) - 1 ≈ 5.9% at 20 buckets/decade (tests verify against
#: numpy.percentile within this bound, away from the clamp edges)
HIST_QUANTILE_REL_ERROR = 10 ** (1 / (2 * HIST_BUCKETS_PER_DECADE)) - 1

_GROWTH = 10 ** (1.0 / HIST_BUCKETS_PER_DECADE)
_NBUCKETS = HIST_DECADES * HIST_BUCKETS_PER_DECADE
#: shared upper-bound table: bucket i covers (bounds[i-1], bounds[i]]
_BOUNDS = tuple(HIST_LO * _GROWTH ** (i + 1) for i in range(_NBUCKETS))

#: metric names are literal snake_case dotted paths — enforced
#: statically by check rule R601 and at runtime here
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)*$")


class Counter:
    """Monotonic counter, optionally split by one label value."""

    kind = "counter"

    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._lock = threading.Lock()
        self._values: Dict[str, float] = {}

    def inc(self, v: float = 1.0, label: str = "") -> None:
        if v < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._values[label] = self._values.get(label, 0.0) + v
        _notify_counter_delta(self.name, label, v)

    def value(self, label: str = "") -> float:
        with self._lock:
            return self._values.get(label, 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def by_label(self) -> Dict[str, float]:
        with self._lock:
            return {k: v for k, v in self._values.items() if k}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {"kind": self.kind,
                                   "total": sum(self._values.values())}
            labeled = {k: v for k, v in self._values.items() if k}
            if labeled:
                out["by_label"] = labeled
            return out


class Gauge:
    """Last-written value, optionally split by one label value."""

    kind = "gauge"

    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._lock = threading.Lock()
        self._values: Dict[str, float] = {}

    def set(self, v: float, label: str = "") -> None:
        with self._lock:
            self._values[label] = float(v)

    def remove(self, label: str) -> None:
        """Drop one label's sample. Gauges describe CURRENT state, so
        an entity that ceases to exist (a retired fleet replica) must
        leave the exposition — a counter's history, by contrast, is
        never removed."""
        with self._lock:
            self._values.pop(label, None)

    def value(self, label: str = "") -> Optional[float]:
        with self._lock:
            return self._values.get(label)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {"kind": self.kind}
            if "" in self._values:
                out["value"] = self._values[""]
            labeled = {k: v for k, v in self._values.items() if k}
            if labeled:
                out["by_label"] = labeled
            return out


#: default windowed-quantile sub-window width (seconds) — the sliding
#: window's time resolution; enable_windows() overrides per histogram
WINDOW_SUB_S = 2.5
#: default longest sliding window served (seconds)
WINDOW_MAX_S = 300.0


class _WindowFrame:
    """One sub-window of a windowed histogram: a SPARSE bucket->count
    map plus exact count/sum/min/max, stamped with its grid-aligned
    start time. Sparse because a sub-window typically touches a few
    buckets out of 201."""

    __slots__ = ("start", "counts", "count", "sum", "mn", "mx")

    def __init__(self, start: float):
        self.start = start
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.mn = math.inf
        self.mx = -math.inf


class Histogram:
    """Streaming histogram over fixed log-spaced buckets.

    O(1) memory, bounded-error quantiles (module docstring): values at
    or below :data:`HIST_LO` land in bucket 0, values beyond the last
    bound in the overflow bucket; exact min/max/sum/count ride along so
    the clamp never hides the extremes.

    **Sliding windows** (opt-in via :meth:`enable_windows`): a rotating
    ring of sub-window bucket snapshots (:class:`_WindowFrame`, width
    ``sub_s``) so p50/p95/p99 are computable over the trailing 10 s /
    1 m / 5 m instead of cumulative-since-start. A window quantile
    carries the SAME :data:`HIST_QUANTILE_REL_ERROR` bound as the
    cumulative one (the bucket grid is shared; min/max are exact per
    frame), plus a time-resolution slack of at most one sub-window of
    extra trailing data. The streaming SLO engine (obs.slo) is the
    consumer."""

    kind = "histogram"

    def __init__(self, name: str, help_: str = "", unit: str = ""):
        self.name, self.help, self.unit = name, help_, unit
        self._lock = threading.Lock()
        self._counts = [0] * (_NBUCKETS + 1)   # +1 = overflow (+Inf)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        # bucket index -> (exemplar id, value): the LAST exemplar-
        # carrying observation per bucket, so a p99 bucket links back
        # to one reconstructable request (rid) in the merged trace.
        self._exemplars: Dict[int, Tuple[str, float]] = {}
        # sliding-window ring (None until enable_windows): guarded by
        # _lock like every other field — observe() appends into the
        # open frame, readers merge the frames inside the window.
        self._frames: Optional[deque] = None
        self._sub_s = WINDOW_SUB_S
        self._time = time.monotonic

    def enable_windows(self, max_window_s: float = WINDOW_MAX_S,
                       sub_s: float = WINDOW_SUB_S,
                       time_fn=None) -> None:
        """Turn on the sliding-window ring (idempotent; the FIRST
        enablement pins the geometry). ``time_fn`` injects a clock for
        deterministic rotation-boundary tests; production uses
        ``time.monotonic``."""
        if sub_s <= 0 or max_window_s < sub_s:
            raise ValueError(
                f"window geometry max={max_window_s} sub={sub_s} "
                "needs 0 < sub_s <= max_window_s")
        with self._lock:
            if self._frames is not None:
                return
            if time_fn is not None:
                self._time = time_fn
            self._sub_s = float(sub_s)
            cap = int(math.ceil(max_window_s / self._sub_s)) + 1
            self._frames = deque(maxlen=max(cap, 2))
            self._frames.append(_WindowFrame(self._time()))

    @property
    def windowed(self) -> bool:
        with self._lock:
            return self._frames is not None

    def _rotate_locked(self) -> float:
        """Close the open frame if its sub-window elapsed; returns
        ``now``. The new frame's start is GRID-ALIGNED to the first
        frame's schedule, so an idle gap yields a fresh frame at the
        right phase instead of one frame stretched across the gap
        (stale samples would then never age out)."""
        # check: allow-concurrency=R702 — every caller holds self._lock
        # (the ``_locked`` suffix is the contract); _time/_frames/_sub_s
        # are only ever mutated under that same lock.
        now, frames, sub_s = self._time(), self._frames, self._sub_s
        last = frames[-1]
        if now - last.start >= sub_s:
            steps = int((now - last.start) // sub_s)
            frames.append(_WindowFrame(last.start + steps * sub_s))
        return now

    def _window_merge_locked(self, window_s: float
                             ) -> Tuple[List[int], int, float, float,
                                        float]:
        """Merge every frame overlapping the trailing ``window_s``
        into one (counts, count, sum, min, max) state. Caller holds
        the lock."""
        # check: allow-concurrency=R702 — caller holds self._lock (the
        # ``_locked`` suffix is the contract); _frames/_sub_s are only
        # ever mutated under that same lock.
        frames, sub_s = self._frames, self._sub_s
        now = self._rotate_locked()
        cutoff = now - float(window_s)
        counts = [0] * (_NBUCKETS + 1)
        count, total = 0, 0.0
        mn, mx = math.inf, -math.inf
        for fr in frames:
            if fr.start + sub_s <= cutoff:
                continue                     # fully aged out
            for i, c in fr.counts.items():
                counts[i] += c
            count += fr.count
            total += fr.sum
            mn = min(mn, fr.mn)
            mx = max(mx, fr.mx)
        return counts, count, total, mn, mx

    @staticmethod
    def bucket_index(v: float) -> int:
        if v <= HIST_LO:
            return 0
        i = int(math.ceil(math.log(v / HIST_LO, _GROWTH))) - 1
        # float log can land one bucket off at exact bounds; fix locally
        while i < _NBUCKETS and v > _BOUNDS[i]:
            i += 1
        while i > 0 and v <= _BOUNDS[i - 1]:
            i -= 1
        return min(i, _NBUCKETS)

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        if not math.isfinite(v):
            return          # a NaN sample must not poison the quantiles
        i = self.bucket_index(v) if v > 0 else 0
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if exemplar is not None:
                self._exemplars[i] = (str(exemplar), v)
            if self._frames is not None:
                self._rotate_locked()
                fr = self._frames[-1]
                fr.counts[i] = fr.counts.get(i, 0) + 1
                fr.count += 1
                fr.sum += v
                fr.mn = min(fr.mn, v)
                fr.mx = max(fr.mx, v)

    def exemplars(self) -> Dict[int, Tuple[str, float]]:
        """bucket index -> (exemplar id, observed value) snapshot."""
        with self._lock:
            return dict(self._exemplars)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @staticmethod
    def _quantile_from(counts: List[int], count: int, mn: float,
                       mx: float, q: float) -> float:
        """Quantile math over one CONSISTENT state copy — quantile()
        and snapshot() both route through this so a concurrent
        observe() between two lock acquisitions can never mix counts
        from one state with min/max from another."""
        if count == 0:
            return math.nan
        rank = q * (count - 1) + 1              # 1-based sample rank
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                break
        if i == 0:
            lo, hi = min(mn, HIST_LO), HIST_LO
        elif i >= _NBUCKETS:
            lo, hi = _BOUNDS[-1], mx
        else:
            lo, hi = _BOUNDS[i - 1], _BOUNDS[i]
        lo, hi = max(lo, 1e-12), max(hi, 1e-12)
        est = math.sqrt(lo * hi)
        return min(max(est, mn), mx)

    def quantile(self, q: float) -> float:
        """Bounded-error quantile estimate (see HIST_QUANTILE_REL_ERROR):
        the geometric midpoint of the bucket holding the q-th sample,
        clamped into the exact [min, max] envelope. NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            counts = list(self._counts)
            count, mn, mx = self._count, self._min, self._max
        return self._quantile_from(counts, count, mn, mx, q)

    def window_quantile(self, window_s: float, q: float) -> float:
        """Bounded-error quantile over the trailing ``window_s``
        seconds (same :data:`HIST_QUANTILE_REL_ERROR` bound as
        :meth:`quantile`). NaN when the window holds no samples.
        Raises if :meth:`enable_windows` was never called."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            if self._frames is None:
                raise ValueError(
                    f"histogram {self.name!r} has no window ring "
                    "(call enable_windows first)")
            counts, count, _, mn, mx = self._window_merge_locked(
                window_s)
        return self._quantile_from(counts, count, mn, mx, q)

    def window_snapshot(self, window_s: float) -> Dict[str, Any]:
        """count/sum/min/max/p50/p95/p99 over the trailing
        ``window_s`` seconds from ONE consistent merged state (same
        one-lock-acquisition discipline as :meth:`snapshot`)."""
        with self._lock:
            if self._frames is None:
                raise ValueError(
                    f"histogram {self.name!r} has no window ring "
                    "(call enable_windows first)")
            counts, count, total, mn, mx = self._window_merge_locked(
                window_s)
        out: Dict[str, Any] = {"window_s": float(window_s),
                               "count": count, "sum": round(total, 6)}
        if count:
            out.update(
                min=mn, max=mx,
                p50=self._quantile_from(counts, count, mn, mx, 0.5),
                p95=self._quantile_from(counts, count, mn, mx, 0.95),
                p99=self._quantile_from(counts, count, mn, mx, 0.99))
        return out

    def window_above(self, window_s: float,
                     threshold: float) -> Tuple[int, int]:
        """(bad, total) sample counts over the trailing ``window_s``:
        ``bad`` counts samples above ``threshold`` at BUCKET
        resolution — samples sharing the threshold's own bucket count
        as good, so the split carries the same relative-error bound as
        the quantiles. The burn-rate evaluator's primitive."""
        with self._lock:
            if self._frames is None:
                raise ValueError(
                    f"histogram {self.name!r} has no window ring "
                    "(call enable_windows first)")
            counts, count, _, mn, mx = self._window_merge_locked(
                window_s)
        if count == 0:
            return 0, 0
        if mx <= threshold:          # exact max rules the window good
            return 0, count
        ti = self.bucket_index(threshold)
        bad = sum(counts[ti + 1:])
        return bad, count

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """(upper bound, cumulative count) pairs, ending with +Inf."""
        with self._lock:
            out = []
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                bound = _BOUNDS[i] if i < _NBUCKETS else math.inf
                out.append((bound, cum))
            return out

    def snapshot(self) -> Dict[str, Any]:
        # ONE lock acquisition for the whole snapshot: computing the
        # quantiles via self.quantile() would re-lock per call, so a
        # concurrent observe() between p50 and p99 could yield
        # quantiles from a different distribution than count/min/max
        # in the same snapshot (the bucket-update-vs-snapshot-read
        # race the R7 audit called out).
        with self._lock:
            counts = list(self._counts)
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
        out: Dict[str, Any] = {"kind": self.kind, "count": count,
                               "sum": round(total, 6)}
        if self.unit:
            out["unit"] = self.unit
        if count:
            out.update(
                min=mn, max=mx,
                p50=self._quantile_from(counts, count, mn, mx, 0.5),
                p95=self._quantile_from(counts, count, mn, mx, 0.95),
                p99=self._quantile_from(counts, count, mn, mx, 0.99))
        return out


class Registry:
    """Thread-safe name → metric table with get-or-create semantics.

    Re-registering an existing name with the SAME kind returns the
    existing metric (the R6 contract: one declaration, any number of
    use sites); a kind conflict raises — two subsystems silently
    sharing one name as counter-and-gauge would corrupt both."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}

    def _get(self, name: str, cls, **kw):
        if not NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} is not snake_case dotted "
                "(check rule R601)")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind} (check rule R602)")
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, Counter, help_=help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, Gauge, help_=help_)

    def histogram(self, name: str, help_: str = "",
                  unit: str = "") -> Histogram:
        return self._get(name, Histogram, help_=help_, unit=unit)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self, prefix: Optional[str] = None) -> None:
        """Drop metrics (all, or those under ``prefix.``) — run-scoped
        emitters (the CLI, the train loop) reset at start the way
        resilience.stats always has."""
        with self._lock:
            if prefix is None:
                self._metrics.clear()
            else:
                for name in [n for n in self._metrics
                             if n == prefix
                             or n.startswith(prefix + ".")]:
                    del self._metrics[name]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.snapshot() for name, m in sorted(metrics.items())}

    # -- OpenMetrics text exposition -----------------------------------------
    def to_openmetrics(self) -> str:
        """The OpenMetrics text format (the serving scrape contract):
        dotted names map to underscores, counters emit ``<name>_total``,
        histograms the cumulative ``_bucket{le=...}`` + ``_sum`` +
        ``_count`` family, ``# EOF`` terminates."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            name = _om_name(m.name)
            lines.append(f"# TYPE {name} {m.kind}")
            if m.help:
                lines.append(f"# HELP {name} {_om_escape(m.help)}")
            if isinstance(m, Counter):
                snap = m.snapshot()
                lines.append(f"{name}_total {_om_num(snap['total'])}")
                for lab, v in sorted(snap.get("by_label", {}).items()):
                    lines.append(f'{name}_total{{key="{_om_escape(lab)}"}}'
                                 f" {_om_num(v)}")
            elif isinstance(m, Gauge):
                snap = m.snapshot()
                if "value" in snap:
                    lines.append(f"{name} {_om_num(snap['value'])}")
                for lab, v in sorted(snap.get("by_label", {}).items()):
                    lines.append(f'{name}{{key="{_om_escape(lab)}"}}'
                                 f" {_om_num(v)}")
            else:                                   # Histogram
                prev = 0
                exem = m.exemplars()
                for bi, (bound, cum) in enumerate(m.bucket_counts()):
                    if cum == prev and bound != math.inf:
                        continue    # sparse render: skip empty prefixes
                    le = "+Inf" if bound == math.inf else _om_num(bound)
                    lines.append(f'{name}_bucket{{le="{le}"}} {cum}')
                    # Exemplar as a comment line the validator (and any
                    # plain-Prometheus scraper) tolerates: the last rid
                    # observed into this bucket, so a tail bucket links
                    # back to one reconstructable request in the trace.
                    ex = exem.get(bi)
                    if ex is not None and cum > prev:
                        lines.append(
                            f'# EXEMPLAR {name}_bucket{{le="{le}"}} '
                            f'{_om_escape(ex[0])} {_om_num(ex[1])}')
                    prev = cum
                lines.append(f"{name}_sum {_om_num(m.sum)}")
                lines.append(f"{name}_count {m.count}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _om_name(dotted: str) -> str:
    return dotted.replace(".", "_")


def _om_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", " ")


def _om_num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (?P<value>\S+)$")
_META_RE = re.compile(
    r"^# (TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)"
    r"|HELP .*|EXEMPLAR .*|EOF)$")


def validate_openmetrics(text: str) -> List[str]:
    """Structural OpenMetrics validation (no external deps): returns a
    list of problems, empty when the exposition is well-formed —
    ``# EOF`` terminated, every sample line parseable, every sample
    name declared by a preceding ``# TYPE``, histogram buckets
    cumulative and consistent with ``_count``."""
    problems: List[str] = []
    lines = text.splitlines()
    if not lines or lines[-1] != "# EOF":
        problems.append("missing terminal '# EOF'")
    declared: Dict[str, str] = {}
    buckets: Dict[str, List[int]] = {}
    counts: Dict[str, int] = {}
    for i, line in enumerate(lines, 1):
        if not line:
            continue
        if line.startswith("#"):
            if not _META_RE.match(line):
                problems.append(f"line {i}: malformed metadata {line!r}")
            elif line.startswith("# TYPE "):
                _, _, name, kind = line.split(" ")
                declared[name] = kind
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {i}: malformed sample {line!r}")
            continue
        try:
            # float() accepts every value repr the emitter can produce
            # (scientific notation incl. negative exponents, inf/nan) —
            # a handwritten character class once rejected '5e-05'.
            float(m.group("value"))
        except ValueError:
            problems.append(f"line {i}: non-numeric sample value "
                            f"{m.group('value')!r}")
            continue
        name = re.split(r"[{ ]", line, 1)[0]
        base = re.sub(r"(_total|_bucket|_sum|_count)$", "", name)
        if name not in declared and base not in declared:
            problems.append(f"line {i}: sample {name!r} has no "
                            "preceding # TYPE")
            continue
        if name.endswith("_bucket"):
            buckets.setdefault(base, []).append(
                int(float(line.rsplit(" ", 1)[1])))
        elif name.endswith("_count") and declared.get(base) == "histogram":
            counts[base] = int(float(line.rsplit(" ", 1)[1]))
    for base, cums in buckets.items():
        if any(b > a for b, a in zip(cums, cums[1:])):
            problems.append(f"histogram {base}: non-cumulative buckets")
        if base in counts and cums and cums[-1] != counts[base]:
            problems.append(f"histogram {base}: +Inf bucket "
                            f"{cums[-1]} != _count {counts[base]}")
    return problems


# -- process-wide registry + enablement --------------------------------------

#: the one process registry: recording is always-on (resilience writes
#: through it); sessions only add export/sampling/flight machinery
REGISTRY = Registry()

_session_lock = threading.Lock()
_session: Optional["TelemetrySession"] = None


def registry() -> Registry:
    return REGISTRY


def enabled() -> bool:
    """Is a TelemetrySession active (export/sampler/flight on)?"""
    return _session is not None


def session() -> Optional["TelemetrySession"]:
    return _session


def _notify_counter_delta(name: str, label: str, v: float) -> None:
    s = _session
    if s is not None and s.flight is not None:
        s.flight.record("metric", name,
                        **({"delta": v, "key": label} if label
                           else {"delta": v}))


# -- span observer (fed by obs.trace) ----------------------------------------

def observe_span(name: str, dur_ms: float, args: Dict[str, Any]) -> None:
    """Called by obs.trace for every completed span while a session is
    active: span-derived phase latency histograms + flight events."""
    s = _session
    if s is None:
        return
    try:
        # One histogram per span name; the name itself rides as the
        # label so the metric name stays a literal (check rule R601).
        REGISTRY.histogram("span.latency_ms", unit="ms").observe(dur_ms)
        h = s.span_histograms.get(name)
        if h is None:
            safe = re.sub(r"[^a-z0-9_.]", "_", name.lower())
            if NAME_RE.match(safe):
                # span names are dotted identifiers already; the dynamic
                # registration is deliberate and allowlisted for R6 at
                # the one seam below.
                h = REGISTRY.histogram(safe + ".ms", unit="ms")  # check: allow-metric-name
            s.span_histograms[name] = h
        if h is not None:
            h.observe(dur_ms)
        if s.flight is not None:
            s.flight.record("span", name, dur_ms=round(dur_ms, 3),
                            **{k: v for k, v in args.items()
                               if isinstance(v, (str, int, float, bool))})
    except Exception:  # check: no-retry — telemetry must not fail the run
        pass


def observe_instant(name: str, args: Dict[str, Any]) -> None:
    s = _session
    if s is None or s.flight is None:
        return
    try:
        s.flight.record("instant", name,
                        **{k: v for k, v in args.items()
                           if isinstance(v, (str, int, float, bool))})
    except Exception:  # check: no-retry — telemetry must not fail the run
        pass


# -- flight recorder ----------------------------------------------------------

#: default ring capacity; $DMLP_TPU_FLIGHT_EVENTS overrides
FLIGHT_EVENTS_DEFAULT = 512


class FlightRecorder:
    """Bounded ring buffer of recent spans/instants/events/metric
    deltas; ``dump()`` writes the post-mortem artifact."""

    def __init__(self, capacity: Optional[int] = None):
        cap = capacity or int(os.environ.get("DMLP_TPU_FLIGHT_EVENTS",
                                             FLIGHT_EVENTS_DEFAULT))
        self._events: deque = deque(maxlen=max(cap, 8))
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.dumped: List[str] = []

    def record(self, kind: str, name: str, **data) -> None:
        ev = {"t_ms": round((time.monotonic() - self._t0) * 1e3, 3),
              "kind": kind, "name": name}
        if data:
            ev["data"] = data
        with self._lock:
            self._events.append(ev)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def dump(self, directory: str, reason: str) -> str:
        """Write ``FLIGHT_<reason>.json``: the last N events, the full
        registry snapshot, and the resilience counters — atomic rename,
        one file per (reason, pid) so concurrent ranks cannot clobber
        each other."""
        os.makedirs(directory, exist_ok=True)
        safe = re.sub(r"[^A-Za-z0-9_]+", "_", reason) or "unknown"
        path = os.path.join(directory,
                            f"FLIGHT_{safe}_pid{os.getpid()}.json")
        doc = {
            "flight_schema": 1,
            "reason": reason,
            "unix_time": time.time(),
            "pid": os.getpid(),
            "events": self.events(),
            "metrics": REGISTRY.snapshot(),
        }
        try:
            from dmlp_tpu.resilience import stats as rs_stats
            doc["resilience"] = rs_stats.snapshot()
        except Exception:  # check: no-retry — dump must still land
            pass
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        self.dumped.append(path)
        return path


def flight_event(name: str, **data) -> None:
    """Record an explicit flight event (no-op without a session) —
    the resilience degrade/supervise paths call this."""
    s = _session
    if s is not None and s.flight is not None:
        try:
            s.flight.record("event", name, **data)
        except Exception:  # check: no-retry — telemetry never raises
            pass


def flight_fault(site: str, classification: str, error: str,
                 dump: bool = False) -> None:
    """Resilience-retry hook: record a fault event; a fatal-classified
    (or retries-exhausted) fault additionally dumps the flight artifact
    immediately — the process may be about to die with the exception."""
    s = _session
    if s is None:
        return
    try:
        REGISTRY.counter("resilience.fatal_faults").inc(
            label=classification)
        if s.flight is not None:
            s.flight.record("fault", site, classification=classification,
                            error=error)
            if dump:
                s.flight.dump(s.flight_dir, "fatal_fault")
    except Exception:  # check: no-retry — telemetry never raises
        pass


def dump_on_crash(reason: str = "crash") -> Optional[str]:
    """Dump the flight buffer if a session is active (the CLI's
    top-level except hook); returns the artifact path or None."""
    s = _session
    if s is None or s.flight is None:
        return None
    try:
        return s.flight.dump(s.flight_dir, reason)
    except Exception:  # check: no-retry — a failing dump must not mask
        return None    # the original crash


# -- background sampler -------------------------------------------------------

#: default sampling interval; $DMLP_TPU_TELEMETRY_INTERVAL_S overrides
SAMPLE_INTERVAL_S = 0.25


class Sampler:
    """Background poll of device memory, live-array bytes, heartbeat
    age, and uptime into gauges. start()/stop() are idempotent; the
    thread is a daemon so a wedged exit never hangs the process."""

    def __init__(self, interval_s: Optional[float] = None):
        self.interval_s = interval_s if interval_s is not None else float(
            os.environ.get("DMLP_TPU_TELEMETRY_INTERVAL_S",
                           SAMPLE_INTERVAL_S))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.ticks = 0
        #: peak observed bytes per basis across the sampler's lifetime
        self.peaks: Dict[str, int] = {}

    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return                       # idempotent
            # Each loop gets its OWN stop event, bound at start: with a
            # shared event, stop();start() racing from two threads
            # could clear the flag before the old loop observed it and
            # leave two sampler loops running (found by check R702's
            # first run over this class).
            stop = threading.Event()
            self._stop = stop
            self._thread = threading.Thread(
                target=self._loop, args=(stop,),
                name="telemetry-sampler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
            self._stop.set()     # the event BOUND to t's loop; setting
            #                      it under the lock orders against a
            #                      concurrent start()'s rebind
        if t is None:
            return                           # idempotent
        t.join(timeout=5.0)

    @property
    def running(self) -> bool:
        with self._lock:
            t = self._thread
        return t is not None and t.is_alive()

    @staticmethod
    def _next_deadline(prev_deadline: float, now: float,
                       interval: float) -> Tuple[float, float]:
        """Advance the tick deadline on a MONOTONIC grid: the next
        deadline is ``prev + k*interval`` for the smallest k landing
        in the future, so the effective period is ``interval`` — not
        ``interval + work time`` (the drift the old sleep-after-work
        loop accumulated: a 0.25 s sampler doing 50 ms of polling ran
        at 0.3 s and every derived rate read ~17% low). Overruns skip
        the missed grid points (no catch-up burst) but keep the
        phase. Returns (new deadline, seconds to wait)."""
        nxt = prev_deadline + interval
        if nxt <= now:
            missed = math.floor((now - prev_deadline) / interval)
            nxt = prev_deadline + (missed + 1) * interval
        return nxt, max(nxt - now, 0.0)

    def _loop(self, stop: threading.Event) -> None:
        deadline = time.monotonic()
        while not stop.is_set():
            self.sample_now()
            deadline, delay = self._next_deadline(
                deadline, time.monotonic(), self.interval_s)
            stop.wait(delay)

    def sample_now(self) -> None:
        """One synchronous sampling tick — also exposed so the engines
        can stamp the watermark exactly at peak residency (between the
        solve enqueue and the result fetch)."""
        try:
            self._sample_memory()
            self._sample_heartbeat()
            REGISTRY.gauge("telemetry.uptime_s").set(
                round(time.monotonic() - self._t0, 3))
            self.ticks += 1
            REGISTRY.gauge("telemetry.sampler_ticks").set(self.ticks)
        except Exception:  # check: no-retry — sampling must never raise
            pass

    def _sample_memory(self) -> None:
        from dmlp_tpu.obs import memwatch
        stats = memwatch.device_memory_stats()
        if stats is None:                    # jax not even imported
            REGISTRY.gauge("mem.stats_unavailable").set(1)
            return
        any_stats = False
        # ONE consistent process-wide quantity per tick: the sum over
        # devices of (allocator peak where reported, else current
        # in-use); the tracked watermark is the max of that sum over
        # ticks. Mixing max-of-per-device-peaks with sum-of-in-use
        # would make the basis an inconsistent quantity.
        total_peakish = 0
        for i, st in enumerate(stats):
            if not st:
                continue
            any_stats = True
            in_use = int(st.get("bytes_in_use", 0))
            REGISTRY.gauge("mem.device.bytes_in_use").set(
                in_use, label=str(i))
            peak = st.get("peak_bytes_in_use")
            if peak is not None:
                REGISTRY.gauge("mem.device.peak_bytes_in_use").set(
                    int(peak), label=str(i))
            total_peakish += int(peak) if peak is not None else in_use
        REGISTRY.gauge("mem.stats_unavailable").set(0 if any_stats else 1)
        if any_stats:
            self.peaks["memory_stats"] = max(
                self.peaks.get("memory_stats", 0), total_peakish)
        live = memwatch.live_array_bytes()
        if live is not None:
            REGISTRY.gauge("mem.live_array_bytes").set(live)
            self.peaks["live_arrays"] = max(
                self.peaks.get("live_arrays", 0), live)
            REGISTRY.gauge("mem.live_array_bytes_peak").set(
                self.peaks["live_arrays"])

    def _sample_heartbeat(self) -> None:
        path = os.environ.get("DMLP_TPU_HEARTBEAT")
        if not path:
            return
        try:
            age = time.time() - os.stat(path).st_mtime
            REGISTRY.gauge("heartbeat.age_s").set(round(age, 3))
        except OSError:
            REGISTRY.gauge("heartbeat.age_s").set(-1)  # no beat yet

    def measured_peak(self) -> Dict[str, Any]:
        """The best watermark this sampler saw: ``memory_stats`` basis
        when the backend reports it, ``live_arrays`` otherwise, or the
        explicit unavailability marker."""
        for basis in ("memory_stats", "live_arrays"):
            if self.peaks.get(basis):
                return {"bytes": self.peaks[basis], "basis": basis}
        return {"unavailable": "no memory basis reported anything "
                               "(backend without memory_stats and no "
                               "live jax arrays sampled)"}


def sample_memory_now() -> None:
    """Engine hook: force one sampler tick at peak residency; no-op
    without an active session."""
    s = _session
    if s is not None and s.sampler is not None:
        s.sampler.sample_now()


# -- HTTP endpoint -------------------------------------------------------------

def _start_http(port: int):
    """Opt-in localhost scrape endpoint: GET /metrics (or /) returns
    the OpenMetrics text. Returns the server (its port in
    ``server_address[1]``; pass port=0 for an ephemeral one)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path not in ("/", "/metrics"):
                self.send_error(404)
                return
            body = REGISTRY.to_openmetrics().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "application/openmetrics-text; version=1.0.0")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):   # silence per-request stderr noise
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    t = threading.Thread(target=srv.serve_forever,
                         name="telemetry-http", daemon=True)
    t.start()
    return srv


# -- session -------------------------------------------------------------------

class TelemetrySession:
    """Everything ``--telemetry`` turns on, as one start/close bundle:
    the sampler, the periodic OpenMetrics snapshot rewrite, the opt-in
    HTTP endpoint, the flight recorder, the trace→telemetry span
    bridge, and the SIGTERM dump hook. Construct via :func:`start`."""

    def __init__(self, path: Optional[str] = None, port: Optional[int] = None,
                 interval_s: Optional[float] = None,
                 flight_dir: Optional[str] = None,
                 handle_signals: bool = True):
        self.path = path
        self.flight_dir = flight_dir or (
            os.path.dirname(os.path.abspath(path)) if path else ".")
        self.flight = FlightRecorder()
        self.sampler = Sampler(interval_s=interval_s)
        self.span_histograms: Dict[str, Optional[Histogram]] = {}
        self.http_server = None
        self.http_port: Optional[int] = None
        self._export_stop = threading.Event()
        self._export_thread: Optional[threading.Thread] = None
        self._prev_sigterm = None
        self._signals_installed = False
        self._port = port
        self._handle_signals = handle_signals
        self._closed = False
        self._drain_hook = None

    def _activate(self) -> None:
        self.sampler.start()
        if self._port is not None:
            self.http_server = _start_http(self._port)
            self.http_port = self.http_server.server_address[1]
            REGISTRY.gauge("telemetry.http_port").set(self.http_port)
        if self.path:
            self._export_thread = threading.Thread(
                target=self._export_loop, name="telemetry-export",
                daemon=True)
            self._export_thread.start()
        if self._handle_signals:
            self._install_sigterm()
        from dmlp_tpu.obs import trace as obs_trace
        obs_trace.set_telemetry_observer(observe_span, observe_instant)

    def _install_sigterm(self) -> None:
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
            self._signals_installed = True
        except ValueError:
            pass    # not the main thread: skip, dump-on-crash still works

    def set_sigterm_drain(self, hook) -> None:
        """Register a graceful-drain hook: while set, SIGTERM invokes
        ``hook()`` (which should only set an event — signal context)
        instead of dumping the flight ring and re-raising the kill. An
        ORDERLY shutdown is not a crash: the serving daemon finishes
        its in-flight micro-batches, flushes the final snapshot itself,
        and exits clean with no FLIGHT artifact. Pass None to restore
        the post-mortem behavior."""
        self._drain_hook = hook

    def _on_sigterm(self, signum, frame):
        hook = self._drain_hook
        if hook is not None:
            try:
                self.flight.record("event", "sigterm_drain")
                hook()
            except Exception:  # check: no-retry — a failing hook must
                pass           # not resurrect the kill mid-drain
            return
        try:
            self.flight.record("event", "sigterm")
            self.flight.dump(self.flight_dir, "sigterm")
            self.write_snapshot()
        finally:
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

    def _export_loop(self) -> None:
        interval = max(self.sampler.interval_s * 4, 1.0)
        while not self._export_stop.wait(interval):
            self.write_snapshot()

    def write_snapshot(self) -> None:
        """Atomic rewrite of the OpenMetrics snapshot file (the
        ``--telemetry FILE`` contract: readers always see a complete,
        valid exposition)."""
        if not self.path:
            return
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                f.write(REGISTRY.to_openmetrics())
            os.replace(tmp, self.path)
        except Exception:  # check: no-retry — export must not kill a run
            pass

    def snapshot_record(self, extra_config: Optional[dict] = None):
        """The telemetry snapshot as a schema RunRecord (kind
        "telemetry") — the ledger-ingestible serialization. Scalar
        gauges/counters become metrics; histograms contribute their
        p50/p95/p99/count."""
        from dmlp_tpu.obs.run import RunRecord, current_device
        metrics: Dict[str, Any] = {}
        for name, snap in REGISTRY.snapshot().items():
            key = name.replace(".", "_")
            if snap["kind"] == "counter":
                metrics[key + "_total"] = snap["total"]
            elif snap["kind"] == "gauge" and "value" in snap:
                metrics[key] = snap["value"]
            elif snap["kind"] == "histogram" and snap["count"]:
                for q in ("p50", "p95", "p99"):
                    metrics[f"{key}_{q}"] = round(snap[q], 6)
                metrics[key + "_count"] = snap["count"]
        return RunRecord(kind="telemetry", tool="dmlp_tpu.telemetry",
                         config=dict(extra_config or {}), metrics=metrics,
                         device=current_device())

    def close(self) -> None:
        """Final snapshot write + teardown. Idempotent."""
        global _session
        if self._closed:
            return
        self._closed = True
        from dmlp_tpu.obs import trace as obs_trace
        obs_trace.set_telemetry_observer(None, None)
        self._export_stop.set()
        t = self._export_thread
        if t is not None:
            t.join(timeout=5.0)
        self.sampler.sample_now()     # one last tick: final gauges
        self.sampler.stop()
        if self.http_server is not None:
            self.http_server.shutdown()
            self.http_server = None
        if self._signals_installed and self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
        self.write_snapshot()
        with _session_lock:
            if _session is self:
                _session = None


def start(path: Optional[str] = None, port: Optional[int] = None,
          interval_s: Optional[float] = None,
          flight_dir: Optional[str] = None,
          handle_signals: bool = True) -> TelemetrySession:
    """Start the process's telemetry session (sampler + export + flight
    recorder). One session at a time: starting over a live session
    closes the old one first."""
    global _session
    s = TelemetrySession(path=path, port=port, interval_s=interval_s,
                         flight_dir=flight_dir,
                         handle_signals=handle_signals)
    with _session_lock:
        prev = _session
        _session = s
    if prev is not None:
        prev.close()
        with _session_lock:
            _session = s    # prev.close() cleared the slot it owned
    try:
        s._activate()
    except BaseException:
        # A failed activation (e.g. the HTTP port is taken) must not
        # leave a half-started session installed with its sampler
        # thread running and no handle to close it.
        s.close()
        raise
    return s


__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY", "registry",
    "Sampler", "FlightRecorder", "TelemetrySession", "start", "enabled",
    "session", "sample_memory_now", "flight_event", "flight_fault",
    "dump_on_crash", "observe_span", "observe_instant",
    "validate_openmetrics", "HIST_QUANTILE_REL_ERROR",
    "HIST_BUCKETS_PER_DECADE", "SAMPLE_INTERVAL_S",
]
