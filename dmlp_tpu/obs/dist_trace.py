"""Per-rank tracing for the multi-process cluster.

The single-process tracer (obs.trace) collects one process's spans; the
cluster path (``python -m dmlp_tpu.distributed``) runs N ranks that each
see only their own timeline. This module is the distributed half:

- every rank installs a :class:`DistTracer` whose Perfetto ``pid`` IS the
  rank (Perfetto loads multi-process traces natively — one process track
  per distinct pid), and writes its own ``trace-rank<NN>.json`` in the
  shared trace directory (no cross-rank file contention);
- rank identity, process count, and the mesh coordinates of the rank's
  addressable devices are embedded both as Chrome ``M`` metadata events
  (rendered as the Perfetto process name/labels) and as a machine-readable
  top-level ``dist`` block;
- ranks have independent clock epochs (``time.perf_counter`` is
  per-process), so each rank stamps a **clock-sync instant** immediately
  after returning from a cluster-wide barrier
  (``multihost_utils.sync_global_devices``). The barrier releases every
  rank within network latency of the same wall instant, so aligning the
  sync instants aligns the rank timelines to ~RTT accuracy —
  ``tools/merge_traces.py`` applies exactly that offset and records it
  per rank in the merged artifact.

Like the rest of obs, this module is import-light (no jax at module
level) and every hook is a no-op when no tracer is installed.
"""

from __future__ import annotations

import os
from typing import Optional

from dmlp_tpu.obs import trace as obs_trace

#: the instant-event name merge/validate key on; one per rank, stamped at
#: the contract barrier
CLOCK_SYNC_EVENT = "dist.clock_sync"


def rank_trace_path(trace_dir: str, rank: int) -> str:
    """The per-rank trace file: ``DIR/trace-rank<NN>.json``."""
    return os.path.join(trace_dir, f"trace-rank{rank:02d}.json")


class DistTracer(obs_trace.Tracer):
    """A Tracer whose Perfetto pid is the cluster rank.

    ``mark_clock_sync()`` stamps the barrier-aligned instant; ``write()``
    adds rank metadata events plus the top-level ``dist`` block the merge
    tool consumes.
    """

    def __init__(self, rank: int, num_ranks: int, annotate: bool = False):
        super().__init__(annotate=annotate)
        self.rank = int(rank)
        self.num_ranks = int(num_ranks)
        self._pid = self.rank          # Perfetto process track = rank
        self._os_pid = os.getpid()
        self._clock_sync_ts_us: Optional[float] = None
        self.mesh_coords = None        # set via record_mesh

    def mark_clock_sync(self) -> None:
        """Stamp the barrier-aligned instant (call immediately after a
        cluster-wide barrier returns). The first stamp wins — the merge
        alignment needs ONE well-defined sync point per rank, and the
        contract barrier (pre-solve) is it; a warmup's earlier barrier
        would also qualify but the contract one brackets the timed
        region every rank has."""
        ts = (obs_trace._clock() - self._epoch) * 1e6
        if self._clock_sync_ts_us is None:
            self._clock_sync_ts_us = ts
        # ONE clock read serves both the dist-block stamp and the event:
        # the merge aligns ranks on clock_sync_ts_us but downstream
        # consumers compare the EVENT timestamps — a second read would
        # leave the two µs apart under scheduler jitter, so aligned
        # sync markers would not coincide exactly.
        self.instant(CLOCK_SYNC_EVENT, ts=ts, rank=self.rank)

    def record_mesh(self, mesh) -> None:
        """Record this rank's mesh-coordinate metadata: the (axis-name ->
        coordinate-range) of the devices this process addresses."""
        try:
            import numpy as np
            shape = dict(zip(mesh.axis_names, mesh.devices.shape))
            local = {d.id for d in mesh.local_devices}
            coords = np.argwhere(
                np.vectorize(lambda d: d.id in local)(mesh.devices))
            span = {ax: [int(coords[:, i].min()), int(coords[:, i].max())]
                    for i, ax in enumerate(mesh.axis_names)}
        except Exception:
            return  # metadata is best-effort; tracing must not raise
        self.mesh_coords = {"mesh_shape": shape, "local_span": span}
        self.instant("dist.mesh", rank=self.rank, **self.mesh_coords)

    def to_dict(self, process_name: str = "dmlp_tpu") -> dict:
        label = f"{process_name} rank {self.rank:02d}/{self.num_ranks}"
        doc = super().to_dict(process_name=label)
        meta = [
            {"name": "process_sort_index", "ph": "M", "pid": self._pid,
             "args": {"sort_index": self.rank}},
            {"name": "process_labels", "ph": "M", "pid": self._pid,
             "args": {"labels": f"rank={self.rank} os_pid={self._os_pid}"}},
        ]
        doc["traceEvents"] = doc["traceEvents"][:1] + meta \
            + doc["traceEvents"][1:]
        doc["dist"] = {
            "rank": self.rank,
            "num_ranks": self.num_ranks,
            "os_pid": self._os_pid,
            "clock_sync_ts_us": self._clock_sync_ts_us,
            # The rank file's OWN domain is still per-process monotonic
            # (the sync instant is alignment *input*, not applied);
            # merge_traces stamps the merged doc "synced" after it
            # applies the offsets, and refuses to skew-compare rank
            # files from mixed domains.
            "clock_source": self.clock_source,
        }
        if self.mesh_coords:
            doc["dist"]["mesh"] = self.mesh_coords
        return doc

    def write_rank_file(self, trace_dir: str) -> str:
        os.makedirs(trace_dir, exist_ok=True)
        path = rank_trace_path(trace_dir, self.rank)
        self.write(path)
        return path


def install(trace_dir: str, rank: int, num_ranks: int,
            annotate: bool = False) -> DistTracer:
    """Create a rank's DistTracer and install it as the process-wide
    collector, so every existing ``obs_span`` site (engines, contract
    run) reports into the per-rank timeline."""
    del trace_dir  # the path is fixed by rank; kept in the signature so
    # call sites name the directory where the file will land
    tracer = DistTracer(rank, num_ranks, annotate=annotate)
    obs_trace.install(tracer)
    return tracer


def clock_sync() -> None:
    """Hook form of :meth:`DistTracer.mark_clock_sync`: stamps the
    installed tracer if it is rank-aware, no-op otherwise (including the
    plain single-process Tracer, which needs no alignment)."""
    t = obs_trace.active()
    if isinstance(t, DistTracer):
        t.mark_clock_sync()
