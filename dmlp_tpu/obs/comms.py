"""Analytic collective-traffic accounting per mesh axis.

The reference's MPI choreography made its wire traffic visible in the
source (Scatterv / Bcast / Gather byte counts, engine.cpp); the JAX form
hides it inside XLA-lowered collectives. This module restores the
accounting analytically — bytes in/out per device and per mesh axis for
each collective the framework dispatches — computed from the *same shape
parameters the dispatch sites use*, so tests can validate the formulas
against hand-computed byte counts for a concrete mesh.

Covered collectives:

- the sharded engine's all-gather merge (parallel.collectives
  .allgather_merge_topk): per data-axis group, every cell gathers the
  other R-1 cells' (Q_local, K) TopK triple;
- the ring engine's merge (ring_allreduce_topk): R-1 ``ppermute`` hops of
  the O(K) accumulator — same per-device bytes as the all-gather, O(K)
  instead of O(R*K) peak memory;
- the train step's grad ``psum`` over the dp axis (ring all-reduce:
  2*(D-1)/D of the gradient bytes per device);
- the MoE all-to-all dispatch (train.experts._moe_a2a_body): three
  ``lax.all_to_all`` ops per step (tokens out, slot metadata, tokens
  back), each moving (EP-1)/EP of its buffer off-device;
- the pipeline's activation hand-off (train.pipeline): one microbatch
  activation ``ppermute`` over the pp axis per schedule tick — a
  (S-1)-link chain for gpipe, an S-link ring for the interleaved
  schedule.

All functions return :class:`CollectiveTraffic` records; ``summarize``
folds a list of them into a per-axis byte table for RunRecord embedding.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

#: arrays in a TopK triple (dists, labels, ids) and their element sizes
_TOPK_ITEMSIZES = (4, 4, 4)  # dists f32, labels i32, ids i32


@dataclasses.dataclass(frozen=True)
class CollectiveTraffic:
    """Byte accounting for one collective pattern on one mesh axis.

    ``bytes_out_per_device``/``bytes_in_per_device`` are what ONE
    participating device sends/receives over the axis for ONE dispatch;
    ``n_groups`` is how many independent device groups run the collective
    (e.g. each query-axis column merges separately); ``count`` is dispatch
    multiplicity (e.g. steps). ``bytes_total`` covers all groups, devices
    and dispatches."""

    collective: str
    axis: str
    axis_size: int
    bytes_out_per_device: int
    bytes_in_per_device: int
    n_groups: int = 1
    count: int = 1
    note: str = ""

    @property
    def bytes_total(self) -> int:
        return (self.bytes_out_per_device * self.axis_size
                * self.n_groups * self.count)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["bytes_total"] = self.bytes_total
        return d


def allgather_topk_traffic(axis_size: int, q_local: int, k: int,
                           axis: str = "data", n_groups: int = 1,
                           count: int = 1) -> CollectiveTraffic:
    """The all-gather merge: each cell contributes its (q_local, k) TopK
    triple and receives the other axis_size-1 cells' triples."""
    payload = q_local * k * sum(_TOPK_ITEMSIZES)
    peer = (axis_size - 1) * payload
    return CollectiveTraffic("all_gather_merge_topk", axis, axis_size,
                             peer, peer, n_groups=n_groups, count=count,
                             note=f"payload {payload} B/cell "
                                  f"(q_local={q_local}, k={k}, 12 B/cand)")


def ring_topk_traffic(axis_size: int, q_local: int, k: int,
                      axis: str = "data", n_groups: int = 1,
                      count: int = 1) -> CollectiveTraffic:
    """The ring merge: axis_size-1 ``ppermute`` hops of the (q_local, k)
    accumulator triple. Same per-device bytes as the all-gather (the win
    is O(k) peak memory, not wire bytes); one hop's payload serializes
    per step instead of one bulk gather."""
    payload = q_local * k * sum(_TOPK_ITEMSIZES)
    hops = max(axis_size - 1, 0)
    return CollectiveTraffic("ring_allreduce_topk", axis, axis_size,
                             hops * payload, hops * payload,
                             n_groups=n_groups, count=count,
                             note=f"{hops} ppermute hops x {payload} B")


def psum_traffic(nbytes: int, axis_size: int, axis: str = "dp",
                 n_groups: int = 1, count: int = 1) -> CollectiveTraffic:
    """Gradient ``psum`` as a ring all-reduce: reduce-scatter + all-gather
    moves 2*(D-1)/D of the payload per device (the standard ring bound)."""
    per_dev = 0 if axis_size <= 1 else round(2 * (axis_size - 1)
                                             * nbytes / axis_size)
    return CollectiveTraffic("psum_grads", axis, axis_size, per_dev,
                             per_dev, n_groups=n_groups, count=count,
                             note=f"ring all-reduce of {nbytes} B grads")


def moe_a2a_traffic(ep: int, capacity: int, hidden: int,
                    itemsize: int = 4, n_groups: int = 1,
                    count: int = 1) -> CollectiveTraffic:
    """The capacity-based MoE dispatch: three ``all_to_all`` ops per step
    (token send buffer (ep, capacity, hidden), slot metadata
    (ep, capacity) int32, and the token return), each keeping 1/ep of its
    buffer local and moving (ep-1)/ep off-device."""
    send = ep * capacity * hidden * itemsize
    meta = ep * capacity * 4
    total_buf = 2 * send + meta  # tokens out + tokens back + metadata
    frac = 0.0 if ep <= 0 else (ep - 1) / ep
    per_dev = round(total_buf * frac)
    return CollectiveTraffic("moe_all_to_all", "ep", ep, per_dev, per_dev,
                             n_groups=n_groups, count=count,
                             note=f"3 a2a/step: 2x{send} B tokens "
                                  f"+ {meta} B meta, (ep-1)/ep off-device")


def tp_psum_activation_traffic(tp: int, rows: int, hidden: int,
                               n_pairs: int = 1, ticks: int = 1,
                               itemsize: int = 4, n_groups: int = 1,
                               count: int = 1) -> CollectiveTraffic:
    """The tensor-parallel activation ``psum``
    (train.pipeline._stage_block3: each col/row layer pair ends in ONE
    psum of the (rows, hidden) f32 activation block over the tp axis).
    Ring all-reduce bound per psum — 2*(tp-1)/tp of the block; a stage
    runs ``n_pairs`` pairs per schedule tick and ``ticks`` ticks per
    step, and the backward pass's transposed psums mirror the forward
    1:1 (fold them via ``count``, like the pipeline ppermute record)."""
    nbytes = rows * hidden * itemsize
    per = 0 if tp <= 1 else round(2 * (tp - 1) * nbytes / tp)
    per_dev = per * n_pairs * ticks
    return CollectiveTraffic("psum_tp_activations", "tp", tp, per_dev,
                             per_dev, n_groups=n_groups, count=count,
                             note=f"{ticks} ticks x {n_pairs} pairs x "
                                  f"ring all-reduce of {nbytes} B "
                                  f"activations")


def ep_psum_combine_traffic(ep: int, tokens: int, hidden: int,
                            itemsize: int = 4, n_groups: int = 1,
                            count: int = 1) -> CollectiveTraffic:
    """The dense (capacity-free) MoE combine
    (train.experts._moe_body): every cell computes its local experts'
    contribution for ALL dp-local tokens and one ``psum`` over the ep
    axis combines the (tokens, hidden) partials. Ring all-reduce bound
    per step; like the a2a record this counts the forward dispatch per
    step (``count`` folds steps)."""
    nbytes = tokens * hidden * itemsize
    per_dev = 0 if ep <= 1 else round(2 * (ep - 1) * nbytes / ep)
    return CollectiveTraffic("psum_ep_combine", "ep", ep, per_dev,
                             per_dev, n_groups=n_groups, count=count,
                             note=f"ring all-reduce of {nbytes} B "
                                  f"expert-output partials")


def pipeline_ppermute_traffic(pp: int, n_micro: int, micro_rows: int,
                              hidden: int, schedule: str = "gpipe",
                              n_virtual: int = 1, itemsize: int = 4,
                              n_groups: int = 1, count: int = 1,
                              ) -> CollectiveTraffic:
    """The dp_pp pipeline's activation hand-off: every schedule tick
    ``ppermute``s one (micro_rows, hidden) activation block per sending
    link of the pp axis.

    The tick counts restate train.pipeline.schedule_ticks (kept in sync
    by test; comms must not import the optax-heavy train package):
    gpipe runs M + S - 1 ticks over an (S-1)-link chain (the last stage
    forwards nothing); interleaved runs M - 1 + V*S ticks over the
    S-link ring (the S-1 -> 0 wraparound carries the level-up hop).
    XLA's ppermute moves the block even on bubble ticks — masking is
    data-, not schedule-level — so ticks, not useful microbatches, is
    the honest multiplier. The forward count is reported; the backward
    pass's reverse-schedule permutes mirror it 1:1 (jax.grad through
    the scan), which ``count`` can absorb (2 * steps for fwd+bwd).
    """
    if schedule == "gpipe":
        ticks, links = n_micro + pp - 1, max(pp - 1, 0)
    elif schedule == "interleaved":
        # A single-stage "ring" dispatches no ppermute at all
        # (train.pipeline._ppi_body skips it when n_stages == 1).
        ticks, links = n_micro - 1 + n_virtual * pp, pp if pp > 1 else 0
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    payload = micro_rows * hidden * itemsize
    total = ticks * links * payload      # one dispatch, all links
    per_dev = 0 if pp <= 0 else round(total / pp)
    return CollectiveTraffic("ppermute_pipeline", "pp", pp, per_dev,
                             per_dev, n_groups=n_groups, count=count,
                             note=f"{schedule}: {ticks} ticks x {links} "
                                  f"links x {payload} B activation")


def host_allgather_candidates_traffic(num_ranks: int, r_shards: int,
                                      qpad: int, kcap: int,
                                      itemsizes=(8, 4, 4),
                                      count: int = 1) -> CollectiveTraffic:
    """The multi-host contract's candidate all-gather
    (parallel.distributed: ``multihost_utils.process_allgather`` of the
    rescored (R, Qpad, K) triple — f64 dists + i32 labels + i32 ids by
    default, hence the (8, 4, 4) itemsizes): every process contributes
    its triple once and receives the other num_ranks-1 processes'.

    This is the ANALYTIC side of the per-rank reconciliation: the trace
    span ``dist.allgather_candidates`` carries the real payload bytes
    (sum of the three arrays' nbytes) plus these shape args, and
    tools/merge_traces.py checks the two agree per rank
    (``bytes_out_per_device`` here == the span's ``nbytes``)."""
    payload = r_shards * qpad * kcap * sum(itemsizes)
    return CollectiveTraffic(
        "host_allgather_candidates", "process", num_ranks, payload,
        max(num_ranks - 1, 0) * payload, count=count,
        note=f"process_allgather of (R={r_shards}, Qpad={qpad}, "
             f"K={kcap}) x {sum(itemsizes)} B/cand")


def engine_comms(merge_strategy: str, mesh_shape, q_local: int,
                 k: int) -> List[CollectiveTraffic]:
    """Traffic for one mesh-engine solve, from the shapes actually
    dispatched: the (r, c) mesh runs one cross-shard merge per query-axis
    column over data-axis groups of r cells, each cell holding a
    (q_local, k) candidate triple. Single-chip solves dispatch no
    collectives — an empty list, deliberately explicit. The "gspmd"
    strategy (auto engine / merge="auto") is ALSO empty: the compiler
    chooses the schedule, so there is no hand-rolled collective to
    model — claiming allgather traffic there would assert bytes the
    program may never move."""
    r, c = mesh_shape
    if r <= 1 or merge_strategy == "gspmd":
        return []
    fn = (ring_topk_traffic if merge_strategy == "ring"
          else allgather_topk_traffic)
    return [fn(r, q_local, k, axis="data", n_groups=c)]


def summarize(traffics: List[CollectiveTraffic]) -> Dict[str, object]:
    """Fold traffic records into the RunRecord-embeddable summary: total
    bytes, per-axis totals, and the individual records."""
    per_axis: Dict[str, int] = {}
    for t in traffics:
        per_axis[t.axis] = per_axis.get(t.axis, 0) + t.bytes_total
    return {"bytes_total": sum(t.bytes_total for t in traffics),
            "bytes_by_axis": per_axis,
            "collectives": [t.to_dict() for t in traffics]}


def train_step_comms(param_bytes: int, mesh_shape, steps: int = 1,
                     moe: Optional[dict] = None,
                     pipeline: Optional[dict] = None,
                     moe_dense: Optional[dict] = None,
                     ) -> List[CollectiveTraffic]:
    """Per-run traffic for the train loop's collective paths: the grad
    ``psum`` over the dp axis, plus the MoE all-to-all when the a2a
    dispatch runs (``moe`` = {"ep", "capacity", "hidden"}), plus the
    dense MoE's ep combine ``psum`` (``moe_dense`` = {"ep", "tokens",
    "hidden"}), plus the pipeline's activation ``ppermute`` when the
    dp_pp/dp_pp3 step runs
    (``pipeline`` = {"pp", "n_micro", "micro_rows", "hidden"}
    [+ "schedule", "n_virtual", "tp", "n_pairs"]; a "tp" > 1 adds the
    dp_pp3 stage blocks' per-pair activation psum over the tp axis; the
    records cover forward AND the mirrored backward-schedule
    permutes/psums — 2x per step).

    ``param_bytes`` is the GLOBAL parameter footprint; every non-dp mesh
    axis (tp / pp / ep) shards the parameters — and hence the gradients
    each dp group all-reduces — so the per-group psum payload is
    param_bytes divided by the product of those axes (the train
    shardings place weights P(..., "tp") etc., never dp-replicated
    within a group)."""
    out: List[CollectiveTraffic] = []
    dp = mesh_shape[0] if mesh_shape else 1
    shard_groups = 1
    if mesh_shape and len(mesh_shape) > 1:
        for ax in mesh_shape[1:]:
            shard_groups *= ax
    if dp > 1:
        out.append(psum_traffic(param_bytes // max(shard_groups, 1), dp,
                                axis="dp", n_groups=shard_groups,
                                count=steps))
    if moe:
        out.append(moe_a2a_traffic(moe["ep"], moe["capacity"],
                                   moe["hidden"], n_groups=dp,
                                   count=steps))
    if moe_dense:
        out.append(ep_psum_combine_traffic(
            moe_dense["ep"], moe_dense["tokens"], moe_dense["hidden"],
            n_groups=dp, count=steps))
    if pipeline:
        out.append(pipeline_ppermute_traffic(
            pipeline["pp"], pipeline["n_micro"], pipeline["micro_rows"],
            pipeline["hidden"], schedule=pipeline.get("schedule", "gpipe"),
            n_virtual=pipeline.get("n_virtual", 1),
            n_groups=pipeline.get("n_groups", dp),
            count=2 * steps))  # forward + reverse-schedule backward
        tp = pipeline.get("tp", 1)
        if tp > 1:
            # dp_pp3 stage blocks: one activation psum per col/row pair
            # per gpipe tick, independent per (dp, pp) cell group.
            pp, n_micro = pipeline["pp"], pipeline["n_micro"]
            out.append(tp_psum_activation_traffic(
                tp, pipeline["micro_rows"], pipeline["hidden"],
                n_pairs=pipeline.get("n_pairs", 2),
                ticks=n_micro + pp - 1,
                n_groups=dp * pp, count=2 * steps))
    return out
