// Native stdin-grammar parser for dmlp_tpu (the TPU-native analog of the
// reference harness's rank-0 ingest, common.cpp:93-117 + parsers :12-55).
//
// The grammar (one header line, num_data data lines, num_queries 'Q' lines,
// whitespace-tokenized decimals) is parsed straight into caller-allocated
// flat arrays — the SoA layout the device pipeline feeds — with strtod,
// which rounds identically to Python's float(), so results are
// bit-identical to the pure-Python parser (dmlp_tpu.io.grammar).
//
// Error contract mirrors common.cpp:101 ("Line is empty") and :114
// ("Line is wrongly formatted").
//
// Build: g++ -O3 -shared -fPIC -o _fastparse.so fastparse.cpp
// (loaded via ctypes by dmlp_tpu.io.native; no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <locale.h>

namespace {

// strtod is LC_NUMERIC-sensitive; a host app that set a comma-decimal
// locale would break the fallback path. Parse under a pinned "C" locale.
locale_t c_locale() {
    static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
    return loc;
}

struct Cursor {
    const char* p;
    const char* end;
};

inline void skip_spaces(Cursor& c) {
    while (c.p < c.end && (*c.p == ' ' || *c.p == '\t' || *c.p == '\r'))
        ++c.p;
}

// Advance past the current line's newline; returns false at EOF.
inline bool next_line(Cursor& c) {
    while (c.p < c.end && *c.p != '\n') ++c.p;
    if (c.p < c.end) ++c.p;
    return c.p < c.end;
}

inline bool at_eol(const Cursor& c) {
    return c.p >= c.end || *c.p == '\n';
}

// A parsed token must end at whitespace/EOL/EOF — trailing garbage
// ("1.5abc", "1_0", "0x10") is a format error, exactly like the
// reference's stringstream extraction followed by a failed next read
// (common.cpp parsers) and the Python parser's per-token conversion.
inline bool token_ends(const char* q, const char* end) {
    return q >= end || *q == ' ' || *q == '\t' || *q == '\r' || *q == '\n';
}

// Parse an integer token. Strict: the token must end at whitespace/EOL
// ("3.5" as a label/k/header value is an error, matching the pure-Python
// parser's accept/reject behavior).
inline bool parse_long(Cursor& c, long* out) {
    skip_spaces(c);
    if (at_eol(c)) return false;
    char* q;
    long v = strtol(c.p, &q, 10);
    if (q == c.p) return false;
    if (!token_ends(q, c.end)) return false;
    c.p = q;
    *out = v;
    return true;
}

// Clinger fast path: a decimal with <= 15 significant digits and a small
// power-of-ten scale converts exactly with one rounding (mantissa and the
// power of ten are both exactly representable), i.e. bit-identical to
// correctly-rounded strtod / Python float(). Covers the generator's %.6f
// values; anything longer, or with an exponent, falls back to strtod.
static const double kPow10[23] = {
    1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11, 1e12,
    1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

inline bool parse_double(Cursor& c, double* out) {
    skip_spaces(c);
    if (at_eol(c)) return false;
    const char* s = c.p;
    bool neg = false;
    if (s < c.end && (*s == '-' || *s == '+')) {
        neg = (*s == '-');
        ++s;
    }
    uint64_t mant = 0;
    int digits = 0, frac = 0;
    const char* d = s;
    while (d < c.end && *d >= '0' && *d <= '9') {
        if (digits < 19) mant = mant * 10 + static_cast<uint64_t>(*d - '0');
        ++digits;
        ++d;
    }
    if (d < c.end && *d == '.') {
        ++d;
        while (d < c.end && *d >= '0' && *d <= '9') {
            if (digits < 19) {
                mant = mant * 10 + static_cast<uint64_t>(*d - '0');
                ++frac;
            }
            ++digits;
            ++d;
        }
    }
    bool has_exp = d < c.end && (*d == 'e' || *d == 'E');
    if (digits > 0 && digits <= 15 && frac <= 22 && !has_exp) {
        if (!token_ends(d, c.end)) return false;  // "1.5abc", "1_0", "0x10"
        double v = static_cast<double>(mant);
        if (frac) v /= kPow10[frac];
        *out = neg ? -v : v;
        c.p = d;
        return true;
    }
    char* q;
    double v = strtod_l(c.p, &q, c_locale());
    if (q == c.p) return false;
    if (!token_ends(q, c.end)) return false;
    c.p = q;
    *out = v;
    return true;
}

// Error messages carry the cursor's byte offset so the Python side
// (io.native) can surface a located ParseError — a truncated pipe or a
// corrupted payload should name WHERE the grammar broke, not just that
// it did.
void set_err(char* errbuf, size_t errlen, const char* msg, long off) {
    if (errbuf && errlen) {
        snprintf(errbuf, errlen, "%s (byte offset %ld)", msg, off);
    }
}

}  // namespace

extern "C" {

// Parse the header line "num_data num_queries num_attrs" (common.cpp:12-15).
// Returns 0 on success.
int dmlp_parse_header(const char* text, size_t len, long* out3) {
    Cursor c{text, text + len};
    for (int i = 0; i < 3; ++i) {
        if (!parse_long(c, &out3[i])) return 1;
    }
    return 0;
}

// Parse the full body into caller-allocated arrays:
//   labels      int32[num_data]
//   data_attrs  float64[num_data * num_attrs]
//   ks          int32[num_queries]
//   query_attrs float64[num_queries * num_attrs]
// Returns 0 on success; nonzero with errbuf set on malformed input.
int dmlp_parse_body(const char* text, size_t len, long num_data,
                    long num_queries, long num_attrs, int32_t* labels,
                    double* data_attrs, int32_t* ks, double* query_attrs,
                    char* errbuf, size_t errlen) {
    Cursor c{text, text + len};
    if (!next_line(c) && num_data + num_queries > 0) {  // skip header
        set_err(errbuf, errlen, "truncated input", (long)(c.p - text));
        return 1;
    }
    for (long i = 0; i < num_data; ++i) {
        skip_spaces(c);
        if (at_eol(c)) {
            set_err(errbuf, errlen, "Line is empty",
                    (long)(c.p - text));  // common.cpp:101
            return 2;
        }
        long label;
        if (!parse_long(c, &label)) {
            set_err(errbuf, errlen, "Line is wrongly formatted",
                        (long)(c.p - text));
            return 3;
        }
        labels[i] = static_cast<int32_t>(label);
        double* row = data_attrs + i * num_attrs;
        for (long a = 0; a < num_attrs; ++a) {
            if (!parse_double(c, &row[a])) {
                set_err(errbuf, errlen, "Line is wrongly formatted",
                        (long)(c.p - text));
                return 3;
            }
        }
        if (!next_line(c) && i + 1 < num_data + num_queries) {
            set_err(errbuf, errlen, "truncated input", (long)(c.p - text));
            return 1;
        }
    }
    for (long i = 0; i < num_queries; ++i) {
        // Query lines must start with 'Q' in column 0 — no leading
        // whitespace, exactly like the Python parser's line[0] != 'Q'
        // check (mirroring common.cpp:108-114).
        if (at_eol(c) || *c.p != 'Q') {
            set_err(errbuf, errlen, "Line is wrongly formatted",
                        (long)(c.p - text));
            return 4;
        }
        ++c.p;
        long k;
        if (!parse_long(c, &k)) {
            set_err(errbuf, errlen, "Line is wrongly formatted",
                        (long)(c.p - text));
            return 4;
        }
        ks[i] = static_cast<int32_t>(k);
        double* row = query_attrs + i * num_attrs;
        for (long a = 0; a < num_attrs; ++a) {
            if (!parse_double(c, &row[a])) {
                set_err(errbuf, errlen, "Line is wrongly formatted",
                        (long)(c.p - text));
                return 4;
            }
        }
        next_line(c);
    }
    return 0;
}

}  // extern "C"
