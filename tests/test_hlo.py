"""obs.hlo — compiled-program introspection and the three-way reconcile.

Four layers: (1) pure-text parsing fixtures, one per collective kind,
covering both ``replica_groups`` spellings, async start/done pairs and
while-loop trip counts, with HAND-COMPUTED byte counts; (2) the live
engines on the 8-virtual-device mesh — the hand-rolled schedules must
reconcile against their own analytic models at ratio 1.0 and the auto
engine must yield a non-empty partitioner schedule; (3) the markers
(memory/cost/trace unavailable), the fingerprint cache, and the CLI
``--hlo-report`` round-trip through the ledger; (4) the R10/R1001 and
R903 check-family fixtures, positive and negative.
"""

import io
import json
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dmlp_tpu.check.analyzer import analyze_paths
from dmlp_tpu.cli import main as cli_main
from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine.auto import AutoShardedEngine
from dmlp_tpu.engine.sharded import RingEngine, ShardedEngine
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.obs import counters as obs_counters
from dmlp_tpu.obs import hlo as obs_hlo
from dmlp_tpu.obs.comms import CollectiveTraffic
from dmlp_tpu.parallel.mesh import make_mesh


def _inp(seed: int = 7, n: int = 256, nq: int = 8, na: int = 4,
         kmax: int = 6) -> KNNInput:
    rng = np.random.default_rng(seed)
    return KNNInput(
        Params(n, nq, na),
        rng.integers(0, 5, n).astype(np.int32),
        rng.uniform(-10, 10, (n, na)),
        rng.integers(1, kmax + 1, nq).astype(np.int32),
        rng.uniform(-10, 10, (nq, na)))


# ---------------------------------------------------------------------------
# parsing fixtures — hand-computed byte counts per collective kind
# ---------------------------------------------------------------------------

AG_EXPLICIT = """\
HloModule jit_ag, num_partitions=8

ENTRY %main.1 (p.1: f32[4,8]) -> f32[16,8] {
  %p.1 = f32[4,8] parameter(0)
  ROOT %ag.2 = f32[16,8] all-gather(f32[4,8] %p.1), channel_id=1, replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}, use_global_device_ids=true
}
"""

AR_IOTA = """\
HloModule jit_ar, num_partitions=8

%add.1 (x.1: f32[], y.1: f32[]) -> f32[] {
  %x.1 = f32[] parameter(0)
  %y.1 = f32[] parameter(1)
  ROOT %a.1 = f32[] add(f32[] %x.1, f32[] %y.1)
}

ENTRY %main.2 (p.2: f32[16]) -> f32[16] {
  %p.2 = f32[16] parameter(0)
  ROOT %ar.2 = f32[16] all-reduce(f32[16] %p.2), channel_id=1, replica_groups=[2,4]<=[8], use_global_device_ids=true, to_apply=%add.1
}
"""

RS_DEFAULT_GROUPS = """\
HloModule jit_rs, num_partitions=8

ENTRY %main (p: f32[8,4]) -> f32[1,4] {
  %p = f32[8,4] parameter(0)
  ROOT %rs = f32[1,4] reduce-scatter(f32[8,4] %p), channel_id=1, replica_groups={}, dimensions={0}, to_apply=%add
}
"""

A2A = """\
HloModule jit_a2a, num_partitions=8

ENTRY %main (p: f32[8,4]) -> f32[8,4] {
  %p = f32[8,4] parameter(0)
  ROOT %a2a = f32[8,4] all-to-all(f32[8,4] %p), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
}
"""

CP = """\
HloModule jit_cp, num_partitions=4

ENTRY %main (p: f32[4,8]) -> f32[4,8] {
  %p = f32[4,8] parameter(0)
  ROOT %cp = f32[4,8] collective-permute(f32[4,8] %p), channel_id=1, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}
}
"""

WHILE_TRIP = """\
HloModule jit_scan, num_partitions=4

%body.5 (param.1: (s32[], f32[8,8])) -> (s32[], f32[8,8]) {
  %param.1 = (s32[], f32[8,8]) parameter(0)
  %gte.1 = f32[8,8] get-tuple-element((s32[], f32[8,8]) %param.1), index=1
  %cp.2 = f32[8,8] collective-permute(f32[8,8] %gte.1), channel_id=2, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}
}

%cond.7 (param.2: (s32[], f32[8,8])) -> pred[] {
  %param.2 = (s32[], f32[8,8]) parameter(0)
  ROOT %lt.1 = pred[] compare(s32[] %gte.2, s32[] %c.1), direction=LT
}

ENTRY %main.9 (p.3: f32[8,8]) -> f32[8,8] {
  %p.3 = f32[8,8] parameter(0)
  %w.4 = (s32[], f32[8,8]) while((s32[], f32[8,8]) %init.1), condition=%cond.7, body=%body.5, backend_config={"known_trip_count":{"n":"3"}}
  ROOT %gte.9 = f32[8,8] get-tuple-element((s32[], f32[8,8]) %w.4), index=1
}
"""

ASYNC_PAIR = """\
HloModule jit_async, num_partitions=8

ENTRY %main (p: f32[4,8]) -> f32[32,8] {
  %p = f32[4,8] parameter(0)
  %ags = (f32[4,8], f32[32,8]) all-gather-start(f32[4,8] %p), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  ROOT %agd = f32[32,8] all-gather-done((f32[4,8], f32[32,8]) %ags)
}
"""


class TestParsing:
    def test_all_gather_explicit_groups(self):
        ops = obs_hlo.parse_collectives(AG_EXPLICIT)
        assert len(ops) == 1
        op = ops[0]
        assert op["kind"] == "all-gather"
        # operand f32[4,8] = 128 B; two groups of 4
        assert op["operand_bytes"] == 128
        assert (op["group_size"], op["n_groups"]) == (4, 2)
        # ring bound: (g-1) * shard per device, all devices, both groups
        assert op["bytes_moved"] == (4 - 1) * 128 * 4 * 2 == 3072

    def test_all_reduce_iota_groups(self):
        ops = obs_hlo.parse_collectives(AR_IOTA)
        assert len(ops) == 1
        op = ops[0]
        assert op["kind"] == "all-reduce"
        assert op["operand_bytes"] == 64            # f32[16]
        assert (op["group_size"], op["n_groups"]) == (4, 2)   # [2,4]<=[8]
        # 2(g-1)/g x buffer per device
        assert op["bytes_moved"] == round(2 * 3 * 64 / 4) * 4 * 2 == 768

    def test_reduce_scatter_default_groups(self):
        ops = obs_hlo.parse_collectives(RS_DEFAULT_GROUPS)
        op = ops[0]
        assert op["kind"] == "reduce-scatter"
        # empty replica_groups: one group of num_partitions=8
        assert (op["group_size"], op["n_groups"]) == (8, 1)
        assert op["bytes_moved"] == round(7 * 128 / 8) * 8 == 896

    def test_all_to_all(self):
        op = obs_hlo.parse_collectives(A2A)[0]
        assert op["kind"] == "all-to-all"
        assert op["bytes_moved"] == round(7 * 128 / 8) * 8 == 896

    def test_collective_permute_pairs(self):
        op = obs_hlo.parse_collectives(CP)[0]
        assert op["kind"] == "collective-permute"
        assert op["n_pairs"] == 4
        assert op["group_size"] == 4      # one 4-cycle ring
        # full operand per source->target pair
        assert op["bytes_moved"] == 128 * 4 == 512

    def test_while_trip_count_multiplies(self):
        op = obs_hlo.parse_collectives(WHILE_TRIP)[0]
        assert op["kind"] == "collective-permute"
        assert op["count"] == 3
        assert "trip_count_unknown" not in op
        # f32[8,8] = 256 B x 4 pairs x 3 iterations
        assert op["bytes_moved"] == 256 * 4 * 3 == 3072

    def test_while_unknown_trip_marked_not_guessed(self):
        text = WHILE_TRIP.replace(
            ', backend_config={"known_trip_count":{"n":"3"}}', "")
        op = obs_hlo.parse_collectives(text)[0]
        assert op["count"] == 1            # honest lower bound
        assert op["trip_count_unknown"] is True

    def test_async_start_counted_done_skipped(self):
        ops = obs_hlo.parse_collectives(ASYNC_PAIR)
        assert len(ops) == 1               # -done is bookkeeping
        assert ops[0]["kind"] == "all-gather"
        assert ops[0]["operand_bytes"] == 128

    def test_totals_and_dispatch_multiplicity(self):
        ops = obs_hlo.parse_collectives(AG_EXPLICIT)
        totals = obs_hlo.collective_totals(ops, dispatch_count=5)
        assert totals["all-gather"]["bytes_moved"] == 3072 * 5
        assert totals["all-gather"]["count"] == 5

    def test_guess_axis_unique_or_unknown(self):
        axes = {"data": 4, "query": 2}
        assert obs_hlo.guess_axis(4, axes) == "data"
        assert obs_hlo.guess_axis(2, axes) == "query"
        assert obs_hlo.guess_axis(8, axes) == "unknown"
        assert obs_hlo.guess_axis(4, {"a": 4, "b": 4}) == "unknown"
        assert obs_hlo.guess_axis(4, None) == "unknown"


# ---------------------------------------------------------------------------
# reconcile legs on fixture reports
# ---------------------------------------------------------------------------

def _fixture_report(text, label="fix"):
    ops = obs_hlo.parse_collectives(text)
    return obs_hlo.HloReport(
        label=label, fingerprint=obs_hlo.fingerprint_text(text),
        collectives=ops, totals=obs_hlo.collective_totals(ops),
        memory={}, cost={})


class TestReconcile:
    def test_comms_exact_match_within_tolerance(self):
        rep = _fixture_report(AG_EXPLICIT)
        # model twin: per-device (g-1) x 128 = 384 B over 2 groups of 4
        model = CollectiveTraffic("all_gather_merge_topk", "data", 4,
                                  384, 384, n_groups=2)
        rec = obs_hlo.reconcile_comms([(rep, 1, "solve")], [model])
        ent = rec["kinds"]["all-gather"]
        assert ent["ratio"] == 1.0
        assert ent["within_tolerance"] is True
        assert ent["models"] == ["all_gather_merge_topk"]

    def test_comms_mismatch_flagged(self):
        rep = _fixture_report(AG_EXPLICIT)
        model = CollectiveTraffic("all_gather_merge_topk", "data", 4,
                                  90, 90, n_groups=2)   # 720 B total
        rec = obs_hlo.reconcile_comms([(rep, 1, "solve")], [model])
        assert rec["kinds"]["all-gather"]["within_tolerance"] is False

    def test_comms_one_sided_markers(self):
        rep = _fixture_report(AG_EXPLICIT)
        model = CollectiveTraffic("psum_grads", "data", 4, 64, 64)
        rec = obs_hlo.reconcile_comms([(rep, 1, "s")], [model])
        assert rec["kinds"]["all-gather"]["hlo_only"] is True
        assert rec["kinds"]["all-reduce"]["model_only"] is True
        empty = obs_hlo.reconcile_comms([], [])
        assert empty["no_collectives"] is True

    def test_trace_leg_markers(self):
        rep = _fixture_report(AG_EXPLICIT)
        rec = obs_hlo.reconcile_trace([(rep, 1, "s")], [])
        assert "trace_unavailable" in rec
        ev = [{"name": "dist.allgather_candidates",
               "args": {"nbytes": 3072}}]
        rec = obs_hlo.reconcile_trace([(rep, 1, "s")], ev)
        assert rec["kinds"]["all-gather"]["ratio"] == 1.0
        assert rec["kinds"]["all-gather"]["within_tolerance"] is True

    def test_memory_leg_marker_and_ratio(self):
        rep = _fixture_report(AG_EXPLICIT)
        rep.memory = {"argument_bytes": 1000, "output_bytes": 200,
                      "temp_bytes": 300}
        rec = obs_hlo.reconcile_memory(
            [(rep, 1, "s")], {"model_bytes": 1500})
        assert rec["hlo_peak_bytes"] == 1500
        assert rec["ratio"] == 1.0 and rec["within_tolerance"] is True
        rep2 = _fixture_report(AR_IOTA, label="m")
        rep2.memory = {"hlo_memory_unavailable": "backend says no"}
        rec = obs_hlo.reconcile_memory([(rep2, 1, "s")], None)
        assert rec["hlo_memory_unavailable"] == "backend says no"


# ---------------------------------------------------------------------------
# markers on hostile compiled objects
# ---------------------------------------------------------------------------

class TestMarkers:
    def test_memory_report_marker_paths(self):
        class _Raises:
            def memory_analysis(self):
                raise RuntimeError("no backend stats")

        class _NoneBack:
            def memory_analysis(self):
                return None

        m = obs_hlo.memory_report(_Raises())
        assert "no backend stats" in m["hlo_memory_unavailable"]
        m = obs_hlo.memory_report(_NoneBack())
        assert "hlo_memory_unavailable" in m

    def test_cost_report_marker(self):
        class _Raises:
            def cost_analysis(self):
                raise NotImplementedError("nope")

        assert "cost_unavailable" in obs_hlo.cost_report(_Raises())

    def test_report_for_fn_unlowerable_returns_none(self):
        assert obs_hlo.report_for_fn(lambda x: x, (1,)) is None

    def test_counters_unrecognized_cost_shape_recorded(self):
        # the obs.counters bugfix: an unknown cost_analysis() shape must
        # leave a diagnosable trail, not a silent None
        obs_counters._unrecognized_shapes.clear()
        assert obs_counters.normalize_cost({"weird_key": 1.0}) is None
        assert obs_counters.normalize_cost([]) is None
        shapes = list(obs_counters._unrecognized_shapes)
        assert any("weird_key" in d.get("keys", []) for d in shapes)
        assert any(d["type"] == "list" for d in shapes)
        obs_counters._unrecognized_shapes.clear()


# ---------------------------------------------------------------------------
# fingerprint cache
# ---------------------------------------------------------------------------

def test_fingerprint_cache_hit_on_same_program():
    fn = jax.jit(lambda x: x * 2 + 1)
    spec = jax.ShapeDtypeStruct((8,), jnp.float32)
    c1 = fn.lower(spec).compile()
    c2 = fn.lower(spec).compile()
    obs_hlo.clear_cache()
    r1 = obs_hlo.report_for(c1, label="first")
    r2 = obs_hlo.report_for(c2, label="second")
    assert r1.fingerprint == r2.fingerprint
    assert r2.label == "first"        # first introspection's label sticks
    assert obs_hlo.cache_stats == {"hits": 1, "misses": 1}
    obs_hlo.clear_cache()


# ---------------------------------------------------------------------------
# live engines on the 8-virtual-device mesh
# ---------------------------------------------------------------------------

def _probe_run(engine, inp):
    probe = obs_counters.install()
    try:
        engine.run(inp)
        reports, skipped = obs_hlo.probe_reports(probe)
    finally:
        obs_counters.uninstall()
    return reports, skipped


class TestLiveEngines:
    def test_sharded_allgather_reconciles_exactly(self):
        eng = ShardedEngine(EngineConfig(mode="sharded"),
                            mesh=make_mesh((4, 2)))
        reports, _sk = _probe_run(eng, _inp())
        assert reports
        rec = obs_hlo.reconcile_comms(reports, eng.last_comms)
        ag = rec["kinds"]["all-gather"]
        assert ag["within_tolerance"] is True
        assert ag["ratio"] == 1.0      # same convention, no fudge factor

    def test_ring_permute_reconciles_with_trip_counts(self):
        eng = RingEngine(EngineConfig(mode="ring"),
                         mesh=make_mesh((4, 2)))
        reports, _sk = _probe_run(eng, _inp(seed=11))
        rec = obs_hlo.reconcile_comms(reports, eng.last_comms)
        cp = rec["kinds"]["collective-permute"]
        # the scanned ring's R-1 hops only reconcile if while-loop trip
        # counts are folded in (1/3 of the model otherwise)
        assert cp["within_tolerance"] is True
        assert cp["ratio"] == 1.0

    def test_auto_engine_schedule_nonempty_with_real_comms(self):
        eng = AutoShardedEngine(EngineConfig(mode="auto"),
                                mesh=make_mesh((4, 2)))
        eng.run(_inp(seed=13))
        rep = eng.comms_from_hlo()
        assert rep is not None and rep.totals
        # the partitioner's schedule becomes a REAL comms record
        assert eng.last_comms
        recs = [t.to_dict() for t in eng.last_comms]
        assert all(r["collective"].startswith("gspmd_") for r in recs)
        # the gspmd_* records reproduce the schedule's bytes (per-device
        # rounding only), so the reconcile against them is exact
        rec = obs_hlo.reconcile_comms([(rep, 1, "auto.solve")],
                                      eng.last_comms)
        for ent in rec["kinds"].values():
            assert ent["within_tolerance"] is True
            assert 0.99 <= ent["ratio"] <= 1.01
        # per-axis attribution lands on declared mesh axes or 'unknown'
        assert {r["axis"] for r in recs} <= {"data", "query", "unknown"}

    def test_build_report_doc_and_flat_metrics(self):
        eng = ShardedEngine(EngineConfig(mode="sharded"),
                            mesh=make_mesh((4, 2)))
        reports, skipped = _probe_run(eng, _inp(seed=5))
        doc = obs_hlo.build_report_doc(
            reports, skipped=skipped, traffics=eng.last_comms,
            mesh_axes={"data": 4, "query": 2})
        assert doc["schema"] == obs_hlo.SCHEMA_VERSION
        assert doc["collective_bytes_total"] > 0
        assert doc["executables"]
        assert "comms_model" in doc["reconcile"]
        assert "trace_unavailable" in doc["reconcile"]["trace"]
        flat = obs_hlo.flat_metrics(doc)
        assert flat["collective_bytes_total"] \
            == doc["collective_bytes_total"]
        assert flat["executables_introspected"] == len(doc["executables"])
        assert flat["all_gather_bytes"] > 0
        json.dumps(doc)                # the record must be JSON-safe


# ---------------------------------------------------------------------------
# CLI --hlo-report round-trip through the ledger
# ---------------------------------------------------------------------------

def _run_cli(args, text):
    out, err = io.StringIO(), io.StringIO()
    rc = cli_main(args, stdin=io.StringIO(text), stdout=out, stderr=err)
    assert rc == 0
    return out.getvalue(), err.getvalue()


@pytest.mark.parametrize("mode", ["sharded", "auto"])
def test_cli_hlo_report_roundtrip(tmp_path, mode):
    text = generate_input_text(90, 11, 4, -3, 3, 1, 7, 3, seed=44)
    base, _ = _run_cli(["--mode", mode], text)
    path = tmp_path / "HLO.jsonl"
    out, _ = _run_cli(["--mode", mode, "--hlo-report", str(path)], text)
    assert out == base          # introspection never changes the contract
    doc = json.loads(path.read_text().splitlines()[-1])
    assert doc["kind"] == "hlo"
    assert doc["config"]["mode"] == mode
    assert doc["metrics"]["collective_bytes_total"] > 0
    rec = doc["comms"]["reconcile"]
    assert "comms_model" in rec and "memory" in rec
    if mode == "sharded":
        ag = rec["comms_model"]["kinds"]["all-gather"]
        assert ag["within_tolerance"] is True

    from dmlp_tpu.obs.ledger import ingest_file
    entry = ingest_file(str(path))
    assert entry["status"] == "parsed"
    series = {p["series"] for p in entry["points"]}
    assert f"hlo/{mode}/collective_bytes_total" in series
    from tools.perf_gate import GATED_PREFIXES
    assert any(s.startswith("hlo/") for s in series)
    assert "hlo/" in GATED_PREFIXES


# ---------------------------------------------------------------------------
# check families R10 (R1001) and R903 — fixtures
# ---------------------------------------------------------------------------

def _write(tmp_path, rel, source):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return str(p)


def _rules(tmp_path, families):
    fs = analyze_paths([str(tmp_path)], families, root=str(tmp_path))
    return sorted(f.rule for f in fs), fs


MESH_SRC = """
DATA_AXIS = "data"
QUERY_AXIS = "query"
"""


class TestR10HloIntro:
    def test_dangling_annotation_caught(self, tmp_path):
        _write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        _write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from dmlp_tpu.parallel.mesh import DATA_AXIS
            def f(x):
                return jax.lax.psum(x, DATA_AXIS)  # check: comms-model=renamed_away_traffic
        """)
        rules, fs = _rules(tmp_path, ["R10"])
        assert rules == ["R1001"]
        assert "renamed_away_traffic" in fs[0].message

    def test_mapped_annotation_clean(self, tmp_path):
        _write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        _write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from dmlp_tpu.parallel.mesh import DATA_AXIS
            def f(x):
                return jax.lax.psum(x, DATA_AXIS)  # check: comms-model=psum_traffic
        """)
        assert _rules(tmp_path, ["R10"])[0] == []

    def test_allow_directive_waives(self, tmp_path):
        _write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            def f(x):
                return jax.lax.psum(x, "data")  # check: comms-model=unmapped_traffic allow-hlo-model
        """)
        assert _rules(tmp_path, ["R10"])[0] == []

    def test_out_of_scope_dirs_skipped(self, tmp_path):
        _write(tmp_path, "dmlp_tpu/obs/x.py", """
            import jax
            def f(x):
                return jax.lax.psum(x, "data")  # check: comms-model=unmapped_traffic
        """)
        assert _rules(tmp_path, ["R10"])[0] == []

    def test_fixture_table_overrides_installed(self, tmp_path):
        # a fixture tree carrying its own obs/hlo.py table: annotations
        # naming REAL package models must flag against the fixture table
        _write(tmp_path, "dmlp_tpu/obs/hlo.py", """
            MODEL_COLLECTIVE_KINDS = {"custom_traffic": "all-gather"}
        """)
        _write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            def f(x):
                return jax.lax.psum(x, "data")  # check: comms-model=psum_traffic
            def g(x):
                return jax.lax.psum(x, "data")  # check: comms-model=custom_traffic
        """)
        rules, fs = _rules(tmp_path, ["R10"])
        assert rules == ["R1001"]
        assert "psum_traffic" in fs[0].message

    def test_real_package_table_covers_every_annotation(self):
        # every comms-model annotation in the real package maps — and
        # every table key names a real obs/comms model (no drift)
        from dmlp_tpu.obs import comms
        for model in obs_hlo.MODEL_COLLECTIVE_KINDS:
            assert callable(getattr(comms, model))
        for kind in obs_hlo.MODEL_COLLECTIVE_KINDS.values():
            assert kind in obs_hlo.COLLECTIVE_KINDS


class TestR903Constraints:
    def test_variable_held_undeclared_axis_caught(self, tmp_path):
        _write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        _write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            def f(x, mesh):
                sh = NamedSharding(mesh, P("typo_axis"))
                return jax.lax.with_sharding_constraint(x, sh)
        """)
        rules, fs = _rules(tmp_path, ["R9"])
        assert "R903" in rules
        assert any("typo_axis" in f.message for f in fs
                   if f.rule == "R903")

    def test_variable_held_declared_axis_clean(self, tmp_path):
        _write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        _write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from dmlp_tpu.parallel.mesh import DATA_AXIS
            def f(x, mesh):
                sh = NamedSharding(mesh, P(DATA_AXIS, None))
                return jax.lax.with_sharding_constraint(x, sh)
        """)
        assert _rules(tmp_path, ["R9"])[0] == []

    def test_opaque_binding_skipped_not_guessed(self, tmp_path):
        _write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        _write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            def f(x, sharding_factory):
                sh = sharding_factory()
                return jax.lax.with_sharding_constraint(x, sh)
        """)
        assert _rules(tmp_path, ["R9"])[0] == []

    def test_scoped_allow_waives(self, tmp_path):
        _write(tmp_path, "dmlp_tpu/parallel/mesh.py", MESH_SRC)
        _write(tmp_path, "dmlp_tpu/engine/x.py", """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            def f(x, mesh):
                sh = NamedSharding(mesh, P("replica_local"))
                # check: allow-auto-shard=R903 allow-auto-shard=R901
                return jax.lax.with_sharding_constraint(x, sh)
        """)
        rules, _fs = _rules(tmp_path, ["R9"])
        assert "R903" not in rules
