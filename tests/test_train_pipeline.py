"""Pipeline-parallel train step vs the flat single-device stack.

The pp step's loss is the mean over the full per-dp-cell batch, so its
gradients must equal the unpipelined model's — any scheduling, masking,
ppermute-transpose, or partial-loss bug shows up as a loss/param
divergence from the flat reference within f32 tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlp_tpu.train.pipeline import (build_pp_state, flat_forward,
                                     flatten_pipeline, make_pp_mesh,
                                     make_pp_train_step)
from dmlp_tpu.train.step import make_optimizer

import optax


def _flat_step(flat, x, y, lr):
    """Plain full-batch SGD step on the flattened stack (the reference)."""
    in_w, in_b, ws, bs, out_w, out_b = [jnp.asarray(a) for a in flat]
    params = {"in_w": in_w, "in_b": in_b, "ws": ws, "bs": bs,
              "out_w": out_w, "out_b": out_b}

    def loss_fn(p):
        h = x.astype(jnp.float32) @ p["in_w"] + p["in_b"]

        def layer(h, wb):
            wi, bi = wb
            return jax.nn.relu(h @ wi + bi), None
        h, _ = jax.lax.scan(layer, h, (p["ws"], p["bs"]))
        logits = h @ p["out_w"] + p["out_b"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return float(loss), new


@pytest.mark.parametrize("dp,pp,n_micro", [(1, 4, 4), (2, 2, 2), (2, 4, 8)])
def test_pp_step_matches_flat_reference(dp, pp, n_micro):
    if len(jax.devices()) < dp * pp:
        pytest.skip(f"needs {dp * pp} devices")
    mesh = make_pp_mesh(dp, pp)
    d_in, hidden, n_classes, lps = 6, 16, 4, 2
    lr = 0.05
    optimizer = make_optimizer("sgd", lr, momentum=0.0)
    state = build_pp_state(mesh, optimizer, d_in, hidden, n_classes, lps,
                           seed=3)
    flat = flatten_pipeline(state["params"])

    rng = np.random.default_rng(0)
    batch = dp * n_micro * 8
    x = rng.normal(size=(batch, d_in)).astype(np.float32)
    y = rng.integers(0, n_classes, batch).astype(np.int32)

    step = make_pp_train_step(mesh, optimizer, n_micro=n_micro,
                              n_classes=n_classes)
    state, m = step(state, jnp.asarray(x), jnp.asarray(y))
    pp_loss = float(m["loss"])

    # Flat reference: the dp mean-of-means equals the full-batch mean
    # only when every dp shard has the same size — true here.
    flat_loss, flat_new = _flat_step(flat, jnp.asarray(x), jnp.asarray(y),
                                     lr)
    assert pp_loss == pytest.approx(flat_loss, rel=1e-5)

    got = flatten_pipeline(state["params"])
    want = (flat_new["in_w"], flat_new["in_b"], flat_new["ws"],
            flat_new["bs"], flat_new["out_w"], flat_new["out_b"])
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-6)


def test_pp_loss_decreases_over_steps():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    mesh = make_pp_mesh(1, 4)
    optimizer = make_optimizer("sgd", 0.05, momentum=0.5)
    state = build_pp_state(mesh, optimizer, 8, 32, 3, 2, seed=1)
    step = make_pp_train_step(mesh, optimizer, n_micro=4, n_classes=3)

    rng = np.random.default_rng(5)
    # Learnable teacher task: labels from a fixed random projection.
    proj = rng.normal(size=(8, 3))
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = np.argmax(x @ proj, -1).astype(np.int32)
    losses = []
    for _ in range(30):
        state, m = step(state, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.5 * losses[0]


def test_pp_forward_equals_flat_forward():
    """Inference check without training: the pipeline's collected outputs
    must be the flat stack's activations (microbatching is a pure
    reshape)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = make_pp_mesh(1, 2)
    optimizer = make_optimizer("sgd", 0.0, momentum=0.0)
    state = build_pp_state(mesh, optimizer, 5, 8, 3, 3, seed=7)
    step = make_pp_train_step(mesh, optimizer, n_micro=2, n_classes=3)

    rng = np.random.default_rng(2)
    x = rng.normal(size=(16, 5)).astype(np.float32)
    y = rng.integers(0, 3, 16).astype(np.int32)
    flat = flatten_pipeline(state["params"])  # before the donated step
    _, m = step(state, jnp.asarray(x), jnp.asarray(y))
    logits = flat_forward(flat, jnp.asarray(x))
    want = float(optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.asarray(y)).mean())
    assert float(m["loss"]) == pytest.approx(want, rel=1e-5)


@pytest.mark.parametrize("dp,tp,pp", [(1, 2, 4), (2, 2, 2)])
def test_pp3_step_matches_flat_reference(dp, tp, pp):
    """The full 3D composition — dp batch split, tp col/row-split stage
    matmuls (one psum per pair), pp microbatched schedule — must produce
    the unpipelined, unsharded model's loss and updated params."""
    from dmlp_tpu.train.pipeline import (build_pp3_state, make_pp3_mesh,
                                         make_pp3_train_step,
                                         pp3_reference_forward)

    if len(jax.devices()) < dp * tp * pp:
        pytest.skip(f"needs {dp * tp * pp} devices")
    mesh = make_pp3_mesh(dp, tp, pp)
    lr = 0.05
    optimizer = make_optimizer("sgd", lr, momentum=0.0)
    state = build_pp3_state(mesh, optimizer, 6, 16, 4, 2, seed=13)
    ref = {k: jnp.asarray(np.asarray(v)) for k, v in state["params"].items()}

    rng = np.random.default_rng(4)
    n_micro = 4
    batch = dp * n_micro * 8
    x = rng.normal(size=(batch, 6)).astype(np.float32)
    y = rng.integers(0, 4, batch).astype(np.int32)

    step = make_pp3_train_step(mesh, optimizer, n_micro=n_micro,
                               n_classes=4)
    state, m = step(state, jnp.asarray(x), jnp.asarray(y))

    def ref_loss_fn(p):
        logits = pp3_reference_forward(p, jnp.asarray(x))
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.asarray(y)).mean()

    ref_loss, grads = jax.value_and_grad(ref_loss_fn)(ref)
    assert float(m["loss"]) == pytest.approx(float(ref_loss), rel=1e-5)
    for k in ref:
        want = np.asarray(ref[k]) - lr * np.asarray(grads[k])
        np.testing.assert_allclose(np.asarray(state["params"][k]), want,
                                   rtol=2e-4, atol=2e-6, err_msg=k)


@pytest.mark.parametrize("dp,pp,vv,n_micro", [(2, 4, 2, 4), (1, 4, 3, 2),
                                              (1, 2, 2, 2)])
def test_interleaved_step_matches_flat_reference(dp, pp, vv, n_micro):
    """VERDICT r4 item 6: the interleaved (1F1B-interleaved / virtual
    stages) schedule must produce the unpipelined flat stack's loss and
    updated params exactly — same criterion as the GPipe equivalence."""
    from dmlp_tpu.train.pipeline import (build_ppi_state, make_pp_mesh,
                                         make_ppi_train_step)

    if len(jax.devices()) < dp * pp:
        pytest.skip(f"needs {dp * pp} devices")
    mesh = make_pp_mesh(dp, pp)
    lr = 0.05
    optimizer = make_optimizer("sgd", lr, momentum=0.0)
    state = build_ppi_state(mesh, optimizer, 6, 16, 4, n_virtual=vv,
                            layers_per_chunk=2, seed=13)
    ref = {k: jnp.asarray(np.asarray(v)) for k, v in state["params"].items()}

    rng = np.random.default_rng(4)
    batch = dp * n_micro * 8
    x = rng.normal(size=(batch, 6)).astype(np.float32)
    y = rng.integers(0, 4, batch).astype(np.int32)

    step = make_ppi_train_step(mesh, optimizer, n_micro=n_micro,
                               n_virtual=vv, n_classes=4)
    state, m = step(state, jnp.asarray(x), jnp.asarray(y))

    def ref_loss_fn(p):
        v, s, pc, h, _ = p["pp_w"].shape
        ws = p["pp_w"].reshape(v * s * pc, h, h)
        bs = p["pp_b"].reshape(v * s * pc, h)
        hh = jnp.asarray(x) @ p["in_w"] + p["in_b"]
        for i in range(v * s * pc):
            hh = jax.nn.relu(hh @ ws[i] + bs[i])
        logits = hh @ p["out_w"] + p["out_b"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.asarray(y)).mean()

    ref_loss, grads = jax.value_and_grad(ref_loss_fn)(ref)
    assert float(m["loss"]) == pytest.approx(float(ref_loss), rel=1e-5)
    for k in ref:
        want = np.asarray(ref[k]) - lr * np.asarray(grads[k])
        np.testing.assert_allclose(np.asarray(state["params"][k]), want,
                                   rtol=2e-4, atol=2e-6, err_msg=k)

    # flatten_interleaved's (level, stage) chunk order must agree with the
    # inline reference's layer order.
    from dmlp_tpu.train.pipeline import flat_forward, flatten_interleaved
    flat_logits = flat_forward(flatten_interleaved(ref), jnp.asarray(x))
    flat_loss = optax.softmax_cross_entropy_with_integer_labels(
        flat_logits, jnp.asarray(y)).mean()
    assert float(flat_loss) == pytest.approx(float(ref_loss), rel=1e-6)


def test_interleaved_schedule_arithmetic_and_gates():
    from dmlp_tpu.train.pipeline import (bubble_fraction, make_pp_mesh,
                                         make_ppi_train_step,
                                         schedule_ticks)
    from dmlp_tpu.train.step import make_optimizer as mo

    assert schedule_ticks("gpipe", 4, 4) == 7
    assert schedule_ticks("interleaved", 4, 4, 2) == 11
    # interleaving divides the fill/drain term by V
    assert bubble_fraction("gpipe", 4, 4) == pytest.approx(3 / 7)
    assert bubble_fraction("interleaved", 4, 4, 2) == pytest.approx(
        1 - 4 / (3 / 2 + 4))
    assert bubble_fraction("interleaved", 4, 4, 2) \
        < bubble_fraction("gpipe", 4, 4)
    with pytest.raises(ValueError, match="n_micro <= n_stages"):
        make_ppi_train_step(make_pp_mesh(1, 2), mo("sgd", 0.1),
                            n_micro=4, n_virtual=2, n_classes=3)


def test_interleaved_via_train_loop():
    from dmlp_tpu.train.loop import train

    _, last = train(steps=6, batch=32, dims=(8, 16, 3), mesh_shape=(2, 4),
                    lr=0.05, log_every=6, parallelism="dp_pp", n_micro=2,
                    pp_schedule="interleaved", n_virtual=2)
    assert np.isfinite(last["loss"])
    with pytest.raises(ValueError, match="pp-schedule"):
        train(steps=1, batch=8, dims=(4, 8, 2), mesh_shape=(1, 1),
              parallelism="dp_tp", pp_schedule="interleaved")
