"""Multi-host feed helpers: shard math, offset-indexed reads, global arrays."""

import numpy as np

from dmlp_tpu.engine.sharded import ShardedEngine
from dmlp_tpu.config import EngineConfig
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.datagen import generate_input_text
from dmlp_tpu.io.grammar import parse_input_text
from dmlp_tpu.parallel.distributed import (initialize, line_offsets,
                                           make_global_dataset,
                                           make_global_queries,
                                           read_data_shard, shard_bounds)
from dmlp_tpu.parallel.mesh import make_mesh


def test_initialize_single_process_noop():
    initialize()           # no args
    initialize(num_processes=1)


def test_shard_bounds_cover_and_balance():
    for n in (0, 1, 7, 64, 101):
        for p in (1, 2, 3, 8):
            spans = [shard_bounds(n, p, i) for i in range(p)]
            assert spans[0][0] == 0 and spans[-1][1] == n
            for (a, b), (c, d) in zip(spans, spans[1:]):
                assert b == c
            sizes = [b - a for a, b in spans]
            assert max(sizes) - min(sizes) <= 1  # balanced, not rank-0-heavy


def test_line_offsets():
    data = b"a\nbb\n\nccc\n"
    offs = line_offsets(data)
    assert offs.tolist() == [0, 2, 5, 6, 10]


def test_read_data_shard_matches_full_parse(tmp_path):
    text = generate_input_text(53, 9, 4, -3, 3, 1, 8, 3, seed=12)
    path = tmp_path / "in.txt"
    path.write_text(text)
    full = parse_input_text(text)

    rows, labels = [], []
    for shard in range(4):
        params, l, a, start, ks, qa = read_data_shard(str(path), 4, shard)
        assert params.num_data == 53
        np.testing.assert_array_equal(ks, full.ks)
        np.testing.assert_array_equal(qa, full.query_attrs)
        lo, hi = shard_bounds(53, 4, shard)
        assert start == lo and a.shape[0] == hi - lo
        rows.append(a)
        labels.append(l)
    np.testing.assert_array_equal(np.concatenate(rows), full.data_attrs)
    np.testing.assert_array_equal(np.concatenate(labels), full.labels)


def test_sharded_feed_global_arrays_to_golden_parity(tmp_path):
    # The whole multi-host feed pipeline, single-process form: offset-
    # indexed shard read -> uniform sentinel padding -> global mesh arrays
    # -> the engine's compiled sharded program (solve_global) -> host
    # finalize. The engine consumes the pre-placed global arrays directly
    # (no per-host full-dataset device_put) and must hit golden parity.
    from dmlp_tpu.engine.finalize import finalize_host
    from dmlp_tpu.parallel.distributed import sharded_solve_from_file

    text = generate_input_text(301, 17, 3, 0, 5, 1, 9, 4, seed=33)
    path = tmp_path / "in.txt"
    path.write_text(text)
    inp = parse_input_text(text)
    mesh = make_mesh()
    engine = ShardedEngine(EngineConfig(mode="sharded", query_block=8),
                           mesh=mesh)

    top, params, ks = sharded_solve_from_file(str(path), engine)
    nq = params.num_queries
    got = finalize_host(np.asarray(top.dists, np.float64)[:nq],
                        np.asarray(top.labels)[:nq],
                        np.asarray(top.ids)[:nq],
                        ks, inp.query_attrs, inp.data_attrs, exact=True)
    want = knn_golden(inp)
    assert all(g.checksum() == w.checksum() for g, w in zip(got, want))


def test_make_global_dataset_placement():
    mesh = make_mesh()
    r = mesh.devices.shape[0]
    n = 16 * r
    ga, gl, gi = make_global_dataset(
        mesh, np.zeros((n, 3), np.float32),
        np.zeros(n, np.int32), np.arange(n, dtype=np.int32))
    assert ga.shape == (n, 3)
    assert len(ga.addressable_shards) == mesh.devices.size
    gq = make_global_queries(mesh, np.zeros((8 * mesh.devices.shape[1], 3),
                                            np.float32))
    assert gq.sharding.spec[0] == "query"
