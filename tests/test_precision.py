"""Low-precision first pass: analytic bound + byte-identity fuzz.

Two halves of the ``precision="bf16"`` contract get hardened here:

- the :func:`~dmlp_tpu.engine.finalize.lowp_eps` cast bound actually
  upper-bounds the bf16-vs-f32 cross-term error, fuzzed on directed
  adversarial corpora (magnitude cancellation: huge norms, tiny true
  distances — exactly where a naive relative bound would blow up);
- with the bound wired through the candidate windows, every engine
  tier under a forced bf16 first pass stays BYTE-identical to its f32
  run and to the f64 golden oracle — including duplicate-heavy tie
  grids straddling block boundaries, where a single flipped comparison
  in the lossy pass would reorder equal-distance neighbors.
"""

import numpy as np
import pytest

import ml_dtypes

from dmlp_tpu.config import EngineConfig
from dmlp_tpu.engine import finalize
from dmlp_tpu.engine.single import SingleChipEngine
from dmlp_tpu.engine.sharded import ShardedEngine
from dmlp_tpu.golden.reference import knn_golden
from dmlp_tpu.io.grammar import KNNInput, Params
from dmlp_tpu.io.report import format_results
from dmlp_tpu.serve.engine import ResidentEngine
from tests.test_engine_single import assert_same_results


def _bf16(x: np.ndarray) -> np.ndarray:
    """Round-trip through bfloat16 — the first-pass cast, in f64."""
    return x.astype(ml_dtypes.bfloat16).astype(np.float64)


# -- the analytic bound -------------------------------------------------------

def test_lowp_eps_zero_for_f32_and_no_silent_int8():
    qn = np.array([1.0, 4.0])
    assert finalize.lowp_eps("f32", qn, 9.0).tolist() == [0.0, 0.0]
    with pytest.raises(KeyError):
        finalize.lowp_eps("int8", qn, 9.0)


@pytest.mark.parametrize("seed", range(301, 311))
def test_lowp_eps_bounds_bf16_cross_term_error(seed):
    """Directed-rounding fuzz: |2(q·d − bf16(q)·bf16(d))| stays within
    lowp_eps on cancellation-heavy corpora. The kernel perturbs ONLY
    the cross term (norms stay f32 from exact inputs), so this is the
    whole cast error the windows must absorb."""
    rng = np.random.default_rng(seed)
    na = int(rng.integers(2, 16))
    scale = float(2.0 ** rng.integers(0, 11))     # norms up to ~2^10
    center = rng.uniform(-1, 1, na) * scale
    # data: a tight cluster on the center (distances ~1e-3 * scale,
    # cross terms ~scale^2 — maximal cancellation) plus spread rows
    n = 400
    cluster = center + rng.normal(0, 1e-3 * scale, (n // 2, na))
    spread = rng.uniform(-scale, scale, (n - n // 2, na))
    data = np.vstack([cluster, spread])
    queries = center + rng.normal(0, 1e-3 * scale, (24, na))
    cross = queries @ data.T                       # f64 exact
    cross_lowp = _bf16(queries) @ _bf16(data).T
    err = 2.0 * np.abs(cross - cross_lowp)
    qn = np.einsum("ij,ij->i", queries, queries)
    dn_max = float(np.max(np.einsum("ij,ij->i", data, data)))
    bound = finalize.lowp_eps("bf16", qn, dn_max)[:, None]
    assert np.all(err <= bound), \
        f"cast error {err.max()} exceeds lowp_eps {bound.min()}"


# -- engine byte-identity under the forced bf16 pass --------------------------

def _case(seed: int) -> KNNInput:
    """Duplicate-biased corpora with n straddling block granules."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(120, 700))
    nq = int(rng.integers(1, 32))
    na = int(rng.integers(1, 9))
    if rng.random() < 0.5:   # integer grid: exact f32 + massive ties
        data = rng.integers(0, 3, (n, na)).astype(np.float64)
        queries = rng.integers(0, 3, (nq, na)).astype(np.float64)
    else:
        data = rng.uniform(-20, 20, (n, na))
        queries = rng.uniform(-20, 20, (nq, na))
    labels = rng.integers(0, 5, n).astype(np.int32)
    ks = rng.integers(1, min(n, 48) + 1, nq).astype(np.int32)
    return KNNInput(Params(n, nq, na), labels, data, ks, queries)


def _cfg(precision: str, **kw) -> EngineConfig:
    return EngineConfig(select="extract", use_pallas=True,
                        precision=precision, **kw)


@pytest.mark.parametrize("seed", range(211, 221))
def test_single_engine_bf16_byte_identical_to_f32_and_golden(seed):
    inp = _case(seed)
    got_b = SingleChipEngine(_cfg("bf16")).run(inp)
    got_f = SingleChipEngine(_cfg("f32")).run(inp)
    gold = knn_golden(inp)
    assert format_results(got_b) == format_results(got_f) \
        == format_results(gold)
    assert_same_results(got_b, gold)


def test_single_engine_reports_active_precision_and_inflation():
    inp = _case(404)
    eng = SingleChipEngine(_cfg("bf16"))
    eng.run(inp)
    rec = eng.last_precision
    assert rec["active"] == "bf16" and rec["configured"] == "bf16"
    assert rec["kcap_inflation"] > 0      # the window actually widened
    eng_f = SingleChipEngine(_cfg("f32"))
    eng_f.run(inp)
    assert eng_f.last_precision["active"] == "f32"
    assert eng_f.last_precision["kcap_inflation"] == 0


def test_bf16_tie_grid_across_block_boundary():
    """All-duplicate integer grid with rows astride the block edge:
    every distance is bf16-representable, so ties are decided purely by
    id order — a first pass that perturbed comparison order would
    reorder the neighbor lists."""
    rng = np.random.default_rng(77)
    n, na = 260, 3                 # straddles the 256 block granule
    data = rng.integers(0, 2, (n, na)).astype(np.float64)
    data[128:140] = data[0]        # duplicate row group across chunks
    queries = data[[0, 5, 129, 255]].copy()
    ks = np.full(4, 48, np.int32)
    labels = rng.integers(0, 3, n).astype(np.int32)
    inp = KNNInput(Params(n, 4, na), labels, data, ks, queries)
    got_b = SingleChipEngine(_cfg("bf16")).run(inp)
    gold = knn_golden(inp)
    assert format_results(got_b) == format_results(gold)
    assert_same_results(got_b, gold)


def test_sharded_engine_bf16_byte_identical():
    inp = _case(555)
    eng = ShardedEngine(EngineConfig(mode="sharded", select="extract",
                                     precision="bf16", data_block=64))
    got = eng.run(inp)
    gold = knn_golden(inp)
    assert format_results(got) == format_results(gold)
    assert_same_results(got, gold)
    assert eng.last_precision["active"] == "bf16"


def test_resident_engine_bf16_matches_f32_and_golden():
    rng = np.random.default_rng(9)
    n, na = 600, 5
    corpus = KNNInput(Params(n, 0, na),
                      rng.integers(0, 4, n).astype(np.int32),
                      rng.uniform(-10, 10, (n, na)),
                      np.zeros(0, np.int32), np.zeros((0, na)))
    q = rng.uniform(-10, 10, (7, na))
    ks = np.array([1, 3, 8, 17, 32, 48, 5], np.int32)
    served_b = ResidentEngine(corpus, EngineConfig(precision="bf16")) \
        .solve_batch(q, ks)
    served_f = ResidentEngine(corpus, EngineConfig(precision="f32")) \
        .solve_batch(q, ks)
    inp = KNNInput(Params(n, len(ks), na), corpus.labels,
                   corpus.data_attrs, ks, q)
    gold = knn_golden(inp)
    assert format_results(served_b) == format_results(served_f) \
        == format_results(gold)


def test_env_kill_switch_and_force(monkeypatch):
    """$DMLP_TPU_PRECISION: "f32" disarms a bf16 config; "bf16" arms a
    default config. Either way the answers stay golden."""
    inp = _case(888)
    monkeypatch.setenv("DMLP_TPU_PRECISION", "f32")
    eng = SingleChipEngine(_cfg("bf16"))
    assert format_results(eng.run(inp)) == format_results(knn_golden(inp))
    assert eng.last_precision["active"] == "f32"
    monkeypatch.setenv("DMLP_TPU_PRECISION", "bf16")
    eng2 = SingleChipEngine(_cfg("auto"))
    assert format_results(eng2.run(inp)) == format_results(knn_golden(inp))
    assert eng2.last_precision["active"] == "bf16"


def test_fast_mode_never_runs_lowp():
    """The bf16 pass is only sound with the f64 rescore behind it —
    fast (non-exact) mode must pin the pass to f32."""
    cfg = _cfg("bf16", exact=False)
    assert cfg.resolve_precision() == "f32"
